"""TPC-H workload: deterministic data generator + queries as SSA programs.

The reference ships dbgen-compatible generators and query runners
(ydb/library/workload/tpch/, ydb/library/benchmarks/queries/tpch/,
CLI `ydb workload tpch` — ydb_cli/commands/ydb_benchmark.cpp). This module
is the TPU build's equivalent harness: a fast numpy generator with dbgen's
column domains and distributions (uniform approximations; deterministic per
seed — benchmark comparisons are engine-vs-engine on identical data, which
is what BASELINE.md requires) and the benchmark queries expressed directly
against the engine API.

Dates are int32 days since epoch; money columns are decimal(2) scaled
int64, matching dbgen's cent-exact semantics.
"""

from __future__ import annotations

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.ssa.ops import Agg, Op
from ydb_tpu.ssa.program import (
    AggSpec,
    AssignStep,
    Call,
    Col,
    Const,
    FilterStep,
    GroupByStep,
    Program,
    ProjectStep,
    SortStep,
    decimal_lit,
)

DEC2 = dtypes.decimal(2)


def _days(s: str) -> int:
    return np.datetime64(s, "D").astype(np.int32).item()


LINEITEM_SCHEMA = dtypes.schema(
    ("l_orderkey", dtypes.INT64, False),
    ("l_partkey", dtypes.INT64, False),
    ("l_suppkey", dtypes.INT64, False),
    ("l_linenumber", dtypes.INT32, False),
    ("l_quantity", DEC2, False),
    ("l_extendedprice", DEC2, False),
    ("l_discount", DEC2, False),
    ("l_tax", DEC2, False),
    ("l_returnflag", dtypes.STRING, False),
    ("l_linestatus", dtypes.STRING, False),
    ("l_shipdate", dtypes.DATE, False),
    ("l_commitdate", dtypes.DATE, False),
    ("l_receiptdate", dtypes.DATE, False),
    ("l_shipinstruct", dtypes.STRING, False),
    ("l_shipmode", dtypes.STRING, False),
)

ORDERS_SCHEMA = dtypes.schema(
    ("o_orderkey", dtypes.INT64, False),
    ("o_custkey", dtypes.INT64, False),
    ("o_orderstatus", dtypes.STRING, False),
    ("o_totalprice", DEC2, False),
    ("o_orderdate", dtypes.DATE, False),
    ("o_orderpriority", dtypes.STRING, False),
    ("o_shippriority", dtypes.INT32, False),
    ("o_comment", dtypes.STRING, False),
)

CUSTOMER_SCHEMA = dtypes.schema(
    ("c_custkey", dtypes.INT64, False),
    ("c_name", dtypes.STRING, False),
    ("c_address", dtypes.STRING, False),
    ("c_nationkey", dtypes.INT32, False),
    ("c_phone", dtypes.STRING, False),
    ("c_acctbal", DEC2, False),
    ("c_mktsegment", dtypes.STRING, False),
    ("c_comment", dtypes.STRING, False),
)

SUPPLIER_SCHEMA = dtypes.schema(
    ("s_suppkey", dtypes.INT64, False),
    ("s_name", dtypes.STRING, False),
    ("s_address", dtypes.STRING, False),
    ("s_nationkey", dtypes.INT32, False),
    ("s_phone", dtypes.STRING, False),
    ("s_acctbal", DEC2, False),
    ("s_comment", dtypes.STRING, False),
)

PART_SCHEMA = dtypes.schema(
    ("p_partkey", dtypes.INT64, False),
    ("p_name", dtypes.STRING, False),
    ("p_mfgr", dtypes.STRING, False),
    ("p_brand", dtypes.STRING, False),
    ("p_type", dtypes.STRING, False),
    ("p_size", dtypes.INT32, False),
    ("p_container", dtypes.STRING, False),
    ("p_retailprice", DEC2, False),
)

PARTSUPP_SCHEMA = dtypes.schema(
    ("ps_partkey", dtypes.INT64, False),
    ("ps_suppkey", dtypes.INT64, False),
    ("ps_availqty", dtypes.INT32, False),
    ("ps_supplycost", DEC2, False),
)

NATION_SCHEMA = dtypes.schema(
    ("n_nationkey", dtypes.INT32, False),
    ("n_regionkey", dtypes.INT32, False),
    ("n_name", dtypes.STRING, False),
)

REGION_SCHEMA = dtypes.schema(
    ("r_regionkey", dtypes.INT32, False),
    ("r_name", dtypes.STRING, False),
)

NATIONS = [
    b"ALGERIA", b"ARGENTINA", b"BRAZIL", b"CANADA", b"EGYPT", b"ETHIOPIA",
    b"FRANCE", b"GERMANY", b"INDIA", b"INDONESIA", b"IRAN", b"IRAQ",
    b"JAPAN", b"JORDAN", b"KENYA", b"MOROCCO", b"MOZAMBIQUE", b"PERU",
    b"CHINA", b"ROMANIA", b"SAUDI ARABIA", b"VIETNAM", b"RUSSIA",
    b"UNITED KINGDOM", b"UNITED STATES",
]
NATION_REGION = [0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4, 0, 0, 0, 1, 2,
                 3, 4, 2, 3, 3, 1]
REGIONS = [b"AFRICA", b"AMERICA", b"ASIA", b"EUROPE", b"MIDDLE EAST"]
SEGMENTS = [b"AUTOMOBILE", b"BUILDING", b"FURNITURE", b"MACHINERY",
            b"HOUSEHOLD"]
SHIPMODES = [b"REG AIR", b"AIR", b"RAIL", b"SHIP", b"TRUCK", b"MAIL", b"FOB"]
INSTRUCTS = [b"DELIVER IN PERSON", b"COLLECT COD", b"NONE",
             b"TAKE BACK RETURN"]
PRIORITIES = [b"1-URGENT", b"2-HIGH", b"3-MEDIUM", b"4-NOT SPECIFIED",
              b"5-LOW"]

# dbgen text grammar stand-ins: bounded pools keep dictionary sizes (and
# plan-time LIKE-mask evaluation) independent of SF while preserving the
# patterns the TPC-H predicates probe for (p_name '%green%', o_comment
# '%special%requests%', s_comment '%Customer%Complaints%', p_type
# '%BRASS' / 'PROMO%', ...). Reference grammar: dbgen dists.dss via
# ydb/library/workload/tpch_workload.cpp data generators.
COLORS = [
    b"almond", b"antique", b"aquamarine", b"azure", b"beige", b"bisque",
    b"black", b"blanched", b"blue", b"blush", b"brown", b"burlywood",
    b"burnished", b"chartreuse", b"chiffon", b"chocolate", b"coral",
    b"cornflower", b"cornsilk", b"cream", b"cyan", b"dark", b"deep",
    b"dim", b"dodger", b"drab", b"firebrick", b"floral", b"forest",
    b"frosted", b"gainsboro", b"ghost", b"goldenrod", b"green", b"grey",
    b"honeydew", b"hot", b"indian", b"ivory", b"khaki", b"lace",
    b"lavender", b"lawn", b"lemon", b"light", b"lime", b"linen",
    b"magenta", b"maroon", b"medium", b"metallic", b"midnight", b"mint",
    b"misty", b"moccasin", b"navajo", b"navy", b"olive", b"orange",
    b"orchid", b"pale", b"papaya", b"peach", b"peru", b"pink", b"plum",
    b"powder", b"puff", b"purple", b"red", b"rose", b"rosy", b"royal",
    b"saddle", b"salmon", b"sandy", b"seashell", b"sienna", b"sky",
    b"slate", b"smoke", b"snow", b"spring", b"steel", b"tan", b"thistle",
    b"tomato", b"turquoise", b"violet", b"wheat", b"white", b"yellow",
]
TYPE_SYL1 = [b"STANDARD", b"SMALL", b"MEDIUM", b"LARGE", b"ECONOMY",
             b"PROMO"]
TYPE_SYL2 = [b"ANODIZED", b"BURNISHED", b"PLATED", b"POLISHED", b"BRUSHED"]
TYPE_SYL3 = [b"TIN", b"NICKEL", b"BRASS", b"STEEL", b"COPPER"]
CONTAINER_SYL1 = [b"SM", b"LG", b"MED", b"JUMBO", b"WRAP"]
CONTAINER_SYL2 = [b"CASE", b"BOX", b"BAG", b"JAR", b"PKG", b"PACK", b"CAN",
                  b"DRUM"]
COMMENT_WORDS = [
    b"furiously", b"carefully", b"quickly", b"blithely", b"slyly",
    b"express", b"regular", b"final", b"ironic", b"pending", b"bold",
    b"unusual", b"even", b"special", b"silent", b"daring", b"requests",
    b"accounts", b"packages", b"deposits", b"instructions", b"theodolites",
    b"dependencies", b"excuses", b"platelets", b"asymptotes", b"somas",
    b"dugouts", b"sleep", b"nag", b"haggle", b"wake", b"cajole", b"detect",
    b"integrate", b"Customer", b"Complaints", b"above", b"against",
    b"along",
]


def _register(dicts: DictionarySet, col: str, values) -> np.ndarray:
    d = dicts.for_column(col)
    return np.fromiter((d.add(v) for v in values), dtype=np.int32,
                       count=len(values))


def _encode_pool(dicts: DictionarySet, col: str, pool: list[bytes],
                 picks: np.ndarray) -> np.ndarray:
    """Bulk dictionary encode: register the pool once, map pick indices."""
    ids = _register(dicts, col, pool)
    return ids[picks]


def _make_comment_pool(rng, size: int, n_words: int = 5) -> list[bytes]:
    """Bounded pool of pseudo-dbgen comments (word-chain grammar)."""
    words = np.array(COMMENT_WORDS, dtype=object)
    out = []
    for _ in range(size):
        k = rng.integers(2, n_words + 1)
        out.append(b" ".join(words[rng.integers(0, len(words), k)]))
    return out


def _encode_values(dicts: DictionarySet, col: str, values) -> np.ndarray:
    """Bulk encode a (possibly huge, mostly-distinct) value list: register
    each distinct value once, then map by index — O(n log n) instead of n
    Python dict probes."""
    arr = np.asarray(values, dtype=object)
    uniq, inv = np.unique(arr, return_inverse=True)
    ids = _register(dicts, col, list(uniq))
    return ids[inv].astype(np.int32)


def lineitem_chunks(sf: float, dicts: DictionarySet, seed: int = 42,
                    chunk_orders: int = 1_000_000):
    """Generate lineitem at ``sf`` in bounded CHUNKS (out-of-core
    ingest: the whole table never exists in memory). Distribution
    SHAPES match TpchData._gen_orders_lineitem (a deliberate second
    copy of those constants: the in-memory generator's single rng
    stream cannot be chunked without changing every seeded dataset —
    keep the two in sync when touching either); each chunk draws from
    its own (seed, chunk) stream so memory is O(chunk), not O(sf).
    Yields
    column dicts in LINEITEM_SCHEMA layout; shared string dictionaries
    populate into ``dicts``."""
    n_orders = int(1_500_000 * sf)
    n_part = max(int(200_000 * sf), 1)
    n_supp = max(int(10_000 * sf), 1)
    start = _days("1992-01-01")
    end = _days("1998-08-02")
    today = _days("1995-06-17")
    rf_dict = dicts.for_column("l_returnflag")
    rf_ids = np.array([rf_dict.add(b"R"), rf_dict.add(b"A"),
                       rf_dict.add(b"N")], dtype=np.int32)
    ls_dict = dicts.for_column("l_linestatus")
    ls_ids = np.array([ls_dict.add(b"O"), ls_dict.add(b"F")],
                      dtype=np.int32)
    smd = dicts.for_column("l_shipmode")
    sm_ids = np.array([smd.add(v) for v in SHIPMODES], dtype=np.int32)
    sid = dicts.for_column("l_shipinstruct")
    si_ids = np.array([sid.add(v) for v in INSTRUCTS], dtype=np.int32)
    for c, off in enumerate(range(0, n_orders, chunk_orders)):
        rng = np.random.default_rng((seed, c))
        n_o = min(chunk_orders, n_orders - off)
        o_orderkey = np.arange(off + 1, off + n_o + 1, dtype=np.int64)
        o_orderdate = rng.integers(start, end + 1, n_o, dtype=np.int32)
        lines = rng.integers(1, 8, n_o, dtype=np.int32)
        n_li = int(lines.sum())
        idx = np.repeat(np.arange(n_o), lines)
        l_quantity = rng.integers(1, 51, n_li, dtype=np.int64) * 100
        part_price = rng.integers(90_000, 110_001, n_li, dtype=np.int64)
        l_extendedprice = (l_quantity // 100) * part_price // 100 * 100
        ship_delay = rng.integers(1, 122, n_li, dtype=np.int32)
        l_shipdate = o_orderdate[idx] + ship_delay
        l_receiptdate = l_shipdate + rng.integers(
            1, 31, n_li, dtype=np.int32)
        ret = np.where(l_receiptdate > today, 2,
                       rng.integers(0, 2, n_li))
        yield {
            "l_orderkey": o_orderkey[idx],
            "l_partkey": rng.integers(1, n_part + 1, n_li,
                                      dtype=np.int64),
            "l_suppkey": rng.integers(1, n_supp + 1, n_li,
                                      dtype=np.int64),
            "l_linenumber": (
                np.arange(n_li, dtype=np.int64)
                - np.repeat(np.cumsum(lines) - lines, lines) + 1
            ).astype(np.int32),
            "l_quantity": l_quantity,
            "l_extendedprice": l_extendedprice,
            "l_discount": rng.integers(0, 11, n_li, dtype=np.int64),
            "l_tax": rng.integers(0, 9, n_li, dtype=np.int64),
            "l_returnflag": rf_ids[ret],
            "l_linestatus": ls_ids[
                (l_shipdate <= today).astype(np.int32)],
            "l_shipdate": l_shipdate.astype(np.int32),
            "l_commitdate": (o_orderdate[idx] + rng.integers(
                30, 91, n_li, dtype=np.int32)).astype(np.int32),
            "l_receiptdate": l_receiptdate.astype(np.int32),
            "l_shipinstruct": si_ids[
                rng.integers(0, len(INSTRUCTS), n_li)],
            "l_shipmode": sm_ids[
                rng.integers(0, len(SHIPMODES), n_li)],
        }


class TpchData:
    """Generated tables as host numpy column dicts + shared dictionaries."""

    def __init__(self, sf: float, seed: int = 42):
        self.sf = sf
        self.dicts = DictionarySet()
        rng = np.random.default_rng(seed)
        self.tables: dict[str, dict[str, np.ndarray]] = {}
        self._gen_orders_lineitem(rng)
        self._gen_customer(rng)
        self._gen_supplier(rng)
        self._gen_part_partsupp(rng)
        self._gen_nation_region()

    # dbgen cardinalities: orders = 1.5M * SF; lineitem ~ 4 lines/order
    def _gen_orders_lineitem(self, rng):
        n_orders = int(1_500_000 * self.sf)
        n_cust = max(int(150_000 * self.sf), 1)
        start = _days("1992-01-01")
        end = _days("1998-08-02")
        o_orderkey = np.arange(1, n_orders + 1, dtype=np.int64)
        o_orderdate = rng.integers(start, end + 1, n_orders, dtype=np.int32)
        o_custkey = rng.integers(1, n_cust + 1, n_orders, dtype=np.int64)
        lines_per_order = rng.integers(1, 8, n_orders, dtype=np.int32)
        n_li = int(lines_per_order.sum())

        li_order_idx = np.repeat(np.arange(n_orders), lines_per_order)
        l_orderkey = o_orderkey[li_order_idx]
        l_linenumber = (
            np.arange(n_li, dtype=np.int64)
            - np.repeat(
                np.cumsum(lines_per_order) - lines_per_order, lines_per_order
            )
            + 1
        ).astype(np.int32)
        n_part = max(int(200_000 * self.sf), 1)
        n_supp = max(int(10_000 * self.sf), 1)
        l_partkey = rng.integers(1, n_part + 1, n_li, dtype=np.int64)
        l_suppkey = rng.integers(1, n_supp + 1, n_li, dtype=np.int64)
        l_quantity = rng.integers(1, 51, n_li, dtype=np.int64) * 100
        # dbgen: extendedprice = qty * part retail price (~90k-110k cents)
        part_price = rng.integers(90_000, 110_001, n_li, dtype=np.int64)
        l_extendedprice = (l_quantity // 100) * part_price // 100 * 100
        l_discount = rng.integers(0, 11, n_li, dtype=np.int64)  # 0.00-0.10
        l_tax = rng.integers(0, 9, n_li, dtype=np.int64)        # 0.00-0.08
        ship_delay = rng.integers(1, 122, n_li, dtype=np.int32)
        l_shipdate = o_orderdate[li_order_idx] + ship_delay
        l_commitdate = o_orderdate[li_order_idx] + rng.integers(
            30, 91, n_li, dtype=np.int32)
        l_receiptdate = l_shipdate + rng.integers(1, 31, n_li, dtype=np.int32)

        today = _days("1995-06-17")
        shipped = l_shipdate <= today
        # returnflag: R or A for shipped-long-ago (50/50), N otherwise
        ret = np.where(
            l_receiptdate > today,
            2,  # N
            rng.integers(0, 2, n_li),  # 0=R 1=A
        )
        rf_dict = self.dicts.for_column("l_returnflag")
        ids = np.array([rf_dict.add(b"R"), rf_dict.add(b"A"),
                        rf_dict.add(b"N")], dtype=np.int32)
        l_returnflag = ids[ret]
        ls_dict = self.dicts.for_column("l_linestatus")
        ls_ids = np.array([ls_dict.add(b"O"), ls_dict.add(b"F")],
                          dtype=np.int32)
        l_linestatus = ls_ids[shipped.astype(np.int32)]
        sm = rng.integers(0, len(SHIPMODES), n_li)
        si = rng.integers(0, len(INSTRUCTS), n_li)
        smd = self.dicts.for_column("l_shipmode")
        sm_ids = np.array([smd.add(v) for v in SHIPMODES], dtype=np.int32)
        sid = self.dicts.for_column("l_shipinstruct")
        si_ids = np.array([sid.add(v) for v in INSTRUCTS], dtype=np.int32)

        self.tables["lineitem"] = {
            "l_orderkey": l_orderkey,
            "l_partkey": l_partkey,
            "l_suppkey": l_suppkey,
            "l_linenumber": l_linenumber,
            "l_quantity": l_quantity,
            "l_extendedprice": l_extendedprice,
            "l_discount": l_discount,
            "l_tax": l_tax,
            "l_returnflag": l_returnflag,
            "l_linestatus": l_linestatus,
            "l_shipdate": l_shipdate.astype(np.int32),
            "l_commitdate": l_commitdate.astype(np.int32),
            "l_receiptdate": l_receiptdate.astype(np.int32),
            "l_shipinstruct": si_ids[si],
            "l_shipmode": sm_ids[sm],
        }
        pr = rng.integers(0, len(PRIORITIES), n_orders)
        prd = self.dicts.for_column("o_orderpriority")
        pr_ids = np.array([prd.add(v) for v in PRIORITIES], dtype=np.int32)
        osd = self.dicts.for_column("o_orderstatus")
        os_ids = np.array([osd.add(b"O"), osd.add(b"F"), osd.add(b"P")],
                          dtype=np.int32)
        status = rng.integers(0, 3, n_orders)
        # o_comment pool: ~2% of entries carry the q13 'special…requests'
        # chain, the rest are plain word chains
        pool = _make_comment_pool(rng, 2048)
        for i in range(0, len(pool), 50):
            pool[i] = pool[i] + b" special handling requests " + pool[i]
        self.tables["orders"] = {
            "o_orderkey": o_orderkey,
            "o_custkey": o_custkey,
            "o_orderstatus": os_ids[status],
            "o_totalprice": rng.integers(
                100_00, 500_000_00, n_orders, dtype=np.int64),
            "o_orderdate": o_orderdate,
            "o_orderpriority": pr_ids[pr],
            "o_shippriority": np.zeros(n_orders, dtype=np.int32),
            "o_comment": _encode_pool(
                self.dicts, "o_comment", pool,
                rng.integers(0, len(pool), n_orders)),
        }

    @staticmethod
    def _phones(rng, nationkey: np.ndarray) -> list[bytes]:
        """dbgen phone format: 'CC-xxx-xxx-xxxx', CC = 10 + nationkey
        (q22 reads substring(c_phone, 1, 2) as the country code)."""
        digits = rng.integers(0, 10, (len(nationkey), 10))
        return [
            b"%d-%d%d%d-%d%d%d-%d%d%d%d" % ((10 + int(nk),) + tuple(d))
            for nk, d in zip(nationkey, digits)
        ]

    def _gen_customer(self, rng):
        n = max(int(150_000 * self.sf), 1)
        seg = rng.integers(0, len(SEGMENTS), n)
        sd = self.dicts.for_column("c_mktsegment")
        seg_ids = np.array([sd.add(v) for v in SEGMENTS], dtype=np.int32)
        nationkey = rng.integers(0, 25, n, dtype=np.int32)
        addr_pool = _make_comment_pool(rng, 512, n_words=3)
        self.tables["customer"] = {
            "c_custkey": np.arange(1, n + 1, dtype=np.int64),
            "c_name": _encode_values(
                self.dicts, "c_name",
                [b"Customer#%09d" % k for k in range(1, n + 1)]),
            "c_address": _encode_pool(
                self.dicts, "c_address", addr_pool,
                rng.integers(0, len(addr_pool), n)),
            "c_nationkey": nationkey,
            "c_phone": _encode_values(
                self.dicts, "c_phone", self._phones(rng, nationkey)),
            "c_acctbal": rng.integers(-999_99, 9999_99, n, dtype=np.int64),
            "c_mktsegment": seg_ids[seg],
            "c_comment": _encode_pool(
                self.dicts, "c_comment", _make_comment_pool(rng, 1024),
                rng.integers(0, 1024, n)),
        }

    def _gen_supplier(self, rng):
        n = max(int(10_000 * self.sf), 1)
        nationkey = rng.integers(0, 25, n, dtype=np.int32)
        addr_pool = _make_comment_pool(rng, 256, n_words=3)
        # ~1.6% of suppliers carry the q16 'Customer Complaints' chain
        comment_pool = _make_comment_pool(rng, 512)
        for i in range(0, len(comment_pool), 64):
            comment_pool[i] = comment_pool[i] + b" Customer loud Complaints"
        self.tables["supplier"] = {
            "s_suppkey": np.arange(1, n + 1, dtype=np.int64),
            "s_name": _encode_values(
                self.dicts, "s_name",
                [b"Supplier#%09d" % k for k in range(1, n + 1)]),
            "s_address": _encode_pool(
                self.dicts, "s_address", addr_pool,
                rng.integers(0, len(addr_pool), n)),
            "s_nationkey": nationkey,
            "s_phone": _encode_values(
                self.dicts, "s_phone", self._phones(rng, nationkey)),
            "s_acctbal": rng.integers(-999_99, 9999_99, n, dtype=np.int64),
            "s_comment": _encode_pool(
                self.dicts, "s_comment", comment_pool,
                rng.integers(0, len(comment_pool), n)),
        }

    def _gen_part_partsupp(self, rng):
        n = max(int(200_000 * self.sf), 1)
        # p_name: 3 colors joined (dbgen: 5 of 92); pool bounded by combos
        picks = rng.integers(0, len(COLORS), (n, 3))
        names = [b" ".join((COLORS[a], COLORS[b], COLORS[c]))
                 for a, b, c in picks]
        mfgr = rng.integers(1, 6, n)
        brand = mfgr * 10 + rng.integers(1, 6, n)
        t1 = rng.integers(0, len(TYPE_SYL1), n)
        t2 = rng.integers(0, len(TYPE_SYL2), n)
        t3 = rng.integers(0, len(TYPE_SYL3), n)
        types = [b" ".join((TYPE_SYL1[a], TYPE_SYL2[b], TYPE_SYL3[c]))
                 for a, b, c in zip(t1, t2, t3)]
        c1 = rng.integers(0, len(CONTAINER_SYL1), n)
        c2 = rng.integers(0, len(CONTAINER_SYL2), n)
        containers = [b" ".join((CONTAINER_SYL1[a], CONTAINER_SYL2[b]))
                      for a, b in zip(c1, c2)]
        self.tables["part"] = {
            "p_partkey": np.arange(1, n + 1, dtype=np.int64),
            "p_name": _encode_values(self.dicts, "p_name", names),
            "p_mfgr": _encode_values(
                self.dicts, "p_mfgr",
                [b"Manufacturer#%d" % m for m in mfgr]),
            "p_brand": _encode_values(
                self.dicts, "p_brand", [b"Brand#%d" % b for b in brand]),
            "p_type": _encode_values(self.dicts, "p_type", types),
            "p_size": rng.integers(1, 51, n, dtype=np.int32),
            "p_container": _encode_values(
                self.dicts, "p_container", containers),
            "p_retailprice": (90_000 + (np.arange(1, n + 1) % 20_001)
                              ).astype(np.int64),
        }
        # partsupp: each part has 4 suppliers (dbgen), pk (partkey, suppkey)
        n_supp = max(int(10_000 * self.sf), 1)
        ps_partkey = np.repeat(np.arange(1, n + 1, dtype=np.int64), 4)
        ps_suppkey = (
            (ps_partkey + np.tile(np.arange(4, dtype=np.int64), n)
             * max(n_supp // 4, 1)) % n_supp + 1
        )
        m = len(ps_partkey)
        self.tables["partsupp"] = {
            "ps_partkey": ps_partkey,
            "ps_suppkey": ps_suppkey,
            "ps_availqty": rng.integers(1, 10_000, m, dtype=np.int32),
            "ps_supplycost": rng.integers(100, 1000_00, m, dtype=np.int64),
        }

    def _gen_nation_region(self):
        self.tables["nation"] = {
            "n_nationkey": np.arange(25, dtype=np.int32),
            "n_regionkey": np.array(NATION_REGION, dtype=np.int32),
            "n_name": _register(self.dicts, "n_name", NATIONS),
        }
        self.tables["region"] = {
            "r_regionkey": np.arange(5, dtype=np.int32),
            "r_name": _register(self.dicts, "r_name", REGIONS),
        }

    def schema(self, table: str) -> dtypes.Schema:
        return {
            "lineitem": LINEITEM_SCHEMA,
            "orders": ORDERS_SCHEMA,
            "customer": CUSTOMER_SCHEMA,
            "supplier": SUPPLIER_SCHEMA,
            "part": PART_SCHEMA,
            "partsupp": PARTSUPP_SCHEMA,
            "nation": NATION_SCHEMA,
            "region": REGION_SCHEMA,
        }[table]


#: catalog primary keys (FK->PK lookup-join planning; schemeshard analog)
PRIMARY_KEYS = {
    "lineitem": ("l_orderkey", "l_linenumber"),
    "orders": ("o_orderkey",),
    "customer": ("c_custkey",),
    "supplier": ("s_suppkey",),
    "part": ("p_partkey",),
    "partsupp": ("ps_partkey", "ps_suppkey"),
    "nation": ("n_nationkey",),
    "region": ("r_regionkey",),
}


# ---------------- queries as SSA programs ----------------


def q1_program() -> Program:
    """TPC-H Q1: pricing summary report (the BASELINE north-star scan).

    select l_returnflag, l_linestatus, sum(qty), sum(price),
           sum(price*(1-disc)), sum(price*(1-disc)*(1+tax)),
           avg(qty), avg(price), avg(disc), count(*)
    from lineitem where l_shipdate <= '1998-12-01' - 90 days
    group by l_returnflag, l_linestatus order by same
    """
    cutoff = _days("1998-12-01") - 90
    one = decimal_lit("1", 2)
    disc_price = Call(Op.MUL, Col("l_extendedprice"),
                      Call(Op.SUB, one, Col("l_discount")))
    # charge: scale-6 decimal; int64 sums hold through ~SF-10 (SF-100 needs
    # the planned two-word accumulator)
    charge = Call(Op.MUL, Col("disc_price"),
                  Call(Op.ADD, one, Col("l_tax")))
    return Program((
        FilterStep(Call(Op.LE, Col("l_shipdate"),
                        Const(cutoff, dtypes.DATE))),
        AssignStep("disc_price", disc_price),
        AssignStep("charge", charge),
        GroupByStep(
            keys=("l_returnflag", "l_linestatus"),
            aggs=(
                AggSpec(Agg.SUM, "l_quantity", "sum_qty"),
                AggSpec(Agg.SUM, "l_extendedprice", "sum_base_price"),
                AggSpec(Agg.SUM, "disc_price", "sum_disc_price"),
                AggSpec(Agg.SUM, "charge", "sum_charge"),
                AggSpec(Agg.AVG, "l_quantity", "avg_qty"),
                AggSpec(Agg.AVG, "l_extendedprice", "avg_price"),
                AggSpec(Agg.AVG, "l_discount", "avg_disc"),
                AggSpec(Agg.COUNT_ALL, None, "count_order"),
            ),
        ),
        SortStep(keys=("l_returnflag", "l_linestatus")),
    ))


def q6_program() -> Program:
    """TPC-H Q6: forecasting revenue change (pure filter + global agg)."""
    d0 = _days("1994-01-01")
    d1 = _days("1995-01-01")
    return Program((
        FilterStep(Call(Op.GE, Col("l_shipdate"), Const(d0, dtypes.DATE))),
        FilterStep(Call(Op.LT, Col("l_shipdate"), Const(d1, dtypes.DATE))),
        FilterStep(Call(Op.GE, Col("l_discount"), decimal_lit("0.05", 2))),
        FilterStep(Call(Op.LE, Col("l_discount"), decimal_lit("0.07", 2))),
        FilterStep(Call(Op.LT, Col("l_quantity"), decimal_lit("24", 2))),
        AssignStep("revenue_item",
                   Call(Op.MUL, Col("l_extendedprice"), Col("l_discount"))),
        GroupByStep(keys=(), aggs=(
            AggSpec(Agg.SUM, "revenue_item", "revenue"),
        )),
    ))


# ---------------- join queries as logical plans ----------------


def q3_plan():
    """TPC-H Q3: shipping priority (BASELINE config 4 join shape).

    customer(BUILDING) semi-> orders(< date) -> lineitem(> date) joins,
    then group by (l_orderkey, o_orderdate, o_shippriority), top-10 by
    revenue.
    """
    from ydb_tpu.plan import LookupJoin, TableScan, Transform
    from ydb_tpu.ssa.program import DictPredicate

    date = _days("1995-03-15")
    customers = TableScan("customer", Program((
        FilterStep(DictPredicate("c_mktsegment", "eq", b"BUILDING")),
        ProjectStep(("c_custkey",)),
    )))
    orders = TableScan("orders", Program((
        FilterStep(Call(Op.LT, Col("o_orderdate"), Const(date, dtypes.DATE))),
        ProjectStep(("o_orderkey", "o_custkey", "o_orderdate",
                     "o_shippriority")),
    )))
    orders_building = LookupJoin(
        probe=orders, build=customers,
        probe_keys=("o_custkey",), build_keys=("c_custkey",), kind="semi",
    )
    lineitem = TableScan("lineitem", Program((
        FilterStep(Call(Op.GT, Col("l_shipdate"), Const(date, dtypes.DATE))),
        ProjectStep(("l_orderkey", "l_extendedprice", "l_discount")),
    )))
    joined = LookupJoin(
        probe=lineitem, build=orders_building,
        probe_keys=("l_orderkey",), build_keys=("o_orderkey",),
        payload=("o_orderdate", "o_shippriority"), kind="inner",
    )
    return Transform(joined, Program((
        AssignStep("rev_item", Call(Op.MUL, Col("l_extendedprice"),
                   Call(Op.SUB, decimal_lit("1", 2), Col("l_discount")))),
        GroupByStep(
            keys=("l_orderkey", "o_orderdate", "o_shippriority"),
            aggs=(AggSpec(Agg.SUM, "rev_item", "revenue"),),
        ),
        # l_orderkey tie-break pins the order beyond the spec's
        # (revenue desc, date) for deterministic comparisons
        SortStep(keys=("revenue", "o_orderdate", "l_orderkey"),
                 descending=(True, False, False), limit=10),
    )))


def q5_plan():
    """TPC-H Q5: local supplier volume (6-table join chain)."""
    from ydb_tpu.plan import LookupJoin, TableScan, Transform
    from ydb_tpu.ssa.program import DictPredicate

    d0, d1 = _days("1994-01-01"), _days("1995-01-01")
    region = TableScan("region", Program((
        FilterStep(DictPredicate("r_name", "eq", b"ASIA")),
        ProjectStep(("r_regionkey",)),
    )))
    nation = LookupJoin(
        probe=TableScan("nation"), build=region,
        probe_keys=("n_regionkey",), build_keys=("r_regionkey",),
        kind="semi",
    )
    orders = TableScan("orders", Program((
        FilterStep(Call(Op.GE, Col("o_orderdate"), Const(d0, dtypes.DATE))),
        FilterStep(Call(Op.LT, Col("o_orderdate"), Const(d1, dtypes.DATE))),
        ProjectStep(("o_orderkey", "o_custkey")),
    )))
    li = TableScan("lineitem", Program((
        ProjectStep(("l_orderkey", "l_suppkey", "l_extendedprice",
                     "l_discount")),
    )))
    li_orders = LookupJoin(
        probe=li, build=orders,
        probe_keys=("l_orderkey",), build_keys=("o_orderkey",),
        payload=("o_custkey",), kind="inner",
    )
    li_supp = LookupJoin(
        probe=li_orders, build=TableScan("supplier"),
        probe_keys=("l_suppkey",), build_keys=("s_suppkey",),
        payload=("s_nationkey",), kind="inner",
    )
    li_cust = LookupJoin(
        probe=li_supp, build=TableScan("customer"),
        probe_keys=("o_custkey",), build_keys=("c_custkey",),
        payload=("c_nationkey",), kind="inner",
    )
    li_nation = LookupJoin(
        probe=li_cust, build=nation,
        probe_keys=("s_nationkey",), build_keys=("n_nationkey",),
        payload=("n_name",), kind="inner",
    )
    return Transform(li_nation, Program((
        # customer and supplier must share the nation
        FilterStep(Call(Op.EQ, Call(Op.CAST_INT64, Col("c_nationkey")),
                        Call(Op.CAST_INT64, Col("s_nationkey")))),
        AssignStep("rev_item", Call(Op.MUL, Col("l_extendedprice"),
                   Call(Op.SUB, decimal_lit("1", 2), Col("l_discount")))),
        GroupByStep(keys=("n_name",),
                    aggs=(AggSpec(Agg.SUM, "rev_item", "revenue"),)),
        SortStep(keys=("revenue",), descending=(True,)),
    )))
