"""SSA scan-program model.

The TPU-native equivalent of the reference's serialized physical scan
program (ydb/core/protos/ssa.proto:19-207; TProgram/TProgramStep/TAssign
ydb/core/formats/arrow/program.h:412,313,111): an ordered list of steps —
assigns, filters, group-by, projection, sort — over named columns. The
program is *logical*; ydb_tpu.ssa.compiler lowers it to one traced JAX
function over a TableBlock.

Design departures from the reference, driven by XLA:
  * Filters do not materialize row selections; they AND into the block's
    live-row mask (late materialization). Row compaction is an explicit
    kernel applied only at block/host/shuffle boundaries.
  * String predicates (==, LIKE, IN, prefix) are `DictPredicate` leaves
    resolved at compile time against host dictionaries into small
    per-id lookup tables shipped to the device (ydb_tpu.blocks.dictionary).
  * GROUP BY lowers to dense-key or sort-based segment reduction with a
    static group capacity — no dynamic hash tables on device.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Union

from ydb_tpu import dtypes
from ydb_tpu.ssa.ops import Agg, Op

# ---------------- expressions ----------------


@dataclasses.dataclass(frozen=True)
class Col:
    name: str


@dataclasses.dataclass(frozen=True)
class Const:
    value: Any
    type: dtypes.LogicalType


@dataclasses.dataclass(frozen=True)
class Call:
    op: Op
    args: tuple["Expr", ...]

    def __init__(self, op: Op, *args: "Expr"):
        object.__setattr__(self, "op", op)
        object.__setattr__(self, "args", tuple(args))


@dataclasses.dataclass(frozen=True)
class DictPredicate:
    """A string predicate resolved against the column dictionary at
    compile time (eq / ne / like / prefix / in_set / not_in_set)."""

    column: str
    kind: str
    pattern: Any  # bytes | str | tuple for in_set


@dataclasses.dataclass(frozen=True)
class UdfCall:
    """A registered scalar UDF applied elementwise (the UDF ABI analog,
    ydb/library/yql/public/udf; SURVEY §2.9 UDF row). ``fn`` is the
    host-side vectorized implementation (numpy arrays in/out), resolved
    from the registry at plan time and carried in the node; the JAX
    lowering runs it through ``jax.pure_callback`` (host roundtrip — the
    price of arbitrary user code, exactly like the reference marshalling
    rows through the UDF ABI), the oracle calls it directly. NULLs:
    output row is NULL iff any argument is NULL."""

    name: str
    args: tuple["Expr", ...]
    out_type: dtypes.LogicalType
    fn: object  # Callable[[np.ndarray, ...], np.ndarray]


@dataclasses.dataclass(frozen=True)
class DictMap:
    """A string->string transform resolved against the column dictionary
    at compile time (substring etc.): builds the OUTPUT dictionary for
    ``out_column`` plus an id->id gather table shipped to the device.
    The device op is a pure int gather; the new dictionary registers in
    the shared DictionarySet so downstream group-by/sort/decode see it."""

    column: str
    kind: str       # "substr"
    args: tuple     # substr: (start_1based, length)
    out_column: str


Expr = Union[Col, Const, Call, DictPredicate, DictMap, UdfCall]


def lit(value, typ: dtypes.LogicalType | None = None) -> Const:
    if typ is None:
        if isinstance(value, bool):
            typ = dtypes.BOOL
        elif isinstance(value, int):
            typ = dtypes.INT64
        elif isinstance(value, float):
            typ = dtypes.DOUBLE
        else:
            raise TypeError(f"cannot infer literal type for {value!r}")
    return Const(value, typ)


def decimal_lit(text: str, scale: int) -> Const:
    """Decimal literal, e.g. decimal_lit('0.05', 2) -> 5 @ scale 2."""
    import decimal as pydec

    v = int(pydec.Decimal(text).scaleb(scale).to_integral_value())
    return Const(v, dtypes.decimal(scale))


# ---------------- steps ----------------


@dataclasses.dataclass(frozen=True)
class AssignStep:
    name: str
    expr: Expr


@dataclasses.dataclass(frozen=True)
class FilterStep:
    expr: Expr  # boolean; NULL counts as False (reference filter semantics)


@dataclasses.dataclass(frozen=True)
class AggSpec:
    func: Agg
    column: str | None  # None for COUNT_ALL
    out_name: str


@dataclasses.dataclass(frozen=True)
class GroupByStep:
    keys: tuple[str, ...]
    aggs: tuple[AggSpec, ...]
    # Optional static cap on distinct groups per block. Default None: the
    # sort-based path sizes its output to the block capacity (a block of N
    # rows has at most N groups), so nothing is dropped. Setting an
    # explicit cap trades that guarantee for memory: groups beyond the cap
    # (in key sort order) ARE truncated — callers own the sizing, e.g.
    # when a downstream LIMIT bounds the useful group count.
    max_groups: int | None = None


@dataclasses.dataclass(frozen=True)
class ProjectStep:
    names: tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class SortStep:
    """ORDER BY [+ LIMIT] — lowers to device argsort / top-k."""

    keys: tuple[str, ...]
    descending: tuple[bool, ...] = ()
    limit: int | None = None


@dataclasses.dataclass(frozen=True)
class WindowStep:
    """Ranking window: rank / dense_rank / row_number OVER
    (PARTITION BY partition ORDER BY order_keys).

    Whole-table semantics: the step must see EVERY row of its input at
    once, so it may only appear in programs executed over a
    materialized block (the planner keeps it out of scan pushdown, and
    the DQ lowering splits it into the merged final phase). Lowers to
    one device lexsort + segment scans + inverse-permutation scatter.
    """

    func: str  # rank | dense_rank | row_number
    partition: tuple[str, ...]
    order_keys: tuple[str, ...]
    descending: tuple[bool, ...]
    out_name: str


Step = Union[AssignStep, FilterStep, GroupByStep, ProjectStep, SortStep,
             WindowStep]


@dataclasses.dataclass(frozen=True)
class Program:
    """An ordered SSA program. Hashable: usable as a jit static arg and as
    the compiled-program cache key (the XLA-era analog of the reference's
    computation-pattern LRU cache, mkql_computation_pattern_cache.h)."""

    steps: tuple[Step, ...]

    def __post_init__(self):
        object.__setattr__(self, "steps", tuple(self.steps))

    @property
    def group_by(self) -> GroupByStep | None:
        for s in self.steps:
            if isinstance(s, GroupByStep):
                return s
        return None


# ---------------- type inference ----------------

_CMP = {Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT, Op.GE}
_LOGIC = {Op.AND, Op.OR, Op.NOT, Op.XOR}
_PRED = {Op.IS_NULL, Op.IS_NOT_NULL, Op.IN_SET}


def infer_type(
    expr: Expr,
    schema: dtypes.Schema,
    assigned: dict[str, dtypes.LogicalType],
) -> dtypes.LogicalType:
    """Result logical type of an expression (static, pre-lowering)."""
    if isinstance(expr, Col):
        if expr.name in assigned:
            return assigned[expr.name]
        return schema.field(expr.name).type
    if isinstance(expr, Const):
        return expr.type
    if isinstance(expr, DictPredicate):
        return dtypes.BOOL
    if isinstance(expr, DictMap):
        return dtypes.STRING
    if isinstance(expr, UdfCall):
        return expr.out_type
    assert isinstance(expr, Call)
    op = expr.op
    if op in _CMP or op in _LOGIC or op in _PRED:
        return dtypes.BOOL
    if op in (Op.CAST_INT32,):
        return dtypes.INT32
    if op in (Op.CAST_INT64,):
        return dtypes.INT64
    if op in (Op.CAST_FLOAT,):
        return dtypes.FLOAT
    if op in (Op.CAST_DOUBLE, Op.SQRT, Op.EXP, Op.LN, Op.LOG10,
              Op.POW, Op.SIN, Op.COS, Op.TAN, Op.ASIN, Op.ACOS,
              Op.ATAN, Op.SINH, Op.COSH, Op.TANH, Op.ASINH, Op.ACOSH,
              Op.ATANH, Op.ATAN2, Op.HYPOT, Op.CBRT, Op.ERF, Op.LOG2,
              Op.EXP2, Op.TRUNC, Op.RINT, Op.RADIANS, Op.DEGREES):
        return dtypes.DOUBLE
    if op is Op.CAST_INT8:
        return dtypes.INT8
    if op is Op.CAST_INT16:
        return dtypes.INT16
    if op is Op.CAST_UINT64:
        return dtypes.UINT64
    if op is Op.CAST_BOOL:
        return dtypes.BOOL
    if op in (Op.YEAR, Op.MONTH, Op.DAY, Op.HOUR, Op.MINUTE,
              Op.SECOND, Op.DAY_OF_WEEK, Op.DAY_OF_YEAR, Op.WEEK,
              Op.QUARTER):
        return dtypes.INT32
    arg_ts = [infer_type(a, schema, assigned) for a in expr.args]
    if op is Op.SIGN:
        # sign's output (-1/0/1) is NOT in a decimal arg's scaled
        # domain; type it as plain int (physical stays int64)
        return (dtypes.INT64 if arg_ts[0].is_decimal
                else arg_ts[0])
    if op in (Op.NEG, Op.ABS, Op.FLOOR, Op.CEIL, Op.ROUND, Op.BIT_NOT,
              Op.NULLIF, Op.SHIFT_LEFT, Op.SHIFT_RIGHT):
        return arg_ts[0]
    if op in (Op.COALESCE,):
        return arg_ts[0]
    if op is Op.IF:
        return arg_ts[1]
    if op in (Op.ADD, Op.SUB, Op.MUL, Op.DIV, Op.MOD):
        return _numeric_result(op, arg_ts)
    if op is Op.DIV_INT:
        if any(t.is_decimal or t.is_floating for t in arg_ts):
            return dtypes.INT64  # integer division of the values
        return _numeric_result(Op.ADD, arg_ts)
    if op in (Op.BIT_AND, Op.BIT_OR, Op.BIT_XOR):
        return _numeric_result(Op.ADD, arg_ts)
    if op in (Op.GREATEST, Op.LEAST):
        return _numeric_result(Op.ADD, arg_ts)
    if op is Op.DICT_GATHER:
        raise TypeError("DICT_GATHER is lowered internally, not user-facing")
    raise NotImplementedError(f"type inference for {op}")


def _numeric_result(op: Op, ts: list[dtypes.LogicalType]) -> dtypes.LogicalType:
    a, b = ts[0], ts[1]
    if (a.is_decimal and b.is_floating) or (b.is_decimal and a.is_floating):
        # mixed decimal x float: the decimal operand descales to float
        # (compiler _descale_mixed); exact decimal arithmetic is lost
        return dtypes.DOUBLE
    if a.is_decimal or b.is_decimal:
        sa = a.scale if a.is_decimal else 0
        sb = b.scale if b.is_decimal else 0
        if op is Op.MUL:
            return dtypes.decimal(sa + sb)
        if op is Op.DIV:
            return dtypes.DOUBLE
        if op in (Op.ADD, Op.SUB, Op.MOD):
            # operands are rescaled to the larger scale by the compiler
            # (_align_decimals), exact at compile time
            return dtypes.decimal(max(sa, sb))
    if a.is_floating or b.is_floating:
        if a.kind == dtypes.Kind.DOUBLE or b.kind == dtypes.Kind.DOUBLE:
            return dtypes.DOUBLE
        return dtypes.FLOAT
    if op is Op.DIV:
        # integer division stays integral (SQL semantics)
        pass
    # widest integer wins
    order = [
        dtypes.Kind.INT8, dtypes.Kind.UINT8, dtypes.Kind.INT16,
        dtypes.Kind.UINT16, dtypes.Kind.INT32, dtypes.Kind.UINT32,
        dtypes.Kind.DATE, dtypes.Kind.INT64, dtypes.Kind.UINT64,
        dtypes.Kind.TIMESTAMP,
    ]
    ka = order.index(a.kind) if a.kind in order else len(order)
    kb = order.index(b.kind) if b.kind in order else len(order)
    win = a if ka >= kb else b
    if win.kind in (dtypes.Kind.DATE, dtypes.Kind.TIMESTAMP):
        return dtypes.INT64
    return win


def agg_result_type(
    spec: AggSpec,
    schema: dtypes.Schema,
    assigned: dict[str, dtypes.LogicalType],
) -> dtypes.LogicalType:
    if spec.func in (Agg.COUNT, Agg.COUNT_ALL):
        return dtypes.INT64
    t = assigned.get(spec.column) or schema.field(spec.column).type
    if spec.func in (Agg.AVG, Agg.VAR_SAMP, Agg.STDDEV_SAMP):
        return dtypes.DOUBLE
    if spec.func is Agg.SUM:
        if t.is_decimal:
            return t
        if t.is_floating:
            return dtypes.DOUBLE
        return dtypes.INT64
    return t
