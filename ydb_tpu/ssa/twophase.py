"""Two-phase aggregation: split a Program at its GROUP BY.

The reference computes grouped aggregates in two phases — per-input partial
states (BlockCombineHashed, mkql_block_agg.cpp:1637) merged after a shuffle
(BlockMergeFinalizeHashed, :1655). The TPU build uses the same split for
three purposes:

  * multi-block scans: each block produces a small partial block; partials
    concat + finalize (ydb_tpu.engine.scan)
  * mesh parallelism: per-device partials merge via psum/all_gather over
    ICI (ydb_tpu.parallel)
  * DQ-style stage graphs: partial on scan tasks, final after HashPartition

``split(program)`` returns (partial, final):
  partial = steps before GROUP BY + a rewritten GROUP BY emitting mergeable
            states (AVG -> SUM+COUNT; COUNT -> COUNT; others unchanged)
  final   = GROUP BY over the partial columns with merge functions
            (SUM of SUMs/COUNTs, MIN of MINs, ...) + assigns restoring AVG
            + the original post-GROUP-BY steps + projection to the original
            output.
Programs without GROUP BY return (program, None): block results concat
directly (pure filter/project programs need no merge).
"""

from __future__ import annotations

from ydb_tpu.ssa.ops import Agg, Op
from ydb_tpu import dtypes
from ydb_tpu.ssa.program import (
    AggSpec,
    AssignStep,
    Call,
    Col,
    Const,
    GroupByStep,
    Program,
    ProjectStep,
    lit,
)


def dict_aliases(partial: Program) -> dict[str, str]:
    """column -> source-column dictionary aliases for the FINAL program:
    string-valued aggregate outputs (MIN(s) AS lo) carry the source
    column's dictionary."""
    gb = partial.group_by
    if gb is None:
        return {}
    return {
        s.out_name: s.column
        for s in gb.aggs
        if s.column is not None and s.out_name != s.column
    }


def combine_of(program: Program) -> Program | None:
    """The associative merge step of a two-phase split: a program that maps
    a batch of partial-state blocks to ONE partial-state block with the
    same columns (SUM of SUMs, MIN of MINs, ...). Because it is closed
    over the partial form and associative, scans can fold partials
    incrementally (tree reduction) instead of retaining every per-block
    partial until the end — the memory-bound analog of the reference's
    streaming combiner (mkql_block_agg.cpp BlockCombineHashed)."""
    partial, final = split(program)
    if final is None:
        return None
    gb = final.steps[0]
    assert isinstance(gb, GroupByStep)
    return Program((gb,))


def split(
    program: Program, with_row_counts: bool = False
) -> tuple[Program, Program | None]:
    """``with_row_counts`` adds an implicit ``__rows`` COUNT_ALL state to
    the partial program — mesh merging needs per-slot liveness to drop dead
    group slots before finalization (ydb_tpu.parallel.dist)."""
    gb_idx = None
    for i, s in enumerate(program.steps):
        if isinstance(s, GroupByStep):
            gb_idx = i
            break
    if gb_idx is None:
        return program, None
    gb: GroupByStep = program.steps[gb_idx]

    partial_aggs: list[AggSpec] = []
    final_aggs: list[AggSpec] = []
    avg_fixups: list[AssignStep] = []
    # derived input columns some partial states aggregate over (the
    # VAR/STDDEV x^2 column); they compute just before the partial
    # group-by
    pre_assigns: list[AssignStep] = []
    _var_cols: set[str] = set()  # VAR/STDDEV state triples per column
    for spec in gb.aggs:
        if spec.func is Agg.AVG:
            s_name = f"__avg_sum_{spec.out_name}"
            c_name = f"__avg_cnt_{spec.out_name}"
            partial_aggs.append(AggSpec(Agg.SUM, spec.column, s_name))
            partial_aggs.append(AggSpec(Agg.COUNT, spec.column, c_name))
            final_aggs.append(AggSpec(Agg.SUM, s_name, s_name))
            final_aggs.append(AggSpec(Agg.SUM, c_name, c_name))
            avg_fixups.append(
                AssignStep(
                    spec.out_name,
                    Call(
                        Op.DIV,
                        Call(Op.CAST_DOUBLE, Col(s_name)),
                        Col(c_name),
                    ),
                )
            )
        elif spec.func in (Agg.COUNT, Agg.COUNT_ALL):
            partial_aggs.append(spec)
            final_aggs.append(AggSpec(Agg.SUM, spec.out_name, spec.out_name))
        elif spec.func is Agg.SUM:
            partial_aggs.append(spec)
            final_aggs.append(AggSpec(Agg.SUM, spec.out_name, spec.out_name))
        elif spec.func is Agg.MIN:
            partial_aggs.append(spec)
            final_aggs.append(AggSpec(Agg.MIN, spec.out_name, spec.out_name))
        elif spec.func is Agg.MAX:
            partial_aggs.append(spec)
            final_aggs.append(AggSpec(Agg.MAX, spec.out_name, spec.out_name))
        elif spec.func is Agg.SOME:
            partial_aggs.append(spec)
            final_aggs.append(AggSpec(Agg.SOME, spec.out_name, spec.out_name))
        elif spec.func in (Agg.VAR_SAMP, Agg.STDDEV_SAMP):
            # decompose into linear states so the distributed merge is
            # a plain psum: SUM(x), SUM(x^2), COUNT(x) in VALUE units
            # (CAST_DOUBLE de-scales decimals); finalize via
            # var = (sq - sum^2/n) / (n - 1), clamped at 0, NULL for
            # n < 2 (safe_div on n-1 == 0). Known trade: the linear
            # form loses precision when |mean| >> stddev (relative
            # error ~ (mean/stddev)^2 * 2^-52) — the price of
            # psum-mergeable states; the CPU oracle deliberately uses
            # stable two-pass var so cross-checks expose that regime.
            # States are shared per SOURCE column: VAR + STDDEV over
            # the same column reuse one (sum, sq, count) triple.
            s_name = f"__var_sum_{spec.column}"
            q_name = f"__var_sq_{spec.column}"
            c_name = f"__var_cnt_{spec.column}"
            if s_name not in _var_cols:
                _var_cols.add(s_name)
                xd_name = f"__vd_{spec.column}"
                pre_assigns.append(AssignStep(
                    xd_name, Call(Op.CAST_DOUBLE, Col(spec.column))))
                pre_assigns.append(AssignStep(
                    q_name, Call(Op.MUL, Col(xd_name), Col(xd_name))))
                partial_aggs.append(AggSpec(Agg.SUM, xd_name, s_name))
                partial_aggs.append(AggSpec(Agg.SUM, q_name, q_name))
                partial_aggs.append(
                    AggSpec(Agg.COUNT, spec.column, c_name))
                for nm in (s_name, q_name, c_name):
                    final_aggs.append(AggSpec(Agg.SUM, nm, nm))
            var = Call(
                Op.DIV,
                Call(Op.SUB, Col(q_name),
                     Call(Op.DIV,
                          Call(Op.MUL, Col(s_name), Col(s_name)),
                          Col(c_name))),
                Call(Op.SUB, Col(c_name), lit(1)))
            var = Call(Op.GREATEST, var, Const(0.0, dtypes.DOUBLE))
            if spec.func is Agg.STDDEV_SAMP:
                var = Call(Op.SQRT, var)
            avg_fixups.append(AssignStep(spec.out_name, var))
        else:
            raise NotImplementedError(f"two-phase split of {spec.func}")

    if with_row_counts:
        partial_aggs.append(AggSpec(Agg.COUNT_ALL, None, "__rows"))
    partial = Program(
        program.steps[:gb_idx] + tuple(pre_assigns)
        + (GroupByStep(gb.keys, tuple(partial_aggs), gb.max_groups),)
    )
    out_names = tuple(gb.keys) + tuple(s.out_name for s in gb.aggs)
    final_steps: list = [
        GroupByStep(gb.keys, tuple(final_aggs), gb.max_groups)
    ]
    final_steps.extend(avg_fixups)
    final_steps.append(ProjectStep(out_names))
    final_steps.extend(program.steps[gb_idx + 1:])
    return partial, Program(tuple(final_steps))
