# ydb-devmem: device-module — pure jnp kernels: every body runs under
# the compiled program trace (XLA temporaries, not HBM residents)
"""Device kernel primitives for SSA programs (pure jnp — XLA fuses these).

TPU analog of the reference's block operators:
  * masked elementwise ops with Arrow null semantics
    (arrow compute + ydb/library/arrow_kernels/operations.h)
  * ``compact`` — BlockCompress (mkql_block_compress.h): row compaction by
    stable-partition permutation, applied only at block boundaries
  * ``grouped_aggregate`` — BlockCombineHashed / ch.group_by
    (mkql_block_agg.cpp:1637, arrow_clickhouse/Aggregator.h:568): dense or
    sort-derived group ids + scatter-reduce with a *static* group capacity;
    invalid rows scatter to an out-of-bounds index in 'drop' mode instead
    of branching
  * ``sort_block`` / top-k — WideTopSort / BlockTop (mkql_block_top.cpp)

All primitives keep static shapes; "how many" results there are is always a
traced int32 scalar, never a shape.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ydb_tpu.blocks.block import Column, TableBlock

# ---------------- null-propagating elementwise ----------------


def binop(fn, a: Column, b: Column) -> Column:
    return Column(fn(a.data, b.data), a.validity & b.validity)


def unop(fn, a: Column) -> Column:
    return Column(fn(a.data), a.validity)


def kleene_and(a: Column, b: Column) -> Column:
    data = a.data & b.data
    # false AND anything = false (valid); else valid iff both valid
    valid = (
        (~a.data & a.validity) | (~b.data & b.validity) | (a.validity & b.validity)
    )
    return Column(data, valid)


def kleene_or(a: Column, b: Column) -> Column:
    data = a.data | b.data
    valid = (
        (a.data & a.validity) | (b.data & b.validity) | (a.validity & b.validity)
    )
    return Column(data, valid)


def safe_div(a: Column, b: Column, float_result: bool) -> Column:
    zero = b.data == 0
    denom = jnp.where(zero, jnp.ones_like(b.data), b.data)
    if float_result:
        data = a.data / denom
    else:
        data = _trunc_div(a.data, denom)
    return Column(data, a.validity & b.validity & ~zero)


def _trunc_div(a, b):
    """SQL integer division truncates toward zero (-7/2 = -3), unlike
    Python/jnp floor division (-7//2 = -4)."""
    q = a // b
    exact = a - q * b == 0
    neg = (a < 0) ^ (b < 0)
    return jnp.where(~exact & neg, q + 1, q)


def trunc_mod(a, b):
    """SQL remainder takes the dividend's sign: -7 % 2 = -1."""
    return a - b * _trunc_div(a, b)


def pred_mask(col: Column) -> jax.Array:
    """Boolean predicate -> selection mask (NULL counts as False)."""
    return col.data & col.validity


def dict_gather(table: jax.Array, ids: Column) -> Column:
    """Lookup a plan-time table (dictionary mask/rank) by string ids."""
    safe = jnp.clip(ids.data, 0, table.shape[0] - 1)
    return Column(table[safe], ids.validity)


# ---------------- calendar (branchless civil-from-days) ----------------


def civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day), vectorized int32 math."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y, m, d):
    """(year, month, day) -> days since 1970-01-01 (inverse of
    civil_from_days; Hinnant's algorithm, vectorized)."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    doy = (153 * jnp.where(m > 2, m - 3, m + 9) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


# ---------------- filter / compact ----------------


def apply_filter(block: TableBlock, mask: jax.Array) -> TableBlock:
    """Late-materialization filter: fold mask into live length accounting by
    compacting. Cheap alternative when no compaction is needed: callers keep
    the mask and pass it to aggregation/sort directly."""
    return compact(block, mask)


def compact(block: TableBlock, selected: jax.Array) -> TableBlock:
    """Move selected live rows to the front (stable), update length.

    selected: bool[capacity]; rows outside the live range must be False
    (callers AND with block.row_mask()).
    """
    keep = selected & block.row_mask()
    # stable partition: sort by (not kept); ties keep original order
    perm = jnp.argsort(~keep, stable=True)
    cols = {
        n: Column(c.data[perm], c.validity[perm] & keep[perm])
        for n, c in block.columns.items()
    }
    n = jnp.sum(keep).astype(jnp.int32)
    return TableBlock(cols, n, block.schema)


# ---------------- grouped aggregation ----------------


def group_ids_dense(
    keys: list[Column],
    bounds: list[int],
    live: jax.Array,
) -> tuple[jax.Array, int]:
    """Dense group ids from small-cardinality keys (dict ids / bounded ints).

    NULL key values get their own slot per key (SQL GROUP BY semantics), so
    each key contributes (bound + 1) values; id 0 means NULL.
    Rows not live get id = num_groups (scatter-drop sentinel).
    """
    num_groups = 1
    gid = jnp.zeros(keys[0].data.shape, dtype=jnp.int32)
    for k, b in zip(keys, bounds):
        enc = jnp.where(k.validity, k.data.astype(jnp.int32) + 1, 0)
        gid = gid * (b + 1) + enc
        num_groups *= b + 1
    gid = jnp.where(live, gid, num_groups)
    return gid, num_groups


def group_ids_sorted(
    keys: list[Column], live: jax.Array, max_groups: int
) -> tuple[jax.Array, jax.Array]:
    """Generic exact group ids via lexicographic sort (no device hash table).

    Returns (gid[capacity] int32 with dead rows = max_groups, n_groups
    scalar). Group ids are assigned in sorted key order, so downstream
    per-group outputs come out key-ordered.
    """
    # sort dead rows last; NULLs first within a key (stable choice)
    sort_keys = []
    for k in reversed(keys):
        sort_keys.append(k.data)
        sort_keys.append(~k.validity)
    sort_keys.append(~live)
    perm = jnp.lexsort(tuple(sort_keys))  # last key is primary
    # invert the permutation with one linear scatter (not a second sort)
    inv = jnp.zeros_like(perm).at[perm].set(
        jnp.arange(perm.shape[0], dtype=perm.dtype)
    )

    live_s = live[perm]

    def sorted_col(k: Column):
        return k.data[perm], k.validity[perm]

    changed = jnp.zeros(live.shape, dtype=bool)
    for k in keys:
        d, v = sorted_col(k)
        # normalize garbage under NULL slots so all NULLs form one group
        d = jnp.where(v, d, jnp.zeros_like(d))
        prev_d = jnp.roll(d, 1)
        prev_v = jnp.roll(v, 1)
        diff = (d != prev_d) | (v != prev_v)
        changed = changed | diff
    changed = changed.at[0].set(True)
    # boundaries only count within the live prefix
    boundary = changed & live_s
    seg_sorted = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    n_groups = jnp.maximum(jnp.max(jnp.where(live_s, seg_sorted, -1)) + 1, 0)
    seg_sorted = jnp.where(live_s, seg_sorted, max_groups)
    gid = seg_sorted[inv]
    return gid, n_groups.astype(jnp.int32)


#: Below this many groups the one-hot masked reduction beats any scatter:
#: XLA lowers it to ONE vectorized pass over the rows with the groups on
#: the lane axis — no serialization, exact in every dtype. This is the
#: within-block analog of BlockCombineHashed's small-key fast path
#: (mkql_block_agg.cpp:1637); TPUs have no scatter unit, so "hash table"
#: becomes "lane-broadcast compare + reduce".
ONEHOT_GROUP_LIMIT = 512

#: test/bench override for the fused multi-aggregate group-by lowering
#: (compiler._resolve_group_by): True/False forces the decision
#: regardless of the environment. Consulted at TRACE time — rebuild
#: executors to switch (same contract as pallas_kernels.FORCE).
FUSED_FORCE: bool | None = None


def fused_group_by_enabled() -> bool:
    """Whether GroupByStep lowers through the fused single-contraction
    path (one shared hit matrix + one ``hits.T @ stacked`` matmul per
    accumulator dtype) instead of one independent one-hot reduction per
    aggregate. Default on; YDB_TPU_FUSED_GROUPBY=0 restores the
    per-aggregate path (the A/B baseline)."""
    if FUSED_FORCE is not None:
        return FUSED_FORCE
    return os.environ.get("YDB_TPU_FUSED_GROUPBY", "1") not in (
        "0", "", "off")


def group_hits(gid: jax.Array, num_groups: int) -> jax.Array:
    """bool (rows x groups) one-hot hit matrix from drop-encoded group
    ids (dead/invalid rows carry gid >= num_groups and match no group).

    This is THE shared expansion of the fused group-by: built once per
    GroupByStep and reused by every linear bank, MIN/MAX reduction and
    the per-group first-row index — where the per-aggregate path
    re-expanded (rows x groups) once per aggregate."""
    groups = jnp.arange(num_groups, dtype=jnp.int32)
    return gid[:, None] == groups[None, :]


def first_live_index(hits: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-group first hit row: (index int32[groups], found bool[groups]).

    Empty groups report index 0 with found=False; callers gather with
    the clamped index and mask by ``found``. One expansion serves every
    GROUP BY key column (they all share the same live mask)."""
    n = hits.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    first = jnp.min(jnp.where(hits, rows[:, None], n), axis=0)
    found = first < n
    return jnp.minimum(first, max(n - 1, 0)), found


def fused_group_reduce(stacked: jax.Array, gid: jax.Array,
                       num_groups: int, dtype=None) -> jax.Array:
    """All linear aggregates in one contraction: (rows x slots) stacked
    inputs -> (groups x slots) per-group sums.

    ``stacked`` columns are pre-masked (invalid contributions already
    zero); ``gid`` is drop-encoded (dead rows >= num_groups). Tiers:

      * groups <= ONEHOT_GROUP_LIMIT — ONE dense matmul
        ``hits.T @ stacked``: the hit matrix materializes once and the
        contraction rides the platform GEMM (MXU on TPU, vendor BLAS on
        CPU) — the TQP move of expressing group-by as matrix algebra.
      * larger, Pallas-eligible dtype — the fused multi-column one-hot
        tile kernel (pallas_kernels.grouped_sum_multi).
      * otherwise — one 2D scatter-add (still one pass for all slots,
        vs one scatter per aggregate on the per-agg path).

    Integer banks contract in integer dtype, so int64 decimal sums stay
    exact — only the summation ORDER differs from the scatter path,
    which for ints is no difference at all.
    """
    dtype = jnp.dtype(dtype or stacked.dtype)
    stacked = stacked.astype(dtype)
    if num_groups <= ONEHOT_GROUP_LIMIT:
        if stacked.shape[0] < _INT_LIMB_MAX_ROWS:
            # f64 GEMM via the bank encoder (exact for ints through
            # 24-bit limbs — XLA's CPU integer dot is a naive loop)
            return fused_group_reduce_banks(
                {dtype: stacked}, gid, num_groups)[dtype]
        hits = group_hits(gid, num_groups).astype(dtype)
        return jax.lax.dot_general(
            hits, stacked, (((0,), (0,)), ((), ())),
            preferred_element_type=dtype)
    from ydb_tpu.ssa import pallas_kernels

    if pallas_kernels.enabled() and pallas_kernels.supported_fused(
            dtype, num_groups, stacked.shape[1]):
        return pallas_kernels.grouped_sum_multi(stacked, gid, num_groups)
    out = jnp.zeros((num_groups, stacked.shape[1]), dtype=dtype)
    return out.at[gid].add(stacked, mode="drop")


#: 24-bit-limb exactness bound: each limb column sums < 2^24 * rows, so
#: rows below this keep every limb sum inside f64's 2^53 integer range.
_INT_LIMB_MAX_ROWS = 1 << 29
#: up to here TWO 32-bit limbs suffice ((2^32-1) * 2^21 < 2^53) — one
#: fewer encoded column per integer slot; typical block capacities
#: (<= 2^21) all take this path.
_INT_LIMB2_MAX_ROWS = 1 << 21


def fused_group_reduce_banks(banks: dict, gid: jax.Array,
                             num_groups: int) -> dict:
    """All of a GroupByStep's linear banks in ONE contraction.

    ``banks`` maps accumulator dtype -> (rows x slots) pre-masked
    values. In the one-hot tier every bank encodes into a single f64
    matrix — float banks as-is, integer banks as three 24-bit limb
    columns (v = c2*2^48 + c1*2^24 + c0; each limb sum stays an exact
    f64 integer below _INT_LIMB_MAX_ROWS rows, so the recombined int64
    is bit-exact) — and contracts against ONE materialized f64 hit
    matrix via the platform GEMM. XLA's CPU s64 dot is a naive loop
    (~4x slower than per-aggregate reductions); the limb trick keeps
    integer exactness while riding BLAS/MXU. The large-group tier
    reduces each bank via fused_group_reduce (Pallas / 2D scatter).
    """
    rows = next(iter(banks.values())).shape[0] if banks else 0
    if num_groups > ONEHOT_GROUP_LIMIT or rows >= _INT_LIMB_MAX_ROWS:
        return {dt: fused_group_reduce(st, gid, num_groups, dtype=dt)
                for dt, st in banks.items()}
    if rows <= _INT_LIMB2_MAX_ROWS:
        shifts, mask = (0, 32), 0xFFFFFFFF
    else:
        shifts, mask = (0, 24, 48), 0xFFFFFF
    enc = []
    plan = []
    for dt, st in banks.items():
        dt = jnp.dtype(dt)
        n_slots = st.shape[1]
        if jnp.issubdtype(dt, jnp.integer):
            v = st.astype(jnp.int64)
            for s in shifts[:-1]:
                enc.append(((v >> s) & mask).astype(jnp.float64))
            enc.append((v >> shifts[-1]).astype(jnp.float64))
            plan.append((dt, n_slots, True))
        else:
            enc.append(st.astype(jnp.float64))
            plan.append((dt, n_slots, False))
    mat = jnp.concatenate(enc, axis=1) if len(enc) > 1 else enc[0]
    hits = group_hits(gid, num_groups).astype(jnp.float64)
    res = jax.lax.dot_general(hits, mat, (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float64)
    out = {}
    off = 0
    for dt, n_slots, is_int in plan:
        if is_int:
            tot = jnp.zeros((num_groups, n_slots), dtype=jnp.int64)
            for s in shifts:
                tot = tot + (
                    res[:, off:off + n_slots].astype(jnp.int64) << s)
                off += n_slots
            out[dt] = tot.astype(dt)
        else:
            out[dt] = res[:, off:off + n_slots].astype(dt)
            off += n_slots
    return out


def _onehot_hits(valid_row, gid, num_groups: int):
    groups = jnp.arange(num_groups, dtype=jnp.int32)
    return (gid[:, None] == groups[None, :]) & valid_row[:, None]


def _onehot_reduce(values, valid_row, gid, num_groups: int, fill,
                   reduce_fn):
    """Masked (rows x groups) reduction — the shared one-hot fast path."""
    hit = _onehot_hits(valid_row, gid, num_groups)
    vals = jnp.where(hit, values[:, None],
                     jnp.asarray(fill, dtype=values.dtype))
    return reduce_fn(vals, axis=0)


def scatter_first(values: jax.Array, valid_row, gid, num_groups: int):
    """Per-group 'some' value: any valid row's value wins (scatter, drop OOB)."""
    if num_groups <= ONEHOT_GROUP_LIMIT and values.ndim == 1:
        n = values.shape[0]
        rows = jnp.arange(n, dtype=jnp.int32)
        hit = _onehot_hits(valid_row, gid, num_groups)
        first = jnp.min(jnp.where(hit, rows[:, None], n), axis=0)
        return jnp.where(first < n, values[jnp.minimum(first, n - 1)],
                         jnp.zeros((), dtype=values.dtype))
    idx = jnp.where(valid_row, gid, num_groups)
    out = jnp.zeros((num_groups,) + values.shape[1:], dtype=values.dtype)
    return out.at[idx].set(values, mode="drop")


def scatter_sum(values, valid_row, gid, num_groups: int, dtype=None):
    dtype = dtype or values.dtype
    if num_groups <= ONEHOT_GROUP_LIMIT:
        return _onehot_reduce(values.astype(dtype), valid_row, gid,
                              num_groups, 0, jnp.sum)
    # larger group counts: one-hot tile kernel when eligible
    # (ydb_tpu/ssa/pallas_kernels.py), else the XLA scatter
    from ydb_tpu.ssa import pallas_kernels

    if pallas_kernels.enabled() and pallas_kernels.supported(
            dtype, num_groups):
        return pallas_kernels.scatter_sum_pallas(
            values, valid_row, gid, num_groups, dtype)
    idx = jnp.where(valid_row, gid, num_groups)
    out = jnp.zeros((num_groups,), dtype=dtype)
    return out.at[idx].add(values.astype(dtype), mode="drop")


def scatter_min(values, valid_row, gid, num_groups: int):
    init = _extreme(values.dtype, maximum=True)
    if num_groups <= ONEHOT_GROUP_LIMIT:
        return _onehot_reduce(values, valid_row, gid, num_groups, init,
                              jnp.min)
    idx = jnp.where(valid_row, gid, num_groups)
    out = jnp.full((num_groups,), init, dtype=values.dtype)
    return out.at[idx].min(values, mode="drop")


def scatter_max(values, valid_row, gid, num_groups: int):
    init = _extreme(values.dtype, maximum=False)
    if num_groups <= ONEHOT_GROUP_LIMIT:
        return _onehot_reduce(values, valid_row, gid, num_groups, init,
                              jnp.max)
    idx = jnp.where(valid_row, gid, num_groups)
    out = jnp.full((num_groups,), init, dtype=values.dtype)
    return out.at[idx].max(values, mode="drop")


def _extreme(dtype, maximum: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if maximum else -jnp.inf
    if dtype == jnp.bool_:
        return True if maximum else False
    info = jnp.iinfo(dtype)
    return info.max if maximum else info.min


# ---------------- sort / top-k ----------------


def sort_perm(
    keys: list[Column],
    descending: list[bool],
    live: jax.Array,
) -> jax.Array:
    """Stable multi-key sort permutation; dead rows sink to the end.

    Descending numeric keys negate via bitwise complement on ints (exact,
    overflow-free) and negation on floats; NULLS LAST within each key.
    """
    sort_keys = []
    for k, desc in zip(reversed(keys), reversed(descending)):
        d = k.data
        if desc:
            if d.dtype == jnp.bool_:
                d = ~d
            elif jnp.issubdtype(d.dtype, jnp.integer):
                d = ~d  # exact order reversal, overflow-free
            else:
                d = -d
        # NULLs last regardless of direction; the null flag is appended
        # after the data key so it is more significant in the lexsort
        sort_keys.append(d)
        sort_keys.append(~k.validity)
    sort_keys.append(~live)
    return jnp.lexsort(tuple(sort_keys))


def sort_block(
    block: TableBlock,
    keys: list[str],
    descending: list[bool],
    limit: int | None = None,
    live: jax.Array | None = None,
) -> TableBlock:
    """Sort live (optionally pre-masked) rows; one lexsort pass does both
    the selection compaction (non-live rows sink) and the ordering."""
    if live is None:
        live = block.row_mask()
    else:
        live = live & block.row_mask()
    perm = sort_perm([block.columns[k] for k in keys], descending, live)
    cols = {
        n: Column(c.data[perm], c.validity[perm] & live[perm])
        for n, c in block.columns.items()
    }
    length = jnp.sum(live).astype(jnp.int32)
    if limit is not None:
        length = jnp.minimum(length, jnp.int32(limit))
    # zero validity past the length so padding never leaks
    cut = jnp.arange(block.capacity, dtype=jnp.int32) < length
    cols = {n: Column(c.data, c.validity & cut) for n, c in cols.items()}
    return TableBlock(cols, length, block.schema)
