"""Pallas TPU kernels for the hot group-by reduction.

The device group-by core is scatter_sum-by-group-id
(ydb_tpu/ssa/kernels.py:210, the BlockCombineHashed analog,
mkql_block_agg.cpp:1637). XLA lowers `.at[idx].add` to a serialized
scatter on TPU; this module provides the classic TPU-native alternative
— tile the rows, expand each tile to a one-hot (rows x groups) matrix
in VMEM and reduce with a vectorized multiply-accumulate — which keeps
the VPU busy instead of round-tripping a scatter.

Numerics: float32 accumulates exactly what the scatter path would
(same adds, different order — fp addition reorders are inherent to any
parallel reduction); int32 accumulates in int32. Other dtypes (int64
decimals, float64) fall back to the scatter path, so results never
silently lose precision. Group counts <= MAX_GROUPS keep the one-hot
tile in VMEM.

Enable on TPU with YDB_TPU_PALLAS=1 (kernels.scatter_sum consults
``enabled()``); tests run the same kernel in interpreter mode on CPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
# jax.enable_x64 was removed from the top-level namespace; the
# experimental context manager is the stable spelling across versions
from jax.experimental import enable_x64 as _enable_x64

ROW_TILE = 1024
MAX_GROUPS = 2048


#: test/bench override: True/False forces the decision regardless of
#: env/backend (consulted at TRACE time — rebuild executors to switch)
FORCE: bool | None = None


def enabled() -> bool:
    if FORCE is not None:
        return FORCE
    v = os.environ.get("YDB_TPU_PALLAS")
    if v is not None:
        return v not in ("0", "", "off")
    return jax.default_backend() == "tpu"


def supported(dtype, num_groups: int) -> bool:
    return (jnp.dtype(dtype) in (jnp.float32, jnp.int32)
            and num_groups <= MAX_GROUPS)


#: fused multi-column kernel slot cap: the out tile is (groups x slots)
#: in VMEM next to the (ROW_TILE x groups) one-hot, so slots stay a
#: single 128-lane tile. Real programs stack well under this (TPC-H Q1
#: needs 5 int64 + 4 f64 + 6 count slots across all its banks).
MAX_FUSED_SLOTS = 128


def supported_fused(dtype, num_groups: int, n_slots: int) -> bool:
    """Eligibility of the fused multi-column tile kernel
    (kernels.fused_group_reduce's >ONEHOT tier)."""
    return supported(dtype, num_groups) and n_slots <= MAX_FUSED_SLOTS


def _pad_rows(a: jax.Array, n: int, fill):
    pad = (-a.shape[0]) % n
    if pad == 0:
        return a
    return jnp.concatenate(
        [a, jnp.full((pad,) + a.shape[1:], fill, dtype=a.dtype)])


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def grouped_sum(values: jax.Array, gid: jax.Array, num_groups: int,
                interpret: bool = False) -> jax.Array:
    """sum of ``values`` per group id; rows with gid >= num_groups are
    dropped (callers encode invalid rows that way, kernels.py:212)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k_pad = max(128, -(-num_groups // 128) * 128)
    vals = _pad_rows(values, ROW_TILE, 0)
    gids = _pad_rows(gid.astype(jnp.int32), ROW_TILE, k_pad)
    tiles = vals.shape[0] // ROW_TILE
    # host-side layout: rows on the sublane axis with a unit lane, so
    # the kernel only ever LANE-BROADCASTS (row, 1) against (row, K) —
    # no in-kernel reshape (Mosaic rejects cross-lane shape casts)
    vals3 = vals.reshape(tiles, ROW_TILE, 1)
    gids3 = gids.reshape(tiles, ROW_TILE, 1)

    def kernel(gid_ref, val_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            out_ref[:, :] = jnp.zeros_like(out_ref)

        g = gid_ref[0, :, :]          # (ROW_TILE, 1)
        v = val_ref[0, :, :]          # (ROW_TILE, 1)
        groups = jax.lax.broadcasted_iota(
            jnp.int32, (ROW_TILE, k_pad), 1)
        onehot = (g == groups).astype(val_ref.dtype)
        # [ROW_TILE, K] * [ROW_TILE, 1] summed over rows -> [1, K]
        out_ref[:, :] += jnp.sum(onehot * v, axis=0, keepdims=True)

    # the engine runs with jax_enable_x64; Mosaic cannot legalize the
    # implicit i64 index/constant types that mode introduces, and
    # nothing in this kernel needs 64 bits — trace it in 32-bit mode
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid=(tiles,),
            in_specs=[
                pl.BlockSpec((1, ROW_TILE, 1), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, ROW_TILE, 1), lambda i: (i, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, k_pad), values.dtype),
            interpret=interpret,
        )(gids3, vals3)
    return out[0, :num_groups]


def scatter_sum_pallas(values, valid_row, gid, num_groups: int,
                       dtype=None, interpret: bool = False):
    """Drop-in twin of kernels.scatter_sum for supported dtypes."""
    dtype = jnp.dtype(dtype or values.dtype)
    idx = jnp.where(valid_row, gid, num_groups)
    return grouped_sum(values.astype(dtype), idx, num_groups,
                       interpret=interpret)


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def grouped_sum_multi(values: jax.Array, gid: jax.Array, num_groups: int,
                      interpret: bool = False) -> jax.Array:
    """Fused multi-column grouped sum: (rows x slots) values ->
    (num_groups x slots) per-group sums in ONE kernel.

    The fused group-by's >ONEHOT_GROUP_LIMIT tier: each row tile expands
    to a (ROW_TILE x groups) one-hot once and contracts against ALL slot
    columns with a single MXU dot — where ``grouped_sum`` would run the
    expansion once per aggregate. Rows with gid >= num_groups drop.
    """
    from jax.experimental import pallas as pl

    k_pad = max(128, -(-num_groups // 128) * 128)
    n_slots = values.shape[1]
    s_pad = max(128, -(-n_slots // 128) * 128)
    vals = _pad_rows(values, ROW_TILE, 0)
    if s_pad != n_slots:
        vals = jnp.concatenate(
            [vals, jnp.zeros((vals.shape[0], s_pad - n_slots),
                             dtype=vals.dtype)], axis=1)
    gids = _pad_rows(gid.astype(jnp.int32), ROW_TILE, k_pad)
    tiles = vals.shape[0] // ROW_TILE
    vals3 = vals.reshape(tiles, ROW_TILE, s_pad)
    gids3 = gids.reshape(tiles, ROW_TILE, 1)

    def kernel(gid_ref, val_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            out_ref[:, :] = jnp.zeros_like(out_ref)

        g = gid_ref[0, :, :]          # (ROW_TILE, 1)
        v = val_ref[0, :, :]          # (ROW_TILE, s_pad)
        groups = jax.lax.broadcasted_iota(
            jnp.int32, (ROW_TILE, k_pad), 1)
        onehot = (g == groups).astype(val_ref.dtype)
        # (ROW_TILE, k_pad)^T contracted with (ROW_TILE, s_pad) on the
        # row axis -> (k_pad, s_pad): one MXU pass covers every slot
        out_ref[:, :] += jax.lax.dot_general(
            onehot, v, (((0,), (0,)), ((), ())),
            preferred_element_type=out_ref.dtype)

    # 32-bit trace for the same Mosaic i64 reason as grouped_sum
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid=(tiles,),
            in_specs=[
                pl.BlockSpec((1, ROW_TILE, 1), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, ROW_TILE, s_pad), lambda i: (i, 0, 0)),
            ],
            out_specs=pl.BlockSpec((k_pad, s_pad), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((k_pad, s_pad), values.dtype),
            interpret=interpret,
        )(gids3, vals3)
    return out[:num_groups, :n_slots]
