"""Whole-plan single-trace lowering: one jitted computation per plan.

The per-node executor walk dispatches every plan fragment separately —
each scan program, join, partial→final merge and top-k boundary exits
XLA, hops through host Python, and re-enters a separately jitted
function. "Query Processing on Tensor Computation Runtimes" compiles
full TPC-H queries to single tensor programs; this module is that
lowering for the plan tree (ydb_tpu.plan.nodes): walk the tree once at
build time, compile every SSA program (span-free ``_compile_program`` —
the whole build is attributed to ONE ``ssa.compile`` span), and emit a
single traceable function

    run_all(inputs, aux) -> (result TableBlock, expand totals)

over a dict of staged input blocks. ``jax.jit(..., donate_argnums=(0,))``
donates the staged inputs so XLA reuses their buffers for intermediates
— nothing round-trips through the host between fragments.

Shape classes: every scanned table stages into a block whose capacity is
its row count rounded up to a size class (capacity quantum for small
tables, quarter-of-power-of-two steps beyond — at most 25% padding).
The jitted function retraces per (plan fingerprint, shape-class vector)
— the executor caches one FusedPlan per class in the cluster compile
cache, so re-running a plan over different data of the same class reuses
the compiled computation. Capacities only move dead padding around: the
join/group-by kernels mask padding by liveness, so fused results are
bit-identical to the per-fragment walk (asserted by tests and the
kernelbench --fusion A/B).

Fusibility (``plan_signature`` returns None otherwise; the executor
falls back to the per-node walk):

  * every scanned table present in ``db.sources`` with
    ``num_rows <= FUSE_MAX_ROWS`` (beyond that the walk's block
    streaming + two-phase partials bound memory; a fused trace would
    stage the whole table);
  * no ``UdfCall`` in any program — UDFs lower through
    ``jax.pure_callback`` (a host round trip), exactly the boundary
    fusion exists to remove;
  * join shapes the kernels support (<= 2 key columns, lookup
    inner/left/semi/anti, expand inner/left).

Expand joins get a static output capacity (probe bound * fanout_hint);
the traced total match count is returned to the host, and on overflow
the executor grows the capacity (``FusedPlan.grow``) and re-dispatches —
the cached plan keeps the grown capacity for later statements, exactly
like ``run_equi_join``'s retry ladder.

Env gates: ``YDB_TPU_FUSE_PLAN=0`` disables fusion (escape hatch);
``YDB_TPU_FUSE_MAX_ROWS`` moves the streaming cutoff;
``YDB_TPU_FUSE_DONATE=0`` keeps inputs undonated (debugging — a donated
block is dead after the dispatch).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import os
import threading
import time
from typing import Callable

import jax
import jax.numpy as jnp

from ydb_tpu import dtypes
from ydb_tpu.analysis import host_ok, memsan
from ydb_tpu.blocks.block import (
    DEFAULT_CAPACITY_QUANTUM,
    Column,
    TableBlock,
    device_aux,
)
from ydb_tpu.engine.scan import merge_blocks_device, required_columns
from ydb_tpu.ssa import join as join_kernels
from ydb_tpu.ssa.compiler import _compile_program
from ydb_tpu.ssa.program import (
    AssignStep,
    Call,
    FilterStep,
    Program,
    UdfCall,
)
from ydb_tpu.plan.nodes import (
    Concat,
    ExpandJoin,
    LookupJoin,
    PlanNode,
    TableScan,
    Transform,
)

#: in-process override: True/False forces fusion on/off regardless of the
#: environment (bench A/B seam); None defers to YDB_TPU_FUSE_PLAN
FUSE_FORCE: bool | None = None

#: tables above this row count keep the streaming walk. Two reasons the
#: cutoff sits where it does: (1) memory — the walk's block loop +
#: two-phase partials bound residency while a fused trace stages whole
#: tables; (2) regime — fusion pays off where per-fragment dispatch and
#: host hops dominate (short interactive queries: measured ~2x at ~6k
#: probe rows, ~1.6x at ~12k), while past ~10^5 rows the kernels are
#: compute-bound and the walk's tighter 1024-quantum padding edges out
#: the shape-class padding. Well under the walk's scan block size
#: (1 << 22), so any fusible table was a SINGLE block on the
#: per-fragment path anyway — identical operand shapes, bit-identical
#: results, no extra memory.
FUSE_MAX_ROWS = int(os.environ.get("YDB_TPU_FUSE_MAX_ROWS", str(1 << 17)))

_DONATE = os.environ.get("YDB_TPU_FUSE_DONATE", "1") not in (
    "0", "", "off")


def fusion_enabled() -> bool:
    if FUSE_FORCE is not None:
        return FUSE_FORCE
    return os.environ.get("YDB_TPU_FUSE_PLAN", "1") not in (
        "0", "", "off")


def shape_class(n: int) -> int:
    """Static staging capacity for an n-row table.

    Size-class quantization (jemalloc-style): small tables round to the
    capacity quantum; beyond 8 quanta, to quarter-of-power-of-two steps
    (..., 5*2^k, 6*2^k, 7*2^k, 2^(k+3), ...). The class count stays
    logarithmic in table size — growing a table by one row must not
    recompile the plan — while dead padding (staged AND computed on
    every dispatch) is bounded at 25%, where plain next-power-of-two
    classes waste up to 2x."""
    q = DEFAULT_CAPACITY_QUANTUM
    n = max(int(n), 1)
    if n <= 8 * q:
        return -(-n // q) * q
    step = 1 << ((n - 1).bit_length() - 3)
    return -(-n // step) * step


class Unfusible(Exception):
    """Raised at build time when a plan that looked fusible is not (the
    executor falls back to the per-node walk)."""


@functools.partial(jax.jit, static_argnums=(1,))
def fit_blocks(blocks: tuple, capacity: int) -> TableBlock:
    """Merge a scan's streamed blocks and fit them to the shape-class
    capacity, in one traced dispatch: live rows compact to the front
    (merge_blocks_device), columns slice or zero-pad to ``capacity``.
    Live rows never exceed ``capacity`` — the shape class derives from
    the source's num_rows upper bound — so the slice only drops padding.
    The outputs are fresh device buffers even for a single pass-through
    block (no donation here, so XLA cannot alias inputs to outputs):
    staged blocks are safe for the fused dispatch to donate even when
    the source block came from the device block cache."""
    b = merge_blocks_device(list(blocks))
    cols = {}
    for n in b.schema.names:
        c = b.columns[n]
        d, v = c.data, c.validity
        if d.shape[0] > capacity:
            d, v = d[:capacity], v[:capacity]
        elif d.shape[0] < capacity:
            pad = capacity - d.shape[0]
            d = jnp.concatenate([d, jnp.zeros(pad, d.dtype)])
            v = jnp.concatenate([v, jnp.zeros(pad, jnp.bool_)])
        cols[n] = Column(d, v)
    return TableBlock(cols, b.length, b.schema)


def _program_has_udf(program: Program | None) -> bool:
    if program is None:
        return False

    def expr_has(e) -> bool:
        if isinstance(e, UdfCall):
            return True
        if isinstance(e, Call):
            return any(expr_has(a) for a in e.args)
        return False

    for s in program.steps:
        if isinstance(s, (AssignStep, FilterStep)) and expr_has(s.expr):
            return True
    return False


@dataclasses.dataclass(frozen=True)
class ScanSite:
    """One distinct TableScan node's staging contract: which columns to
    stage, under which schema, at which shape-class capacity."""

    key: str                      # input-dict key ("t0", "t1", ...)
    table: str
    node: TableScan
    read_cols: tuple[str, ...]
    in_schema: dtypes.Schema
    capacity: int


@dataclasses.dataclass
class PlanSignature:
    """A fusible plan's shape: scan sites + fragment count. The cache
    key (plan fingerprint + shape-class vector) derives from this."""

    plan: PlanNode
    sites: list[ScanSite]
    fused_stages: int  # plan fragments folded into the one trace

    def cache_key(self, db) -> tuple:
        return (
            "plan_fuse",
            self.plan,
            tuple((s.table, s.capacity, s.read_cols, s.in_schema)
                  for s in self.sites),
            id(db.dicts),
            tuple(sorted(db.key_spaces.items())) if db.key_spaces
            else None,
        )


def plan_signature(plan: PlanNode, db) -> PlanSignature | None:
    """Classify a plan: its scan sites and shape classes when the whole
    tree is fusible, None otherwise. Cheap (no compilation) — the
    executor calls this per statement before consulting the cache."""
    sites: list[ScanSite] = []
    by_node: dict[int, ScanSite] = {}
    stages = 0

    def visit(node) -> bool:
        nonlocal stages
        if id(node) in by_node:
            return True  # shared subtree: one site, traced once
        if isinstance(node, TableScan):
            # dict.get never triggers lazy sys-view materialization
            src = db.sources.get(node.table)
            if src is None or not hasattr(src, "num_rows"):
                return False
            n = int(src.num_rows)
            if n > FUSE_MAX_ROWS:
                return False
            if _program_has_udf(node.program):
                return False
            if node.program is not None:
                read_cols = required_columns(node.program, src.schema)
            else:
                read_cols = tuple(node.columns if node.columns is not None
                                  else src.schema.names)
            site = ScanSite(
                key=f"t{len(sites)}", table=node.table, node=node,
                read_cols=read_cols,
                in_schema=src.schema.select(read_cols),
                capacity=shape_class(n),
            )
            by_node[id(node)] = site
            sites.append(site)
            stages += 1
            return True
        if isinstance(node, LookupJoin):
            if node.kind not in ("inner", "left", "semi", "anti"):
                return False
            if len(node.probe_keys) > 2:
                return False
            stages += 1
            return visit(node.probe) and visit(node.build)
        if isinstance(node, ExpandJoin):
            if node.kind not in ("inner", "left"):
                return False
            if len(node.probe_keys) > 2:
                return False
            stages += 1
            return visit(node.probe) and visit(node.build)
        if isinstance(node, Transform):
            if _program_has_udf(node.program):
                return False
            stages += 1
            return visit(node.input)
        if isinstance(node, Concat):
            stages += 1
            return all(visit(i) for i in node.inputs)
        return False

    if not visit(plan):
        return None
    return PlanSignature(plan=plan, sites=sites, fused_stages=stages)


# plan_signature memo: the classification walk is O(plan nodes) of
# Python per statement, and plans on the warm path come out of the
# cluster plan cache with stable identity — so the walk result is
# recomputed for the same tree thousands of times per second on the
# serving tier. Keyed by id(plan): safe because the memo value holds
# sig.plan (a strong ref), so the id cannot be recycled while the
# entry lives; an ``is`` check guards the lookup anyway. Validators
# re-check the db-dependent inputs (source identity, row count,
# schema identity) in O(sites); any drift recomputes. Only fusible
# results memoize — a None verdict may hinge on sources the walk
# never recorded.
_SIG_CACHE_ENTRIES = 256
_sig_cache: "collections.OrderedDict" = collections.OrderedDict()
_sig_lock = threading.Lock()


def plan_signature_cached(plan: PlanNode, db) -> PlanSignature | None:
    """``plan_signature`` behind an identity-keyed memo with O(sites)
    revalidation — the per-statement entry point for dispatchers."""
    key = id(plan)
    with _sig_lock:
        hit = _sig_cache.get(key)
        if hit is not None:
            sig, validators = hit
            if sig.plan is plan and _sig_valid(validators, db):
                _sig_cache.move_to_end(key)
                return sig
            del _sig_cache[key]
    # signature-cache miss: one classification walk, then memoized
    # ydb-lint: disable=H004
    sig = plan_signature(plan, db)
    if sig is None:
        return None
    # .get throughout: bracket access on lazy source maps can
    # materialize sys views (same contract as the walk above)
    validators = tuple(
        (s.table, id(src), int(src.num_rows), id(src.schema))
        for s in sig.sites
        for src in (db.sources.get(s.table),))
    with _sig_lock:
        _sig_cache[key] = (sig, validators)
        _sig_cache.move_to_end(key)
        while len(_sig_cache) > _SIG_CACHE_ENTRIES:
            _sig_cache.popitem(last=False)
    return sig


def _sig_valid(validators, db) -> bool:
    for table, src_id, n, sch_id in validators:
        src = db.sources.get(table)
        if src is None or id(src) != src_id:
            return False
        if int(src.num_rows) != n or id(src.schema) != sch_id:
            return False
    return True


def _union_nullability(schemas: list[dtypes.Schema]) -> dtypes.Schema:
    """Concat's output schema: a column is nullable as soon as ANY
    branch's is (mirrors blocks.concat_blocks)."""
    base = schemas[0]
    return dtypes.Schema(tuple(
        dtypes.Field(f.name, f.type,
                     any(s.field(f.name).nullable for s in schemas))
        for f in base.fields))


def lookup_schema(node: LookupJoin, p_sch: dtypes.Schema,
                  b_sch: dtypes.Schema) -> dtypes.Schema:
    """run_equi_join's output schema for a lookup join node."""
    if node.kind in ("semi", "anti"):
        return p_sch
    fields = list(p_sch.fields)
    for n in node.payload:
        f = b_sch.field(n)
        fields.append(dtypes.Field(
            n + node.suffix, f.type,
            f.nullable or node.kind == "left"))
    return dtypes.Schema(tuple(fields))


def expand_schema(node: ExpandJoin, p_sch: dtypes.Schema,
                  b_sch: dtypes.Schema) -> dtypes.Schema:
    """expand_join's output schema for an expand join node."""
    fields = [p_sch.field(n) for n in node.probe_payload]
    for n in node.build_payload:
        f = b_sch.field(n)
        fields.append(dtypes.Field(
            n + node.build_suffix, f.type,
            f.nullable or node.kind == "left"))
    return dtypes.Schema(tuple(fields))


class FusedPlan:
    """A compiled whole-plan computation + its staging contract.

    Cached in the cluster compile cache per (plan fingerprint,
    shape-class vector). ``run`` dispatches the single jitted function;
    ``grow`` widens an expand join's static capacity after an overflow
    and re-jits (the cached plan keeps the grown capacity, so later
    statements skip the retry)."""

    def __init__(self, sites, out_schema, aux, run_all, expand_caps,
                 fused_stages, donate):
        self.sites = sites
        self.out_schema = out_schema
        self.aux = aux                  # device-staged, prefixed
        self._run_all = run_all         # python callable (re-jittable)
        self.expand_caps = expand_caps  # mutable: grows on overflow
        self.fused_stages = fused_stages
        self.donate = donate
        self.first_trace_seconds: float | None = None
        self._traced = False
        self._jit = self._make_jit()
        # batch-size -> jitted vmapped dispatch (run_stacked); cleared
        # by grow() with the serial jit — both bake expand capacities
        self._stacked_jits: dict = {}
        self._stacked_traced: set = set()
        # non-donating serial dispatch (run_shared): the batch
        # dispatcher's dedup path hands SHARED staged blocks (scan-share
        # attach) that later members must still be able to read
        self._jit_shared = None
        self._shared_traced = False

    def _make_jit(self):
        # Wrap in a fresh function object per call: jax's tracing cache
        # keys on function *equality*, and bound methods of the same
        # instance compare equal, so ``jax.jit(self._run_all)`` after
        # grow() would silently reuse the old-capacity trace.
        run_all = self._run_all

        def _dispatch(inputs, aux):
            return run_all(inputs, aux)

        return jax.jit(
            _dispatch,
            donate_argnums=(0,) if self.donate else ())

    def run(self, inputs: dict) -> tuple[TableBlock, list[int]]:
        """One dispatch: (result block, expand totals). The first
        dispatch per trace is timed synchronously into
        ``first_trace_seconds`` (jit trace + XLA compile), so profiles
        split compile from execute; warm dispatches stay async. With
        donation on, ``inputs`` is consumed — callers re-stage to
        retry."""
        from ydb_tpu.obs import timeline

        if self._traced:
            if timeline.timeline_enabled():
                # warm dispatch interval (async enqueue — no forced
                # sync; the block boundary shows where results landed)
                t0 = time.perf_counter()
                out, totals = self._jit(inputs, self.aux)
                timeline.RING.record(
                    "plan.dispatch", "dispatch", t0,
                    time.perf_counter(), timeline.current_trace_id())
            else:
                out, totals = self._jit(inputs, self.aux)
        else:
            import warnings

            t0 = time.perf_counter()
            with warnings.catch_warnings():
                # expected: only inputs whose shape/dtype matches some
                # intermediate get reused; the rest "were not usable"
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                out, totals = self._jit(inputs, self.aux)
            jax.block_until_ready(out)
            self._traced = True
            self.first_trace_seconds = (
                (self.first_trace_seconds or 0.0)
                + time.perf_counter() - t0)
        if memsan.armed():
            # result-block footprint (nbytes is shape metadata — no
            # device sync on the warm async path)
            memsan.charge(memsan.nbytes_of(out), "dispatch",
                          owner="run")
        return out, [int(t) for t in totals]

    def run_shared(self, inputs: dict) -> tuple[TableBlock, list[int]]:
        """Serial dispatch over staged blocks that OTHER statements may
        still read (the batch dispatcher's shared-scan dedup: N queued
        statements whose staged inputs are identical run the plan once
        and every member slices... the same result). Identical XLA
        program to :meth:`run` except donation is off — donating a
        shared block would let the dispatch scribble over a buffer a
        batchmate is about to read."""
        if self._jit_shared is None:
            run_all = self._run_all

            def _dispatch(inputs, aux):
                return run_all(inputs, aux)

            # one-time lazy wrapper creation, cached on the plan (the
            # trace/compile happens on first call, counted there)
            # ydb-lint: disable=H003
            self._jit_shared = jax.jit(_dispatch)
        if self._shared_traced:
            out, totals = self._jit_shared(inputs, self.aux)
        else:
            t0 = time.perf_counter()
            out, totals = self._jit_shared(inputs, self.aux)
            # first-trace timing sync only; warm dispatches stay async
            # ydb-lint: disable=H001
            jax.block_until_ready(out)
            self._shared_traced = True
            self.first_trace_seconds = (
                (self.first_trace_seconds or 0.0)
                + time.perf_counter() - t0)
        if memsan.armed():
            memsan.charge(memsan.nbytes_of(out), "dispatch",
                          owner="run_shared")
        return out, [int(t) for t in totals]

    def _make_stacked_jit(self, batch: int):
        # Fresh wrapper per (batch, capacity generation) for the same
        # function-equality reason as _make_jit. The vmapped body maps
        # ONLY over the stacked inputs; aux (dictionary tables, join
        # constants) is closed over unbatched — every batch member is
        # the same executable, so aux is genuinely shared.
        run_all = self._run_all

        def _dispatch(inputs, aux):
            return jax.vmap(lambda i: run_all(i, aux))(inputs)

        return jax.jit(
            _dispatch,
            donate_argnums=(0,) if self.donate else ())

    def run_stacked(self, inputs_list: list[dict]) \
            -> tuple[TableBlock, list[int]]:
        """One micro-batched dispatch over B compatible statements'
        staged inputs: stack each site's member blocks along a new
        leading axis (TableBlock is a pytree — jnp.stack copies into
        fresh buffers, so donation of the stacked operand never touches
        the per-member staged blocks) and run the vmapped plan once.
        Returns the batched result (leading dim B on every leaf) plus
        per-expand-slot totals MAXed over members — the overflow/grow
        protocol is per-capacity, and the widest member governs.
        Callers slice members off with :func:`slice_member`."""
        batch = len(inputs_list)
        with memsan.seam("stack"):
            stacked = _stack_members(inputs_list)
        # the stack copy is transient: donated into the dispatch (or
        # dropped right after it), so its bytes release once the
        # batched result exists
        ticket = memsan.charge(
            memsan.nbytes_of(stacked), "stack",
            owner="run_stacked") if memsan.armed() else None
        try:
            jf = self._stacked_jits.get(batch)
            if jf is None:
                jf = self._make_stacked_jit(batch)
                self._stacked_jits[batch] = jf
            if batch in self._stacked_traced:
                out, totals = jf(stacked, self.aux)
            else:
                import warnings

                t0 = time.perf_counter()
                with warnings.catch_warnings():
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable")
                    out, totals = jf(stacked, self.aux)
                jax.block_until_ready(out)
                self._stacked_traced.add(batch)
                self.first_trace_seconds = (
                    (self.first_trace_seconds or 0.0)
                    + time.perf_counter() - t0)
        finally:
            memsan.release(ticket)
        if memsan.armed():
            memsan.charge(memsan.nbytes_of(out), "dispatch",
                          owner="run_stacked")
        # totals come back shape (B,); the grow protocol keys on the
        # worst member (capacities are trace-time constants shared by
        # the whole batch)
        return out, [int(max(t)) for t in jax.device_get(totals)]

    def overflowed(self, totals: list[int]) -> list[int]:
        """Expand-join indexes whose match total exceeded capacity."""
        return [i for i, t in enumerate(totals)
                if t > self.expand_caps[i]]

    def grow(self, idx: int, total: int) -> None:
        """Widen expand join ``idx`` to hold ``total`` rows (rounded to
        the capacity quantum, run_equi_join's exact-retry step) and
        re-jit — the fresh jit wrapper forces a retrace, since the
        capacity is a trace-time constant, not an input shape."""
        q = DEFAULT_CAPACITY_QUANTUM
        self.expand_caps[idx] = (total + q - 1) // q * q
        self._traced = False
        self._jit = self._make_jit()
        # stacked/shared dispatches bake the same capacities: drop them
        # all so the next batch retraces at the grown size
        self._stacked_jits.clear()
        self._stacked_traced.clear()
        self._jit_shared = None
        self._shared_traced = False


def _stack_members(inputs_list: list[dict]):
    """Stack B members' staged inputs along a new leading axis.
    ``jnp.stack`` copies into fresh buffers, so donation of the stacked
    operand never touches the per-member staged blocks (which may be
    shared with concurrent statements through the scan share)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *inputs_list)


def slice_member(out: TableBlock, i: int) -> TableBlock:
    """Member ``i``'s result out of a :meth:`FusedPlan.run_stacked`
    batched block: index the leading batch axis off every leaf (lazy
    device gathers — each waiting session materializes only its own
    slice). The static treedef (names, schema) carries through, so the
    slice is a plain TableBlock indistinguishable from a serial run's."""
    return jax.tree_util.tree_map(lambda x: x[i], out)


def build(sig: PlanSignature, db) -> FusedPlan:
    """Compile a fusible plan into one FusedPlan.

    Every node's SSA program is verified (analysis.verify runs inside
    ``_compile_program``) and lowered up front — the whole pipeline is
    typed end to end before any trace. One ``ssa.compile`` span covers
    the full build (the walk would emit one per fragment)."""
    from ydb_tpu.obs import tracing

    with tracing.span("ssa.compile") as sp:
        fused = _build(sig, db)
        sp.set(fused_stages=fused.fused_stages,
               cols=sum(len(s.read_cols) for s in sig.sites))
    return fused


class PlanLowering:
    """Overridable whole-plan lowering: one walk over the plan tree
    emitting trace-time closures per node.

    The single-chip lowering below is the base; the mesh lowering
    (parallel/mesh_fuse.MeshLowering) subclasses the join/transform
    hooks to insert all_to_all repartitions and two-phase partial→final
    merges while inheriting the scan/concat/shared-subtree machinery —
    the seam that keeps single-chip the degenerate 1-device case
    instead of a third executor."""

    def __init__(self, sig: PlanSignature, db):
        self.sig = sig
        self.db = db
        self.site_by_node = {id(s.node): s for s in sig.sites}
        self.aux_np: dict = {}
        # grow-protocol capacity slots (FusedPlan.grow): parallel lists
        # of static capacity + slot kind ("expand" here; subclasses add
        # their own kinds, e.g. the mesh lowering's "shuffle")
        self.caps: list[int] = []
        self.cap_kinds: list[str] = []
        self._lowered: dict[int, tuple] = {}  # id -> (emit, schema, cap)
        self._n_nodes = 0

    def compiled(self, program, schema, dicts, dict_aliases=None,
                 partial_slots: bool = False):
        """Lower one fragment's program; its aux tables merge into the
        plan-wide dict under a per-fragment prefix. Returns (run, cp) —
        the prefixed runner plus the CompiledProgram (out_schema,
        group_layout) for callers that dispatch on layout."""
        cp = _compile_program(program, schema, dicts, self.db.key_spaces,
                              partial_slots=partial_slots,
                              dict_aliases=dict_aliases)
        pfx = f"n{self._n_nodes}."
        self._n_nodes += 1
        self.aux_np.update({pfx + k: v for k, v in cp.aux.items()})
        keys = tuple(cp.aux.keys())

        def run(block, aux):
            return cp.run(block, {k: aux[pfx + k] for k in keys})

        return run, cp

    def lower(self, node) -> tuple[Callable, dtypes.Schema, int]:
        hit = self._lowered.get(id(node))
        if hit is not None:
            return hit
        emit, sch, cap = self._lower(node)
        nid = id(node)

        # trace-time memo: a shared subtree (CTE referenced twice)
        # contributes its ops ONCE to the XLA graph, exactly like the
        # walk's _memo executes it once per statement
        def memo_emit(inputs, aux, memo, totals, _e=emit, _nid=nid):
            h = memo.get(_nid)
            if h is None:
                h = _e(inputs, aux, memo, totals)
                memo[_nid] = h
            return h

        out = (memo_emit, sch, cap)
        self._lowered[nid] = out
        return out

    def _lower(self, node):
        if isinstance(node, TableScan):
            return self.lower_scan(node)
        if isinstance(node, LookupJoin):
            return self.lower_lookup(node)
        if isinstance(node, ExpandJoin):
            return self.lower_expand(node)
        if isinstance(node, Transform):
            return self.lower_transform(node)
        if isinstance(node, Concat):
            return self.lower_concat(node)
        raise Unfusible(f"node does not lower: {node!r}")

    def lower_scan(self, node: TableScan):
        site = self.site_by_node[id(node)]
        src = self.db.sources[node.table]
        if node.program is None:
            sch = site.in_schema

            def emit(inputs, aux, memo, totals, _k=site.key,
                     _cols=site.read_cols):
                return inputs[_k].select(_cols)

            return emit, sch, site.capacity
        run, cp = self.compiled(
            node.program, site.in_schema,
            getattr(src, "dicts", None) or self.db.dicts)

        def emit(inputs, aux, memo, totals, _k=site.key,
                 _cols=site.read_cols, _run=run):
            return _run(inputs[_k].select(_cols), aux)

        return emit, cp.out_schema, site.capacity

    def lower_lookup(self, node: LookupJoin):
        p_emit, p_sch, p_cap = self.lower(node.probe)
        b_emit, b_sch, _ = self.lower(node.build)
        sch = lookup_schema(node, p_sch, b_sch)

        def emit(inputs, aux, memo, totals, _n=node, _pe=p_emit,
                 _be=b_emit):
            return join_kernels.run_equi_join(
                _pe(inputs, aux, memo, totals),
                _be(inputs, aux, memo, totals),
                _n.probe_keys, _n.build_keys, kind=_n.kind,
                suffix=_n.suffix, payload=_n.payload)

        return emit, sch, p_cap

    def expand_slot(self, probe_cap: int, fanout_hint: float) -> int:
        """Register one expand join's static output capacity; returns
        the slot index (totals[i] carries the traced match count)."""
        # probe_cap is an upper bound on the probe subtree's live rows
        # (group-bys only shrink), sized like run_equi_join's first
        # round; overflow grows it exactly (FusedPlan.grow)
        self.caps.append(max(
            int(probe_cap * fanout_hint), DEFAULT_CAPACITY_QUANTUM))
        self.cap_kinds.append("expand")
        return len(self.caps) - 1

    def expand_total(self, total):
        """Hook: how an expand join's traced match count reaches the
        host (the mesh lowering pmax-reduces it over the shard axis)."""
        return total

    def lower_expand(self, node: ExpandJoin):
        p_emit, p_sch, p_cap = self.lower(node.probe)
        b_emit, b_sch, _ = self.lower(node.build)
        sch = expand_schema(node, p_sch, b_sch)
        ei = self.expand_slot(p_cap, node.fanout_hint)
        caps = self.caps

        def emit(inputs, aux, memo, totals, _n=node, _pe=p_emit,
                 _be=b_emit, _ei=ei):
            out, total = join_kernels.expand_join(
                _pe(inputs, aux, memo, totals),
                _be(inputs, aux, memo, totals),
                list(_n.probe_keys), list(_n.build_keys),
                list(_n.probe_payload), list(_n.build_payload),
                out_capacity=caps[_ei],
                build_suffix=_n.build_suffix, kind=_n.kind)
            totals[_ei] = self.expand_total(total)
            return out

        # report the initial bound so parents (nested expands) can
        # size their own caps; if this cap later grows on overflow
        # the parent under-sizes at worst, and its own overflow
        # check grows it the same way
        return emit, sch, self.caps[ei]

    def lower_transform(self, node: Transform):
        i_emit, i_sch, i_cap = self.lower(node.input)
        run, cp = self.compiled(node.program, i_sch, self.db.dicts,
                                dict_aliases=dict(node.dict_aliases))

        def emit(inputs, aux, memo, totals, _ie=i_emit, _run=run):
            return _run(_ie(inputs, aux, memo, totals), aux)

        return emit, cp.out_schema, i_cap

    def lower_concat(self, node: Concat):
        parts = [self.lower(i) for i in node.inputs]
        sch = _union_nullability([p[1] for p in parts])
        caps = [p[2] for p in parts]
        cap = (sum(caps) if all(c is not None for c in caps)
               else None)

        def emit(inputs, aux, memo, totals, _parts=parts, _sch=sch):
            blocks = [
                # restamp to the union schema so the merged block
                # types like concat_blocks' output
                TableBlock(b.columns, b.length, _sch)
                for b in (p[0](inputs, aux, memo, totals)
                          for p in _parts)
            ]
            return merge_blocks_device(blocks)

        return emit, sch, cap


@host_ok("fused-plan compile: reached only on a compile-cache miss;"
         " the built FusedPlan is cached by plan fingerprint")
def _build(sig: PlanSignature, db) -> FusedPlan:
    lo = PlanLowering(sig, db)
    root, out_schema, _ = lo.lower(sig.plan)
    caps = lo.caps

    def run_all(inputs, aux):
        totals: list = [jnp.int64(0)] * len(caps)
        out = root(inputs, aux, {}, totals)
        return out, tuple(totals)

    return FusedPlan(sig.sites, out_schema, device_aux(lo.aux_np),
                     run_all, caps, sig.fused_stages, _DONATE)
