"""SSA program → one traced JAX function over a TableBlock.

The analog of the reference's program parse + apply pipeline
(ydb/core/tx/program/program.cpp:553 TProgramContainer::Init;
TProgramStep::Apply formats/arrow/program.h:394) — except here "apply" is a
*trace*: the whole step list lowers into a single XLA computation (assigns,
filters, group-by, sort fused into one HBM pass wherever XLA can).

Compilation resolves string predicates against host dictionaries into small
device lookup tables ("aux inputs"), picks dense vs sort-based group-id
assignment from key cardinalities, and fixes the output schema. The result
is pure: ``run(block, aux) -> block`` — jit it, vmap it, shard_map it.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import Column, TableBlock, device_aux
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.ssa import kernels
from ydb_tpu.ssa.ops import Agg, Op
from ydb_tpu.ssa.program import (
    AggSpec,
    AssignStep,
    Call,
    Col,
    Const,
    DictMap,
    DictPredicate,
    Expr,
    FilterStep,
    GroupByStep,
    ProjectStep,
    Program,
    SortStep,
    UdfCall,
    WindowStep,
    agg_result_type,
    infer_type,
)


@dataclasses.dataclass
class CompiledProgram:
    """A lowered program plus its plan-time inputs.

    ``group_layout`` describes the group-by output layout for distributed
    merging (ydb_tpu.parallel):
      ("dense_slots", n)  — uncompacted fixed slots, psum-mergeable
      ("keyless", 1)      — single-row global aggregate, psum-mergeable
      ("dense", n)        — dense ids, compacted but shape-stable (n slots)
      ("compact", None)   — compacted rows; merge via all_gather + re-agg
      (None, None)        — no group-by in the program
    """

    run: Callable  # (TableBlock, dict[str, jax.Array]) -> TableBlock
    aux: dict[str, np.ndarray]  # plan-time tables (dict masks etc.)
    out_schema: dtypes.Schema
    in_schema: dtypes.Schema
    group_layout: tuple = (None, None)
    # aux staged to the device once, on first dispatch — restaging the
    # whole dict per call cost an H2D transfer per statement. Staleness
    # is impossible: the compile caches key on the dict contents and
    # drop the whole CompiledProgram when plan-time tables change. A
    # first-dispatch race double-stages idempotently (last write wins).
    _staged: "dict | None" = dataclasses.field(
        default=None, repr=False, compare=False)

    def __call__(self, block: TableBlock) -> TableBlock:
        if self._staged is None:
            self._staged = device_aux(self.aux)
        return self.run(block, self._staged)


class _Lowering:
    """Single-pass lowering context (types + aux tables + trace builder)."""

    def __init__(self, schema: dtypes.Schema, dicts: DictionarySet | None,
                 key_spaces: dict[str, int] | None,
                 partial_slots: bool = False,
                 dict_aliases: dict[str, str] | None = None):
        self.schema = schema
        self.dicts = dicts
        self.key_spaces = dict(key_spaces or {})
        # column -> source column whose dictionary it carries (aggregate
        # outputs like MIN(s) AS lo keep s's dictionary)
        self.dict_aliases = dict(dict_aliases or {})
        # partial_slots: keep dense group-by states in their slots
        # (uncompacted) so per-device states align elementwise for
        # psum/pmin/pmax merging over the mesh
        self.partial_slots = partial_slots
        self.group_layout: tuple = (None, None)
        # advisory NDV-based distinct-group estimate (compile_program)
        self.group_est: float | None = None
        self.types: dict[str, dtypes.LogicalType] = {
            f.name: f.type for f in schema.fields
        }
        self.aux: dict[str, np.ndarray] = {}
        self._aux_n = 0

    def add_aux(self, prefix: str, table: np.ndarray) -> str:
        key = f"{prefix}#{self._aux_n}"
        self._aux_n += 1
        self.aux[key] = table
        return key

    def dictionary(self, name: str):
        """Dictionary for a (possibly renamed) string column, or None."""
        if self.dicts is None:
            return None
        src = self.dict_aliases.get(name, name)
        return self.dicts[src] if src in self.dicts else None

    def key_bound(self, name: str, t: dtypes.LogicalType) -> int | None:
        """Static cardinality bound for a group-by key column, if known.

        ``t`` is the column's *current* type (assigned columns included)."""
        if t.kind == dtypes.Kind.BOOL:
            return 2
        if t.is_string:
            d = self.dictionary(name)
            if d is not None:
                return len(d)
        return self.key_spaces.get(name)


def compile_program(
    program: Program,
    schema: dtypes.Schema,
    dicts: DictionarySet | None = None,
    key_spaces: dict[str, int] | None = None,
    partial_slots: bool = False,
    dict_aliases: dict[str, str] | None = None,
    group_est: float | None = None,
) -> CompiledProgram:
    # program lowering is attributed to the active query trace (the
    # "ssa.compile" spans are one half of the compile-vs-execute split;
    # the other half — the first jitted dispatch's XLA compile — is
    # timed at the call sites). NULL span when no trace is active.
    from ydb_tpu.obs import tracing

    with tracing.span("ssa.compile") as _sp:
        _sp.set(steps=len(program.steps), cols=len(schema.names))
        return _compile_program(program, schema, dicts, key_spaces,
                                partial_slots, dict_aliases, group_est)


def _compile_program(
    program: Program,
    schema: dtypes.Schema,
    dicts: DictionarySet | None = None,
    key_spaces: dict[str, int] | None = None,
    partial_slots: bool = False,
    dict_aliases: dict[str, str] | None = None,
    group_est: float | None = None,
) -> CompiledProgram:
    # mandatory precondition: no program reaches the trace unverified.
    # Malformed programs raise VerificationError (a PlanError) with
    # step-indexed diagnostics instead of an opaque trace-time failure.
    # (Lazy import: ydb_tpu.ssa.__init__ imports this module, and the
    # verifier's own program imports would re-enter it mid-init.)
    from ydb_tpu.analysis import verify as _verify

    analysis = _verify.check_program(program, schema)
    out_nullable = analysis.out_nullable
    if partial_slots and program.group_by is not None:
        # slot layouts keep dead group slots in place (invalid values,
        # zero counts) so every output column is effectively nullable
        out_nullable = {n: True for n in out_nullable}

    ctx = _Lowering(schema, dicts, key_spaces, partial_slots, dict_aliases)
    # advisory distinct-group estimate (stats.cost NDV product): picks
    # between equally-exact group-by tiers; never a correctness bound
    ctx.group_est = group_est

    # ---- static pass: resolve plan, types, aux tables, output schema ----
    plan: list = []  # (kind, payload) closures prepared statically
    cur_types = dict(ctx.types)
    cur_names = list(schema.names)
    # static nullability at each step (the verifier's inference rules):
    # the fused group-by collapses per-column valid counts and input
    # masking for columns that provably carry no NULLs
    cur_nullable = {f.name: f.nullable for f in schema.fields}

    def resolve_expr(expr: Expr):
        """Return (lower_fn(env, aux) -> Column, LogicalType)."""
        if isinstance(expr, Col):
            t = cur_types[expr.name]
            name = expr.name
            return (lambda env, aux: env[name]), t
        if isinstance(expr, Const):
            t = expr.type
            val = expr.value

            def lower_const(env, aux, _t=t, _v=val):
                any_col = next(iter(env.values()))
                n = any_col.data.shape[0]
                if _v is None:  # typed NULL (CASE without ELSE)
                    return Column(jnp.zeros((n,), dtype=_t.physical),
                                  jnp.zeros((n,), dtype=bool))
                data = jnp.full((n,), _v, dtype=_t.physical)
                return Column(data, jnp.ones((n,), dtype=bool))

            return lower_const, t
        if isinstance(expr, DictPredicate):
            return _resolve_dict_predicate(ctx, expr, cur_types)
        if isinstance(expr, DictMap):
            return _resolve_dict_map(ctx, expr, cur_types)
        if isinstance(expr, UdfCall):
            arg_fns = [resolve_expr(a)[0] for a in expr.args]
            out_dtype = expr.out_type.physical
            user_fn = expr.fn

            def call_host(*arrs, _fn=user_fn, _dt=out_dtype):
                return np.asarray(_fn(*arrs), dtype=_dt)

            def lower_udf(env, aux, _fns=tuple(arg_fns),
                          _dt=out_dtype, _call=call_host):
                cols = [f(env, aux) for f in _fns]
                valid = cols[0].validity
                for c in cols[1:]:
                    valid = valid & c.validity
                out = jax.pure_callback(
                    _call,
                    jax.ShapeDtypeStruct(cols[0].data.shape, _dt),
                    *[c.data for c in cols],
                )
                return Column(out, valid)

            return lower_udf, expr.out_type
        assert isinstance(expr, Call)
        return _resolve_call(ctx, expr, cur_types, resolve_expr)

    for step in program.steps:
        if isinstance(step, AssignStep):
            fn, t = resolve_expr(step.expr)
            cur_types[step.name] = t
            cur_nullable[step.name] = _verify.infer_nullable(
                step.expr, cur_nullable)
            if step.name not in cur_names:
                cur_names.append(step.name)
            plan.append(("assign", (step.name, fn)))
        elif isinstance(step, FilterStep):
            fn, t = resolve_expr(step.expr)
            if t.kind != dtypes.Kind.BOOL:
                raise TypeError(f"filter predicate must be bool, got {t}")
            plan.append(("filter", fn))
        elif isinstance(step, GroupByStep):
            lowered = _resolve_group_by(ctx, step, cur_types,
                                        cur_nullable)
            plan.append(("group_by", lowered))
            cur_names = list(lowered.out_names)
            cur_types = dict(lowered.out_types)
            # aggregate outputs may be NULL for empty/dead groups;
            # conservative for any later step
            cur_nullable = {n: True for n in cur_names}
        elif isinstance(step, ProjectStep):
            missing = [n for n in step.names if n not in cur_types]
            if missing:
                raise KeyError(f"projection of unknown columns {missing}")
            cur_names = list(step.names)
            plan.append(("project", tuple(step.names)))
        elif isinstance(step, SortStep):
            desc = step.descending or (False,) * len(step.keys)
            # string keys order by dictionary *rank*, not id: ship a
            # plan-time rank table per string key (ydb_tpu.blocks.dictionary)
            ranks = []
            for k in step.keys:
                t = cur_types[k]
                if t.is_string:
                    d = ctx.dictionary(k)
                    if d is None:
                        raise ValueError(
                            f"ORDER BY on string column {k} needs its"
                            " dictionary")
                    ranks.append(ctx.add_aux(f"rank.{k}", d.sort_rank()))
                else:
                    ranks.append(None)
            plan.append(
                ("sort", (tuple(step.keys), tuple(desc), step.limit,
                          tuple(ranks))))
        elif isinstance(step, WindowStep):
            if step.func not in ("rank", "dense_rank", "row_number"):
                raise NotImplementedError(
                    f"window function {step.func}")
            # string keys compare by dictionary RANK (partition needs
            # only equality, but ranks are equality-preserving too, so
            # one treatment covers both roles)
            wranks = []
            for k in step.partition + step.order_keys:
                t = cur_types[k]
                if t.is_string:
                    d = ctx.dictionary(k)
                    if d is None:
                        raise ValueError(
                            f"window key on string column {k} needs"
                            " its dictionary")
                    wranks.append(
                        ctx.add_aux(f"wrank.{k}", d.sort_rank()))
                else:
                    wranks.append(None)
            desc = step.descending or (False,) * len(step.order_keys)
            cur_types[step.out_name] = dtypes.INT64
            if step.out_name not in cur_names:
                cur_names.append(step.out_name)
            plan.append(("window", (
                step.func, tuple(step.partition),
                tuple(step.order_keys), tuple(desc), tuple(wranks),
                step.out_name)))
        else:
            raise NotImplementedError(f"step {step}")

    out_schema = dtypes.Schema(
        tuple(dtypes.Field(n, cur_types[n], out_nullable.get(n, True))
              for n in cur_names)
    )

    # ---- trace-time pass ----
    def run(block: TableBlock, aux: dict[str, jax.Array]) -> TableBlock:
        env: dict[str, Column] = dict(block.columns)
        mask = block.row_mask()
        length = block.length
        names = list(block.columns.keys())

        for kind, payload in plan:
            if kind == "assign":
                name, fn = payload
                env[name] = fn(env, aux)
                if name not in names:
                    names.append(name)
            elif kind == "filter":
                # mask-only (late materialization); `length` keeps the live
                # range until a compaction point (group_by/sort/output)
                pred = payload(env, aux)
                mask = mask & kernels.pred_mask(pred)
            elif kind == "project":
                names = list(payload)
                env = {n: env[n] for n in names}
            elif kind == "group_by":
                gb = payload
                env, length = gb.lower(env, aux, mask)
                names = list(gb.out_names)
                mask = (
                    jnp.arange(next(iter(env.values())).data.shape[0],
                               dtype=jnp.int32) < length
                )
            elif kind == "sort":
                keys, desc, limit, ranks = payload
                cols = {n: env[n] for n in names}
                sort_cols = []
                for k, rk in zip(keys, ranks):
                    c = cols[k] if k in cols else env[k]
                    if rk is not None:
                        c = kernels.dict_gather(aux[rk], c)
                    sort_cols.append(c)
                tmp_names = list(names)
                for i, c in enumerate(sort_cols):
                    cols[f"__sort{i}"] = c
                    tmp_names.append(f"__sort{i}")
                blk = TableBlock(
                    cols, length,
                    dtypes.Schema(tuple(
                        dtypes.Field(n, cur_types.get(n, dtypes.INT64))
                        for n in tmp_names)),
                )
                # single lexsort pass: the filter mask rides in as `live`
                # (non-selected rows sink past the length cut)
                blk = kernels.sort_block(
                    blk, [f"__sort{i}" for i in range(len(keys))],
                    list(desc), limit, live=mask)
                env = {n: blk.columns[n] for n in names}
                length = blk.length
                mask = blk.row_mask()
            elif kind == "window":
                func, pkeys, okeys, desc, wranks, out_name = payload
                cap = next(iter(env.values())).data.shape[0]
                live = mask & (jnp.arange(cap, dtype=jnp.int32)
                               < length)
                vals = []
                for k, rk in zip(pkeys + okeys, wranks):
                    c = env[k]
                    if rk is not None:
                        c = kernels.dict_gather(aux[rk], c)
                    d_ = c.data
                    if d_.dtype == jnp.bool_:
                        d_ = d_.astype(jnp.int32)
                    vals.append(d_)
                pvals = vals[:len(pkeys)]
                ovals = []
                for d_, dsc in zip(vals[len(pkeys):], desc):
                    ovals.append(-d_ if dsc else d_)
                # lexsort: LAST key is primary — liveness first, then
                # partition, then order keys
                perm = jnp.lexsort(tuple(
                    reversed([(~live).astype(jnp.int32)]
                             + pvals + ovals)))
                idx = jnp.arange(cap, dtype=jnp.int32)

                def changed(cols_sorted):
                    ch = idx == 0
                    for c in cols_sorted:
                        ch = ch | (c != jnp.roll(c, 1))
                    return ch

                sp = [c[perm] for c in pvals]
                so = [c[perm] for c in ovals]
                new_part = changed(sp)
                new_order = new_part | changed(so)
                seg_start = jax.lax.cummax(
                    jnp.where(new_part, idx, 0))
                if func == "row_number":
                    out_sorted = idx - seg_start + 1
                elif func == "rank":
                    peer_start = jax.lax.cummax(
                        jnp.where(new_order, idx, 0))
                    out_sorted = peer_start - seg_start + 1
                else:  # dense_rank
                    dense = jnp.cumsum(new_order.astype(jnp.int64))
                    out_sorted = dense - dense[seg_start] + 1
                out = jnp.zeros(cap, dtype=jnp.int64).at[perm].set(
                    out_sorted.astype(jnp.int64))
                env[out_name] = Column(out, live)
                if out_name not in names:
                    names.append(out_name)
        out_cols = {n: env[n] for n in out_schema.names}
        blk = TableBlock(out_cols, length, out_schema)
        return kernels.compact(blk, mask)

    return CompiledProgram(run=run, aux=ctx.aux, out_schema=out_schema,
                           in_schema=schema, group_layout=ctx.group_layout)


# ---------------- expression lowering helpers ----------------


def _resolve_dict_predicate(ctx: _Lowering, p: DictPredicate, cur_types):
    t = cur_types[p.column]
    if not t.is_string:
        raise TypeError(f"dict predicate on non-string column {p.column}")
    d = ctx.dictionary(p.column)
    if d is None:
        raise ValueError(f"no dictionary for column {p.column}")
    if p.kind in ("eq", "ne"):
        want = d.eq_id(p.pattern)
        table = np.zeros(max(len(d), 1), dtype=np.bool_)
        if want >= 0:
            table[want] = True
        if p.kind == "ne":
            table = ~table
    elif p.kind == "like":
        table = d.like_mask(p.pattern)
    elif p.kind == "prefix":
        table = d.prefix_mask(p.pattern)
    elif p.kind in ("in_set", "not_in_set"):
        table = np.zeros(max(len(d), 1), dtype=np.bool_)
        for v in p.pattern:
            i = d.eq_id(v)
            if i >= 0:
                table[i] = True
        if p.kind == "not_in_set":
            table = ~table
    elif p.kind == "custom":
        table = _custom_dict_mask(d, p.pattern)
    else:
        raise NotImplementedError(f"dict predicate kind {p.kind}")
    if table.size == 0:
        table = np.zeros(1, dtype=np.bool_)
    key = ctx.add_aux(f"dict.{p.column}.{p.kind}", table)
    col = p.column

    def lower(env, aux, _key=key, _col=col):
        return kernels.dict_gather(aux[_key], env[_col])

    return lower, dtypes.BOOL


def dict_map_table(d, out_d, kind: str, args: tuple) -> np.ndarray:
    """id->id gather table for a string transform: apply the transform to
    every dictionary value, register results in the output dictionary.
    Shared by the JAX lowering and the CPU oracle (identical id
    assignment: first-seen order over the source dictionary)."""
    if kind == "substr":
        start, length = args  # SQL 1-based start
        lo = start - 1
        out = [out_d.add(v[lo:lo + length]) for v in d.values]
    elif kind == "upper":
        out = [out_d.add(v.upper()) for v in d.values]
    elif kind == "lower":
        out = [out_d.add(v.lower()) for v in d.values]
    elif kind == "trim":
        out = [out_d.add(v.strip()) for v in d.values]
    elif kind == "ltrim":
        out = [out_d.add(v.lstrip()) for v in d.values]
    elif kind == "rtrim":
        out = [out_d.add(v.rstrip()) for v in d.values]
    elif kind == "replace":
        old, new = args
        out = [out_d.add(v.replace(old, new)) for v in d.values]
    elif kind == "concat_suffix":
        (lit,) = args
        out = [out_d.add(v + lit) for v in d.values]
    elif kind == "concat_prefix":
        (lit,) = args
        out = [out_d.add(lit + v) for v in d.values]
    elif kind == "gethost":
        # URL -> host part (Url::GetHost): strip scheme, path, query
        def _host(v: bytes) -> bytes:
            s = v.split(b"://", 1)[-1]
            return s.split(b"/", 1)[0].split(b"?", 1)[0]

        out = [out_d.add(_host(v)) for v in d.values]
    elif kind == "cutwww":
        # Url::CutWWW: drop one leading "www." if present
        out = [out_d.add(v[4:] if v.startswith(b"www.") else v)
               for v in d.values]
    elif kind == "strlen":
        # int output: byte length per dictionary value (no out dict)
        out = [len(v) for v in d.values]
    elif kind == "xrank":
        # cross-dictionary compare: rank each value within the sorted
        # union of this column's and the peer column's dictionaries
        # (out_d here is the PEER dictionary, not an output dict); both
        # sides of the comparison derive identical ranks from the same
        # union, so ==/!=/</<= on the ranks match byte-string compare.
        ranks = {v: i for i, v in enumerate(
            sorted(set(d.values) | set(out_d.values)))}
        out = [ranks[v] for v in d.values]
    else:
        raise NotImplementedError(f"dict map kind {kind}")
    return np.asarray(out or [0], dtype=np.int32)


def _resolve_dict_map(ctx: _Lowering, m: DictMap, cur_types):
    t = cur_types[m.column]
    if not t.is_string:
        raise TypeError(f"dict map on non-string column {m.column}")
    d = ctx.dictionary(m.column)
    if d is None:
        raise ValueError(f"no dictionary for column {m.column}")
    if ctx.dicts is None:
        raise ValueError("dict map needs a shared DictionarySet")
    # for "xrank" out_column names the PEER dictionary (already
    # registered) and the result is an int rank, not a string
    out_d = ctx.dicts.for_column(m.out_column)
    table = dict_map_table(d, out_d, m.kind, m.args)
    key = ctx.add_aux(f"map.{m.column}.{m.kind}", table)
    col = m.column

    def lower(env, aux, _key=key, _col=col):
        return kernels.dict_gather(aux[_key], env[_col])

    return lower, (dtypes.INT32 if m.kind in ("xrank", "strlen")
                   else dtypes.STRING)


def _custom_dict_mask(d, pattern) -> np.ndarray:
    """Plan-time masks beyond the fixed kinds. ("ord", op, val) = ordered
    byte-string comparison evaluated over the dictionary values."""
    from ydb_tpu.blocks.dictionary import _as_bytes

    tag = pattern[0]
    if tag == "ord":
        _, op, val = pattern
        val = _as_bytes(val)
        cmp = {
            "lt": lambda v: v < val,
            "le": lambda v: v <= val,
            "gt": lambda v: v > val,
            "ge": lambda v: v >= val,
        }[op]
        return d.match_mask(cmp)
    if tag == "suffix":
        _, val = pattern
        val = _as_bytes(val)
        return d.match_mask(lambda v: v.endswith(val))
    raise NotImplementedError(f"custom dict predicate {tag}")


def _as_f64(f):
    """Float-domain math over any numeric input: cast to f64 first."""
    return lambda *xs: f(*(x.astype(jnp.float64) for x in xs))


_SIMPLE_BINOPS = {
    Op.EQ: lambda a, b: a == b,
    Op.NE: lambda a, b: a != b,
    Op.LT: lambda a, b: a < b,
    Op.LE: lambda a, b: a <= b,
    Op.GT: lambda a, b: a > b,
    Op.GE: lambda a, b: a >= b,
    Op.ADD: lambda a, b: a + b,
    Op.SUB: lambda a, b: a - b,
    Op.MUL: lambda a, b: a * b,
    Op.XOR: lambda a, b: a ^ b,
    Op.GREATEST: jnp.maximum,
    Op.LEAST: jnp.minimum,
    Op.ATAN2: _as_f64(jnp.arctan2),
    Op.HYPOT: _as_f64(jnp.hypot),
    Op.BIT_AND: lambda a, b: a & b,
    Op.BIT_OR: lambda a, b: a | b,
    Op.BIT_XOR: lambda a, b: a ^ b,
    Op.SHIFT_LEFT: lambda a, b: a << b,
    Op.SHIFT_RIGHT: lambda a, b: a >> b,
}

_SIMPLE_UNOPS = {
    Op.NOT: lambda a: ~a,
    Op.NEG: lambda a: -a,
    Op.ABS: jnp.abs,
    Op.SQRT: jnp.sqrt,
    Op.EXP: jnp.exp,
    Op.LN: jnp.log,
    Op.LOG10: lambda a: jnp.log(a) / jnp.log(10.0),
    Op.FLOOR: jnp.floor,
    Op.CEIL: jnp.ceil,
    Op.ROUND: jnp.round,
    Op.SIGN: jnp.sign,
    Op.SIN: _as_f64(jnp.sin),
    Op.COS: _as_f64(jnp.cos),
    Op.TAN: _as_f64(jnp.tan),
    Op.ASIN: _as_f64(jnp.arcsin),
    Op.ACOS: _as_f64(jnp.arccos),
    Op.ATAN: _as_f64(jnp.arctan),
    Op.SINH: _as_f64(jnp.sinh),
    Op.COSH: _as_f64(jnp.cosh),
    Op.TANH: _as_f64(jnp.tanh),
    Op.ASINH: _as_f64(jnp.arcsinh),
    Op.ACOSH: _as_f64(jnp.arccosh),
    Op.ATANH: _as_f64(jnp.arctanh),
    Op.CBRT: _as_f64(jnp.cbrt),
    Op.ERF: _as_f64(lambda x: jax.scipy.special.erf(x)),
    Op.LOG2: _as_f64(jnp.log2),
    Op.EXP2: _as_f64(jnp.exp2),
    Op.TRUNC: _as_f64(jnp.trunc),
    Op.RINT: _as_f64(jnp.round),
    Op.RADIANS: _as_f64(jnp.deg2rad),
    Op.DEGREES: _as_f64(jnp.rad2deg),
    Op.BIT_NOT: lambda a: ~a,
}


def _resolve_call(ctx: _Lowering, call: Call, cur_types, resolve_expr):
    op = call.op
    resolved = [resolve_expr(a) for a in call.args]
    fns = [r[0] for r in resolved]
    ts = [r[1] for r in resolved]
    out_t = infer_type(call, ctx.schema, cur_types)

    # mixed decimal x float: descale the decimal side to float (the
    # comparison/arithmetic then runs in double — exactness is already
    # lost the moment a float entered)
    if op in (Op.ADD, Op.SUB, Op.MUL, Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT,
              Op.GE, Op.DIV, Op.GREATEST, Op.LEAST):
        fns, ts = _descale_mixed(fns, ts)
    # rescale decimal operands to a common scale for add/sub/compare
    if op in (Op.ADD, Op.SUB, Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT, Op.GE,
              Op.MOD, Op.GREATEST, Op.LEAST):
        fns, ts = _align_decimals(op, call, fns, ts)

    if op in _SIMPLE_BINOPS and len(fns) == 2:
        f = _SIMPLE_BINOPS[op]
        fa, fb = fns

        def lower(env, aux, _f=f, _fa=fa, _fb=fb):
            return kernels.binop(_f, _fa(env, aux), _fb(env, aux))

        return lower, out_t
    if op in _SIMPLE_UNOPS and len(fns) == 1:
        f = _SIMPLE_UNOPS[op]
        fa = fns[0]

        def lower(env, aux, _f=f, _fa=fa):
            return kernels.unop(_f, _fa(env, aux))

        return lower, out_t
    if op is Op.AND:
        fa, fb = fns

        def lower(env, aux, _fa=fa, _fb=fb):
            return kernels.kleene_and(_fa(env, aux), _fb(env, aux))

        return lower, out_t
    if op is Op.OR:
        fa, fb = fns

        def lower(env, aux, _fa=fa, _fb=fb):
            return kernels.kleene_or(_fa(env, aux), _fb(env, aux))

        return lower, out_t
    if op is Op.DIV:
        fa, fb = fns
        ta, tb = ts[0], ts[1]
        as_float = out_t.is_floating
        sa = 10.0 ** ta.scale if ta.is_decimal else 1.0
        sb = 10.0 ** tb.scale if tb.is_decimal else 1.0

        def lower(env, aux, _fa=fa, _fb=fb, _sa=sa, _sb=sb, _ff=as_float):
            a, b = _fa(env, aux), _fb(env, aux)
            if _ff and (_sa != 1.0 or _sb != 1.0):
                a = Column(a.data.astype(jnp.float64) / _sa, a.validity)
                b = Column(b.data.astype(jnp.float64) / _sb, b.validity)
            elif _ff:
                a = Column(a.data.astype(jnp.float64), a.validity)
            return kernels.safe_div(a, b, _ff)

        return lower, out_t
    if op is Op.MOD:
        fa, fb = fns

        def lower(env, aux, _fa=fa, _fb=fb):
            a, b = _fa(env, aux), _fb(env, aux)
            zero = b.data == 0
            denom = jnp.where(zero, jnp.ones_like(b.data), b.data)
            return Column(
                kernels.trunc_mod(a.data, denom),
                a.validity & b.validity & ~zero,
            )

        return lower, out_t
    if op is Op.POW:
        fa, fb = fns

        def lower(env, aux, _fa=fa, _fb=fb):
            a, b = _fa(env, aux), _fb(env, aux)
            return Column(
                jnp.power(a.data.astype(jnp.float64),
                          b.data.astype(jnp.float64)),
                a.validity & b.validity,
            )

        return lower, out_t
    if op is Op.IS_NULL:
        fa = fns[0]

        def lower(env, aux, _fa=fa):
            a = _fa(env, aux)
            return Column(~a.validity, jnp.ones_like(a.validity))

        return lower, out_t
    if op is Op.IS_NOT_NULL:
        fa = fns[0]

        def lower(env, aux, _fa=fa):
            a = _fa(env, aux)
            return Column(a.validity, jnp.ones_like(a.validity))

        return lower, out_t
    if op is Op.COALESCE:
        def lower(env, aux, _fns=tuple(fns)):
            cols = [f(env, aux) for f in _fns]
            data = cols[-1].data
            valid = cols[-1].validity
            for c in reversed(cols[:-1]):
                data = jnp.where(c.validity, c.data, data)
                valid = c.validity | valid
            return Column(data, valid)

        return lower, out_t
    if op is Op.IF:
        fc, fa, fb = fns

        def lower(env, aux, _fc=fc, _fa=fa, _fb=fb):
            c, a, b = _fc(env, aux), _fa(env, aux), _fb(env, aux)
            take_a = kernels.pred_mask(c)
            return Column(
                jnp.where(take_a, a.data, b.data),
                c.validity & jnp.where(take_a, a.validity, b.validity),
            )

        return lower, out_t
    if op in (Op.CAST_INT32, Op.CAST_INT64, Op.CAST_FLOAT,
              Op.CAST_DOUBLE, Op.CAST_INT8, Op.CAST_INT16,
              Op.CAST_UINT64, Op.CAST_BOOL):
        fa = fns[0]
        ta = ts[0]
        scale = 10.0 ** ta.scale if ta.is_decimal else None
        target = out_t.physical

        def lower(env, aux, _fa=fa, _sc=scale, _tp=target):
            a = _fa(env, aux)
            d = a.data
            if _sc is not None:
                if np.issubdtype(_tp, np.floating):
                    d = d.astype(jnp.float64) / _sc
                else:
                    d = d // int(_sc)
            return Column(d.astype(_tp), a.validity)

        return lower, out_t
    if op in (Op.YEAR, Op.MONTH, Op.DAY):
        fa = fns[0]
        ta = ts[0]
        is_ts = ta.kind == dtypes.Kind.TIMESTAMP
        part = {Op.YEAR: 0, Op.MONTH: 1, Op.DAY: 2}[op]

        def lower(env, aux, _fa=fa, _ts=is_ts, _p=part):
            a = _fa(env, aux)
            days = a.data // 86_400_000_000 if _ts else a.data
            parts = kernels.civil_from_days(days)
            return Column(parts[_p], a.validity)

        return lower, out_t
    if op in (Op.HOUR, Op.MINUTE, Op.SECOND):
        fa = fns[0]
        if ts[0].kind != dtypes.Kind.TIMESTAMP:
            raise TypeError(f"{op} needs a timestamp operand")
        div = {Op.HOUR: 3_600_000_000, Op.MINUTE: 60_000_000,
               Op.SECOND: 1_000_000}[op]
        mod = 24 if op is Op.HOUR else 60

        def lower(env, aux, _fa=fa, _d=div, _m=mod):
            a = _fa(env, aux)
            return Column(
                ((a.data // _d) % _m).astype(jnp.int32), a.validity)

        return lower, out_t
    if op in (Op.DAY_OF_WEEK, Op.DAY_OF_YEAR, Op.WEEK, Op.QUARTER):
        fa = fns[0]
        is_ts = ts[0].kind == dtypes.Kind.TIMESTAMP

        def lower(env, aux, _fa=fa, _ts=is_ts, _op=op):
            a = _fa(env, aux)
            days = a.data // 86_400_000_000 if _ts else a.data
            days = days.astype(jnp.int64)
            if _op is Op.DAY_OF_WEEK:
                out = (days + 4) % 7  # 1970-01-01 = Thursday; 0=Sunday
            elif _op is Op.QUARTER:
                _y, m, _d = kernels.civil_from_days(days)
                out = (m - 1) // 3 + 1
            else:
                y, _m, _d = kernels.civil_from_days(days)
                doy = days - kernels.days_from_civil(
                    y, jnp.ones_like(y), jnp.ones_like(y)) + 1
                out = doy if _op is Op.DAY_OF_YEAR else (doy - 1) // 7 + 1
            return Column(out.astype(jnp.int32), a.validity)

        return lower, out_t
    if op is Op.DIV_INT:
        fa, fb = fns
        ta, tb = ts[0], ts[1]
        sa = 10.0 ** ta.scale if ta.is_decimal else 1.0
        sb = 10.0 ** tb.scale if tb.is_decimal else 1.0
        descale = (ta.is_decimal or tb.is_decimal or ta.is_floating
                   or tb.is_floating)

        def lower(env, aux, _fa=fa, _fb=fb, _sa=sa, _sb=sb,
                  _ds=descale):
            a, b = _fa(env, aux), _fb(env, aux)
            if _ds:
                # integer division of the VALUES: descale, divide,
                # truncate toward zero -> int64
                zero = b.data == 0
                av = a.data.astype(jnp.float64) / _sa
                bv = jnp.where(zero, 1.0,
                               b.data.astype(jnp.float64) / _sb)
                q = jnp.trunc(av / bv).astype(jnp.int64)
                return Column(q, a.validity & b.validity & ~zero)
            return kernels.safe_div(a, b, False)

        return lower, out_t
    if op is Op.NULLIF:
        fa, fb = fns
        ta, tb = ts[0], ts[1]
        # compare in VALUE space (scale-aligned decimals / descaled
        # floats) but return a's ORIGINAL data + type
        sa = ta.scale if ta.is_decimal else 0
        sb = tb.scale if tb.is_decimal else 0
        use_float = ta.is_floating or tb.is_floating
        m = max(sa, sb)

        def lower(env, aux, _fa=fa, _fb=fb, _sa=sa, _sb=sb, _m=m,
                  _ff=use_float):
            a, b = _fa(env, aux), _fb(env, aux)
            if _ff:
                av = a.data.astype(jnp.float64) / (10.0 ** _sa)
                bv = b.data.astype(jnp.float64) / (10.0 ** _sb)
            else:
                av = a.data * (10 ** (_m - _sa))
                bv = b.data * (10 ** (_m - _sb))
            equal = (av == bv) & b.validity
            return Column(a.data, a.validity & ~equal)

        return lower, out_t
    if op is Op.IN_SET:
        # IN over numeric literals: OR of equalities
        fa = fns[0]
        consts = call.args[1:]

        def lower(env, aux, _fa=fa, _cs=tuple(c.value for c in consts)):
            a = _fa(env, aux)
            hit = jnp.zeros_like(a.validity)
            for v in _cs:
                hit = hit | (a.data == v)
            return Column(hit, a.validity)

        return lower, out_t
    raise NotImplementedError(f"lowering for op {op}")


def _descale_mixed(fns, ts):
    """decimal op float -> both float (scaled-int decimals descale)."""
    if len(ts) != 2:
        return fns, ts
    a, b = ts
    if not ((a.is_decimal and b.is_floating)
            or (b.is_decimal and a.is_floating)):
        return fns, ts

    def descaled(fn, scale):
        div = 10.0 ** scale

        def lower(env, aux, _fn=fn, _d=div):
            c = _fn(env, aux)
            return Column(c.data.astype(jnp.float64) / _d, c.validity)

        return lower

    out = list(fns)
    t_out = list(ts)
    for i, t in enumerate(ts):
        if t.is_decimal:
            out[i] = descaled(fns[i], t.scale)
            t_out[i] = dtypes.DOUBLE
    return out, t_out


def _align_decimals(op, call, fns, ts):
    """Rescale decimal operands to a common scale (exact, compile-time)."""
    if len(ts) != 2:
        return fns, ts
    a, b = ts
    if not (a.is_decimal or b.is_decimal):
        return fns, ts
    sa = a.scale if a.is_decimal else 0
    sb = b.scale if b.is_decimal else 0
    if sa == sb:
        return fns, ts
    target = max(sa, sb)

    def rescaled(fn, frm, to):
        mult = 10 ** (to - frm)

        def lower(env, aux, _fn=fn, _m=mult):
            c = _fn(env, aux)
            if jnp.issubdtype(c.data.dtype, jnp.floating):
                # float operand meeting a decimal: scale FIRST, then round
                # to the integer grid (casting first would truncate to 0)
                d = jnp.round(c.data * _m).astype(jnp.int64)
            else:
                d = c.data.astype(jnp.int64) * _m
            return Column(d, c.validity)

        return lower

    out = list(fns)
    t_out = [dtypes.decimal(target), dtypes.decimal(target)]
    if sa < target:
        out[0] = rescaled(fns[0], sa, target)
    if sb < target:
        out[1] = rescaled(fns[1], sb, target)
    return out, t_out


# ---------------- group-by lowering ----------------


@dataclasses.dataclass
class _GroupByLowered:
    lower: Callable  # (env, aux, live_mask) -> (env, length)
    out_names: tuple[str, ...]
    out_types: dict[str, dtypes.LogicalType]


#: Dense group-id path cap: above this many key combinations the sorted
#: path wins (scatter target arrays stay small).
_DENSE_GROUP_LIMIT = 65536


def _resolve_group_by(ctx: _Lowering, step: GroupByStep, cur_types,
                      cur_nullable: dict | None = None):
    keys = step.keys
    bounds = []
    for k in keys:
        if k not in cur_types:
            raise KeyError(f"group-by key {k} not in scope")
        bounds.append(ctx.key_bound(k, cur_types[k]))
    # exact distinct-combination bound: the product of per-key
    # cardinality bounds (+1 for the NULL slot each), when every key
    # has one (dictionary sizes, stats zone maps, caller key_spaces)
    bound_product: int | None = None
    if keys and all(b is not None for b in bounds):
        bound_product = 1
        for b in bounds:
            bound_product *= b + 1
    num_groups = bound_product or 0
    dense = bound_product is not None and \
        bound_product <= _DENSE_GROUP_LIMIT
    if dense and ctx.group_est is not None and not ctx.partial_slots \
            and num_groups > 64 and num_groups > 8 * ctx.group_est:
        # NDV says the mixed-radix slot space is mostly dead (e.g. two
        # 100-ary keys with 50 real combinations): the sorted tier at
        # bound_product capacity beats scattering into dead slots. Both
        # tiers are exact — this is purely a cost choice. partial_slots
        # callers need the slot layout for mesh psum merging, so they
        # keep dense.
        dense = False

    out_types: dict[str, dtypes.LogicalType] = {}
    for k in keys:
        out_types[k] = cur_types[k]
    specs: list[tuple[AggSpec, dtypes.LogicalType]] = []
    # MIN/MAX over a string column must order by dictionary *rank*; ship
    # the rank table and reduce over (rank << 32 | id) packed keys.
    str_rank_aux: dict[str, str] = {}
    for spec in step.aggs:
        t = agg_result_type(spec, ctx.schema, cur_types)
        out_types[spec.out_name] = t
        specs.append((spec, t))
        if (
            spec.func in (Agg.MIN, Agg.MAX)
            and cur_types[spec.column].is_string
        ):
            d = ctx.dictionary(spec.column)
            if d is None:
                raise ValueError(
                    f"MIN/MAX over string column {spec.column} needs its"
                    " dictionary"
                )
            if spec.column not in str_rank_aux:
                str_rank_aux[spec.column] = ctx.add_aux(
                    f"rank.{spec.column}", d.sort_rank()
                )
    out_names = tuple(keys) + tuple(s.out_name for s, _ in specs)

    key_names = tuple(keys)
    use_dense = dense
    b_tuple = tuple(bounds) if dense else ()
    explicit_cap = step.max_groups
    group_bound = bound_product  # exact cap for the sorted tier
    keep_slots = ctx.partial_slots and (dense or not keys)
    if not keys:
        ctx.group_layout = ("keyless", 1)
    elif keep_slots:
        ctx.group_layout = ("dense_slots", num_groups)
    elif dense:
        # dense group-ids, compacted output: array shape is num_groups
        # regardless of input capacity, so partial states are shape-stable
        # and can fold incrementally (ScanExecutor combine path)
        ctx.group_layout = ("dense", num_groups)
    else:
        ctx.group_layout = ("compact", None)

    src_types = {
        s.column: cur_types[s.column] for s, _ in specs
        if s.column is not None
    }
    # statically NULL-free aggregate inputs: their valid-count is the
    # live count and their values need no validity masking — for the
    # common all-NOT-NULL schema this collapses every per-column count
    # slot and every input mask out of the fused pipeline
    nonnull_cols = {
        s.column for s, _ in specs
        if s.column is not None
        and not (cur_nullable or {}).get(s.column, True)
    }
    # integer SUM states double as AVG numerators (the fused reduction
    # keeps integer sums exact, so the f64 cast afterwards is at least
    # as precise as accumulating f64 per row)
    int_sum_cols = {
        s.column: jnp.dtype(t.physical) for s, t in specs
        if s.func is Agg.SUM
        and jnp.issubdtype(jnp.dtype(t.physical), jnp.integer)
    }

    def trace_fused(env, aux, live, gid, ng, kcols, capacity):
        """Fused lowering: ONE shared hit expansion per GroupByStep.

        All linear aggregates (COUNT/SUM/AVG/VAR/STDDEV states) stack
        into per-accumulator-dtype banks and reduce with one
        ``hits.T @ stacked`` contraction each
        (kernels.fused_group_reduce); MIN/MAX and the key columns reuse
        the same bool hit matrix — where the per-aggregate path expands
        (rows x groups) once per aggregate AND once per key.
        """
        onehot = ng <= kernels.ONEHOT_GROUP_LIMIT
        # counts ride the f64 GEMM bank in the one-hot tier (exact below
        # 2^53, merges with the AVG/VAR sums into one matmul); the
        # large-group tier keeps them int32 so they stay Pallas-eligible
        count_dt = jnp.float64 if onehot else jnp.int32

        bank_vecs: dict = {}   # accumulator dtype -> list of row vectors
        slot_ix: dict = {}     # state key -> (dtype, slot index)

        def slot(key, dtype, make_vec):
            dtype = jnp.dtype(dtype)
            if key not in slot_ix:
                vecs = bank_vecs.setdefault(dtype, [])
                slot_ix[key] = (dtype, len(vecs))
                vecs.append(make_vec().astype(dtype))

        def cnt_key(col):
            # NULL-free column: its valid count IS the live count
            return ("live",) if col in nonnull_cols else ("cnt", col)

        def masked(c, col):
            return (c.data if col in nonnull_cols
                    else jnp.where(c.validity, c.data,
                                   jnp.zeros_like(c.data)))

        slot(("live",), count_dt,
             lambda: jnp.ones((capacity,), dtype=jnp.int32))
        for spec, t in specs:
            if spec.func is Agg.COUNT_ALL:
                continue
            c = env[spec.column]
            # per-column valid count: COUNT's value, everyone's validity
            slot(cnt_key(spec.column), count_dt,
                 lambda _c=c: _c.validity.astype(jnp.int32))
            if spec.func is Agg.SUM:
                acc = jnp.dtype(t.physical)
                slot(("sum", spec.column, acc.name), acc,
                     lambda _c=c, _col=spec.column: masked(_c, _col))
            elif spec.func is Agg.AVG:
                if spec.column in int_sum_cols:
                    # share the exact integer SUM state
                    slot(("sum", spec.column,
                          int_sum_cols[spec.column].name),
                         int_sum_cols[spec.column],
                         lambda _c=c, _col=spec.column: masked(_c, _col))
                else:
                    slot(("sum", spec.column, "float64"), jnp.float64,
                         lambda _c=c, _col=spec.column:
                         masked(_c, _col).astype(jnp.float64))
            elif spec.func in (Agg.VAR_SAMP, Agg.STDDEV_SAMP):
                scale = (10.0 ** src_types[spec.column].scale
                         if src_types[spec.column].is_decimal else 1.0)

                def mk_vals(_c=c, _col=spec.column, _s=scale):
                    v = masked(_c, _col).astype(jnp.float64)
                    if _s != 1.0:
                        v = v / _s
                    return v

                slot(("vsum", spec.column), jnp.float64, mk_vals)
                slot(("vsq", spec.column), jnp.float64,
                     lambda _mk=mk_vals: _mk() ** 2)

        results = kernels.fused_group_reduce_banks(
            {dtype: (vecs[0][:, None] if len(vecs) == 1
                     else jnp.stack(vecs, axis=1))
             for dtype, vecs in bank_vecs.items()},
            gid, ng)

        def state(key):
            dtype, i = slot_ix[key]
            return results[dtype][:, i]

        def count_of(key):
            return state(key).astype(jnp.int64)

        live_count = count_of(("live",))
        group_live = live_count > 0

        hits = kernels.group_hits(gid, ng) if onehot else None
        new_env: dict[str, Column] = {}
        if key_names and use_dense:
            # dense slot ids ARE the keys: decode each key value from
            # the slot index arithmetically (enc = value + 1, 0 = NULL,
            # group_ids_dense's mixed-radix encoding) — zero row passes
            strides = []
            acc = 1
            for b in reversed(b_tuple):
                strides.append(acc)
                acc *= b + 1
            strides.reverse()
            slot_ids = jnp.arange(ng, dtype=jnp.int32)
            for k, c, b, stride in zip(key_names, kcols, b_tuple,
                                       strides):
                enc = (slot_ids // stride) % (b + 1)
                kd = jnp.maximum(enc - 1, 0).astype(c.data.dtype)
                kv = (enc > 0) & group_live
                new_env[k] = Column(kd, kv)
        elif key_names and onehot:
            # one first-row expansion shared by EVERY key column
            first, found = kernels.first_live_index(hits)
            for k, c in zip(key_names, kcols):
                kd = jnp.where(found, c.data[first],
                               jnp.zeros_like(c.data[first]))
                kv = c.validity[first] & found
                new_env[k] = Column(kd, kv & group_live)
        else:
            for k, c in zip(key_names, kcols):
                kd = kernels.scatter_first(c.data, live, gid, ng)
                kv = kernels.scatter_first(c.validity, live, gid, ng)
                new_env[k] = Column(kd, kv & group_live)

        for spec, t in specs:
            if spec.func is Agg.COUNT_ALL:
                data = live_count
                valid = (jnp.ones_like(group_live) if not key_names
                         else group_live)
                new_env[spec.out_name] = Column(data, valid)
                continue
            c = env[spec.column]
            nn = count_of(cnt_key(spec.column))
            if spec.func is Agg.COUNT:
                data = nn
                valid = (jnp.ones_like(group_live) if not key_names
                         else group_live)
            elif spec.func is Agg.SUM:
                data = state(("sum", spec.column,
                              jnp.dtype(t.physical).name))
                valid = nn > 0
            elif spec.func in (Agg.MIN, Agg.MAX):
                vals = c.data
                packed = spec.column in str_rank_aux
                if packed:
                    rank = kernels.dict_gather(
                        aux[str_rank_aux[spec.column]], c
                    ).data
                    vals = (
                        rank.astype(jnp.int64) << 32
                    ) | c.data.astype(jnp.int64)
                if onehot:
                    fill = kernels._extreme(
                        vals.dtype, maximum=spec.func is Agg.MIN)
                    hv = (hits if spec.column in nonnull_cols
                          else hits & c.validity[:, None])
                    expanded = jnp.where(
                        hv, vals[:, None],
                        jnp.asarray(fill, dtype=vals.dtype))
                    reduce_fn = (jnp.min if spec.func is Agg.MIN
                                 else jnp.max)
                    data = reduce_fn(expanded, axis=0)
                elif spec.func is Agg.MIN:
                    data = kernels.scatter_min(
                        vals, live & c.validity, gid, ng)
                else:
                    data = kernels.scatter_max(
                        vals, live & c.validity, gid, ng)
                if packed:
                    data = (data & 0xFFFFFFFF).astype(jnp.int32)
                valid = nn > 0
            elif spec.func is Agg.AVG:
                src_t = src_types[spec.column]
                if spec.column in int_sum_cols:
                    s = state(("sum", spec.column,
                               int_sum_cols[spec.column].name)
                              ).astype(jnp.float64)
                else:
                    s = state(("sum", spec.column, "float64"))
                if src_t.is_decimal:
                    s = s / (10.0 ** src_t.scale)
                data = s / jnp.maximum(nn, 1)
                valid = nn > 0
            elif spec.func is Agg.SOME:
                data = kernels.scatter_first(
                    c.data, live & c.validity, gid, ng)
                valid = nn > 0
            elif spec.func in (Agg.VAR_SAMP, Agg.STDDEV_SAMP):
                s = state(("vsum", spec.column))
                q = state(("vsq", spec.column))
                nf = nn.astype(jnp.float64)
                var = (q - s * s / jnp.maximum(nf, 1.0)) \
                    / jnp.maximum(nf - 1.0, 1.0)
                var = jnp.maximum(var, 0.0)  # fp cancellation
                data = (jnp.sqrt(var)
                        if spec.func is Agg.STDDEV_SAMP else var)
                valid = nn > 1
            else:
                raise NotImplementedError(spec.func)
            new_env[spec.out_name] = Column(data, valid)
        return new_env, group_live

    def trace_peragg(env, aux, live, gid, ng, kcols):
        """Reference lowering: one independent scatter/one-hot reduction
        per aggregate (the pre-fusion path, kept as the A/B baseline —
        kernels.fused_group_by_enabled() selects at trace time)."""
        # counts accumulate in int32 per block (a block holds < 2^31
        # rows) and widen after: int32 is what the Pallas one-hot
        # reduction supports, so COUNT/AVG-count ride the MXU-friendly
        # path on TPU instead of the serialized scatter
        live_count = kernels.scatter_sum(
            jnp.ones_like(gid, dtype=jnp.int32), live, gid, ng,
            dtype=jnp.int32,
        ).astype(jnp.int64)
        group_live = live_count > 0

        new_env: dict[str, Column] = {}
        for k, c in zip(key_names, kcols):
            kd = kernels.scatter_first(c.data, live, gid, ng)
            kv = kernels.scatter_first(c.validity, live, gid, ng)
            new_env[k] = Column(kd, kv & group_live)

        for spec, t in specs:
            if spec.func is Agg.COUNT_ALL:
                data = live_count
                # keyless COUNT over zero rows is 0, not NULL
                valid = (
                    jnp.ones_like(group_live) if not key_names else group_live
                )
            else:
                c = env[spec.column]
                vrow = live & c.validity
                nn = kernels.scatter_sum(
                    jnp.ones_like(gid, dtype=jnp.int32), vrow, gid, ng,
                    dtype=jnp.int32,
                ).astype(jnp.int64)
                if spec.func is Agg.COUNT:
                    data = nn
                    valid = (
                        jnp.ones_like(group_live)
                        if not key_names
                        else group_live
                    )
                elif spec.func is Agg.SUM:
                    data = kernels.scatter_sum(
                        c.data, vrow, gid, ng, dtype=t.physical
                    )
                    valid = nn > 0
                elif spec.func in (Agg.MIN, Agg.MAX):
                    vals = c.data
                    packed = spec.column in str_rank_aux
                    if packed:
                        rank = kernels.dict_gather(
                            aux[str_rank_aux[spec.column]], c
                        ).data
                        vals = (
                            rank.astype(jnp.int64) << 32
                        ) | c.data.astype(jnp.int64)
                    if spec.func is Agg.MIN:
                        data = kernels.scatter_min(vals, vrow, gid, ng)
                    else:
                        data = kernels.scatter_max(vals, vrow, gid, ng)
                    if packed:
                        data = (data & 0xFFFFFFFF).astype(jnp.int32)
                    valid = nn > 0
                elif spec.func is Agg.AVG:
                    src_t = cur_types[spec.column]
                    s = kernels.scatter_sum(
                        c.data, vrow, gid, ng, dtype=jnp.float64
                    )
                    if src_t.is_decimal:
                        s = s / (10.0 ** src_t.scale)
                    data = s / jnp.maximum(nn, 1)
                    valid = nn > 0
                elif spec.func is Agg.SOME:
                    data = kernels.scatter_first(c.data, vrow, gid, ng)
                    valid = nn > 0
                elif spec.func in (Agg.VAR_SAMP, Agg.STDDEV_SAMP):
                    src_t = cur_types[spec.column]
                    vals = c.data.astype(jnp.float64)
                    if src_t.is_decimal:
                        vals = vals / (10.0 ** src_t.scale)
                    s = kernels.scatter_sum(
                        vals, vrow, gid, ng, dtype=jnp.float64)
                    q = kernels.scatter_sum(
                        vals * vals, vrow, gid, ng, dtype=jnp.float64)
                    nf = nn.astype(jnp.float64)
                    var = (q - s * s / jnp.maximum(nf, 1.0)) \
                        / jnp.maximum(nf - 1.0, 1.0)
                    var = jnp.maximum(var, 0.0)  # fp cancellation
                    data = (jnp.sqrt(var)
                            if spec.func is Agg.STDDEV_SAMP else var)
                    valid = nn > 1
                else:
                    raise NotImplementedError(spec.func)
            new_env[spec.out_name] = Column(data, valid)
        return new_env, group_live

    def lower(env, aux, live):
        kcols = [env[k] for k in key_names]
        capacity = next(iter(env.values())).data.shape[0]
        ng_scalar = None
        if key_names:
            if use_dense:
                gid, ng = kernels.group_ids_dense(kcols, list(b_tuple), live)
            else:
                # a block of N rows has at most N groups: default the group
                # capacity to the block capacity so nothing is ever
                # silently dropped; an explicit max_groups caps it, and
                # a statistics-derived bound product (exact — distinct
                # combinations cannot exceed it) sizes the capacity
                # instead of the block-capacity worst case.
                caps = [capacity]
                if explicit_cap is not None:
                    caps.append(explicit_cap)
                if group_bound is not None:
                    caps.append(group_bound)
                ng = max(1, min(caps))
                gid, ng_scalar = kernels.group_ids_sorted(kcols, live, ng)
                ng_scalar = jnp.minimum(ng_scalar, jnp.int32(ng))
        else:
            # global aggregate: one group
            gid = jnp.where(live, 0, 1).astype(jnp.int32)
            ng = 1

        if kernels.fused_group_by_enabled():
            new_env, group_live = trace_fused(
                env, aux, live, gid, ng, kcols, capacity)
        else:
            new_env, group_live = trace_peragg(
                env, aux, live, gid, ng, kcols)

        if key_names and keep_slots:
            # mesh-mergeable layout: every slot stays in place; dead slots
            # carry invalid values and zero counts
            length = jnp.int32(ng)
        elif key_names and not use_dense:
            # sorted path: groups already dense [0, n); length = ng_scalar
            length = ng_scalar
        elif not key_names:
            # keyless aggregate always yields exactly one row (SQL:
            # SELECT COUNT(*) ... WHERE false => one row with 0)
            length = jnp.int32(1)
        else:
            # dense path: compact scattered group slots to the front
            blk = TableBlock(
                new_env, jnp.int32(ng),
                dtypes.Schema(tuple(
                    dtypes.Field(n, out_types[n]) for n in out_names)),
            )
            blk = kernels.compact(blk, group_live)
            new_env = dict(blk.columns)
            length = blk.length
        return new_env, length

    return _GroupByLowered(lower=lower, out_names=out_names,
                           out_types=out_types)
