from ydb_tpu.ssa.ops import Op, Agg  # noqa: F401
from ydb_tpu.ssa.program import (  # noqa: F401
    AggSpec,
    AssignStep,
    Call,
    Col,
    Const,
    DictPredicate,
    FilterStep,
    GroupByStep,
    ProjectStep,
    Program,
    SortStep,
)
from ydb_tpu.ssa.compiler import compile_program  # noqa: F401
