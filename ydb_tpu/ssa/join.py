"""Device equi-join kernels.

The reference joins rows with hash tables (GraceJoin partitioned hash join
mkql_grace_join.cpp:558, MapJoin broadcast mkql_map_join.cpp). Dynamic hash
tables don't exist on TPU; the TPU-native designs here are sort-based with
static shapes:

  * ``lookup_join`` — N:1 join (probe side may repeat keys; build keys
    unique, e.g. any FK -> PK join): sort build by key once, then
    ``searchsorted`` + gather per probe row. Output shape == probe shape;
    a found-mask drives inner/left/semi/anti variants. This covers every
    TPC-H dimension join.
  * ``expand_join`` — N:M join via prefix-sum expansion into a static
    output capacity: per-probe match counts -> cumulative offsets ->
    each output slot maps back to (probe row, k-th match) with two
    searchsorted passes. Exact while total matches <= out capacity; the
    returned total lets callers detect overflow and re-run with a larger
    capacity (grace-style bucketing keeps capacities bounded after a
    hash repartition).

Multi-key joins pack keys into one int64 via the shuffle hash (exact for
<=64-bit concatenations; otherwise hash with verify-on-gather).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ydb_tpu.blocks.block import Column, TableBlock


def _key_i64(cols: list[Column]) -> jax.Array:
    """Combine key columns into one int64 per row, exactly.

    One column passes through; two int columns pack as (a << 32) | b —
    exact while both values fit in 32 bits (all TPC-H/ClickBench composite
    keys do, e.g. partsupp's (partkey, suppkey)). Wider composites need a
    pre-assigned join-key column (planner's job), not a lossy hash: a hash
    here would silently drop/duplicate matches on collision. Liveness /
    NULL handling lives entirely in _join_keys_live.
    """
    if len(cols) == 1:
        return cols[0].data.astype(jnp.int64)
    if len(cols) == 2:
        a = cols[0].data.astype(jnp.int64)
        b = cols[1].data.astype(jnp.int64)
        return (a << 32) | (b & jnp.int64(0xFFFFFFFF))
    raise NotImplementedError(
        ">2 join key columns: pre-pack a composite key column"
    )


def _sorted_build(bk: jax.Array, blive: jax.Array):
    """Sort build keys with dead rows last, WITHOUT a value sentinel
    (sentinels collide with legitimate INT64_MAX keys).

    Returns (order, bk_sorted, n_live): live keys sorted in the prefix
    [0, n_live); suffix positions are overwritten with the prefix's last
    value so the whole array stays sorted for searchsorted. Matches are
    validated against idx < n_live, so suffix duplicates never count.
    """
    perm_keys = (bk, ~blive)  # primary: liveness (live first), then key
    order = jnp.lexsort(perm_keys)
    bk_sorted = bk[order]
    n_live = jnp.sum(blive).astype(jnp.int32)
    cap = bk.shape[0]
    last_live = bk_sorted[jnp.maximum(n_live - 1, 0)]
    pos = jnp.arange(cap, dtype=jnp.int32)
    bk_sorted = jnp.where(pos < n_live, bk_sorted, last_live)
    return order, bk_sorted, n_live


def _join_keys_live(block: TableBlock, keys: list[str]) -> tuple:
    cols = [block.columns[k] for k in keys]
    live = block.row_mask()
    for c in cols:
        live = live & c.validity  # NULL keys drop out of equi-joins
    return _key_i64(cols), live


def lookup_join(
    probe: TableBlock,
    build: TableBlock,
    probe_keys: list[str],
    build_keys: list[str],
    payload: list[str],
    suffix: str = "",
    null_extended: bool = False,
) -> tuple[TableBlock, jax.Array]:
    """N:1 equi-join: gather ``payload`` columns from build into probe.

    Returns (probe + payload columns, found_mask). Build keys must be
    unique among live rows (duplicate keys: one match wins). Inner join =
    compact by found; left join = keep all, payload validity = found.
    """
    pk, plive = _join_keys_live(probe, probe_keys)
    bk, blive = _join_keys_live(build, build_keys)

    order, bk_sorted, n_live = _sorted_build(bk, blive)
    idx = jnp.searchsorted(bk_sorted, pk)
    idx = jnp.clip(idx, 0, bk_sorted.shape[0] - 1)
    found = (idx < n_live) & (bk_sorted[idx] == pk) & plive
    src = order[idx]

    if len(set(payload)) != len(payload):
        raise ValueError(f"duplicate payload columns {payload}")
    out_cols = dict(probe.columns)
    sch = probe.schema
    for name in payload:
        c = build.columns[name]
        out_name = name + suffix
        if out_name in probe.columns:
            # a silent overwrite would leave the schema typed as the probe
            # column while the data came from the build side
            raise ValueError(
                f"payload column {out_name!r} collides with a probe column;"
                " pass a suffix"
            )
        out_cols[out_name] = Column(
            c.data[src], c.validity[src] & found
        )
        f = build.schema.field(name)
        from ydb_tpu import dtypes

        # a LEFT join NULL-extends unmatched rows, so its payload is
        # nullable no matter what the build side declares
        sch = sch.with_field(
            dtypes.Field(out_name, f.type, f.nullable or null_extended))
    return TableBlock(out_cols, probe.length, sch), found


def run_equi_join(
    probe: TableBlock,
    build: TableBlock,
    probe_keys,
    build_keys,
    kind: str = "inner",
    suffix: str = "",
    expand: bool = False,
    payload=(),
    probe_payload=(),
    build_payload=(),
    fanout_hint: float = 4.0,
) -> TableBlock:
    """One dispatch for every equi-join shape — the single-chip plan
    executor and the DQ grace-bucket join call THIS so their semantics
    cannot drift (test_sql_dq.py asserts bit parity between the paths).

    Lookup (N:1) joins support inner/left/semi/anti; expand (N:M) joins
    support inner/left and retry with exact capacity on overflow.
    """
    from ydb_tpu.ssa import kernels

    if not expand:
        joined, found = lookup_join(
            probe, build, list(probe_keys), list(build_keys),
            list(payload), suffix, null_extended=(kind == "left"))
        if kind == "inner":
            return kernels.compact(joined, found)
        if kind == "left":
            return joined
        if kind == "semi":
            return kernels.compact(probe, found)
        if kind == "anti":
            return kernels.compact(probe, ~found & probe.row_mask())
        raise ValueError(kind)
    if kind not in ("inner", "left"):
        # expand_join silently computes INNER for anything else
        raise ValueError(f"expand join does not support kind {kind!r}")
    cap = max(int(probe.capacity * fanout_hint), 1024)
    while True:
        out, total = expand_join(
            probe, build, list(probe_keys), list(build_keys),
            list(probe_payload), list(build_payload),
            out_capacity=cap, build_suffix=suffix, kind=kind)
        if int(total) <= cap:
            return out
        cap = int(int(total) + 1023) // 1024 * 1024  # exact retry


def expand_join(
    probe: TableBlock,
    build: TableBlock,
    probe_keys: list[str],
    build_keys: list[str],
    probe_payload: list[str],
    build_payload: list[str],
    out_capacity: int,
    build_suffix: str = "",
    kind: str = "inner",
) -> tuple[TableBlock, jax.Array]:
    """N:M equi-join with static output capacity.

    ``kind``: "inner" emits matches only; "left" additionally emits every
    unmatched live probe row once with NULL build payload (LEFT OUTER).
    Returns (joined block, total rows). Rows beyond ``out_capacity``
    are truncated — callers check ``total <= out_capacity`` (host
    side) and retry bigger or pre-partition (grace) if exceeded.
    """
    pk, plive = _join_keys_live(probe, probe_keys)
    bk, blive = _join_keys_live(build, build_keys)
    # LEFT JOIN keeps probe rows whose key is NULL too (they just match
    # nothing): row liveness for emission is the block mask, while
    # _join_keys_live's plive already excludes NULL keys from matching
    row_live = probe.row_mask()

    order, bk_sorted, n_live = _sorted_build(bk, blive)
    lo = jnp.searchsorted(bk_sorted, pk, side="left")
    hi = jnp.searchsorted(bk_sorted, pk, side="right")
    # the suffix repeats the last live key: clamp ranges to the live prefix
    lo = jnp.minimum(lo, n_live)
    hi = jnp.minimum(hi, n_live)
    # int64 accounting: skewed keys can exceed 2^31 matches, and a wrapped
    # total would defeat the overflow-retry protocol
    matches = jnp.where(plive, (hi - lo).astype(jnp.int64), jnp.int64(0))
    if kind == "left":
        counts = jnp.where(row_live, jnp.maximum(matches, 1), 0)
    else:
        counts = matches
    offsets = jnp.cumsum(counts)  # inclusive
    total = offsets[-1] if counts.shape[0] else jnp.int64(0)
    starts = offsets - counts

    # map each output slot j to (probe row i, k-th match)
    j = jnp.arange(out_capacity, dtype=offsets.dtype)
    i = jnp.searchsorted(offsets, j, side="right")
    i = jnp.clip(i, 0, probe.capacity - 1)
    valid_out = j < jnp.minimum(total, out_capacity)
    k = j - starts[i]
    # matched: this output slot carries a real build match (a left join's
    # pad slot for an unmatched probe row has k == 0 == matches[i])
    matched = valid_out & (k < matches[i])
    b_src = order[jnp.clip(lo[i] + k, 0, build.capacity - 1)]

    from ydb_tpu import dtypes

    cols: dict[str, Column] = {}
    fields = []
    for name in probe_payload:
        c = probe.columns[name]
        cols[name] = Column(c.data[i], c.validity[i] & valid_out)
        fields.append(probe.schema.field(name))
    for name in build_payload:
        c = build.columns[name]
        out_name = name + build_suffix
        cols[out_name] = Column(c.data[b_src], c.validity[b_src] & matched)
        f = build.schema.field(name)
        fields.append(dtypes.Field(
            out_name, f.type, f.nullable or kind == "left"))
    length = jnp.minimum(total, out_capacity).astype(jnp.int32)
    return (
        TableBlock(cols, length, dtypes.Schema(tuple(fields))),
        total,
    )
