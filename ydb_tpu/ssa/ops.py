"""Scalar and aggregate operation vocabulary for SSA programs.

The TPU analog of the reference's kernel-op enums — simple scalar ops
(ydb/library/arrow_kernels/operations.h: casts, comparison, logic,
arithmetic, string match, math) and aggregate functions
(ydb/core/formats/arrow/program.h `EAggregate`). Each op lowers to a jnp
expression over column arrays in ydb_tpu.ssa.kernels; XLA fuses chains of
them into single HBM passes.
"""

from __future__ import annotations

import enum


class Op(enum.Enum):
    # comparison (null-propagating)
    EQ = "eq"
    NE = "ne"
    LT = "lt"
    LE = "le"
    GT = "gt"
    GE = "ge"
    # logic (Kleene where nullable)
    AND = "and"
    OR = "or"
    NOT = "not"
    XOR = "xor"
    # arithmetic
    ADD = "add"
    SUB = "sub"
    MUL = "mul"
    DIV = "div"
    MOD = "mod"
    NEG = "neg"
    ABS = "abs"
    DIV_INT = "div_int"  # integer division; /0 -> NULL like DIV
    # bit ops (integer domains)
    BIT_AND = "bit_and"
    BIT_OR = "bit_or"
    BIT_XOR = "bit_xor"
    BIT_NOT = "bit_not"
    SHIFT_LEFT = "shift_left"
    SHIFT_RIGHT = "shift_right"
    # math
    SQRT = "sqrt"
    SIN = "sin"
    COS = "cos"
    TAN = "tan"
    ASIN = "asin"
    ACOS = "acos"
    ATAN = "atan"
    SINH = "sinh"
    COSH = "cosh"
    TANH = "tanh"
    ASINH = "asinh"
    ACOSH = "acosh"
    ATANH = "atanh"
    ATAN2 = "atan2"
    HYPOT = "hypot"
    CBRT = "cbrt"
    ERF = "erf"
    LOG2 = "log2"
    EXP2 = "exp2"
    TRUNC = "trunc"
    RINT = "rint"
    RADIANS = "radians"
    DEGREES = "degrees"
    EXP = "exp"
    LN = "ln"
    LOG10 = "log10"
    FLOOR = "floor"
    CEIL = "ceil"
    ROUND = "round"
    POW = "pow"
    SIGN = "sign"
    GREATEST = "greatest"
    LEAST = "least"
    # null handling
    IS_NULL = "is_null"
    IS_NOT_NULL = "is_not_null"
    COALESCE = "coalesce"
    IF = "if"
    NULLIF = "nullif"  # NULL when equal, else first arg
    # casts
    CAST_INT32 = "cast_int32"
    CAST_INT64 = "cast_int64"
    CAST_FLOAT = "cast_float"
    CAST_DOUBLE = "cast_double"
    CAST_INT8 = "cast_int8"
    CAST_INT16 = "cast_int16"
    CAST_UINT64 = "cast_uint64"
    CAST_BOOL = "cast_bool"
    # date parts (DATE=int32 days / TIMESTAMP=int64 us)
    YEAR = "year"
    MONTH = "month"
    DAY = "day"
    HOUR = "hour"
    MINUTE = "minute"
    SECOND = "second"
    DAY_OF_WEEK = "day_of_week"    # 0 = Sunday (spec convention)
    DAY_OF_YEAR = "day_of_year"    # 1-based
    WEEK = "week"                  # 1 + (doy-1)//7 (simple week-of-year)
    QUARTER = "quarter"
    # string ops on dictionary ids (plan-time resolved masks)
    DICT_GATHER = "dict_gather"   # aux table lookup by id (masks, ranks)
    IN_SET = "in_set"


class Agg(enum.Enum):
    """Aggregate functions (reference: program.h EAggregate — some/count/
    min/max/sum + numrows; avg decomposes into sum+count)."""

    COUNT = "count"          # non-null count
    COUNT_ALL = "count_all"  # row count (NumRows)
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    AVG = "avg"
    SOME = "some"            # any value (first non-null)
    # sample variance/stddev (TPC-DS q17/q39 stddev_samp): NULL for
    # groups of fewer than two non-null values. Two-phase split
    # decomposes them into SUM(x) + SUM(x^2) + COUNT partials, so the
    # distributed merge stays linear.
    VAR_SAMP = "var_samp"
    STDDEV_SAMP = "stddev_samp"


#: Merge rule applied when combining partial aggregate states between
#: shards (reference two-phase agg: BlockCombineHashed partial states merged
#: by BlockMergeFinalizeHashed, mkql_block_agg.cpp). SUM-like states psum
#: over the mesh; MIN/MAX take elementwise extremes.
PARTIAL_MERGE = {
    Agg.COUNT: Agg.SUM,
    Agg.COUNT_ALL: Agg.SUM,
    Agg.SUM: Agg.SUM,
    Agg.MIN: Agg.MIN,
    Agg.MAX: Agg.MAX,
    Agg.SOME: Agg.SOME,
    # VAR/STDDEV never appear in PARTIAL programs (twophase.split
    # decomposes them into SUM/SUM/COUNT states first); no entry.
}
