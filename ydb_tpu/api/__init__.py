from ydb_tpu.api.client import ApiError, Driver
from ydb_tpu.api.server import make_server

__all__ = ["Driver", "ApiError", "make_server"]
