"""api/ — protocol fronts (gRPC-style proxy, pgwire, kafka, sqs).

The gRPC surface (client.py / server.py) needs protoc-generated
messages; the pure-Python fronts (pgwire.py) do not. Import lazily so
``ydb_tpu.api.pgwire`` works in environments without protoc — the
gRPC pieces still raise at first use there.
"""


def __getattr__(name):
    if name in ("Driver", "ApiError"):
        from ydb_tpu.api import client

        return getattr(client, name)
    if name == "make_server":
        from ydb_tpu.api.server import make_server

        return make_server
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["Driver", "ApiError", "make_server"]
