"""gRPC server: the node front door.

Mirror of the reference's gRPC request proxy + per-service impls
(grpc_request_proxy.h:30, ydb/services/ydb; SURVEY.md §2.12): each RPC
routes through one request proxy (auth hook + per-call dispatch) into
the in-process service set (Cluster). Method handlers are registered
generically against the protobuf messages, so no grpc_tools codegen is
needed — protoc generates the messages, grpc carries them.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict
from concurrent import futures

import grpc
import numpy as np

from ydb_tpu import serving
from ydb_tpu.analysis import leaksan
from ydb_tpu.api.build import ensure_protos
from ydb_tpu.api.arrow_io import oracle_to_ipc
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.tx.coordinator import TxResult

pb = ensure_protos()


class RequestProxy:
    """Auth + dispatch front (grpc_request_proxy analog). Tokens: when
    ``auth_tokens`` is set, every call must carry metadata
    ('x-ydb-auth-ticket', <token>)."""

    def __init__(self, cluster: Cluster,
                 auth_tokens: set[str] | None = None):
        self.cluster = cluster
        self.auth_tokens = auth_tokens
        # bounded LRU of server-side sessions: evicting the oldest
        # caps memory against clients that never DeleteSession
        self.sessions: "OrderedDict[str, object]" = OrderedDict()
        self.max_sessions = 1024
        self._next_session = itertools.count(1)
        # leak-sanitizer handle per server-side session (serving.conn):
        # closed by _drop_session, so an eviction/delete/close path
        # that forgets a session fails the drain assertion
        self._conn_leaks: dict[str, object] = {}
        # Cluster/tablet state is not thread-safe: every mutating entry
        # point (RPC handlers AND the serve loop's run_background)
        # serializes on this lock
        self.lock = threading.Lock()
        self.endpoints: tuple = ()
        # long-running operations (Operation service)
        self._operations: dict = {}
        self._op_lock = threading.Lock()
        self._op_seq = 0
        # KeyValue volumes (booted on access from the durable registry)
        self._kv_volumes: dict = {}

    def check_auth(self, context) -> str | None:
        """Validates the ticket; returns it (the ACL principal) when
        auth is on, None for open clusters."""
        if self.auth_tokens is None:
            return None
        md = dict(context.invocation_metadata())
        ticket = md.get("x-ydb-auth-ticket")
        if ticket in self.auth_tokens:
            return ticket
        context.abort(grpc.StatusCode.UNAUTHENTICATED, "bad ticket")
        return None

    # ---- Query ----

    def _resolve_tenant(self, context, principal):
        """Connection metadata -> workload pool: an explicit
        'x-ydb-tenant' header wins, else the principal's registry
        binding, else the default pool (serving/tenants.py)."""
        try:
            md = dict(context.invocation_metadata())
        except Exception:  # noqa: BLE001 - metadata-less test contexts
            md = {}
        return serving.resolve_tenant(
            self.cluster, tenant=md.get("x-ydb-tenant"),
            principal=principal)

    def create_session(self, request, context):
        principal = self.check_auth(context)
        tenant = self._resolve_tenant(context, principal)
        with self.lock:
            sid = f"session-{next(self._next_session)}"
            session = self.cluster.session()
            session.principal = principal
            session.tenant = tenant
            self.sessions[sid] = session
            lk = leaksan.track("serving.conn", f"grpc:{tenant}")
            if lk is not None:
                self._conn_leaks[sid] = lk
            while len(self.sessions) > self.max_sessions:
                old_sid, _ = next(iter(self.sessions.items()))
                self._drop_session(old_sid)
        return pb.CreateSessionResponse(session_id=sid)

    def _drop_session(self, session_id: str) -> None:
        """Remove a server-side session; an open interactive tx rolls
        back first so its shard locks never leak (the hazard
        execute_script's finally block guards against)."""
        s = self.sessions.pop(session_id, None)
        if self._conn_leaks:
            leaksan.close(self._conn_leaks.pop(session_id, None))
        if s is not None and getattr(s, "_tx", None) is not None:
            s._tx_release()
            s._api_tx_id = None

    def _owned_session(self, session_id, principal, context):
        """Session ids are guessable; a ticket may only drive sessions
        it created (no cross-principal ACL identity borrowing)."""
        session = self.sessions.get(session_id)
        if session is not None and session.principal != principal:
            context.abort(grpc.StatusCode.PERMISSION_DENIED,
                          "session belongs to another principal")
        return session

    def delete_session(self, request, context):
        principal = self.check_auth(context)
        with self.lock:
            if self._owned_session(request.session_id, principal,
                                   context) is not None:
                self._drop_session(request.session_id)
        return pb.DeleteSessionResponse()

    def execute_query(self, request, context):
        principal = self.check_auth(context)
        session = self._owned_session(request.session_id, principal,
                                      context)
        if session is None:
            session = self.cluster.session()  # sessionless query
            session.principal = principal
            session.tenant = self._resolve_tenant(context, principal)
        try:
            # reads outside an open transaction skip the single-writer
            # lock: concurrent clients' SELECTs co-occupy the batch
            # window (kqp/batch.py) instead of serializing here
            if getattr(session, "_tx", None) is None \
                    and serving.is_read_statement(request.sql):
                out = session.execute(request.sql)
            else:
                with self.lock:
                    out = session.execute(request.sql)
        except Exception as e:  # noqa: BLE001 - surface to the client
            return pb.ExecuteQueryResponse(
                status=pb.ExecuteQueryResponse.ERROR, error=str(e))
        resp = pb.ExecuteQueryResponse(
            status=pb.ExecuteQueryResponse.SUCCESS)
        if out is None:  # DDL: no result set, no tx step
            resp.committed = True
        elif isinstance(out, str):  # EXPLAIN: the rendered plan
            resp.plan_text = out
        elif isinstance(out, OracleTable):
            # out.dicts is the per-result view the session bound (alias
            # -> source dictionary), not the raw cluster set
            resp.arrow_ipc = oracle_to_ipc(out)
        elif isinstance(out, TxResult):
            resp.tx_step = out.step
            resp.committed = out.committed
            if not out.committed:
                resp.status = pb.ExecuteQueryResponse.ERROR
                resp.error = out.error or "not committed"
        return resp

    # ---- Scheme ----

    def list_directory(self, request, context):
        self.check_auth(context)
        path = request.path or "/"
        if not self.cluster.scheme.exists(path):
            return pb.ListDirectoryResponse(error=f"no path {path}")
        children = []
        for child in self.cluster.scheme.children(path):
            children.append(pb.SchemeEntry(
                path=child, kind=self.cluster.scheme.kind(child)))
        return pb.ListDirectoryResponse(children=children)

    def describe_table(self, request, context):
        self.check_auth(context)
        desc = self.cluster.scheme.describe(request.path)
        if desc is None:
            return pb.DescribeTableResponse(
                error=f"{request.path} is not a table")
        from ydb_tpu.scheme.model import type_to_str

        return pb.DescribeTableResponse(
            path=desc.path,
            columns=[pb.ColumnMeta(name=f.name, type=type_to_str(f.type),
                                   nullable=f.nullable)
                     for f in desc.schema.fields],
            primary_key=list(desc.primary_key),
            shards=desc.n_shards,
            store=desc.store,
            schema_version=desc.schema_version,
        )

    # ---- Topic ----

    def _topic(self, name: str):
        return self.cluster.topics.get(name)

    def topic_write(self, request, context):
        self.check_auth(context)
        topic = self._topic(request.topic)
        if topic is None:
            return pb.TopicWriteResponse(
                error=f"no topic {request.topic}")
        with self.lock:
            p, off = topic.write(
                request.data.decode("utf-8", "surrogateescape"),
                key=request.key or None,
                producer=request.producer or None,
                seqno=request.seqno if request.producer else None,
            )
        return pb.TopicWriteResponse(partition=p, offset=off)

    def topic_read(self, request, context):
        self.check_auth(context)
        topic = self._topic(request.topic)
        if topic is None:
            return pb.TopicReadResponse(error=f"no topic {request.topic}")
        with self.lock:
            reader = topic.reader(request.consumer)
            msgs = reader.read_batch(request.limit or 100)
        return pb.TopicReadResponse(messages=[
            pb.TopicMessage(
                partition=m["partition"], offset=m["offset"],
                data=m["data"].encode("utf-8", "surrogateescape"))
            for m in msgs
        ])

    def topic_stream_read(self, request, context):
        """Server-streaming read session (the persqueue_v1 read-session
        analog): batches stream as data arrives; session-local read
        positions start at the committed offsets, so two sessions of one
        consumer do not double-deliver within themselves; auto_commit
        durably advances the consumer."""
        import time as _t

        self.check_auth(context)
        pos: dict[int, int] = {}
        idle_ms = request.idle_timeout_ms
        max_batch = request.max_batch or 100
        last_data = _t.monotonic()
        pending_commit: list[dict] = []
        while context.is_active():
            batch = []
            error = None
            with self.lock:
                topic = self._topic(request.topic)
                if topic is None:
                    error = f"no topic {request.topic}"
                else:
                    if pending_commit and request.auto_commit:
                        # commit the PREVIOUS batch only now that its
                        # yield completed: a disconnect mid-transfer
                        # must not lose committed-but-undelivered rows
                        topic.reader(request.consumer).commit_batch(
                            pending_commit)
                        pending_commit = []
                    for pi, part in enumerate(topic.partitions):
                        start = pos.get(
                            pi, part.committed(request.consumer))
                        if part.head_offset <= start:
                            pos[pi] = start  # idle partition: no scan
                            continue
                        for m in part.read(start, max_batch):
                            batch.append(dict(m, partition=pi))
                            start = m["offset"] + 1
                        pos[pi] = start
            # NEVER yield while holding the lock: a slow client's flow
            # control would wedge every RPC on the node
            if error is not None:
                yield pb.TopicReadResponse(error=error)
                return
            if batch:
                last_data = _t.monotonic()
                yield pb.TopicReadResponse(messages=[
                    pb.TopicMessage(
                        partition=m["partition"], offset=m["offset"],
                        data=m["data"].encode("utf-8",
                                              "surrogateescape"))
                    for m in batch
                ])
                pending_commit = batch
            else:
                if idle_ms and (_t.monotonic() - last_data) * 1000 > \
                        idle_ms:
                    break
                _t.sleep(0.02)
        # graceful end: the final delivered batch commits too
        if pending_commit and request.auto_commit:
            with self.lock:
                topic = self._topic(request.topic)
                if topic is not None:
                    topic.reader(request.consumer).commit_batch(
                        pending_commit)

    def topic_stream_write(self, request_iterator, context):
        """Bidirectional write session: one ack per item, producer
        seqno dedup exactly as unary writes."""
        self.check_auth(context)
        for item in request_iterator:
            ack = None
            with self.lock:
                topic = self._topic(item.topic)
                if topic is None:
                    ack = pb.StreamWriteAck(
                        error=f"no topic {item.topic}")
                else:
                    try:
                        p, off = topic.write(
                            item.data.decode("utf-8", "surrogateescape"),
                            key=item.key or None,
                            producer=item.producer or None,
                            seqno=item.seqno if item.producer else None,
                        )
                        ack = pb.StreamWriteAck(partition=p, offset=off)
                    except Exception as e:  # noqa: BLE001
                        ack = pb.StreamWriteAck(error=str(e))
            # yield outside the lock (slow-client flow control)
            yield ack

    def topic_commit(self, request, context):
        self.check_auth(context)
        topic = self._topic(request.topic)
        if topic is None:
            return pb.TopicCommitResponse(
                error=f"no topic {request.topic}")
        if not 0 <= request.partition < len(topic.partitions):
            return pb.TopicCommitResponse(
                error=f"partition {request.partition} out of range")
        with self.lock:
            topic.partitions[request.partition].commit(
                request.consumer, request.offset + 1)
        return pb.TopicCommitResponse()

    # ---- Export/Import (ydb_export/ydb_import analog) ----

    def _run_export(self, table: str, name: str) -> dict:
        from ydb_tpu.engine.backup import export_table
        from ydb_tpu.tx import ShardedTable

        # the export streams under the cluster lock: portion metadata
        # is not safe to read concurrently with locked writers
        # (compaction/GC under run_background), and the miniature
        # prefers a stalled RPC to a torn read
        with self.lock:
            t = self.cluster.tables.get(table)
            if t is None:
                raise ValueError(f"unknown table {table}")
            if not isinstance(t, ShardedTable):
                raise ValueError("export supports column-store tables")
            return export_table(t, self.cluster.store, name or table)

    def export_backup(self, request, context):
        self.check_auth(context)
        if request.async_op:
            op_id = self._start_operation(
                "export", self._run_export, request.table,
                request.name)
            return pb.ExportResponse(operation_id=op_id)
        try:
            man = self._run_export(request.table, request.name)
        except ValueError as e:
            return pb.ExportResponse(error=str(e))
        return pb.ExportResponse(rows=man["rows"],
                                 parts=len(man["parts"]),
                                 snapshot=man["snapshot"])

    def execute_script(self, request, context):
        """Multi-statement script in ONE session (ydb_scripting shape):
        statements run in order, the script aborts at the first error
        (pg simple-query semantics), and the final SELECT's result
        ships back as arrow IPC."""
        principal = self.check_auth(context)
        session = self.cluster.session()
        session.principal = principal
        results = []
        last_ipc = b""
        try:
            for stmt in _split_script(request.script):
                try:
                    with self.lock:
                        out = session.execute(stmt)
                except Exception as e:  # noqa: BLE001 - abort script
                    results.append(pb.ScriptStatementResult(
                        sql=stmt[:128], error=str(e)))
                    return pb.ExecuteScriptResponse(
                        error=f"{stmt[:64]}: {e}", statements=results)
                if isinstance(out, TxResult) and not out.committed:
                    # a failed COMMIT raises nothing — it reports; the
                    # script must still abort, not claim success
                    err = out.error or "not committed"
                    results.append(pb.ScriptStatementResult(
                        sql=stmt[:128], error=err))
                    return pb.ExecuteScriptResponse(
                        error=f"{stmt[:64]}: {err}",
                        statements=results)
                if isinstance(out, OracleTable):
                    rows = out.num_rows
                    last_ipc = oracle_to_ipc(out)
                else:
                    rows = 0
                results.append(pb.ScriptStatementResult(
                    sql=stmt[:128], rows=rows))
        finally:
            tx_open = session._tx is not None
            if tx_open:
                # an open interactive tx would silently drop buffered
                # writes AND leak its shard locks: roll it back
                with self.lock:
                    session._tx_release()
        if tx_open:
            return pb.ExecuteScriptResponse(
                error="script ended with an open transaction "
                      "(rolled back)", statements=results)
        return pb.ExecuteScriptResponse(statements=results,
                                        last_result_ipc=last_ipc)

    # ---- Operation service (long-running ops, ydb_operation analog) --

    def _start_operation(self, kind: str, fn, *args) -> str:
        with self._op_lock:
            self._op_seq += 1
            op_id = f"op-{self._op_seq}"
            st = {"id": op_id, "kind": kind, "ready": False,
                  "error": "", "result": None}
            self._operations[op_id] = st
            # bounded like the session map: forget the oldest FINISHED
            # ops so clients that never CancelOperation cannot grow
            # memory without limit
            if len(self._operations) > 1024:
                for old_id in [k for k, v in self._operations.items()
                               if v["ready"]][:len(self._operations)
                                              - 1024]:
                    del self._operations[old_id]

        seat = leaksan.track("serving.seat", f"op:{kind}")

        def run():
            try:
                st["result"] = fn(*args)
            except Exception as e:  # noqa: BLE001 - surfaced on poll
                st["error"] = str(e)
            finally:
                # the handoff ends HERE: drop the thread object and
                # the seat before publishing ready, so finished op
                # records never strand a Thread (they used to pin one
                # each until the record aged past the 1024 bound) and
                # the sanitizer sees the seat drain when the work
                # drains — even if fn dies on a BaseException
                with self._op_lock:
                    st.pop("thread", None)
                leaksan.close(seat)
                st["ready"] = True

        # the handle rides in the op record so close() can join
        # stragglers instead of abandoning them at process exit
        t = threading.Thread(target=run, daemon=True,
                             name=f"op-{kind}")
        st["thread"] = t
        try:
            t.start()
        except BaseException:
            # the seat's owner is the thread; if it never launched,
            # the spawn path must drain what it tracked
            with self._op_lock:
                st.pop("thread", None)
            leaksan.close(seat)
            raise
        return op_id

    def close(self, timeout: float = 10.0) -> None:
        """Join outstanding operation threads and drop every
        server-side session (orderly shutdown path: serve() callers
        should close the proxy after stopping gRPC, before
        Cluster.stop — which asserts all serving.* handles drained)."""
        with self._op_lock:
            threads = [st.get("thread") for st in
                       self._operations.values()]
        for t in threads:
            if t is not None and t.is_alive():
                t.join(timeout=timeout)
        with self.lock:
            for sid in list(self.sessions):
                self._drop_session(sid)

    def _op_status(self, st) -> "pb.OperationStatus":
        rows = 0
        if st["ready"] and st["result"] is not None:
            rows = st["result"].get("rows", 0)
        return pb.OperationStatus(id=st["id"], ready=st["ready"],
                                  error=st["error"], rows=rows,
                                  kind=st["kind"])

    def get_operation(self, request, context):
        self.check_auth(context)
        with self._op_lock:
            st = self._operations.get(request.id)
        if st is None:
            return pb.OperationStatus(id=request.id,
                                      error="unknown operation")
        return self._op_status(st)

    def list_operations(self, request, context):
        self.check_auth(context)
        with self._op_lock:
            sts = list(self._operations.values())
        return pb.ListOperationsResponse(
            operations=[self._op_status(st) for st in sts])

    def cancel_operation(self, request, context):
        """Forget a finished operation (running exports hold the
        cluster lock and complete; cancellation is bookkeeping, as for
        most of the reference's non-cancellable op kinds)."""
        self.check_auth(context)
        with self._op_lock:
            st = self._operations.get(request.id)
            if st is None:
                return pb.OperationStatus(id=request.id,
                                          error="unknown operation")
            if st["ready"]:
                del self._operations[request.id]
                return self._op_status(st)
        return pb.OperationStatus(id=request.id,
                                  error="operation still running")

    def import_backup(self, request, context):
        """Restore a backup as a CLUSTER table: scheme entry created,
        string ids remapped from the manifest's dictionaries into the
        cluster-shared set, rows streamed through the normal insert
        path (so WAL/portions/dedup semantics all apply)."""
        self.check_auth(context)
        from ydb_tpu.engine.backup import read_manifest, schema_from_json
        from ydb_tpu.engine.portion import read_portion_blob
        from ydb_tpu.scheme.model import TableDescription
        from ydb_tpu.scheme.shard import SchemeError

        with self.lock:
            c = self.cluster
            try:
                man = read_manifest(c.store, request.name)
            except KeyError:
                return pb.ImportResponse(
                    error=f"no backup {request.name}")
            target = request.table or man["name"]
            if target in c.tables:
                return pb.ImportResponse(
                    error=f"table {target} already exists")
            schema = schema_from_json(man["schema"])
            desc = TableDescription(
                path="/" + target, schema=schema,
                primary_key=(man["pk_column"],),
                n_shards=request.shards or man["n_shards"],
                store="column", ttl_column=man.get("ttl_column"),
                upsert=man["upsert"],
            )
            try:
                c.scheme.create_table(desc)
            except SchemeError as e:
                return pb.ImportResponse(error=str(e))
            try:
                t = c._instantiate(desc)
                # remap manifest dictionary ids -> cluster-shared ids
                remap: dict[str, np.ndarray] = {}
                for col, values in man["dicts"].items():
                    d = c.dicts.for_column(col)
                    remap[col] = np.array(
                        [d.add(v.encode("latin1")) for v in values],
                        dtype=np.int32)
                rows = 0
                for part in man["parts"]:
                    cols, valid = read_portion_blob(c.store,
                                                    part["blob_id"])
                    for col in list(cols):
                        if col in remap and \
                                schema.field(col).type.is_string:
                            cols[col] = remap[col][cols[col]]
                    t.insert(cols, valid or None)
                    rows += part["rows"]
            except Exception as e:  # noqa: BLE001 - import must not
                # leave a half-populated table registered: roll the DDL
                # back so a retry does not hit "already exists"
                t2 = c.tables.pop(target, None)
                prefixes = t2.storage_prefixes() if t2 is not None \
                    else []
                try:
                    c.scheme.drop_table("/" + target,
                                        trash_prefixes=prefixes)
                    c._sweep_trash()
                except Exception:  # noqa: BLE001 - keep first error
                    pass
                return pb.ImportResponse(error=f"import failed: {e}")
            c._plan_cache.clear()
        return pb.ImportResponse(rows=rows)

    def list_backups(self, request, context):
        self.check_auth(context)
        import json as _json

        out = []
        with self.lock:
            for blob_id in self.cluster.store.list("backup/"):
                if not blob_id.endswith("/manifest"):
                    continue
                man = _json.loads(self.cluster.store.get(blob_id))
                out.append(pb.BackupInfo(name=man["name"],
                                         rows=man["rows"],
                                         snapshot=man["snapshot"]))
        return pb.ListBackupsResponse(backups=out)

    # ---- RateLimiter (ydb_rate_limiter analog over runtime.quoter) ----

    def _quoter(self):
        from ydb_tpu.runtime.quoter import Quoter

        if self.cluster.quoter is None:
            self.cluster.quoter = Quoter()
        return self.cluster.quoter

    def create_resource(self, request, context):
        self.check_auth(context)
        if request.rate <= 0:
            return pb.CreateResourceResponse(error="rate must be > 0")
        with self.lock:
            q = self._quoter()
            if q.exists(request.path):
                # re-creating would refill the bucket to full burst — a
                # throttled client could defeat its own limit
                return pb.CreateResourceResponse(
                    error=f"resource {request.path} already exists")
            q.configure(request.path, request.rate,
                        request.burst if request.burst > 0 else None)
        return pb.CreateResourceResponse()

    def acquire_resource(self, request, context):
        self.check_auth(context)
        amount = request.amount or 1.0
        with self.lock:
            q = self._quoter()
            if q.describe(request.path) is None and not any(
                    q.exists(p) for p in _ancestors(request.path)):
                return pb.AcquireResourceResponse(
                    error=f"no resource {request.path}")
            ok = q.try_acquire(request.path, amount)
            retry = 0.0 if ok else q.wait_time(request.path, amount)
        return pb.AcquireResourceResponse(acquired=ok,
                                          retry_after_s=retry)

    def describe_resource(self, request, context):
        self.check_auth(context)
        with self.lock:
            desc = self._quoter().describe(request.path)
        if desc is None:
            return pb.DescribeResourceResponse(
                error=f"no resource {request.path}")
        return pb.DescribeResourceResponse(
            rate=desc["rate"], burst=desc["burst"],
            tokens=desc["tokens"])

    # ---- Monitoring (ydb_monitoring analog over obs.sysview) ----

    def health_check(self, request, context):
        self.check_auth(context)
        with self.lock:
            h = self.cluster.health()
        return pb.HealthCheckResponse(
            status=h["status"],
            issues=[pb.HealthIssue(message=i["message"],
                                   component=i.get("component", ""),
                                   severity=i.get("severity", ""))
                    for i in h.get("issues", [])])

    # ---- Coordination (kesus sessions + semaphores) ----

    def _kesus(self):
        if getattr(self.cluster, "_coord_kesus", None) is None:
            from ydb_tpu.tablet.kesus import KesusTablet

            self.cluster._coord_kesus = KesusTablet(
                "coordination", self.cluster.store)
        k = self.cluster._coord_kesus
        # sweep expired sessions on every access: a dead client's
        # semaphore holds release at its timeout, not never
        k.tick()
        return k

    def coord_session(self, request, context):
        self.check_auth(context)
        with self.lock:
            sid = self._kesus().attach_session(
                timeout_s=request.timeout_s or 30.0)
        return pb.CoordSessionResponse(session_id=sid)

    def coord_create_semaphore(self, request, context):
        self.check_auth(context)
        if request.limit < 0:
            return pb.CoordSemaphoreResponse(
                error="limit must be positive")
        try:
            with self.lock:
                self._kesus().create_semaphore(
                    request.name, int(request.limit) or 1)
        except Exception as e:  # noqa: BLE001
            return pb.CoordSemaphoreResponse(error=str(e))
        return pb.CoordSemaphoreResponse()

    def coord_acquire(self, request, context):
        self.check_auth(context)
        if request.count < 0:
            # a negative hold would INCREASE capacity for everyone else
            return pb.CoordSemaphoreResponse(
                error="count must be positive")
        try:
            with self.lock:
                ok = self._kesus().acquire(
                    request.session_id, request.name,
                    count=int(request.count) or 1,
                    timeout_s=request.timeout_s or 0.0)
        except Exception as e:  # noqa: BLE001
            return pb.CoordSemaphoreResponse(error=str(e))
        return pb.CoordSemaphoreResponse(acquired=bool(ok))

    def coord_release(self, request, context):
        self.check_auth(context)
        try:
            with self.lock:
                self._kesus().release(request.session_id, request.name)
        except Exception as e:  # noqa: BLE001
            return pb.CoordSemaphoreResponse(error=str(e))
        return pb.CoordSemaphoreResponse()

    def coord_describe(self, request, context):
        self.check_auth(context)
        try:
            with self.lock:
                d = self._kesus().describe(request.name)
        except KeyError:
            return pb.CoordSemaphoreResponse(
                error=f"no semaphore {request.name}")
        except Exception as e:  # noqa: BLE001
            return pb.CoordSemaphoreResponse(error=str(e))
        return pb.CoordSemaphoreResponse(
            count=sum(d.get("owners", {}).values()),
            limit=d.get("limit", 0),
            waiters=[int(w) for w in d.get("waiters", [])],
            owners=[int(o) for o in d.get("owners", {})])

    def coord_ping(self, request, context):
        self.check_auth(context)
        with self.lock:
            ok = self._kesus().ping_session(request.session_id)
        return pb.CoordSessionResponse(
            session_id=request.session_id,
            error="" if ok else "unknown session")

    def coord_detach(self, request, context):
        self.check_auth(context)
        with self.lock:
            self._kesus().detach_session(request.session_id)
        return pb.CoordSessionResponse(session_id=request.session_id)

    # ---- Cms (dynamic config over runtime.console) ----

    def _console(self):
        if getattr(self.cluster, "console", None) is None:
            from ydb_tpu.runtime.console import Console

            self.cluster.console = Console(self.cluster.store)
            # accepted configs must APPLY, not just persist: a
            # subscriber pushes the resolved knobs into the running
            # cluster (the ConfigsDispatcher contract)
            proxy = self

            class _Apply:
                # Console._notify calls subscriber._deliver(console)
                # (the ConfigsDispatcher contract)
                def _deliver(self, _console):
                    proxy._apply_config()

            self.cluster.console.subscribe(_Apply())
        return self.cluster.console

    def _apply_config(self):
        cfg = self.cluster.console.resolve()
        self.cluster.n_shards = cfg.n_shards
        self.cluster.icb.set("compact_portion_threshold",
                             cfg.compact_portion_threshold)
        self.cluster.icb.set("split_rows_per_shard",
                             cfg.split_rows_per_shard)

    def cms_get_config(self, request, context):
        self.check_auth(context)
        with self.lock:
            yaml_text, ver = self._console().get_config()
        return pb.GetConfigResponse(yaml=yaml_text or "", version=ver)

    def cms_set_config(self, request, context):
        self.check_auth(context)
        try:
            with self.lock:
                expect = (None if request.expect_version == -1
                          else int(request.expect_version))
                ver = self._console().set_config(
                    request.yaml, expected_version=expect)
        except Exception as e:  # noqa: BLE001
            return pb.SetConfigResponse(error=str(e))
        return pb.SetConfigResponse(version=ver)

    # ---- Auth ----

    def who_am_i(self, request, context):
        principal = self.check_auth(context)
        return pb.WhoAmIResponse(user=principal or "",
                                 authenticated=principal is not None)

    # ---- Discovery ----

    def list_endpoints(self, request, context):
        self.check_auth(context)
        return pb.ListEndpointsResponse(endpoints=[
            pb.EndpointInfo(address=a, port=p)
            for a, p in self.endpoints
        ])

    # ---- FederationDiscovery (ydb_federation_discovery_v1 analog) ----

    def list_federation_databases(self, request, context):
        """A single-database cluster reports itself as the whole
        federation (the reference's non-federated deployments answer
        the same way)."""
        self.check_auth(context)
        ep = (f"{self.endpoints[0][0]}:{self.endpoints[0][1]}"
              if self.endpoints else "")
        return pb.ListFederationDatabasesResponse(
            self_location="local",
            databases=[pb.FederationDatabaseInfo(
                name="/local", endpoint=ep, status="AVAILABLE")])

    # ---- Table service (ydb_table_v1 analog: structured DDL, tx
    # control, BulkUpsert, streaming ReadTable) ----

    def _ddl_ast(self):
        from ydb_tpu.sql import ast as sqlast
        return sqlast

    def _acl_session(self, principal):
        """Principal-bound session: its _check_access enforces path
        ACLs exactly as the SQL front door does (principal=None is the
        ACL-exempt internal case, so every handler that acts for a
        client must bind the ticket)."""
        s = self.cluster.session()
        s.principal = principal
        return s

    def _acl_denied(self, principal, *checks) -> str:
        """checks: (perm, path) pairs; returns the denial message for
        the response's error field, or '' when allowed."""
        s = self._acl_session(principal)
        try:
            for perm, path in checks:
                s._check_access(perm, path)
        except Exception as e:  # noqa: BLE001
            return str(e)
        return ""

    def table_create(self, request, context):
        principal = self.check_auth(context)
        denied = self._acl_denied(principal,
                                  ("ddl", "/" + request.path))
        if denied:
            return pb.CreateTableResponse(error=denied)
        sqlast = self._ddl_ast()
        opts = []
        if request.store:
            opts.append(("store", request.store))
        if request.shards:
            opts.append(("shards", str(request.shards)))
        stmt = sqlast.CreateTable(
            table=request.path,
            columns=tuple((c.name, c.type, c.not_null)
                          for c in request.columns),
            primary_key=tuple(request.primary_key),
            options=tuple(opts))
        try:
            with self.lock:
                self.cluster.create_table(stmt)
        except Exception as e:  # noqa: BLE001 - surface to the client
            return pb.CreateTableResponse(error=str(e))
        return pb.CreateTableResponse()

    def table_drop(self, request, context):
        principal = self.check_auth(context)
        denied = self._acl_denied(principal,
                                  ("ddl", "/" + request.path))
        if denied:
            return pb.DropTableResponse(error=denied)
        sqlast = self._ddl_ast()
        try:
            with self.lock:
                self.cluster.drop_table(sqlast.DropTable(
                    table=request.path))
        except Exception as e:  # noqa: BLE001
            return pb.DropTableResponse(error=str(e))
        return pb.DropTableResponse()

    def table_alter(self, request, context):
        principal = self.check_auth(context)
        denied = self._acl_denied(principal,
                                  ("ddl", "/" + request.path))
        if denied:
            return pb.AlterTableResponse(error=denied)
        sqlast = self._ddl_ast()
        stmt = sqlast.AlterTable(
            table=request.path,
            add_columns=tuple((c.name, c.type)
                              for c in request.add_columns))
        try:
            with self.lock:
                self.cluster.alter_table(stmt)
                desc = self.cluster.scheme.describe(request.path)
        except Exception as e:  # noqa: BLE001
            return pb.AlterTableResponse(error=str(e))
        return pb.AlterTableResponse(
            schema_version=desc.schema_version if desc else 0)

    def table_copy(self, request, context):
        """CopyTable: clone schema, stream every row through the
        normal insert path (schemeshard copy-table analog; the
        miniature copies data rather than sharing parts)."""
        principal = self.check_auth(context)
        denied = self._acl_denied(principal,
                                  ("read", "/" + request.src),
                                  ("ddl", "/" + request.dst))
        if denied:
            return pb.CopyTableResponse(error=denied)
        sqlast = self._ddl_ast()

        with self.lock:
            desc = self.cluster.scheme.describe(request.src)
            if desc is None:
                return pb.CopyTableResponse(
                    error=f"{request.src} is not a table")
            stmt = sqlast.CreateTable(
                table=request.dst,
                columns=tuple((f.name, _sql_type(f.type),
                               not f.nullable)
                              for f in desc.schema.fields),
                primary_key=tuple(desc.primary_key),
                options=(("store", desc.store),
                         ("shards", str(desc.n_shards))))
            try:
                self.cluster.create_table(stmt)
                session = self._acl_session(principal)
                out = session.execute(
                    f"SELECT * FROM {request.src}")
                rows = out.num_rows
                if rows:
                    cols, val = _oracle_to_insert(
                        out, self.cluster.tables[request.src].schema)
                    self.cluster.tables[request.dst].insert(cols, val)
                    self.cluster._plan_cache.clear()
            except Exception as e:  # noqa: BLE001
                return pb.CopyTableResponse(error=str(e))
        return pb.CopyTableResponse(rows=rows)

    def table_execute(self, request, context):
        """ExecuteDataQuery with client-driven TxControl: begin opens
        an interactive tx (BEGIN), commit closes it (COMMIT), tx_id
        continues one across calls — the session actor's tx state
        machine (kqp_session_actor.cpp) driven from the wire."""
        principal = self.check_auth(context)
        session = self._owned_session(request.session_id, principal,
                                      context)
        if session is None:
            return pb.ExecuteDataQueryResponse(
                error=f"unknown session {request.session_id}")
        tx = request.tx
        resp = pb.ExecuteDataQueryResponse()
        with self.lock:
            # validate the control block BEFORE touching session
            # state (and inside the lock, so a concurrent call on the
            # same session cannot slip past): a bad tx_id / double
            # begin ran no statement, so it must not disturb an
            # unrelated in-flight transaction
            open_id = getattr(session, "_api_tx_id", None)
            if open_id is not None and \
                    getattr(session, "_tx", None) is None:
                # the tx was closed out-of-band (SQL COMMIT/ROLLBACK
                # through another service on this shared session)
                session._api_tx_id = open_id = None
            if tx.tx_id and tx.tx_id != open_id:
                return pb.ExecuteDataQueryResponse(
                    error=f"unknown tx {tx.tx_id} in this session")
            if tx.begin and open_id is not None:
                return pb.ExecuteDataQueryResponse(
                    error="session already has an open tx")
            try:
                if tx.begin and not tx.commit:
                    # begin+commit together = single-shot autocommit
                    # (the session's default), so only a bare begin
                    # opens interactive state
                    session.execute("BEGIN")
                    self._tx_seq = getattr(self, "_tx_seq", 0) + 1
                    session._api_tx_id = f"tx-{self._tx_seq}"
                out = session.execute(request.sql)
                if tx.commit and getattr(session, "_api_tx_id",
                                         None):
                    res = session.execute("COMMIT")
                    session._api_tx_id = None
                    if isinstance(res, TxResult):
                        resp.tx_step = res.step
                        resp.committed = res.committed
                        if not res.committed:
                            resp.error = res.error or \
                                "not committed"
                            return resp
                elif getattr(session, "_api_tx_id", None):
                    resp.tx_id = session._api_tx_id
            except Exception as e:  # noqa: BLE001
                # a failed statement aborts the interactive tx,
                # matching the reference's session-actor semantics
                if getattr(session, "_api_tx_id", None):
                    session._tx_release()
                    session._api_tx_id = None
                return pb.ExecuteDataQueryResponse(error=str(e))
        if isinstance(out, OracleTable):
            resp.arrow_ipc = oracle_to_ipc(out)
        elif isinstance(out, TxResult):
            resp.tx_step = out.step
            resp.committed = out.committed
            if not out.committed:
                resp.error = out.error or "not committed"
        return resp

    def table_bulk_upsert(self, request, context):
        """BulkUpsert: Arrow IPC payload straight into the shards,
        bypassing SQL compilation (rpc_load_rows.cpp analog — the
        reference's Arrow-format bulk path made primary)."""
        principal = self.check_auth(context)
        denied = self._acl_denied(principal,
                                  ("write", "/" + request.table))
        if denied:
            return pb.BulkUpsertResponse(error=denied)
        from ydb_tpu.api.arrow_io import ipc_to_table

        with self.lock:
            t = self.cluster.tables.get(request.table)
            if t is None:
                return pb.BulkUpsertResponse(
                    error=f"unknown table {request.table}")
            try:
                at = ipc_to_table(request.arrow_ipc)
                cols, val = _arrow_to_insert(at, t.schema)
                res = t.insert(cols, val)
                self.cluster._plan_cache.clear()
            except Exception as e:  # noqa: BLE001
                return pb.BulkUpsertResponse(error=str(e))
        return pb.BulkUpsertResponse(rows=at.num_rows, tx_step=res.step)

    def table_read_stream(self, request, context):
        """Server-streaming ReadTable: one consistent snapshot scan,
        batched as Arrow IPC frames (rpc_read_table.cpp analog)."""
        principal = self.check_auth(context)
        batch_rows = request.batch_rows or 65536
        with self.lock:
            session = self._acl_session(principal)
            cols = ", ".join(request.columns) if request.columns \
                else "*"
            try:
                out = session.execute(
                    f"SELECT {cols} FROM {request.path}")
            except Exception as e:  # noqa: BLE001
                yield pb.ReadTableBatch(error=str(e))
                return
            # zero-copy slice views under the lock; serialization and
            # flow control happen OUTSIDE it (result buffers are
            # private to this query, so no torn reads)
            slices = []
            for lo in range(0, out.num_rows, batch_rows) or [0]:
                sl = OracleTable(
                    {k: (np.asarray(v[0])[lo:lo + batch_rows],
                         np.asarray(v[1])[lo:lo + batch_rows])
                     for k, v in out.cols.items()}, out.schema)
                sl.dicts = out.dicts
                slices.append(sl)
        for sl in slices:
            yield pb.ReadTableBatch(arrow_ipc=oracle_to_ipc(sl))

    def table_explain(self, request, context):
        principal = self.check_auth(context)
        with self.lock:
            session = self._acl_session(principal)
            try:
                plan = session.execute(f"EXPLAIN {request.sql}")
            except Exception as e:  # noqa: BLE001
                return pb.ExplainQueryResponse(error=str(e))
        return pb.ExplainQueryResponse(plan_text=plan or "")

    # ---- KeyValue service (ydb_keyvalue_v1 analog over the KeyValue
    # tablet: volumes live in the cluster store, reboot-durable) ----

    def _kv_registered(self, path: str) -> bool:
        """Exact-key registry probe (a prefix listing would make
        volume 'a' shadow 'ab')."""
        try:
            self.cluster.store.get(f"kv/volumes/{path}")
            return True
        except KeyError:
            return False

    def _kv_volume(self, path: str):
        """Boot-on-access from the durable registry: a proxy restart
        loses nothing."""
        from ydb_tpu.tablet.keyvalue import KeyValueTablet

        if path in self._kv_volumes:
            return self._kv_volumes[path]
        if not self._kv_registered(path):
            return None
        vol = KeyValueTablet.boot(f"kvvol/{path}", self.cluster.store)
        self._kv_volumes[path] = vol
        return vol

    def kv_create_volume(self, request, context):
        self.check_auth(context)
        from ydb_tpu.tablet.keyvalue import KeyValueTablet

        if "/" in request.path or not request.path:
            return pb.KvVolumeResponse(
                error="volume names must be non-empty and '/'-free "
                      "(they key the tablet store)")
        with self.lock:
            if self._kv_registered(request.path):
                return pb.KvVolumeResponse(
                    error=f"volume {request.path} exists")
            self.cluster.store.put(f"kv/volumes/{request.path}", b"1")
            self._kv_volumes[request.path] = KeyValueTablet.boot(
                f"kvvol/{request.path}", self.cluster.store)
        return pb.KvVolumeResponse()

    def kv_drop_volume(self, request, context):
        self.check_auth(context)
        with self.lock:
            vol = self._kv_volume(request.path)
            if vol is None:
                return pb.KvVolumeResponse(
                    error=f"no volume {request.path}")
            vol.delete_range(None, None)
            self.cluster.store.delete(f"kv/volumes/{request.path}")
            self._kv_volumes.pop(request.path, None)
        return pb.KvVolumeResponse()

    def kv_write(self, request, context):
        self.check_auth(context)
        with self.lock:
            vol = self._kv_volume(request.volume)
            if vol is None:
                return pb.KvWriteResponse(
                    error=f"no volume {request.volume}")
            vol.write(request.key, request.value)
        return pb.KvWriteResponse()

    def kv_read(self, request, context):
        self.check_auth(context)
        with self.lock:
            vol = self._kv_volume(request.volume)
            if vol is None:
                return pb.KvReadResponse(
                    error=f"no volume {request.volume}")
            v = vol.read(request.key)
        if v is None:
            return pb.KvReadResponse(found=False)
        return pb.KvReadResponse(found=True, value=v)

    def kv_list_range(self, request, context):
        self.check_auth(context)
        with self.lock:
            vol = self._kv_volume(request.volume)
            if vol is None:
                return pb.KvListRangeResponse(
                    error=f"no volume {request.volume}")
            pairs = vol.read_range(getattr(request, "from") or None,
                                   request.to or None,
                                   limit=request.limit or 1000)
        return pb.KvListRangeResponse(pairs=[
            pb.KvPair(key=k, value=v) for k, v in pairs])

    def kv_delete_range(self, request, context):
        self.check_auth(context)
        with self.lock:
            vol = self._kv_volume(request.volume)
            if vol is None:
                return pb.KvDeleteRangeResponse(
                    error=f"no volume {request.volume}")
            n = vol.delete_range(getattr(request, "from") or None,
                                 request.to or None)
        return pb.KvDeleteRangeResponse(deleted=n)

    def kv_rename(self, request, context):
        self.check_auth(context)
        with self.lock:
            vol = self._kv_volume(request.volume)
            if vol is None:
                return pb.KvRenameResponse(
                    error=f"no volume {request.volume}")
            ok = vol.rename(request.old_key, request.new_key)
        return pb.KvRenameResponse(renamed=ok)


def _split_script(script: str) -> list[str]:
    """';'-split OUTSIDE single-quoted literals ('' escapes stay
    inside, matching the SQL tokenizer)."""
    out, buf, in_str = [], [], False
    i = 0
    while i < len(script):
        ch = script[i]
        if in_str:
            if ch == "'":
                if i + 1 < len(script) and script[i + 1] == "'":
                    buf.append("''")
                    i += 2
                    continue
                in_str = False
            buf.append(ch)
        elif ch == "'":
            in_str = True
            buf.append(ch)
        elif ch == ";":
            stmt = "".join(buf).strip()
            if stmt:
                out.append(stmt)
            buf = []
        else:
            buf.append(ch)
        i += 1
    stmt = "".join(buf).strip()
    if stmt:
        out.append(stmt)
    return out


def _sql_type(t) -> str:
    """Type -> DDL spelling that _parse_type round-trips (type_to_str's
    'decimal(scale)' is the schema-JSON spelling, not valid DDL)."""
    if t.is_decimal:
        return f"decimal(38,{t.scale})"
    return t.kind.value


def _oracle_to_insert(out: OracleTable, schema):
    """Result set -> (columns, validity) in the shard-insert shape
    (strings back to raw bytes so the target's dictionaries re-encode)."""
    cols, val = {}, {}
    for f in schema.fields:
        ids = np.asarray(out.column(f.name))
        valid = np.asarray(out.validity(f.name), dtype=bool)
        if f.type.is_string:
            d = out.dicts[f.name] if (out.dicts and f.name in
                                      out.dicts) else None
            if d is None or len(d) == 0:
                cols[f.name] = [b""] * len(ids)
            else:
                cols[f.name] = d.decode(
                    np.clip(ids, 0, len(d) - 1))
        else:
            cols[f.name] = np.asarray(ids, dtype=f.type.physical)
        val[f.name] = valid
    return cols, val


def _arrow_to_insert(at, schema):
    """Arrow IPC payload -> (columns, validity) in the shard-insert
    shape; column set must cover the schema (BulkUpsert writes whole
    rows, as the reference's does). Strings stay raw (the target
    table's own dictionaries re-encode on insert); every other type
    converts through the one shared rule set in blocks.arrow_bridge."""
    from ydb_tpu.blocks.arrow_bridge import _column_to_numpy
    from ydb_tpu.blocks.dictionary import DictionarySet

    names = set(at.column_names)
    missing = [f.name for f in schema.fields if f.name not in names]
    if missing:
        raise ValueError(f"BulkUpsert must set all columns; "
                         f"missing {missing}")
    cols, val = {}, {}
    for f in schema.fields:
        col = at.column(f.name).combine_chunks()
        if f.type.is_string:
            cols[f.name] = ["" if v is None else v
                            for v in col.to_pylist()]
            val[f.name] = np.asarray(col.is_valid())
        else:
            # dicts arg unused on the non-string path
            cols[f.name], val[f.name] = _column_to_numpy(
                col, f, DictionarySet())
    return cols, val


def _ancestors(path: str) -> list[str]:
    parts = path.split("/")
    return ["/".join(parts[:i]) for i in range(1, len(parts))]


_SERVICES = {
    "ydb_tpu.Query": {
        "CreateSession": ("create_session", pb.CreateSessionRequest,
                          pb.CreateSessionResponse),
        "DeleteSession": ("delete_session", pb.DeleteSessionRequest,
                          pb.DeleteSessionResponse),
        "ExecuteQuery": ("execute_query", pb.ExecuteQueryRequest,
                         pb.ExecuteQueryResponse),
    },
    "ydb_tpu.Scheme": {
        "ListDirectory": ("list_directory", pb.ListDirectoryRequest,
                          pb.ListDirectoryResponse),
        "DescribeTable": ("describe_table", pb.DescribeTableRequest,
                          pb.DescribeTableResponse),
    },
    "ydb_tpu.Topic": {
        "Write": ("topic_write", pb.TopicWriteRequest,
                  pb.TopicWriteResponse),
        "Read": ("topic_read", pb.TopicReadRequest, pb.TopicReadResponse),
        "Commit": ("topic_commit", pb.TopicCommitRequest,
                   pb.TopicCommitResponse),
        "StreamRead": ("topic_stream_read", pb.StreamReadRequest,
                       pb.TopicReadResponse, "unary_stream"),
        "StreamWrite": ("topic_stream_write", pb.StreamWriteItem,
                        pb.StreamWriteAck, "stream_stream"),
    },
    "ydb_tpu.Export": {
        "ExportBackup": ("export_backup", pb.ExportRequest,
                         pb.ExportResponse),
        "ListBackups": ("list_backups", pb.ListBackupsRequest,
                        pb.ListBackupsResponse),
    },
    "ydb_tpu.RateLimiter": {
        "CreateResource": ("create_resource", pb.CreateResourceRequest,
                           pb.CreateResourceResponse),
        "AcquireResource": ("acquire_resource",
                            pb.AcquireResourceRequest,
                            pb.AcquireResourceResponse),
        "DescribeResource": ("describe_resource",
                             pb.DescribeResourceRequest,
                             pb.DescribeResourceResponse),
    },
    "ydb_tpu.Scripting": {
        "ExecuteScript": ("execute_script", pb.ExecuteScriptRequest,
                          pb.ExecuteScriptResponse),
    },
    "ydb_tpu.Operation": {
        "GetOperation": ("get_operation", pb.GetOperationRequest,
                         pb.OperationStatus),
        "ListOperations": ("list_operations", pb.ListOperationsRequest,
                           pb.ListOperationsResponse),
        "CancelOperation": ("cancel_operation",
                            pb.CancelOperationRequest,
                            pb.OperationStatus),
    },
    "ydb_tpu.Monitoring": {
        "HealthCheck": ("health_check", pb.HealthCheckRequest,
                        pb.HealthCheckResponse),
    },
    "ydb_tpu.Coordination": {
        "CreateSession": ("coord_session", pb.CoordSessionRequest,
                          pb.CoordSessionResponse),
        "CreateSemaphore": ("coord_create_semaphore",
                            pb.CoordSemaphoreRequest,
                            pb.CoordSemaphoreResponse),
        "AcquireSemaphore": ("coord_acquire",
                             pb.CoordSemaphoreRequest,
                             pb.CoordSemaphoreResponse),
        "ReleaseSemaphore": ("coord_release",
                             pb.CoordSemaphoreRequest,
                             pb.CoordSemaphoreResponse),
        "DescribeSemaphore": ("coord_describe",
                              pb.CoordSemaphoreRequest,
                              pb.CoordSemaphoreResponse),
        "PingSession": ("coord_ping", pb.CoordSessionRequest,
                        pb.CoordSessionResponse),
        "DeleteSession": ("coord_detach", pb.CoordSessionRequest,
                          pb.CoordSessionResponse),
    },
    "ydb_tpu.Cms": {
        "GetConfig": ("cms_get_config", pb.GetConfigRequest,
                      pb.GetConfigResponse),
        "SetConfig": ("cms_set_config", pb.SetConfigRequest,
                      pb.SetConfigResponse),
    },
    "ydb_tpu.Auth": {
        "WhoAmI": ("who_am_i", pb.WhoAmIRequest, pb.WhoAmIResponse),
    },
    "ydb_tpu.Discovery": {
        "ListEndpoints": ("list_endpoints", pb.ListEndpointsRequest,
                          pb.ListEndpointsResponse),
    },
    "ydb_tpu.FederationDiscovery": {
        "ListFederationDatabases": (
            "list_federation_databases",
            pb.ListFederationDatabasesRequest,
            pb.ListFederationDatabasesResponse),
    },
    "ydb_tpu.Table": {
        "CreateSession": ("create_session", pb.CreateSessionRequest,
                          pb.CreateSessionResponse),
        "DeleteSession": ("delete_session", pb.DeleteSessionRequest,
                          pb.DeleteSessionResponse),
        "CreateTable": ("table_create", pb.CreateTableRequest,
                        pb.CreateTableResponse),
        "DropTable": ("table_drop", pb.DropTableRequest,
                      pb.DropTableResponse),
        "AlterTable": ("table_alter", pb.AlterTableAddColumnsRequest,
                       pb.AlterTableResponse),
        "CopyTable": ("table_copy", pb.CopyTableRequest,
                      pb.CopyTableResponse),
        "DescribeTable": ("describe_table", pb.DescribeTableRequest,
                          pb.DescribeTableResponse),
        "ExecuteDataQuery": ("table_execute",
                             pb.ExecuteDataQueryRequest,
                             pb.ExecuteDataQueryResponse),
        "ExplainDataQuery": ("table_explain", pb.ExplainQueryRequest,
                             pb.ExplainQueryResponse),
        "BulkUpsert": ("table_bulk_upsert", pb.BulkUpsertRequest,
                       pb.BulkUpsertResponse),
        "StreamReadTable": ("table_read_stream", pb.ReadTableRequest,
                            pb.ReadTableBatch, "unary_stream"),
    },
    "ydb_tpu.KeyValue": {
        "CreateVolume": ("kv_create_volume", pb.KvVolumeRequest,
                         pb.KvVolumeResponse),
        "DropVolume": ("kv_drop_volume", pb.KvVolumeRequest,
                       pb.KvVolumeResponse),
        "ExecuteTransaction": ("kv_write", pb.KvWriteRequest,
                               pb.KvWriteResponse),
        "Read": ("kv_read", pb.KvReadRequest, pb.KvReadResponse),
        "ListRange": ("kv_list_range", pb.KvListRangeRequest,
                      pb.KvListRangeResponse),
        "DeleteRange": ("kv_delete_range", pb.KvDeleteRangeRequest,
                        pb.KvDeleteRangeResponse),
        "Rename": ("kv_rename", pb.KvRenameRequest,
                   pb.KvRenameResponse),
    },
    "ydb_tpu.Import": {
        "ImportBackup": ("import_backup", pb.ImportRequest,
                         pb.ImportResponse),
    },
}


def make_server(cluster: Cluster, port: int = 0,
                auth_tokens: set[str] | None = None,
                max_workers: int = 8) -> tuple[grpc.Server, int]:
    """Returns (server, bound_port). port=0 picks a free port."""
    proxy = RequestProxy(cluster, auth_tokens)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    proxy.endpoints = (("127.0.0.1", bound),)

    for service, methods in _SERVICES.items():
        handlers = {}
        for rpc_name, spec in methods.items():
            attr, req_cls, resp_cls = spec[:3]
            kind = spec[3] if len(spec) > 3 else "unary_unary"
            ctor = {
                "unary_unary": grpc.unary_unary_rpc_method_handler,
                "unary_stream": grpc.unary_stream_rpc_method_handler,
                "stream_unary": grpc.stream_unary_rpc_method_handler,
                "stream_stream": grpc.stream_stream_rpc_method_handler,
            }[kind]
            handlers[rpc_name] = ctor(
                getattr(proxy, attr),
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )
        server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(service, handlers),))
    server.request_proxy = proxy
    return server, bound
