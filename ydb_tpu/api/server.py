"""gRPC server: the node front door.

Mirror of the reference's gRPC request proxy + per-service impls
(grpc_request_proxy.h:30, ydb/services/ydb; SURVEY.md §2.12): each RPC
routes through one request proxy (auth hook + per-call dispatch) into
the in-process service set (Cluster). Method handlers are registered
generically against the protobuf messages, so no grpc_tools codegen is
needed — protoc generates the messages, grpc carries them.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict
from concurrent import futures

import grpc

from ydb_tpu.api.build import ensure_protos
from ydb_tpu.api.arrow_io import oracle_to_ipc
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.tx.coordinator import TxResult

pb = ensure_protos()


class RequestProxy:
    """Auth + dispatch front (grpc_request_proxy analog). Tokens: when
    ``auth_tokens`` is set, every call must carry metadata
    ('x-ydb-auth-ticket', <token>)."""

    def __init__(self, cluster: Cluster,
                 auth_tokens: set[str] | None = None):
        self.cluster = cluster
        self.auth_tokens = auth_tokens
        # bounded LRU of server-side sessions: evicting the oldest
        # caps memory against clients that never DeleteSession
        self.sessions: "OrderedDict[str, object]" = OrderedDict()
        self.max_sessions = 1024
        self._next_session = itertools.count(1)
        # Cluster/tablet state is not thread-safe: every mutating entry
        # point (RPC handlers AND the serve loop's run_background)
        # serializes on this lock
        self.lock = threading.Lock()
        self.endpoints: tuple = ()

    def check_auth(self, context) -> str | None:
        """Validates the ticket; returns it (the ACL principal) when
        auth is on, None for open clusters."""
        if self.auth_tokens is None:
            return None
        md = dict(context.invocation_metadata())
        ticket = md.get("x-ydb-auth-ticket")
        if ticket in self.auth_tokens:
            return ticket
        context.abort(grpc.StatusCode.UNAUTHENTICATED, "bad ticket")
        return None

    # ---- Query ----

    def create_session(self, request, context):
        principal = self.check_auth(context)
        with self.lock:
            sid = f"session-{next(self._next_session)}"
            session = self.cluster.session()
            session.principal = principal
            self.sessions[sid] = session
            while len(self.sessions) > self.max_sessions:
                self.sessions.popitem(last=False)
        return pb.CreateSessionResponse(session_id=sid)

    def _owned_session(self, session_id, principal, context):
        """Session ids are guessable; a ticket may only drive sessions
        it created (no cross-principal ACL identity borrowing)."""
        session = self.sessions.get(session_id)
        if session is not None and session.principal != principal:
            context.abort(grpc.StatusCode.PERMISSION_DENIED,
                          "session belongs to another principal")
        return session

    def delete_session(self, request, context):
        principal = self.check_auth(context)
        with self.lock:
            if self._owned_session(request.session_id, principal,
                                   context) is not None:
                self.sessions.pop(request.session_id, None)
        return pb.DeleteSessionResponse()

    def execute_query(self, request, context):
        principal = self.check_auth(context)
        session = self._owned_session(request.session_id, principal,
                                      context)
        if session is None:
            session = self.cluster.session()  # sessionless query
            session.principal = principal
        try:
            with self.lock:
                out = session.execute(request.sql)
        except Exception as e:  # noqa: BLE001 - surface to the client
            return pb.ExecuteQueryResponse(
                status=pb.ExecuteQueryResponse.ERROR, error=str(e))
        resp = pb.ExecuteQueryResponse(
            status=pb.ExecuteQueryResponse.SUCCESS)
        if out is None:  # DDL: no result set, no tx step
            resp.committed = True
        elif isinstance(out, str):  # EXPLAIN: the rendered plan
            resp.plan_text = out
        elif isinstance(out, OracleTable):
            # out.dicts is the per-result view the session bound (alias
            # -> source dictionary), not the raw cluster set
            resp.arrow_ipc = oracle_to_ipc(out)
        elif isinstance(out, TxResult):
            resp.tx_step = out.step
            resp.committed = out.committed
            if not out.committed:
                resp.status = pb.ExecuteQueryResponse.ERROR
                resp.error = out.error or "not committed"
        return resp

    # ---- Scheme ----

    def list_directory(self, request, context):
        self.check_auth(context)
        path = request.path or "/"
        if not self.cluster.scheme.exists(path):
            return pb.ListDirectoryResponse(error=f"no path {path}")
        children = []
        for child in self.cluster.scheme.children(path):
            children.append(pb.SchemeEntry(
                path=child, kind=self.cluster.scheme.kind(child)))
        return pb.ListDirectoryResponse(children=children)

    def describe_table(self, request, context):
        self.check_auth(context)
        desc = self.cluster.scheme.describe(request.path)
        if desc is None:
            return pb.DescribeTableResponse(
                error=f"{request.path} is not a table")
        from ydb_tpu.scheme.model import type_to_str

        return pb.DescribeTableResponse(
            path=desc.path,
            columns=[pb.ColumnMeta(name=f.name, type=type_to_str(f.type),
                                   nullable=f.nullable)
                     for f in desc.schema.fields],
            primary_key=list(desc.primary_key),
            shards=desc.n_shards,
            store=desc.store,
            schema_version=desc.schema_version,
        )

    # ---- Topic ----

    def _topic(self, name: str):
        return self.cluster.topics.get(name)

    def topic_write(self, request, context):
        self.check_auth(context)
        topic = self._topic(request.topic)
        if topic is None:
            return pb.TopicWriteResponse(
                error=f"no topic {request.topic}")
        with self.lock:
            p, off = topic.write(
                request.data.decode("utf-8", "surrogateescape"),
                key=request.key or None,
                producer=request.producer or None,
                seqno=request.seqno if request.producer else None,
            )
        return pb.TopicWriteResponse(partition=p, offset=off)

    def topic_read(self, request, context):
        self.check_auth(context)
        topic = self._topic(request.topic)
        if topic is None:
            return pb.TopicReadResponse(error=f"no topic {request.topic}")
        with self.lock:
            reader = topic.reader(request.consumer)
            msgs = reader.read_batch(request.limit or 100)
        return pb.TopicReadResponse(messages=[
            pb.TopicMessage(
                partition=m["partition"], offset=m["offset"],
                data=m["data"].encode("utf-8", "surrogateescape"))
            for m in msgs
        ])

    def topic_stream_read(self, request, context):
        """Server-streaming read session (the persqueue_v1 read-session
        analog): batches stream as data arrives; session-local read
        positions start at the committed offsets, so two sessions of one
        consumer do not double-deliver within themselves; auto_commit
        durably advances the consumer."""
        import time as _t

        self.check_auth(context)
        pos: dict[int, int] = {}
        idle_ms = request.idle_timeout_ms
        max_batch = request.max_batch or 100
        last_data = _t.monotonic()
        pending_commit: list[dict] = []
        while context.is_active():
            batch = []
            error = None
            with self.lock:
                topic = self._topic(request.topic)
                if topic is None:
                    error = f"no topic {request.topic}"
                else:
                    if pending_commit and request.auto_commit:
                        # commit the PREVIOUS batch only now that its
                        # yield completed: a disconnect mid-transfer
                        # must not lose committed-but-undelivered rows
                        topic.reader(request.consumer).commit_batch(
                            pending_commit)
                        pending_commit = []
                    for pi, part in enumerate(topic.partitions):
                        start = pos.get(
                            pi, part.committed(request.consumer))
                        if part.head_offset <= start:
                            pos[pi] = start  # idle partition: no scan
                            continue
                        for m in part.read(start, max_batch):
                            batch.append(dict(m, partition=pi))
                            start = m["offset"] + 1
                        pos[pi] = start
            # NEVER yield while holding the lock: a slow client's flow
            # control would wedge every RPC on the node
            if error is not None:
                yield pb.TopicReadResponse(error=error)
                return
            if batch:
                last_data = _t.monotonic()
                yield pb.TopicReadResponse(messages=[
                    pb.TopicMessage(
                        partition=m["partition"], offset=m["offset"],
                        data=m["data"].encode("utf-8",
                                              "surrogateescape"))
                    for m in batch
                ])
                pending_commit = batch
            else:
                if idle_ms and (_t.monotonic() - last_data) * 1000 > \
                        idle_ms:
                    break
                _t.sleep(0.02)
        # graceful end: the final delivered batch commits too
        if pending_commit and request.auto_commit:
            with self.lock:
                topic = self._topic(request.topic)
                if topic is not None:
                    topic.reader(request.consumer).commit_batch(
                        pending_commit)

    def topic_stream_write(self, request_iterator, context):
        """Bidirectional write session: one ack per item, producer
        seqno dedup exactly as unary writes."""
        self.check_auth(context)
        for item in request_iterator:
            ack = None
            with self.lock:
                topic = self._topic(item.topic)
                if topic is None:
                    ack = pb.StreamWriteAck(
                        error=f"no topic {item.topic}")
                else:
                    try:
                        p, off = topic.write(
                            item.data.decode("utf-8", "surrogateescape"),
                            key=item.key or None,
                            producer=item.producer or None,
                            seqno=item.seqno if item.producer else None,
                        )
                        ack = pb.StreamWriteAck(partition=p, offset=off)
                    except Exception as e:  # noqa: BLE001
                        ack = pb.StreamWriteAck(error=str(e))
            # yield outside the lock (slow-client flow control)
            yield ack

    def topic_commit(self, request, context):
        self.check_auth(context)
        topic = self._topic(request.topic)
        if topic is None:
            return pb.TopicCommitResponse(
                error=f"no topic {request.topic}")
        if not 0 <= request.partition < len(topic.partitions):
            return pb.TopicCommitResponse(
                error=f"partition {request.partition} out of range")
        with self.lock:
            topic.partitions[request.partition].commit(
                request.consumer, request.offset + 1)
        return pb.TopicCommitResponse()

    # ---- Discovery ----

    def list_endpoints(self, request, context):
        self.check_auth(context)
        return pb.ListEndpointsResponse(endpoints=[
            pb.EndpointInfo(address=a, port=p)
            for a, p in self.endpoints
        ])


_SERVICES = {
    "ydb_tpu.Query": {
        "CreateSession": ("create_session", pb.CreateSessionRequest,
                          pb.CreateSessionResponse),
        "DeleteSession": ("delete_session", pb.DeleteSessionRequest,
                          pb.DeleteSessionResponse),
        "ExecuteQuery": ("execute_query", pb.ExecuteQueryRequest,
                         pb.ExecuteQueryResponse),
    },
    "ydb_tpu.Scheme": {
        "ListDirectory": ("list_directory", pb.ListDirectoryRequest,
                          pb.ListDirectoryResponse),
        "DescribeTable": ("describe_table", pb.DescribeTableRequest,
                          pb.DescribeTableResponse),
    },
    "ydb_tpu.Topic": {
        "Write": ("topic_write", pb.TopicWriteRequest,
                  pb.TopicWriteResponse),
        "Read": ("topic_read", pb.TopicReadRequest, pb.TopicReadResponse),
        "Commit": ("topic_commit", pb.TopicCommitRequest,
                   pb.TopicCommitResponse),
        "StreamRead": ("topic_stream_read", pb.StreamReadRequest,
                       pb.TopicReadResponse, "unary_stream"),
        "StreamWrite": ("topic_stream_write", pb.StreamWriteItem,
                        pb.StreamWriteAck, "stream_stream"),
    },
    "ydb_tpu.Discovery": {
        "ListEndpoints": ("list_endpoints", pb.ListEndpointsRequest,
                          pb.ListEndpointsResponse),
    },
}


def make_server(cluster: Cluster, port: int = 0,
                auth_tokens: set[str] | None = None,
                max_workers: int = 8) -> tuple[grpc.Server, int]:
    """Returns (server, bound_port). port=0 picks a free port."""
    proxy = RequestProxy(cluster, auth_tokens)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    proxy.endpoints = (("127.0.0.1", bound),)

    for service, methods in _SERVICES.items():
        handlers = {}
        for rpc_name, spec in methods.items():
            attr, req_cls, resp_cls = spec[:3]
            kind = spec[3] if len(spec) > 3 else "unary_unary"
            ctor = {
                "unary_unary": grpc.unary_unary_rpc_method_handler,
                "unary_stream": grpc.unary_stream_rpc_method_handler,
                "stream_unary": grpc.stream_unary_rpc_method_handler,
                "stream_stream": grpc.stream_stream_rpc_method_handler,
            }[kind]
            handlers[rpc_name] = ctor(
                getattr(proxy, attr),
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )
        server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(service, handlers),))
    server.request_proxy = proxy
    return server, bound
