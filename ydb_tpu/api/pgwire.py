"""PostgreSQL wire-protocol frontend (protocol 3.0, simple query flow).

Mirror of the reference's pgwire compatibility layer
(ydb/core/local_pgwire/local_pgwire_connection.cpp, ydb/core/pgproxy):
a TCP listener that speaks the PostgreSQL v3 message protocol and
routes SQL text into the same in-process session layer the gRPC Query
service uses, so any stock PostgreSQL client (psql, psycopg, JDBC in
simple-query mode) can talk to the cluster.

Supported flow:
  * SSL/GSS negotiation requests (politely refused with 'N'),
  * StartupMessage with optional cleartext-password auth checked
    against the same token set as the gRPC request proxy,
  * ParameterStatus + BackendKeyData + ReadyForQuery handshake,
  * simple Query ('Q') with multi-statement strings, text-format
    results (RowDescription/DataRow/CommandComplete),
  * CancelRequest (connection-level no-op), Terminate ('X'),
  * extended-protocol messages are answered with a clear error and
    the stream resynchronizes on Sync — simple-query clients are the
    compatibility target, exactly like the reference's initial pgwire.

Every connection owns one session; cluster state is single-writer, so
statement execution serializes on the shared lock (the same contract as
api/server.RequestProxy.lock).
"""

from __future__ import annotations

import itertools
import socket
import socketserver
import struct
import threading

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.tx.coordinator import TxResult

_PROTO_V3 = 196608        # 3.0
_SSL_REQUEST = 80877103
_GSSENC_REQUEST = 80877104
_CANCEL_REQUEST = 80877102

# (type oid, typlen) per logical kind; values always travel in text
# format, the oid is what drives client-side parsing
_PG_OID = {
    dtypes.Kind.BOOL: (16, 1),
    dtypes.Kind.INT8: (21, 2),
    dtypes.Kind.INT16: (21, 2),
    dtypes.Kind.INT32: (23, 4),
    dtypes.Kind.INT64: (20, 8),
    dtypes.Kind.UINT8: (21, 2),
    dtypes.Kind.UINT16: (23, 4),
    dtypes.Kind.UINT32: (20, 8),
    dtypes.Kind.UINT64: (20, 8),
    dtypes.Kind.FLOAT: (700, 4),
    dtypes.Kind.DOUBLE: (701, 8),
    dtypes.Kind.DATE: (1082, 4),
    dtypes.Kind.TIMESTAMP: (1114, 8),
    dtypes.Kind.DECIMAL: (1700, -1),
    dtypes.Kind.STRING: (25, -1),
}


def _msg(type_byte: bytes, payload: bytes) -> bytes:
    return type_byte + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode("utf-8", "surrogateescape") + b"\x00"


def _error(message: str, code: str = "XX000") -> bytes:
    fields = (b"S" + _cstr("ERROR") + b"V" + _cstr("ERROR")
              + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00")
    return _msg(b"E", fields)


def _format_value(kind: dtypes.Kind, scale: int, v) -> bytes:
    if kind == dtypes.Kind.BOOL:
        return b"t" if v else b"f"
    if kind == dtypes.Kind.DATE:
        return str(np.datetime64(int(v), "D")).encode()
    if kind == dtypes.Kind.TIMESTAMP:
        return str(np.datetime64(int(v), "us")).encode().replace(
            b"T", b" ")
    if kind == dtypes.Kind.DECIMAL:
        import decimal as pydec

        return str(pydec.Decimal(int(v)).scaleb(-scale)).encode()
    if kind in (dtypes.Kind.FLOAT, dtypes.Kind.DOUBLE):
        return f"{float(v):.17g}".encode()
    return str(int(v)).encode()


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):  # noqa: C901 - one protocol, one state machine
        srv: PgWireServer = self.server.pg  # type: ignore[attr-defined]
        sock = self.request
        sock.settimeout(srv.idle_timeout)
        try:
            if not self._startup(srv, sock):
                return
            self._session_loop(srv, sock)
        except (ConnectionError, socket.timeout, OSError):
            pass

    # -- startup / auth --

    def _read_exact(self, sock, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client closed")
            buf += chunk
        return buf

    def _startup(self, srv, sock) -> bool:
        while True:
            (length,) = struct.unpack("!I", self._read_exact(sock, 4))
            payload = self._read_exact(sock, length - 4)
            (code,) = struct.unpack("!I", payload[:4])
            if code in (_SSL_REQUEST, _GSSENC_REQUEST):
                sock.sendall(b"N")  # not supported, retry in clear
                continue
            if code == _CANCEL_REQUEST:
                return False  # per protocol: no response, just close
            if code != _PROTO_V3:
                sock.sendall(_error(
                    f"unsupported protocol {code >> 16}.{code & 0xffff}",
                    "0A000"))
                return False
            params = payload[4:].split(b"\x00")
            kv = dict(zip(params[0::2], params[1::2]))
            self.user = kv.get(b"user", b"").decode()
            break
        self.principal = None
        if srv.auth_tokens is not None:
            sock.sendall(_msg(b"R", struct.pack("!I", 3)))  # cleartext
            t, body = self._read_message(sock)
            if t != b"p" or body[:-1].decode() not in srv.auth_tokens:
                sock.sendall(_error("password authentication failed",
                                    "28P01"))
                return False
            self.principal = body[:-1].decode()  # the ACL subject
        sock.sendall(_msg(b"R", struct.pack("!I", 0)))  # AuthenticationOk
        for k, v in (("server_version", "15.0 ydb-tpu"),
                     ("server_encoding", "UTF8"),
                     ("client_encoding", "UTF8"),
                     ("DateStyle", "ISO, YMD"),
                     ("integer_datetimes", "on")):
            sock.sendall(_msg(b"S", _cstr(k) + _cstr(v)))
        backend_id = next(srv._backend_ids)
        sock.sendall(_msg(b"K", struct.pack("!II", backend_id, 0)))
        self._ready(sock)
        return True

    def _read_message(self, sock):
        t = self._read_exact(sock, 1)
        (length,) = struct.unpack("!I", self._read_exact(sock, 4))
        return t, self._read_exact(sock, length - 4)

    def _ready(self, sock):
        sock.sendall(_msg(b"Z", b"I"))

    # -- query loop --

    def _session_loop(self, srv, sock):
        session = srv.cluster.session()
        session.principal = getattr(self, "principal", None)
        skip_to_sync = False
        while True:
            t, body = self._read_message(sock)
            if t == b"X":
                return
            if skip_to_sync:
                if t == b"S":
                    skip_to_sync = False
                    self._ready(sock)
                continue
            if t == b"Q":
                self._simple_query(srv, sock, session,
                                   body.rstrip(b"\x00").decode(
                                       "utf-8", "surrogateescape"))
                self._ready(sock)
            elif t in (b"P", b"B", b"D", b"E", b"C", b"F", b"H"):
                sock.sendall(_error(
                    "extended query protocol not supported; use "
                    "simple query", "0A000"))
                skip_to_sync = True
            elif t == b"S":
                self._ready(sock)
            # anything else (e.g. stray password): ignore

    def _simple_query(self, srv, sock, session, text: str):
        statements = [s.strip() for s in text.split(";")]
        statements = [s for s in statements if s]
        if not statements:
            sock.sendall(_msg(b"I", b""))  # EmptyQueryResponse
            return
        for stmt in statements:
            try:
                with srv.lock:
                    out = session.execute(stmt)
            except Exception as e:  # noqa: BLE001 - wire it to client
                sock.sendall(_error(str(e), "42601"))
                return  # abort rest of the query string (pg semantics)
            if not self._send_result(sock, stmt, out):
                return  # failed DML also aborts the rest

    def _send_result(self, sock, stmt: str, out) -> bool:
        """Sends the per-statement response; False = error sent (the
        caller must abort the rest of the query string, pg semantics)."""
        verb = (stmt.split(None, 1)[0] if stmt.split() else "").upper()
        if out is None:  # DDL
            sock.sendall(_msg(b"C", _cstr(verb or "OK")))
        elif isinstance(out, str):  # EXPLAIN text
            self._send_rowdesc(
                sock, [("QUERY PLAN", dtypes.Kind.STRING, 0)])
            for line in out.splitlines():
                v = line.encode()
                sock.sendall(_msg(
                    b"D", struct.pack("!H", 1)
                    + struct.pack("!I", len(v)) + v))
            sock.sendall(_msg(b"C", _cstr("EXPLAIN")))
        elif isinstance(out, OracleTable):
            self._send_table(sock, out)
        elif isinstance(out, TxResult):
            if not out.committed:
                sock.sendall(_error(out.error or "not committed",
                                    "40001"))
                return False
            tag = ("INSERT 0 0" if verb in ("INSERT", "UPSERT")
                   else verb or "OK")
            sock.sendall(_msg(b"C", _cstr(tag)))
        else:
            sock.sendall(_msg(b"C", _cstr(verb or "OK")))
        return True

    def _send_rowdesc(self, sock, cols):
        parts = [struct.pack("!H", len(cols))]
        for name, kind, _scale in cols:
            oid, typlen = _PG_OID[kind]
            parts.append(
                _cstr(name)
                + struct.pack("!IhIhih", 0, 0, oid, typlen, -1, 0))
        sock.sendall(_msg(b"T", b"".join(parts)))

    def _send_table(self, sock, out: OracleTable):
        fields = list(out.schema.fields)
        self._send_rowdesc(
            sock, [(f.name, f.type.kind, getattr(f.type, "scale", 0))
                   for f in fields])
        n = out.num_rows
        text_cols = []
        for f in fields:
            vals, valid = out.cols[f.name]
            valid = np.asarray(valid, dtype=bool)
            if f.type.is_string:
                decoded = out.strings(f.name)
                col = [None if not valid[i] else
                       decoded[i] for i in range(n)]
            else:
                scale = getattr(f.type, "scale", 0)
                col = [None if not valid[i] else
                       _format_value(f.type.kind, scale, vals[i])
                       for i in range(n)]
            text_cols.append(col)
        for i in range(n):
            parts = [struct.pack("!H", len(fields))]
            for col in text_cols:
                v = col[i]
                if v is None:
                    parts.append(struct.pack("!i", -1))
                else:
                    parts.append(struct.pack("!I", len(v)) + v)
            sock.sendall(_msg(b"D", b"".join(parts)))
        sock.sendall(_msg(b"C", _cstr(f"SELECT {n}")))


class PgWireServer:
    """Threaded PostgreSQL-wire listener over a Cluster.

    ``lock`` serializes statement execution against other front doors;
    pass RequestProxy.lock to co-host with the gRPC server."""

    def __init__(self, cluster, host: str = "127.0.0.1", port: int = 0,
                 auth_tokens: set[str] | None = None,
                 lock: threading.Lock | None = None,
                 idle_timeout: float = 300.0):
        self.cluster = cluster
        self.auth_tokens = auth_tokens
        self.lock = lock if lock is not None else threading.Lock()
        self.idle_timeout = idle_timeout
        self._backend_ids = itertools.count(1)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._server.pg = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "PgWireServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="pgwire")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
