"""PostgreSQL wire-protocol frontend (protocol 3.0, simple query flow).

Mirror of the reference's pgwire compatibility layer
(ydb/core/local_pgwire/local_pgwire_connection.cpp, ydb/core/pgproxy):
a TCP listener that speaks the PostgreSQL v3 message protocol and
routes SQL text into the same in-process session layer the gRPC Query
service uses, so any stock PostgreSQL client (psql, psycopg, JDBC in
simple-query mode) can talk to the cluster.

Supported flow:
  * SSL/GSS negotiation requests (politely refused with 'N'),
  * StartupMessage with optional cleartext-password auth checked
    against the same token set as the gRPC request proxy,
  * ParameterStatus + BackendKeyData + ReadyForQuery handshake,
  * simple Query ('Q') with multi-statement strings, text-format
    results (RowDescription/DataRow/CommandComplete),
  * the extended query protocol: Parse/Bind/Describe/Execute/Close/
    Flush/Sync with text-format $n parameters (inlined at Bind by a
    quote-aware single-pass scanner; Parse-time type OIDs honored,
    the unspecified-OID numeric heuristic documented in
    _render_param), Execute row limits with PortalSuspended, portals
    surviving Sync inside explicit transactions. Describe(portal)
    returns the real row shape; Describe(statement) answers NoData
    (drivers needing statement-level metadata — JDBC default flow —
    must describe the portal). Describe(statement) answers the
    declared parameter oids plus the PLANNED row shape (the JDBC
    PreparedStatement.getMetaData path), and Bind may request binary
    result formats for int/float/bool/text columns (fixed-width
    network-order; text bytes are format-invariant). Binary parameter formats are
    rejected with clear errors,
  * CancelRequest (connection-level no-op), Terminate ('X').

Every connection owns one session. Cluster state is single-writer, so
DDL/DML/transaction statements serialize on the shared lock (the same
contract as api/server.RequestProxy.lock) — but read statements
(SELECT/EXPLAIN, outside an open transaction) execute WITHOUT it, so
concurrent connections co-occupy the cross-query batch window
(kqp/batch.py) and compatible SELECTs from different sockets share one
device dispatch. Tenancy: a ``tenant`` startup parameter (or the
authenticated principal's binding) resolves through the cluster front
door (serving/), and every live connection is a ``serving.conn``
leak-sanitizer handle asserted drained on disconnect.
"""

from __future__ import annotations

import itertools
import socket
import socketserver
import struct
import threading

import numpy as np

from ydb_tpu import dtypes, serving
from ydb_tpu.analysis import leaksan
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.tx.coordinator import TxResult

_PROTO_V3 = 196608        # 3.0
_SSL_REQUEST = 80877103
_GSSENC_REQUEST = 80877104
_CANCEL_REQUEST = 80877102

# (type oid, typlen) per logical kind; values always travel in text
# format, the oid is what drives client-side parsing
_PG_OID = {
    dtypes.Kind.BOOL: (16, 1),
    dtypes.Kind.INT8: (21, 2),
    dtypes.Kind.INT16: (21, 2),
    dtypes.Kind.INT32: (23, 4),
    dtypes.Kind.INT64: (20, 8),
    dtypes.Kind.UINT8: (21, 2),
    dtypes.Kind.UINT16: (23, 4),
    dtypes.Kind.UINT32: (20, 8),
    dtypes.Kind.UINT64: (20, 8),
    dtypes.Kind.FLOAT: (700, 4),
    dtypes.Kind.DOUBLE: (701, 8),
    dtypes.Kind.DATE: (1082, 4),
    dtypes.Kind.TIMESTAMP: (1114, 8),
    dtypes.Kind.DECIMAL: (1700, -1),
    dtypes.Kind.STRING: (25, -1),
}


class _PgError(Exception):
    def __init__(self, message: str, code: str = "XX000"):
        super().__init__(message)
        self.code = code


class _SkipToSync(Exception):
    """An ErrorResponse was already sent; discard until Sync."""


class _Cursor:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def take(self, n: int) -> bytes:
        b = self.buf[self.off:self.off + n]
        self.off += n
        return b

    def cstr(self) -> str:
        end = self.buf.index(b"\x00", self.off)
        s = self.buf[self.off:end].decode("utf-8", "surrogateescape")
        self.off = end + 1
        return s

    def u16(self) -> int:
        return struct.unpack("!H", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack("!i", self.take(4))[0]


import re as _re

_NUMERIC_RE = _re.compile(r"^[+-]?(\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?$")

# pg type OIDs whose text form may inline unquoted
_NUMERIC_OIDS = {20, 21, 23, 26, 700, 701, 1700}
_TEXTUAL_OIDS = {25, 1043, 1042, 18, 19}


def _render_param(raw: bytes, oid: int) -> str:
    if raw is None:
        return "NULL"
    text = raw.decode("utf-8", "surrogateescape")
    if oid in _NUMERIC_OIDS:
        if not _NUMERIC_RE.match(text):
            raise _PgError(f"invalid numeric parameter {text!r}",
                           "22P02")
        return text
    if oid == 0 and _NUMERIC_RE.match(text):
        # unspecified type: numeric-looking text inlines unquoted (a
        # documented heuristic — drivers that mean the STRING '42'
        # should declare a text OID at Parse time)
        return text
    return "'" + text.replace("'", "''") + "'"


def _substitute_params(query: str, params: list,
                       oids: list[int]) -> str:
    """Inline text-format parameters into $n placeholders with ONE
    linear scan that tracks quoting: placeholders inside string
    literals stay untouched, and inlined values are emitted as opaque
    units that are never re-scanned (no nested re-substitution, no
    quote breakout from parameter contents)."""
    rendered = [
        _render_param(p, oids[i] if i < len(oids) else 0)
        for i, p in enumerate(params)
    ]
    out = []
    i = 0
    n = len(query)
    in_quote = False
    while i < n:
        ch = query[i]
        if in_quote:
            out.append(ch)
            if ch == "'":
                # doubled quote = escaped quote inside the literal
                if i + 1 < n and query[i + 1] == "'":
                    out.append("'")
                    i += 1
                else:
                    in_quote = False
            i += 1
            continue
        if ch == "'":
            in_quote = True
            out.append(ch)
            i += 1
            continue
        if ch == "$" and i + 1 < n and query[i + 1].isdigit():
            j = i + 1
            while j < n and query[j].isdigit():
                j += 1
            idx = int(query[i + 1:j])
            if not 1 <= idx <= len(rendered):
                raise _PgError(
                    f"there is no parameter ${idx}", "08P01")
            out.append(rendered[idx - 1])
            i = j
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def _msg(type_byte: bytes, payload: bytes) -> bytes:
    return type_byte + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode("utf-8", "surrogateescape") + b"\x00"


def _error(message: str, code: str = "XX000") -> bytes:
    fields = (b"S" + _cstr("ERROR") + b"V" + _cstr("ERROR")
              + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00")
    return _msg(b"E", fields)


def _col_fmt(res_fmts, ci: int) -> int:
    """Per-column result format from Bind's codes: none = text, one =
    applies to all, else positional (pg protocol 3.0 semantics)."""
    if not res_fmts:
        return 0
    if len(res_fmts) == 1:
        return res_fmts[0]
    return res_fmts[ci] if ci < len(res_fmts) else 0


# binary result encodings per kind (JDBC's binary transfer mode):
# network-order fixed-width for ints/floats/bool; text (same bytes)
# for strings. Kinds absent here refuse binary with 0A000.
_BIN_PACK = {
    dtypes.Kind.INT8: "!h", dtypes.Kind.INT16: "!h",
    dtypes.Kind.UINT8: "!h", dtypes.Kind.INT32: "!i",
    dtypes.Kind.UINT16: "!i", dtypes.Kind.INT64: "!q",
    dtypes.Kind.UINT32: "!q",
    dtypes.Kind.FLOAT: "!f", dtypes.Kind.DOUBLE: "!d",
}


def _binary_value(kind: dtypes.Kind, v) -> bytes:
    pack = _BIN_PACK.get(kind)
    if pack is not None:
        return struct.pack(
            pack, float(v) if pack in ("!f", "!d") else int(v))
    if kind == dtypes.Kind.BOOL:
        return b"\x01" if v else b"\x00"
    # UINT64 deliberately absent: its advertised oid is 20 (signed
    # int8), so a '!Q' payload >= 2^63 would silently decode negative
    raise _PgError(
        f"binary result format not supported for {kind.name}", "0A000")


def _format_value(kind: dtypes.Kind, scale: int, v) -> bytes:
    if kind == dtypes.Kind.BOOL:
        return b"t" if v else b"f"
    if kind == dtypes.Kind.DATE:
        return str(np.datetime64(int(v), "D")).encode()
    if kind == dtypes.Kind.TIMESTAMP:
        return str(np.datetime64(int(v), "us")).encode().replace(
            b"T", b" ")
    if kind == dtypes.Kind.DECIMAL:
        import decimal as pydec

        return str(pydec.Decimal(int(v)).scaleb(-scale)).encode()
    if kind in (dtypes.Kind.FLOAT, dtypes.Kind.DOUBLE):
        return f"{float(v):.17g}".encode()
    return str(int(v)).encode()


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):  # noqa: C901 - one protocol, one state machine
        srv: PgWireServer = self.server.pg  # type: ignore[attr-defined]
        sock = self.request
        sock.settimeout(srv.idle_timeout)
        try:
            if not self._startup(srv, sock):
                return
            self._session_loop(srv, sock)
        except (ConnectionError, socket.timeout, OSError):
            pass

    # -- startup / auth --

    def _read_exact(self, sock, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client closed")
            buf += chunk
        return buf

    def _startup(self, srv, sock) -> bool:
        while True:
            (length,) = struct.unpack("!I", self._read_exact(sock, 4))
            payload = self._read_exact(sock, length - 4)
            (code,) = struct.unpack("!I", payload[:4])
            if code in (_SSL_REQUEST, _GSSENC_REQUEST):
                sock.sendall(b"N")  # not supported, retry in clear
                continue
            if code == _CANCEL_REQUEST:
                return False  # per protocol: no response, just close
            if code != _PROTO_V3:
                sock.sendall(_error(
                    f"unsupported protocol {code >> 16}.{code & 0xffff}",
                    "0A000"))
                return False
            params = payload[4:].split(b"\x00")
            kv = dict(zip(params[0::2], params[1::2]))
            self.user = kv.get(b"user", b"").decode()
            # arbitrary startup parameters ride here; "tenant" routes
            # the connection to its workload pool (serving/tenants.py)
            self.startup_kv = kv
            break
        self.principal = None
        if srv.auth_tokens is not None:
            sock.sendall(_msg(b"R", struct.pack("!I", 3)))  # cleartext
            t, body = self._read_message(sock)
            if t != b"p" or body[:-1].decode() not in srv.auth_tokens:
                sock.sendall(_error("password authentication failed",
                                    "28P01"))
                return False
            self.principal = body[:-1].decode()  # the ACL subject
        sock.sendall(_msg(b"R", struct.pack("!I", 0)))  # AuthenticationOk
        for k, v in (("server_version", "15.0 ydb-tpu"),
                     ("server_encoding", "UTF8"),
                     ("client_encoding", "UTF8"),
                     ("DateStyle", "ISO, YMD"),
                     ("integer_datetimes", "on")):
            sock.sendall(_msg(b"S", _cstr(k) + _cstr(v)))
        backend_id = next(srv._backend_ids)
        sock.sendall(_msg(b"K", struct.pack("!II", backend_id, 0)))
        self._ready(sock)
        return True

    def _read_message(self, sock):
        t = self._read_exact(sock, 1)
        (length,) = struct.unpack("!I", self._read_exact(sock, 4))
        return t, self._read_exact(sock, length - 4)

    def _ready(self, sock):
        sock.sendall(_msg(b"Z", b"I"))

    # -- query loop --

    def _session_loop(self, srv, sock):
        session = srv.cluster.session()
        session.principal = getattr(self, "principal", None)
        kv = getattr(self, "startup_kv", {})
        hint = kv.get(b"tenant", b"").decode() or None
        session.tenant = serving.resolve_tenant(
            srv.cluster, tenant=hint, principal=session.principal)
        conn = leaksan.track(
            "serving.conn", f"pgwire:{session.tenant}")
        try:
            self._message_loop(srv, sock, session)
        finally:
            leaksan.close(conn)

    def _message_loop(self, srv, sock, session):
        skip_to_sync = False
        statements: dict[str, dict] = {}  # Parse'd prepared statements
        portals: dict[str, dict] = {}     # Bind'd portals
        while True:
            t, body = self._read_message(sock)
            if t == b"X":
                return
            if skip_to_sync:
                if t == b"S":
                    skip_to_sync = False
                    if session._tx is None:
                        portals.clear()
                    self._ready(sock)
                continue
            try:
                if t == b"Q":
                    self._simple_query(srv, sock, session,
                                       body.rstrip(b"\x00").decode(
                                           "utf-8", "surrogateescape"))
                    self._ready(sock)
                elif t == b"P":
                    self._parse_msg(body, statements)
                    sock.sendall(_msg(b"1", b""))  # ParseComplete
                elif t == b"B":
                    self._bind_msg(body, statements, portals)
                    sock.sendall(_msg(b"2", b""))  # BindComplete
                elif t == b"D":
                    self._describe_msg(srv, sock, session, body,
                                       statements, portals)
                elif t == b"E":
                    self._execute_msg(srv, sock, session, body, portals)
                elif t == b"C":  # Close statement/portal
                    kind, name = body[0:1], body[1:-1].decode()
                    (statements if kind == b"S" else portals).pop(
                        name, None)
                    sock.sendall(_msg(b"3", b""))  # CloseComplete
                elif t == b"H":  # Flush: everything is already sent
                    pass
                elif t == b"S":
                    # Sync ends the implicit transaction and its
                    # portals; inside an explicit BEGIN they survive
                    # (libpq cursor-style fetch loops rely on this)
                    if session._tx is None:
                        portals.clear()
                    self._ready(sock)
            except _SkipToSync:
                skip_to_sync = True  # error already on the wire
            except _PgError as e:
                sock.sendall(_error(str(e), e.code))
                skip_to_sync = True
            except (ConnectionError, OSError):
                raise
            except Exception as e:  # noqa: BLE001 - wire it to client
                sock.sendall(_error(str(e), "XX000"))
                skip_to_sync = True
            # anything else (e.g. stray password): ignore

    # -- extended query protocol (Parse/Bind/Describe/Execute) --

    def _parse_msg(self, body: bytes, statements: dict) -> None:
        r = _Cursor(body)
        name = r.cstr()
        query = r.cstr()
        n_oids = r.u16()
        oids = [struct.unpack("!I", r.take(4))[0]
                for _ in range(n_oids)]
        statements[name] = {"query": query, "oids": oids}

    def _bind_msg(self, body: bytes, statements: dict,
                  portals: dict) -> None:
        r = _Cursor(body)
        portal = r.cstr()
        stmt_name = r.cstr()
        stmt = statements.get(stmt_name)
        if stmt is None:
            raise _PgError(f"unknown prepared statement "
                           f"{stmt_name!r}", "26000")
        n_fmt = r.u16()
        fmts = [r.u16() for _ in range(n_fmt)]
        n_params = r.u16()
        params = []
        for i in range(n_params):
            ln = r.i32()
            raw = None if ln == -1 else r.take(ln)
            fmt = fmts[i] if i < len(fmts) else (fmts[0] if fmts else 0)
            if raw is not None and fmt != 0:
                raise _PgError("binary parameters not supported",
                               "0A000")
            params.append(raw)
        n_res = r.u16()
        res_fmts = [r.u16() for _ in range(n_res)]
        sql = _substitute_params(stmt["query"], params, stmt["oids"])
        portals[portal] = {"sql": sql, "result": None, "done": False,
                           "described": stmt.get("described_s", False),
                           "sent": 0, "complete": False,
                           "res_fmts": res_fmts}

    def _exec_stmt(self, srv, session, sql: str):
        """Run one statement with the right concurrency contract:
        reads (outside an open transaction) execute without the
        server's write lock so concurrent connections can co-occupy
        the batch window; everything that can mutate cluster state
        keeps the single-writer lock."""
        if getattr(session, "_tx", None) is None \
                and serving.is_read_statement(sql):
            return session.execute(sql)
        with srv.lock:
            return session.execute(sql)

    def _run_portal(self, srv, session, portal: dict) -> None:
        if portal["done"]:
            return
        portal["result"] = self._exec_stmt(srv, session, portal["sql"])
        portal["done"] = True
        # reject unsupported binary columns NOW — a clean ErrorResponse
        # before any RowDescription/DataRow reaches the wire
        out = portal["result"]
        fmts = portal.get("res_fmts")
        if fmts and isinstance(out, OracleTable):
            for ci, f in enumerate(out.schema.fields):
                if _col_fmt(fmts, ci) == 1 and not f.type.is_string \
                        and f.type.kind not in _BIN_PACK \
                        and f.type.kind != dtypes.Kind.BOOL:
                    raise _PgError(
                        f"binary result format not supported for "
                        f"{f.type.kind.name}", "0A000")

    def _describe_msg(self, srv, sock, session, body, statements,
                      portals) -> None:
        kind, name = body[0:1], body[1:-1].decode()
        if kind == b"S":
            stmt = statements.get(name)
            if stmt is None:
                raise _PgError(f"unknown prepared statement {name!r}",
                               "26000")
            # ParameterDescription: the oids Parse declared
            oids = stmt["oids"]
            sock.sendall(_msg(b"t", struct.pack(
                "!H", len(oids)) + b"".join(
                struct.pack("!I", o) for o in oids)))
            cols = self._statement_row_shape(srv, stmt)
            if cols is None:
                sock.sendall(_msg(b"n", b""))  # NoData
            else:
                self._send_rowdesc(sock, cols)
                # the client HAS the shape: Execute on portals of this
                # statement must not inject a duplicate RowDescription
                stmt["described_s"] = True
            return
        portal = portals.get(name)
        if portal is None:
            raise _PgError(f"unknown portal {name!r}", "34000")
        # the portal runs here (once); Execute streams the cached
        # result — Describe must announce the real row shape
        self._run_portal(srv, session, portal)
        out = portal["result"]
        if isinstance(out, OracleTable):
            self._send_rowdesc(
                sock, [(f.name, f.type.kind,
                        getattr(f.type, "scale", 0))
                       for f in out.schema.fields],
                res_fmts=portal.get("res_fmts"))
            portal["described"] = True
        else:
            sock.sendall(_msg(b"n", b""))  # NoData (DML/DDL)

    def _statement_row_shape(self, srv, stmt):
        """Row shape of a prepared statement WITHOUT executing it (the
        JDBC PreparedStatement.getMetaData path): plan against the
        catalog with type-appropriate dummy parameters. Result column
        types come from the catalog, not the parameter values, so the
        dummies do not distort the shape. None = NoData (DML/DDL,
        or a shape we cannot plan without execution)."""
        try:
            from ydb_tpu.sql import ast as _ast
            from ydb_tpu.sql.parser import parse as _parse
            from ydb_tpu.sql.planner import plan_select_full

            n_params = len(set(_re.findall(r"\$(\d+)",
                                           stmt["query"])))
            dummies = []
            for i in range(n_params):
                oid = (stmt["oids"][i]
                       if i < len(stmt["oids"]) else 25)
                dummies.append(b"" if oid == 25 else b"0")
            sql = _substitute_params(stmt["query"], dummies,
                                     stmt["oids"])
            parsed = _parse(sql)
            if not isinstance(parsed, (_ast.Select, _ast.UnionAll)):
                return None
            with srv.lock:
                pq = plan_select_full(parsed,
                                      srv.cluster.catalog())
            return [(n, pq.out_types[n].kind,
                     getattr(pq.out_types[n], "scale", 0))
                    for n in pq.out_names]
        except Exception:  # noqa: BLE001 - fall back to NoData
            return None

    def _execute_msg(self, srv, sock, session, body, portals) -> None:
        r = _Cursor(body)
        name = r.cstr()
        max_rows = r.i32()
        portal = portals.get(name)
        if portal is None:
            raise _PgError(f"unknown portal {name!r}", "34000")
        self._run_portal(srv, session, portal)
        out = portal["result"]
        if isinstance(out, OracleTable):
            if portal["complete"]:  # re-Execute after completion:
                sock.sendall(_msg(b"C", _cstr("SELECT 0")))
                return
            n = out.num_rows
            start = portal["sent"]
            take = (n - start if max_rows <= 0
                    else min(max_rows, n - start))
            self._send_table(sock, out,
                             with_rowdesc=not portal["described"],
                             start=start, limit=take,
                             send_complete=False,
                             res_fmts=portal.get("res_fmts"))
            portal["described"] = True  # shape announced at most once
            portal["sent"] = start + take
            if portal["sent"] >= n:
                portal["complete"] = True
                sock.sendall(_msg(b"C", _cstr(f"SELECT {take}")))
            else:
                sock.sendall(_msg(b"s", b""))  # PortalSuspended
            return
        if portal["complete"]:
            # effects applied exactly once; re-Execute re-acks only
            verb = (portal["sql"].split(None, 1)[0]
                    if portal["sql"].split() else "OK").upper()
            sock.sendall(_msg(b"C", _cstr(verb)))
            return
        ok = self._send_result(sock, portal["sql"], out,
                               with_rowdesc=False)
        portal["complete"] = True
        if not ok:
            raise _SkipToSync()

    def _simple_query(self, srv, sock, session, text: str):
        statements = [s.strip() for s in text.split(";")]
        statements = [s for s in statements if s]
        if not statements:
            sock.sendall(_msg(b"I", b""))  # EmptyQueryResponse
            return
        for stmt in statements:
            try:
                out = self._exec_stmt(srv, session, stmt)
            except Exception as e:  # noqa: BLE001 - wire it to client
                sock.sendall(_error(str(e), "42601"))
                return  # abort rest of the query string (pg semantics)
            if not self._send_result(sock, stmt, out):
                return  # failed DML also aborts the rest

    def _send_result(self, sock, stmt: str, out,
                     with_rowdesc: bool = True) -> bool:
        """Sends the per-statement response; False = error sent (the
        caller must abort the rest of the query string, pg semantics).
        ``with_rowdesc=False`` for the extended protocol, where
        RowDescription only answers Describe."""
        verb = (stmt.split(None, 1)[0] if stmt.split() else "").upper()
        if out is None:  # DDL
            sock.sendall(_msg(b"C", _cstr(verb or "OK")))
        elif isinstance(out, str):  # EXPLAIN text
            if with_rowdesc:
                self._send_rowdesc(
                    sock, [("QUERY PLAN", dtypes.Kind.STRING, 0)])
            for line in out.splitlines():
                v = line.encode()
                sock.sendall(_msg(
                    b"D", struct.pack("!H", 1)
                    + struct.pack("!I", len(v)) + v))
            sock.sendall(_msg(b"C", _cstr("EXPLAIN")))
        elif isinstance(out, OracleTable):
            self._send_table(sock, out, with_rowdesc=with_rowdesc)
        elif isinstance(out, TxResult):
            if not out.committed:
                sock.sendall(_error(out.error or "not committed",
                                    "40001"))
                return False
            tag = ("INSERT 0 0" if verb in ("INSERT", "UPSERT")
                   else verb or "OK")
            sock.sendall(_msg(b"C", _cstr(tag)))
        else:
            sock.sendall(_msg(b"C", _cstr(verb or "OK")))
        return True

    def _send_rowdesc(self, sock, cols, res_fmts=None):
        parts = [struct.pack("!H", len(cols))]
        for ci, (name, kind, _scale) in enumerate(cols):
            oid, typlen = _PG_OID[kind]
            fmt = _col_fmt(res_fmts, ci)
            parts.append(
                _cstr(name)
                + struct.pack("!IhIhih", 0, 0, oid, typlen, -1, fmt))
        sock.sendall(_msg(b"T", b"".join(parts)))

    def _send_table(self, sock, out: OracleTable,
                    with_rowdesc: bool = True, start: int = 0,
                    limit: int | None = None,
                    send_complete: bool = True,
                    res_fmts=None):
        fields = list(out.schema.fields)
        if with_rowdesc:
            self._send_rowdesc(
                sock,
                [(f.name, f.type.kind, getattr(f.type, "scale", 0))
                 for f in fields], res_fmts=res_fmts)
        n = out.num_rows
        hi = n if limit is None else min(n, start + limit)
        text_cols = []
        for ci, f in enumerate(fields):
            vals, valid = out.cols[f.name]
            valid = np.asarray(valid, dtype=bool)
            binary = _col_fmt(res_fmts, ci) == 1
            if f.type.is_string:
                # text and binary wire forms of text ARE the same bytes
                decoded = out.strings(f.name)
                col = [None if not valid[i] else
                       decoded[i] for i in range(start, hi)]
            elif binary:
                col = [None if not valid[i] else
                       _binary_value(f.type.kind, vals[i])
                       for i in range(start, hi)]
            else:
                scale = getattr(f.type, "scale", 0)
                col = [None if not valid[i] else
                       _format_value(f.type.kind, scale, vals[i])
                       for i in range(start, hi)]
            text_cols.append(col)
        for i in range(hi - start):
            parts = [struct.pack("!H", len(fields))]
            for col in text_cols:
                v = col[i]
                if v is None:
                    parts.append(struct.pack("!i", -1))
                else:
                    parts.append(struct.pack("!I", len(v)) + v)
            sock.sendall(_msg(b"D", b"".join(parts)))
        if send_complete:
            sock.sendall(_msg(b"C", _cstr(f"SELECT {hi - start}")))


class PgWireServer:
    """Threaded PostgreSQL-wire listener over a Cluster.

    ``lock`` serializes statement execution against other front doors;
    pass RequestProxy.lock to co-host with the gRPC server."""

    def __init__(self, cluster, host: str = "127.0.0.1", port: int = 0,
                 auth_tokens: set[str] | None = None,
                 lock: threading.Lock | None = None,
                 idle_timeout: float = 300.0):
        self.cluster = cluster
        self.auth_tokens = auth_tokens
        self.lock = lock if lock is not None else threading.Lock()
        self.idle_timeout = idle_timeout
        self._backend_ids = itertools.count(1)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._server.pg = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "PgWireServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="pgwire")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
