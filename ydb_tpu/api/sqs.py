"""SQS-compatible HTTP queue proxy over the topic (PersQueue) plane.

Mirror of the reference's message-queue surface (ydb/core/ymq — the
SQS-compatible queue service — and core/http_proxy routing HTTP
requests into it; SURVEY.md §2.12 row "SQS/HTTP proxy"): an HTTP
listener speaking the AWS SQS JSON protocol (X-Amz-Target:
AmazonSQS.<Action>, POST application/x-amz-json-1.0) so stock SQS
clients and plain HTTP callers can use the framework as a queue.

Queue semantics over topics:
  * a queue is a single-partition topic + a per-queue consumer;
  * ReceiveMessage leases messages for ``VisibilityTimeout`` seconds:
    a message delivered but not deleted reappears after the timeout
    (at-least-once, like SQS standard queues);
  * DeleteMessage acks by receipt handle; the consumer's committed
    offset advances over a prefix of deleted messages, so the durable
    state is the PQ commit plus a small in-flight lease table;
  * ApproximateNumberOfMessages = topic backlog minus committed.

Supported actions: CreateQueue, DeleteQueue, ListQueues, GetQueueUrl,
SendMessage, ReceiveMessage, DeleteMessage, PurgeQueue,
GetQueueAttributes.
"""

from __future__ import annotations

import json
import secrets
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ydb_tpu.engine.blobs import BlobStore, MemBlobStore
from ydb_tpu.topic.topic import Topic


class SqsError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


class _Queue:
    """One SQS queue = one single-partition topic + lease table."""

    def __init__(self, name: str, store: BlobStore, now=time.time,
                 visibility_timeout: float = 30.0):
        self.name = name
        self.topic = Topic(f"sqs/{name}", store, n_partitions=1)
        self.part = self.topic.partitions[0]
        self.now = now
        self.visibility_timeout = visibility_timeout
        # offset -> (receipt_handle, invisible_until)
        self.leases: dict[int, tuple[str, float]] = {}
        self.deleted: set[int] = set()

    def send(self, body: str, attributes: dict | None = None) -> str:
        payload = json.dumps({"body": body,
                              "attributes": attributes or {}})
        offs = self.part.write([{"data": payload}])
        return f"{self.name}-{offs[0]}"

    def _advance_commit(self) -> None:
        """Commit the consumer offset over the deleted prefix."""
        committed = self.part.committed("sqs")
        while committed in self.deleted:
            self.deleted.discard(committed)
            committed += 1
        self.part.commit("sqs", committed)

    def receive(self, max_messages: int = 1,
                visibility_timeout: float | None = None) -> list[dict]:
        now = self.now()
        vis = (visibility_timeout if visibility_timeout is not None
               else self.visibility_timeout)
        out = []
        start = self.part.committed("sqs")
        for msg in self.part.read(start, limit=max(64, max_messages)):
            off = msg["offset"]
            if off in self.deleted:
                continue
            lease = self.leases.get(off)
            if lease is not None and lease[1] > now:
                continue  # still invisible to other consumers
            handle = secrets.token_hex(12)
            self.leases[off] = (handle, now + vis)
            payload = json.loads(msg["data"])
            out.append({
                "MessageId": f"{self.name}-{off}",
                "ReceiptHandle": handle,
                "Body": payload["body"],
                "Attributes": payload["attributes"],
            })
            if len(out) >= max_messages:
                break
        return out

    def delete(self, receipt_handle: str) -> None:
        for off, (handle, _until) in list(self.leases.items()):
            if handle == receipt_handle:
                del self.leases[off]
                self.deleted.add(off)
                self._advance_commit()
                return
        raise SqsError("ReceiptHandleIsInvalid",
                       f"no in-flight message for {receipt_handle!r}")

    def purge(self) -> None:
        self.leases.clear()
        self.deleted.clear()
        self.part.commit("sqs", self.part.head_offset)

    def attributes(self) -> dict:
        backlog = self.part.head_offset - self.part.committed("sqs")
        in_flight = sum(1 for _off, (_h, until) in self.leases.items()
                        if until > self.now())
        return {
            "ApproximateNumberOfMessages":
                str(max(0, backlog - len(self.deleted) - in_flight)),
            "ApproximateNumberOfMessagesNotVisible": str(in_flight),
            "VisibilityTimeout": str(int(self.visibility_timeout)),
        }


class SqsService:
    """Action dispatch, shared by the HTTP front and direct callers."""

    def __init__(self, store: BlobStore | None = None, now=time.time,
                 base_url: str = "http://localhost"):
        self.store = store if store is not None else MemBlobStore()
        self.now = now
        self.base_url = base_url
        self.queues: dict[str, _Queue] = {}

    def _queue(self, params: dict) -> _Queue:
        url = params.get("QueueUrl", "")
        name = params.get("QueueName") or url.rsplit("/", 1)[-1]
        q = self.queues.get(name)
        if q is None:
            raise SqsError("QueueDoesNotExist", f"no queue {name!r}")
        return q

    def dispatch(self, action: str, params: dict) -> dict:
        fn = getattr(self, f"_do_{action.lower()}", None)
        if fn is None:
            raise SqsError("InvalidAction", f"unknown action {action}")
        return fn(params)

    def _url(self, name: str) -> str:
        return f"{self.base_url}/queue/{name}"

    def _do_createqueue(self, p: dict) -> dict:
        name = p["QueueName"]
        if name not in self.queues:
            attrs = p.get("Attributes", {})
            vis = float(attrs.get("VisibilityTimeout", 30))
            self.queues[name] = _Queue(name, self.store, now=self.now,
                                       visibility_timeout=vis)
        return {"QueueUrl": self._url(name)}

    def _do_deletequeue(self, p: dict) -> dict:
        self.queues.pop(self._queue(p).name, None)
        return {}

    def _do_listqueues(self, p: dict) -> dict:
        prefix = p.get("QueueNamePrefix", "")
        return {"QueueUrls": [self._url(n) for n in sorted(self.queues)
                              if n.startswith(prefix)]}

    def _do_getqueueurl(self, p: dict) -> dict:
        return {"QueueUrl": self._url(self._queue(p).name)}

    def _do_sendmessage(self, p: dict) -> dict:
        q = self._queue(p)
        mid = q.send(p["MessageBody"],
                     p.get("MessageAttributes"))
        return {"MessageId": mid}

    def _do_receivemessage(self, p: dict) -> dict:
        q = self._queue(p)
        msgs = q.receive(
            max_messages=int(p.get("MaxNumberOfMessages", 1)),
            visibility_timeout=(
                float(p["VisibilityTimeout"])
                if "VisibilityTimeout" in p else None))
        return {"Messages": msgs}

    def _do_deletemessage(self, p: dict) -> dict:
        self._queue(p).delete(p["ReceiptHandle"])
        return {}

    def _do_purgequeue(self, p: dict) -> dict:
        self._queue(p).purge()
        return {}

    def _do_getqueueattributes(self, p: dict) -> dict:
        return {"Attributes": self._queue(p).attributes()}


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def do_POST(self):  # noqa: N802 - http.server API
        srv: SqsHttpServer = self.server.sqs  # type: ignore[attr-defined]
        target = self.headers.get("X-Amz-Target", "")
        action = target.split(".")[-1] if "." in target else target
        length = int(self.headers.get("Content-Length", 0))
        try:
            params = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            self._reply(400, {"__type": "InvalidRequest",
                              "message": "bad JSON"})
            return
        if not action:
            action = params.pop("Action", "")
        try:
            with srv.lock:
                out = srv.service.dispatch(action, params)
            self._reply(200, out)
        except SqsError as e:
            self._reply(400, {"__type": e.code, "message": str(e)})
        except Exception as e:  # noqa: BLE001 - surface, don't die
            self._reply(500, {"__type": "InternalFailure",
                              "message": repr(e)})

    def _reply(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/x-amz-json-1.0")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class SqsHttpServer:
    """Threaded SQS-wire HTTP listener."""

    def __init__(self, store: BlobStore | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 lock: threading.Lock | None = None, now=time.time):
        self.lock = lock if lock is not None else threading.Lock()
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.sqs = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self.service = SqsService(
            store, now=now, base_url=f"http://{host}:{self.port}")
        self._thread: threading.Thread | None = None

    def start(self) -> "SqsHttpServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="sqs")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
