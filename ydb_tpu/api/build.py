"""protoc codegen on demand (cached by mtime), mirroring the native
library's build-at-import pattern."""

from __future__ import annotations

import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
PROTO = os.path.join(_DIR, "protos", "ydb_tpu_api.proto")
GEN_DIR = os.path.join(_DIR, "_gen")
GEN = os.path.join(GEN_DIR, "ydb_tpu_api_pb2.py")


def ensure_protos():
    if not (os.path.exists(GEN) and
            os.path.getmtime(GEN) >= os.path.getmtime(PROTO)):
        os.makedirs(GEN_DIR, exist_ok=True)
        open(os.path.join(GEN_DIR, "__init__.py"), "a").close()
        subprocess.run(
            ["protoc", f"--python_out={GEN_DIR}",
             f"--proto_path={os.path.dirname(PROTO)}",
             os.path.basename(PROTO)],
            check=True, capture_output=True, timeout=60,
        )
    import importlib
    import sys

    if GEN_DIR not in sys.path:
        sys.path.insert(0, GEN_DIR)
    return importlib.import_module("ydb_tpu_api_pb2")
