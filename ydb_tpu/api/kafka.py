"""Kafka wire-protocol frontend over the topic (PersQueue) plane.

Mirror of the reference's Kafka compatibility proxy
(ydb/core/kafka_proxy/kafka_connection.cpp, actors/): a TCP listener
speaking the Kafka binary protocol so stock Kafka clients can produce
to and consume from the framework's topics. Topics map 1:1 to
``cluster.topics`` entries; Kafka consumer groups map to PersQueue
consumers (committed offset == next-to-read in both models, so offsets
pass through unchanged).

Supported APIs (pinned to pre-flexible versions, so the framing is the
classic fixed one — the same subset the reference proxy started with):

  ApiVersions v0, Metadata v1, Produce v2 (MessageSet v1 with CRC
  verification), Fetch v2, ListOffsets v1 (earliest/latest),
  FindCoordinator v0, OffsetCommit v2, OffsetFetch v1,
  SaslHandshake v1 + SaslAuthenticate v0 (PLAIN, password = cluster
  auth token; all other APIs reject until authenticated when a token
  set is configured).

Message values and keys are bytes on the wire; the PQ plane stores
both as str, so they round-trip via UTF-8 with surrogateescape
(exactly like the gRPC topic service, api/server.py topic_write).
"""

from __future__ import annotations

import socketserver
import struct
import threading
import zlib

ERR_NONE = 0
ERR_UNKNOWN_TOPIC = 3
ERR_CORRUPT_MESSAGE = 2
ERR_UNSUPPORTED_VERSION = 35
ERR_SASL_AUTH_FAILED = 58
ERR_ILLEGAL_SASL_STATE = 34

_SUPPORTED = {
    0: (2, 2),    # Produce
    1: (2, 2),    # Fetch
    2: (1, 1),    # ListOffsets
    3: (1, 1),    # Metadata
    8: (2, 2),    # OffsetCommit
    9: (1, 1),    # OffsetFetch
    10: (0, 0),   # FindCoordinator
    17: (1, 1),   # SaslHandshake
    18: (0, 0),   # ApiVersions
    36: (0, 0),   # SaslAuthenticate
}

# APIs allowed before SASL authentication completes (when auth is on)
_PRE_AUTH_APIS = {17, 18, 36}


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def _take(self, n: int) -> bytes:
        b = self.buf[self.off:self.off + n]
        if len(b) < n:
            raise ValueError("short kafka message")
        self.off += n
        return b

    def int8(self) -> int:
        return struct.unpack("!b", self._take(1))[0]

    def int16(self) -> int:
        return struct.unpack("!h", self._take(2))[0]

    def int32(self) -> int:
        return struct.unpack("!i", self._take(4))[0]

    def int64(self) -> int:
        return struct.unpack("!q", self._take(8))[0]

    def string(self) -> str | None:
        n = self.int16()
        if n == -1:
            return None
        return self._take(n).decode("utf-8", "surrogateescape")

    def bytes_(self) -> bytes | None:
        n = self.int32()
        if n == -1:
            return None
        return self._take(n)

    def array(self, fn) -> list:
        n = self.int32()
        if n == -1:
            return []
        return [fn() for _ in range(n)]


class _Writer:
    def __init__(self):
        self.parts: list[bytes] = []

    def int8(self, v):
        self.parts.append(struct.pack("!b", v))

    def int16(self, v):
        self.parts.append(struct.pack("!h", v))

    def int32(self, v):
        self.parts.append(struct.pack("!i", v))

    def int64(self, v):
        self.parts.append(struct.pack("!q", v))

    def string(self, v: str | None):
        if v is None:
            self.int16(-1)
        else:
            b = v.encode("utf-8", "surrogateescape")
            self.int16(len(b))
            self.parts.append(b)

    def bytes_(self, v: bytes | None):
        if v is None:
            self.int32(-1)
        else:
            self.int32(len(v))
            self.parts.append(v)

    def array(self, items, fn):
        self.int32(len(items))
        for it in items:
            fn(it)

    def blob(self) -> bytes:
        return b"".join(self.parts)


# ---- MessageSet v1 (magic 1) ----


def _encode_message(offset: int, ts_ms: int, key: bytes | None,
                    value: bytes | None) -> bytes:
    body = _Writer()
    body.int8(1)          # magic
    body.int8(0)          # attributes (no compression)
    body.int64(ts_ms)
    body.bytes_(key)
    body.bytes_(value)
    b = body.blob()
    crc = zlib.crc32(b) & 0xFFFFFFFF
    msg = struct.pack("!I", crc) + b
    return struct.pack("!qi", offset, len(msg)) + msg


def encode_message_set(msgs) -> bytes:
    """msgs: iterable of (offset, ts_ms, key|None, value|None)."""
    return b"".join(_encode_message(*m) for m in msgs)


def decode_message_set(buf: bytes):
    """Yields (offset, ts_ms, key, value); raises on CRC mismatch.
    Accepts magic 0 (no timestamp) and magic 1."""
    r = _Reader(buf)
    out = []
    while r.off + 12 <= len(r.buf):
        offset = r.int64()
        size = r.int32()
        if r.off + size > len(r.buf):
            break  # partial trailing message (legal in Kafka fetches)
        body = r._take(size)
        (crc,) = struct.unpack("!I", body[:4])
        if zlib.crc32(body[4:]) & 0xFFFFFFFF != crc:
            raise ValueError("message CRC mismatch")
        m = _Reader(body[4:])
        magic = m.int8()
        m.int8()  # attributes
        ts_ms = m.int64() if magic >= 1 else -1
        key = m.bytes_()
        value = m.bytes_()
        out.append((offset, ts_ms, key, value))
    return out


# ---- request handling ----


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: KafkaServer = self.server.kafka  # type: ignore[attr-defined]
        sock = self.request
        sock.settimeout(srv.idle_timeout)
        self.authenticated = srv.auth_tokens is None
        try:
            while True:
                hdr = self._read_exact(sock, 4)
                if hdr is None:
                    return
                (size,) = struct.unpack("!i", hdr)
                payload = self._read_exact(sock, size)
                if payload is None:
                    return
                resp = self._dispatch(srv, payload)
                if resp is not None:
                    sock.sendall(struct.pack("!i", len(resp)) + resp)
        except (ConnectionError, OSError, ValueError):
            pass

    def _read_exact(self, sock, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _dispatch(self, srv, payload: bytes) -> bytes | None:
        r = _Reader(payload)
        api_key = r.int16()
        api_version = r.int16()
        correlation_id = r.int32()
        r.string()  # client_id
        w = _Writer()
        w.int32(correlation_id)
        lo_hi = _SUPPORTED.get(api_key)
        if lo_hi is None or not lo_hi[0] <= api_version <= lo_hi[1]:
            if api_key == 18:  # ApiVersions error still lists versions
                w.int16(ERR_UNSUPPORTED_VERSION)
                self._api_versions_body(w)
            else:
                w.int16(ERR_UNSUPPORTED_VERSION)
            return w.blob()
        if not self.authenticated and api_key not in _PRE_AUTH_APIS:
            w.int16(ERR_SASL_AUTH_FAILED)
            return w.blob()
        handler = {
            0: self._produce, 1: self._fetch, 2: self._list_offsets,
            3: self._metadata, 8: self._offset_commit,
            9: self._offset_fetch, 10: self._find_coordinator,
            17: self._sasl_handshake, 18: self._api_versions,
            36: self._sasl_authenticate,
        }[api_key]
        if handler(srv, r, w) is False:  # acks=0: no response at all
            return None
        return w.blob()

    # -- ApiVersions v0 --

    def _api_versions_body(self, w):
        w.int32(len(_SUPPORTED))
        for key, (lo, hi) in sorted(_SUPPORTED.items()):
            w.int16(key)
            w.int16(lo)
            w.int16(hi)

    def _api_versions(self, srv, r, w):
        w.int16(ERR_NONE)
        self._api_versions_body(w)

    # -- SASL (PLAIN only; KIP-152 authenticate-over-kafka-frames) --

    def _sasl_handshake(self, srv, r, w):
        mechanism = r.string()
        if mechanism == "PLAIN":
            w.int16(ERR_NONE)
        else:
            w.int16(ERR_UNSUPPORTED_VERSION)
        w.int32(1)
        w.string("PLAIN")

    def _sasl_authenticate(self, srv, r, w):
        token = r.bytes_() or b""
        # PLAIN: authzid \0 authcid \0 password — the password is the
        # cluster auth token (same token set as the gRPC front)
        parts = token.split(b"\x00")
        password = parts[2].decode() if len(parts) == 3 else ""
        if srv.auth_tokens is not None and password in srv.auth_tokens:
            self.authenticated = True
            w.int16(ERR_NONE)
            w.string(None)    # error message
            w.bytes_(b"")     # auth bytes
        else:
            w.int16(ERR_SASL_AUTH_FAILED)
            w.string("authentication failed")
            w.bytes_(b"")

    # -- Metadata v1 --

    def _metadata(self, srv, r, w):
        requested = r.array(r.string)
        with srv.lock:
            names = (sorted(srv.cluster.topics)
                     if not requested else requested)
            topics = [(n, srv.cluster.topics.get(n)) for n in names]
            w.int32(1)                      # brokers
            w.int32(srv.node_id)
            w.string(srv.host)
            w.int32(srv.port)
            w.string(None)                  # rack
            w.int32(srv.node_id)            # controller id
            w.int32(len(topics))
            for name, t in topics:
                w.int16(ERR_NONE if t is not None else ERR_UNKNOWN_TOPIC)
                w.string(name)
                w.int8(0)                   # is_internal
                parts = t.partitions if t is not None else []
                w.int32(len(parts))
                for pi in range(len(parts)):
                    w.int16(ERR_NONE)
                    w.int32(pi)
                    w.int32(srv.node_id)    # leader
                    w.int32(1)              # replicas
                    w.int32(srv.node_id)
                    w.int32(1)              # isr
                    w.int32(srv.node_id)

    # -- Produce v2 --

    def _produce(self, srv, r, w):
        acks = r.int16()
        r.int32()  # timeout_ms
        results = []  # (topic, [(partition, error, base_offset, ts)])
        n_topics = r.int32()
        for _ in range(n_topics):
            tname = r.string()
            per_part = []
            n_parts = r.int32()
            for _ in range(n_parts):
                pid = r.int32()
                records = r.bytes_() or b""
                with srv.lock:
                    topic = srv.cluster.topics.get(tname)
                    if topic is None or pid >= len(topic.partitions):
                        per_part.append((pid, ERR_UNKNOWN_TOPIC, -1, -1))
                        continue
                    try:
                        decoded = decode_message_set(records)
                    except ValueError:
                        per_part.append(
                            (pid, ERR_CORRUPT_MESSAGE, -1, -1))
                        continue
                    msgs = []
                    for _off, ts_ms, key, value in decoded:
                        m = {"data": (value or b"").decode(
                            "utf-8", "surrogateescape")}
                        if key is not None:
                            m["key"] = key.decode(
                                "utf-8", "surrogateescape")
                        if ts_ms and ts_ms > 0:
                            m["ts"] = ts_ms / 1000.0
                        msgs.append(m)
                    offs = topic.partitions[pid].write(msgs)
                    base = offs[0] if offs else -1
                    per_part.append((pid, ERR_NONE, base, -1))
            results.append((tname, per_part))
        if acks == 0:
            return False  # fire-and-forget: no response at all
        w.int32(len(results))
        for tname, per_part in results:
            w.string(tname)
            w.int32(len(per_part))
            for pid, err, base, ts in per_part:
                w.int32(pid)
                w.int16(err)
                w.int64(base)
                w.int64(ts)
        w.int32(0)  # throttle_time_ms

    # -- Fetch v2 --

    def _fetch(self, srv, r, w):
        r.int32()  # replica_id
        r.int32()  # max_wait_ms
        r.int32()  # min_bytes
        n_topics = r.int32()
        w.int32(0)  # throttle_time_ms
        out = []
        for _ in range(n_topics):
            tname = r.string()
            per_part = []
            n_parts = r.int32()
            for _ in range(n_parts):
                pid = r.int32()
                fetch_offset = r.int64()
                max_bytes = r.int32()
                with srv.lock:
                    topic = srv.cluster.topics.get(tname)
                    if topic is None or pid >= len(topic.partitions):
                        per_part.append(
                            (pid, ERR_UNKNOWN_TOPIC, -1, b""))
                        continue
                    part = topic.partitions[pid]
                    hw = part.head_offset
                    msgs = part.read(fetch_offset,
                                     limit=max(1, max_bytes // 32))
                    wire = []
                    total = 0
                    for m in msgs:
                        value = m["data"].encode(
                            "utf-8", "surrogateescape")
                        key = m.get("key")
                        if key is not None:
                            key = key.encode("utf-8", "surrogateescape")
                        total += len(value) + 34
                        if wire and total > max_bytes:
                            break
                        wire.append((m["offset"],
                                     int(m.get("ts", 0) * 1000),
                                     key, value))
                    per_part.append(
                        (pid, ERR_NONE, hw, encode_message_set(wire)))
            out.append((tname, per_part))
        w.int32(len(out))
        for tname, per_part in out:
            w.string(tname)
            w.int32(len(per_part))
            for pid, err, hw, mset in per_part:
                w.int32(pid)
                w.int16(err)
                w.int64(hw)
                w.bytes_(mset)

    # -- ListOffsets v1 --

    def _list_offsets(self, srv, r, w):
        r.int32()  # replica_id
        n_topics = r.int32()
        out = []
        for _ in range(n_topics):
            tname = r.string()
            per_part = []
            for _ in range(r.int32()):
                pid = r.int32()
                ts = r.int64()
                with srv.lock:
                    topic = srv.cluster.topics.get(tname)
                    if topic is None or pid >= len(topic.partitions):
                        per_part.append((pid, ERR_UNKNOWN_TOPIC, -1, -1))
                        continue
                    part = topic.partitions[pid]
                    off = (part.tail_offset if ts == -2
                           else part.head_offset)
                    per_part.append((pid, ERR_NONE, -1, off))
            out.append((tname, per_part))
        w.int32(len(out))
        for tname, per_part in out:
            w.string(tname)
            w.int32(len(per_part))
            for pid, err, ts, off in per_part:
                w.int32(pid)
                w.int16(err)
                w.int64(ts)
                w.int64(off)

    # -- FindCoordinator v0 --

    def _find_coordinator(self, srv, r, w):
        r.string()  # group id
        w.int16(ERR_NONE)
        w.int32(srv.node_id)
        w.string(srv.host)
        w.int32(srv.port)

    # -- OffsetCommit v2 --

    def _offset_commit(self, srv, r, w):
        group = r.string()
        r.int32()   # generation
        r.string()  # member id
        r.int64()   # retention
        out = []
        for _ in range(r.int32()):
            tname = r.string()
            per_part = []
            for _ in range(r.int32()):
                pid = r.int32()
                offset = r.int64()
                r.string()  # metadata
                with srv.lock:
                    topic = srv.cluster.topics.get(tname)
                    if topic is None or pid >= len(topic.partitions):
                        per_part.append((pid, ERR_UNKNOWN_TOPIC))
                        continue
                    # Kafka committed offset == next-to-read ==
                    # PQ consumer offset: direct pass-through; rewinds
                    # are explicit client seeks, so they must apply
                    topic.partitions[pid].commit(group, offset,
                                                 allow_rewind=True)
                    per_part.append((pid, ERR_NONE))
            out.append((tname, per_part))
        w.int32(len(out))
        for tname, per_part in out:
            w.string(tname)
            w.int32(len(per_part))
            for pid, err in per_part:
                w.int32(pid)
                w.int16(err)

    # -- OffsetFetch v1 --

    def _offset_fetch(self, srv, r, w):
        group = r.string()
        out = []
        for _ in range(r.int32()):
            tname = r.string()
            per_part = []
            for _ in range(r.int32()):
                pid = r.int32()
                with srv.lock:
                    topic = srv.cluster.topics.get(tname)
                    if topic is None or pid >= len(topic.partitions):
                        per_part.append((pid, -1, ERR_UNKNOWN_TOPIC))
                        continue
                    off = topic.partitions[pid].committed(group)
                    per_part.append((pid, off, ERR_NONE))
            out.append((tname, per_part))
        w.int32(len(out))
        for tname, per_part in out:
            w.string(tname)
            w.int32(len(per_part))
            for pid, off, err in per_part:
                w.int32(pid)
                w.int64(off)
                w.string(None)  # metadata
                w.int16(err)


class KafkaServer:
    """Threaded Kafka-wire listener over a Cluster's topics.

    ``lock`` serializes topic access against other front doors; pass
    RequestProxy.lock to co-host with the gRPC server."""

    def __init__(self, cluster, host: str = "127.0.0.1", port: int = 0,
                 lock: threading.Lock | None = None, node_id: int = 1,
                 auth_tokens: set[str] | None = None,
                 idle_timeout: float = 300.0):
        self.cluster = cluster
        self.host = host
        self.node_id = node_id
        self.lock = lock if lock is not None else threading.Lock()
        self.auth_tokens = auth_tokens
        self.idle_timeout = idle_timeout

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._server.kafka = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "KafkaServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="kafka-wire")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
