"""Result-set wire format: OracleTable <-> Arrow IPC.

The reference streams scan results as Arrow batches (TEvScanData); the
API layer keeps that columnar shape on the wire: strings decode from
dictionary ids, decimals become decimal128, dates become date32.
"""

from __future__ import annotations

import decimal as pydec
import io

import numpy as np
import pyarrow as pa

from ydb_tpu import dtypes
from ydb_tpu.engine.oracle import OracleTable


def oracle_to_ipc(table: OracleTable, dicts=None) -> bytes:
    dicts = dicts if dicts is not None else table.dicts
    arrays = []
    fields = []
    n = table.num_rows
    for f in table.schema.fields:
        vals, valid = table.cols[f.name]
        mask = ~np.asarray(valid, dtype=bool)
        t = f.type
        if t.is_string:
            if not (dicts and f.name in dicts):
                if mask.all():
                    arr = pa.nulls(n, type=pa.string())
                else:
                    # silent all-NULL output would corrupt results —
                    # fail loudly like OracleTable.strings does
                    raise ValueError(
                        f"no dictionary bound for string column "
                        f"{f.name!r}")
            else:
                d = dicts[f.name]
                values = pa.array(
                    [v.decode("utf-8", "surrogateescape")
                     for v in d.values],
                    type=pa.string())
                idx = pa.array(np.asarray(vals, dtype=np.int32),
                               mask=mask if mask.any() else None)
                arr = pa.DictionaryArray.from_arrays(
                    idx, values).dictionary_decode()
            fields.append(pa.field(f.name, pa.string(), f.nullable))
        elif t.is_decimal:
            ints = np.asarray(vals, dtype=np.int64)
            py = [None if mask[i] else
                  pydec.Decimal(int(ints[i])).scaleb(-t.scale)
                  for i in range(n)]
            typ = pa.decimal128(38, t.scale)
            arr = pa.array(py, type=typ)
            fields.append(pa.field(f.name, typ, f.nullable))
        elif t.kind == dtypes.Kind.DATE:
            arr = pa.array(np.asarray(vals, dtype=np.int32),
                           type=pa.date32(),
                           mask=mask if mask.any() else None)
            fields.append(pa.field(f.name, pa.date32(), f.nullable))
        elif t.kind == dtypes.Kind.TIMESTAMP:
            arr = pa.array(np.asarray(vals, dtype=np.int64),
                           type=pa.timestamp("us"),
                           mask=mask if mask.any() else None)
            fields.append(pa.field(f.name, pa.timestamp("us"),
                                   f.nullable))
        else:
            arr = pa.array(np.asarray(vals),
                           mask=mask if mask.any() else None)
            fields.append(pa.field(f.name, arr.type, f.nullable))
        arrays.append(arr)
    batch = pa.record_batch(arrays, schema=pa.schema(fields))
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    return sink.getvalue()


def ipc_to_table(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(io.BytesIO(data)) as reader:
        return reader.read_all()
