"""Client SDK: driver + per-service clients over gRPC.

Mirror of the reference's SDK shape (TDriver/TTableClient,
public/sdk/cpp; SURVEY.md layer 9): a Driver owns the channel and auth
metadata; service clients hang off it. Query results come back as
pyarrow Tables.
"""

from __future__ import annotations

import grpc

from ydb_tpu.api.arrow_io import ipc_to_table
from ydb_tpu.api.build import ensure_protos

pb = ensure_protos()


class ApiError(Exception):
    pass


class Driver:
    def __init__(self, endpoint: str, auth_token: str | None = None):
        self.channel = grpc.insecure_channel(endpoint)
        self.metadata = (
            (("x-ydb-auth-ticket", auth_token),) if auth_token else ()
        )

    def close(self):
        self.channel.close()

    def _call(self, method: str, request, resp_cls):
        rpc = self.channel.unary_unary(
            method,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        return rpc(request, metadata=self.metadata)

    def query_client(self) -> "QueryClient":
        return QueryClient(self)

    def scheme_client(self) -> "SchemeClient":
        return SchemeClient(self)

    def topic_client(self) -> "TopicClient":
        return TopicClient(self)

    def export_client(self) -> "ExportClient":
        return ExportClient(self)

    def rate_limiter_client(self) -> "RateLimiterClient":
        return RateLimiterClient(self)

    def table_client(self) -> "TableClient":
        return TableClient(self)

    def keyvalue_client(self) -> "KeyValueClient":
        return KeyValueClient(self)

    def federation_databases(self) -> list[dict]:
        resp = self._call(
            "/ydb_tpu.FederationDiscovery/ListFederationDatabases",
            pb.ListFederationDatabasesRequest(),
            pb.ListFederationDatabasesResponse)
        return [{"name": d.name, "endpoint": d.endpoint,
                 "status": d.status} for d in resp.databases]

    def discovery(self) -> list[tuple[str, int]]:
        resp = self._call("/ydb_tpu.Discovery/ListEndpoints",
                          pb.ListEndpointsRequest(),
                          pb.ListEndpointsResponse)
        return [(e.address, e.port) for e in resp.endpoints]


class QueryClient:
    def __init__(self, driver: Driver):
        self.driver = driver
        resp = driver._call("/ydb_tpu.Query/CreateSession",
                            pb.CreateSessionRequest(),
                            pb.CreateSessionResponse)
        self.session_id = resp.session_id

    def close(self):
        """Release the server-side session."""
        self.driver._call("/ydb_tpu.Query/DeleteSession",
                          pb.DeleteSessionRequest(
                              session_id=self.session_id),
                          pb.DeleteSessionResponse)

    def execute(self, sql: str):
        """pyarrow.Table for SELECT; (step, committed) for DML/DDL."""
        resp = self.driver._call(
            "/ydb_tpu.Query/ExecuteQuery",
            pb.ExecuteQueryRequest(session_id=self.session_id, sql=sql),
            pb.ExecuteQueryResponse)
        if resp.status != pb.ExecuteQueryResponse.SUCCESS:
            raise ApiError(resp.error)
        if resp.plan_text:
            return resp.plan_text  # EXPLAIN
        if resp.arrow_ipc:
            return ipc_to_table(resp.arrow_ipc)
        return (resp.tx_step, resp.committed)


class SchemeClient:
    def __init__(self, driver: Driver):
        self.driver = driver

    def list_directory(self, path: str = "/"):
        resp = self.driver._call(
            "/ydb_tpu.Scheme/ListDirectory",
            pb.ListDirectoryRequest(path=path), pb.ListDirectoryResponse)
        if resp.error:
            raise ApiError(resp.error)
        return [(e.path, e.kind) for e in resp.children]

    def describe_table(self, path: str):
        resp = self.driver._call(
            "/ydb_tpu.Scheme/DescribeTable",
            pb.DescribeTableRequest(path=path), pb.DescribeTableResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp


class TopicClient:
    def __init__(self, driver: Driver):
        self.driver = driver

    def write(self, topic: str, data: bytes | str, key: str = "",
              producer: str = "", seqno: int = 0):
        if isinstance(data, str):
            data = data.encode()
        resp = self.driver._call(
            "/ydb_tpu.Topic/Write",
            pb.TopicWriteRequest(topic=topic, key=key, data=data,
                                 producer=producer, seqno=seqno),
            pb.TopicWriteResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.partition, resp.offset

    def read(self, topic: str, consumer: str, limit: int = 100):
        resp = self.driver._call(
            "/ydb_tpu.Topic/Read",
            pb.TopicReadRequest(topic=topic, consumer=consumer,
                                limit=limit),
            pb.TopicReadResponse)
        if resp.error:
            raise ApiError(resp.error)
        return [(m.partition, m.offset, m.data) for m in resp.messages]

    def stream_read(self, topic: str, consumer: str,
                    max_batch: int = 100, auto_commit: bool = True,
                    idle_timeout_ms: int = 0):
        """Streaming read session: yields (partition, offset, data)
        until the server ends the stream (idle timeout) or the caller
        breaks out (cancelling the RPC)."""
        rpc = self.driver.channel.unary_stream(
            "/ydb_tpu.Topic/StreamRead",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.TopicReadResponse.FromString,
        )
        stream = rpc(pb.StreamReadRequest(
            topic=topic, consumer=consumer, max_batch=max_batch,
            auto_commit=auto_commit, idle_timeout_ms=idle_timeout_ms,
        ), metadata=self.driver.metadata)
        try:
            for resp in stream:
                if resp.error:
                    raise ApiError(resp.error)
                for m in resp.messages:
                    yield m.partition, m.offset, m.data
        finally:
            stream.cancel()

    def stream_write(self, topic: str, items):
        """Streaming write session: ``items`` yields (data, key,
        producer, seqno) tuples (or bare bytes); returns the acks."""
        def gen():
            for it in items:
                if isinstance(it, (bytes, str)):
                    data, key, producer, seqno = it, "", "", 0
                else:
                    data, key, producer, seqno = it
                if isinstance(data, str):
                    data = data.encode()
                yield pb.StreamWriteItem(
                    topic=topic, key=key, data=data,
                    producer=producer, seqno=seqno)

        rpc = self.driver.channel.stream_stream(
            "/ydb_tpu.Topic/StreamWrite",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.StreamWriteAck.FromString,
        )
        acks = []
        for ack in rpc(gen(), metadata=self.driver.metadata):
            if ack.error:
                raise ApiError(ack.error)
            acks.append((ack.partition, ack.offset))
        return acks

    def commit(self, topic: str, consumer: str, partition: int,
               offset: int):
        resp = self.driver._call(
            "/ydb_tpu.Topic/Commit",
            pb.TopicCommitRequest(topic=topic, consumer=consumer,
                                  partition=partition, offset=offset),
            pb.TopicCommitResponse)
        if resp.error:
            raise ApiError(resp.error)


class ExportClient:
    """Export/Import service (ydb_export/ydb_import analog)."""

    def __init__(self, driver: Driver):
        self.driver = driver

    def export_table(self, table: str, name: str = ""):
        resp = self.driver._call(
            "/ydb_tpu.Export/ExportBackup",
            pb.ExportRequest(table=table, name=name), pb.ExportResponse)
        if resp.error:
            raise ApiError(resp.error)
        return {"rows": resp.rows, "parts": resp.parts,
                "snapshot": resp.snapshot}

    def import_table(self, name: str, table: str = "", shards: int = 0):
        resp = self.driver._call(
            "/ydb_tpu.Import/ImportBackup",
            pb.ImportRequest(name=name, table=table, shards=shards),
            pb.ImportResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.rows

    def list_backups(self):
        resp = self.driver._call(
            "/ydb_tpu.Export/ListBackups", pb.ListBackupsRequest(),
            pb.ListBackupsResponse)
        return [(b.name, b.rows, b.snapshot) for b in resp.backups]


class RateLimiterClient:
    """RateLimiter service (kesus token buckets over runtime.quoter)."""

    def __init__(self, driver: Driver):
        self.driver = driver

    def create_resource(self, path: str, rate: float,
                        burst: float = 0.0):
        resp = self.driver._call(
            "/ydb_tpu.RateLimiter/CreateResource",
            pb.CreateResourceRequest(path=path, rate=rate, burst=burst),
            pb.CreateResourceResponse)
        if resp.error:
            raise ApiError(resp.error)

    def acquire(self, path: str, amount: float = 1.0):
        """(acquired, retry_after_seconds)"""
        resp = self.driver._call(
            "/ydb_tpu.RateLimiter/AcquireResource",
            pb.AcquireResourceRequest(path=path, amount=amount),
            pb.AcquireResourceResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.acquired, resp.retry_after_s

    def describe_resource(self, path: str):
        resp = self.driver._call(
            "/ydb_tpu.RateLimiter/DescribeResource",
            pb.DescribeResourceRequest(path=path),
            pb.DescribeResourceResponse)
        if resp.error:
            raise ApiError(resp.error)
        return {"rate": resp.rate, "burst": resp.burst,
                "tokens": resp.tokens}


class TableClient:
    """Table service (ydb_table_v1 / TTableClient analog): structured
    DDL, data queries with client tx control, Arrow BulkUpsert,
    streaming ReadTable."""

    def __init__(self, driver: Driver):
        self.driver = driver
        resp = driver._call("/ydb_tpu.Table/CreateSession",
                            pb.CreateSessionRequest(),
                            pb.CreateSessionResponse)
        self.session_id = resp.session_id

    def close(self):
        self.driver._call("/ydb_tpu.Table/DeleteSession",
                          pb.DeleteSessionRequest(
                              session_id=self.session_id),
                          pb.DeleteSessionResponse)

    def create_table(self, path: str, columns, primary_key,
                     store: str = "", shards: int = 0):
        """columns: [(name, type, not_null)] triples."""
        resp = self.driver._call(
            "/ydb_tpu.Table/CreateTable",
            pb.CreateTableRequest(
                path=path,
                columns=[pb.TableColumnSpec(
                    name=n, type=t, not_null=nn)
                    for n, t, nn in columns],
                primary_key=list(primary_key),
                store=store, shards=shards),
            pb.CreateTableResponse)
        if resp.error:
            raise ApiError(resp.error)

    def drop_table(self, path: str):
        resp = self.driver._call(
            "/ydb_tpu.Table/DropTable",
            pb.DropTableRequest(path=path), pb.DropTableResponse)
        if resp.error:
            raise ApiError(resp.error)

    def alter_table(self, path: str, add_columns) -> int:
        """add_columns: [(name, type)]; returns new schema version."""
        resp = self.driver._call(
            "/ydb_tpu.Table/AlterTable",
            pb.AlterTableAddColumnsRequest(
                path=path,
                add_columns=[pb.TableColumnSpec(name=n, type=t)
                             for n, t in add_columns]),
            pb.AlterTableResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.schema_version

    def copy_table(self, src: str, dst: str) -> int:
        resp = self.driver._call(
            "/ydb_tpu.Table/CopyTable",
            pb.CopyTableRequest(src=src, dst=dst),
            pb.CopyTableResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.rows

    def execute(self, sql: str, begin: bool = False,
                commit: bool = False, tx_id: str = ""):
        """Returns (result, tx_id): result is a pyarrow Table for
        SELECT, (step, committed) for DML; tx_id is non-empty while an
        interactive tx stays open."""
        resp = self.driver._call(
            "/ydb_tpu.Table/ExecuteDataQuery",
            pb.ExecuteDataQueryRequest(
                session_id=self.session_id, sql=sql,
                tx=pb.TxControl(begin=begin, commit=commit,
                                tx_id=tx_id)),
            pb.ExecuteDataQueryResponse)
        if resp.error:
            raise ApiError(resp.error)
        if resp.arrow_ipc:
            return ipc_to_table(resp.arrow_ipc), resp.tx_id
        return (resp.tx_step, resp.committed), resp.tx_id

    def explain(self, sql: str) -> str:
        resp = self.driver._call(
            "/ydb_tpu.Table/ExplainDataQuery",
            pb.ExplainQueryRequest(sql=sql), pb.ExplainQueryResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.plan_text

    def bulk_upsert(self, table: str, arrow_table) -> int:
        """pyarrow.Table -> the shards, bypassing SQL compilation."""
        import io

        import pyarrow as pa

        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, arrow_table.schema) as w:
            w.write_table(arrow_table)
        resp = self.driver._call(
            "/ydb_tpu.Table/BulkUpsert",
            pb.BulkUpsertRequest(table=table,
                                 arrow_ipc=sink.getvalue()),
            pb.BulkUpsertResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.rows

    def read_table(self, path: str, columns=(), batch_rows: int = 0):
        """Yields pyarrow Tables (one per server batch)."""
        rpc = self.driver.channel.unary_stream(
            "/ydb_tpu.Table/StreamReadTable",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.ReadTableBatch.FromString,
        )
        stream = rpc(pb.ReadTableRequest(
            path=path, columns=list(columns), batch_rows=batch_rows),
            metadata=self.driver.metadata)
        for batch in stream:
            if batch.error:
                raise ApiError(batch.error)
            yield ipc_to_table(batch.arrow_ipc)


class KeyValueClient:
    """KeyValue service (ydb_keyvalue_v1 analog over KeyValue tablets)."""

    def __init__(self, driver: Driver):
        self.driver = driver

    def create_volume(self, path: str):
        resp = self.driver._call(
            "/ydb_tpu.KeyValue/CreateVolume",
            pb.KvVolumeRequest(path=path), pb.KvVolumeResponse)
        if resp.error:
            raise ApiError(resp.error)

    def drop_volume(self, path: str):
        resp = self.driver._call(
            "/ydb_tpu.KeyValue/DropVolume",
            pb.KvVolumeRequest(path=path), pb.KvVolumeResponse)
        if resp.error:
            raise ApiError(resp.error)

    def write(self, volume: str, key: str, value: bytes):
        resp = self.driver._call(
            "/ydb_tpu.KeyValue/ExecuteTransaction",
            pb.KvWriteRequest(volume=volume, key=key, value=value),
            pb.KvWriteResponse)
        if resp.error:
            raise ApiError(resp.error)

    def read(self, volume: str, key: str) -> bytes | None:
        resp = self.driver._call(
            "/ydb_tpu.KeyValue/Read",
            pb.KvReadRequest(volume=volume, key=key),
            pb.KvReadResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.value if resp.found else None

    def list_range(self, volume: str, lo: str = "", hi: str = "",
                   limit: int = 0) -> list[tuple[str, bytes]]:
        req = pb.KvListRangeRequest(volume=volume, to=hi, limit=limit)
        setattr(req, "from", lo)
        resp = self.driver._call("/ydb_tpu.KeyValue/ListRange", req,
                                 pb.KvListRangeResponse)
        if resp.error:
            raise ApiError(resp.error)
        return [(p.key, p.value) for p in resp.pairs]

    def delete_range(self, volume: str, lo: str = "",
                     hi: str = "") -> int:
        req = pb.KvDeleteRangeRequest(volume=volume, to=hi)
        setattr(req, "from", lo)
        resp = self.driver._call("/ydb_tpu.KeyValue/DeleteRange", req,
                                 pb.KvDeleteRangeResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.deleted

    def rename(self, volume: str, old_key: str, new_key: str) -> bool:
        resp = self.driver._call(
            "/ydb_tpu.KeyValue/Rename",
            pb.KvRenameRequest(volume=volume, old_key=old_key,
                               new_key=new_key),
            pb.KvRenameResponse)
        if resp.error:
            raise ApiError(resp.error)
        return resp.renamed
