"""Small shared helpers used across planes."""

from __future__ import annotations


def fnv1a_64(s: str | bytes) -> int:
    """FNV-1a 64-bit — the shared string hash for blob->disk rotation
    and topic key->partition routing (one implementation so conventions
    never diverge)."""
    h = 1469598103934665603
    for b in (s.encode() if isinstance(s, str) else s):
        h = ((h ^ b) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h
