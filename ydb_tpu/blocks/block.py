"""Fixed-shape device column blocks — the unit of TPU columnar execution.

The reference's execution unit is an Arrow RecordBatch flowing through block
operators (ydb/library/yql/minikql/comp_nodes/mkql_blocks.cpp, block infra
computation/mkql_block_impl.h). XLA wants static shapes, so the TPU analog is
a ``TableBlock``: every column padded to a common ``capacity`` with an int32
``length`` scalar giving the live row count. Rows in [length, capacity) are
padding; kernels mask them out via ``row_mask``.

TableBlock is a pytree, so it flows through jit / vmap / shard_map / psum
directly. The schema and capacity are static (part of the treedef): changing
either triggers recompilation, matching the compiled-pattern-cache design
(reference: mkql_computation_pattern_cache.h — here the XLA compile cache).

NULLs: each column carries a validity bitmask (bool array). Kernels follow
Arrow/Kleene semantics where the reference does.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu import dtypes


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def host_ok(reason: str):
    """Dispatch-purity marker, same shape as ``analysis.host_ok`` —
    redeclared here because this module sits below the analysis
    package in the import graph (analysis.verify -> ssa -> blocks) and
    cannot import it. The hotpath analyzer matches the decorator by
    name; the runtime attribute is identical."""

    def mark(fn):
        fn.__host_ok__ = reason
        return fn

    return mark


def budget_ok(reason: str):
    """Device-memory marker, same shape as ``analysis.budget_ok`` —
    redeclared here for the same import-graph reason as ``host_ok``
    above. The devmem analyzer matches the decorator by name; the
    runtime attribute is identical."""

    def mark(fn):
        fn.__budget_ok__ = reason
        return fn

    return mark


# Pad capacities to a lane-friendly multiple; keeps layouts tileable on the
# VPU (8x128 lanes) and stabilizes jit cache keys across slightly different
# batch sizes.
DEFAULT_CAPACITY_QUANTUM = 1024


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Column:
    """One device column: physical values + validity mask.

    ``data`` is the physical representation per ydb_tpu.dtypes (strings are
    int32 dictionary ids, decimals scaled int64). ``validity`` is True for
    non-null rows; padding rows have validity False.
    """

    data: jax.Array
    validity: jax.Array

    def tree_flatten(self):
        return (self.data, self.validity), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def capacity(self) -> int:
        return self.data.shape[0]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TableBlock:
    """A batch of rows as named device columns, padded to ``capacity``.

    Dynamic leaves: per-column data/validity arrays + ``length`` scalar.
    Static treedef: schema (names + logical types) and capacity.
    """

    columns: dict[str, Column]
    length: jax.Array  # int32 scalar: live rows
    schema: dtypes.Schema

    def tree_flatten(self):
        names = tuple(self.columns.keys())
        children = tuple(self.columns[n] for n in names) + (self.length,)
        return children, (names, self.schema)

    @classmethod
    def tree_unflatten(cls, aux, children):
        names, schema = aux
        cols = dict(zip(names, children[:-1]))
        return cls(cols, children[-1], schema)

    # ---- construction ----

    @staticmethod
    @host_ok("host->device ingest boundary: stages already-materialized"
             " host arrays (tail-padding them is part of the transfer)")
    def from_numpy(
        arrays: Mapping[str, np.ndarray],
        schema: dtypes.Schema,
        validity: Mapping[str, np.ndarray] | None = None,
        capacity: int | None = None,
    ) -> "TableBlock":
        """Build a block from host numpy arrays (already physically encoded).

        Low-copy staging: a capacity-aligned array passes straight to the
        device transfer (on CPU backends ``jnp.asarray`` can even alias
        aligned owning arrays — zero host copies); only a short tail is
        ever padded, instead of zero-filling and re-copying a
        full-capacity buffer per column. Callers must therefore not
        mutate ``arrays``/``validity`` after handing them over — the
        scan pipeline's payloads are single-owner by construction.
        """
        # deferred import: blocks sits below the analysis package in
        # the import graph (analysis.verify -> ssa -> blocks)
        from ydb_tpu.analysis import memsan
        names = schema.names
        n = len(next(iter(arrays.values()))) if arrays else 0
        cap = capacity if capacity is not None else _round_up(
            max(n, 1), DEFAULT_CAPACITY_QUANTUM
        )
        if cap < n:
            raise ValueError(f"capacity {cap} < rows {n}")
        cols = {}
        with memsan.seam("staging"):
            for name in names:
                f = schema.field(name)
                a = np.asarray(arrays[name], dtype=f.type.physical)
                v = None if validity is None else validity.get(name)
                if v is None:
                    v = np.ones(n, dtype=np.bool_)
                else:
                    v = np.asarray(v, dtype=np.bool_)
                if cap != n:
                    # tail-only padding; padding validity stays False so
                    # it can never leak live rows
                    a = np.concatenate(
                        [a, np.zeros(cap - n, dtype=f.type.physical)])
                    v = np.concatenate(
                        [v, np.zeros(cap - n, dtype=np.bool_)])
                cols[name] = Column(jnp.asarray(a), jnp.asarray(v))
            blk = TableBlock(cols, jnp.asarray(n, dtype=jnp.int32),
                             schema)
        if memsan.armed():
            memsan.charge(memsan.nbytes_of(blk), "staging",
                          owner="from_numpy")
        return blk

    # ---- views ----

    @property
    def capacity(self) -> int:
        return next(iter(self.columns.values())).capacity if self.columns else 0

    @budget_ok("capacity-length index mask: fused away under jit;"
               " eager use is one bounded int32[capacity] vector")
    def row_mask(self) -> jax.Array:
        """bool[capacity]: True for live (non-padding) rows."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.length

    def column(self, name: str) -> Column:
        return self.columns[name]

    def select(self, names) -> "TableBlock":
        return TableBlock(
            {n: self.columns[n] for n in names},
            self.length,
            self.schema.select(names),
        )

    def with_column(
        self, name: str, col: Column, typ: dtypes.LogicalType
    ) -> "TableBlock":
        cols = dict(self.columns)
        cols[name] = col
        sch = self.schema
        if name not in sch:
            sch = sch.with_field(dtypes.Field(name, typ))
        return TableBlock(cols, self.length, sch)

    # ---- host materialization (tests / result delivery) ----

    # device->host slicing quantum: live-row counts round up to this
    # before the device-side slice, so the tiny slice program re-traces
    # per QUANTIZED length, not per exact length
    _SLICE_QUANTUM = 8192

    @classmethod
    def _clip(cls, arr, n: int):
        """Device-side slice to (about) the live prefix when the saving
        is substantial. Aggregate outputs are padded to the block
        capacity (a 2M-row block with 4 live groups); pulling the whole
        padded buffer over a slow device link dwarfs the query."""
        cap = arr.shape[0]
        if cap > 4 * cls._SLICE_QUANTUM and n <= cap // 4:
            m = -(-n // cls._SLICE_QUANTUM) * cls._SLICE_QUANTUM
            arr = arr[:min(cap, m)]
        return arr

    @host_ok("deliberate result fetch: every column rides ONE batched"
             " device_get (one link round trip per statement)")
    def host_columns(
        self, validity: bool = True
    ) -> "tuple[dict[str, np.ndarray], dict[str, np.ndarray]]":
        """(data, validity) of live rows in ONE batched device fetch.

        Per-array fetches pay a full device-link round trip EACH; on a
        high-latency link that — not bandwidth — dominates small
        results, so every column (and its validity) rides one
        ``jax.device_get``."""
        n = int(self.length)
        pack = {
            k: ((self._clip(c.data, n), self._clip(c.validity, n))
                if validity else (self._clip(c.data, n),))
            for k, c in self.columns.items()
        }
        got = jax.device_get(pack)
        data = {k: v[0][:n] for k, v in got.items()}
        valid = ({k: v[1][:n] for k, v in got.items()} if validity
                 else {})
        return data, valid

    @host_ok("deliberate result fetch (delegates to host_columns)")
    def to_numpy(self) -> dict[str, np.ndarray]:
        """Live rows only, as physical numpy arrays (nulls not decoded)."""
        return self.host_columns(validity=False)[0]

    @host_ok("deliberate result fetch: one batched validity device_get")
    def validity_numpy(self) -> dict[str, np.ndarray]:
        n = int(self.length)
        got = jax.device_get(
            {k: self._clip(c.validity, n)
             for k, c in self.columns.items()})
        return {k: v[:n] for k, v in got.items()}


@host_ok("one-time aux staging at compile/first-dispatch time; values"
         " already device-resident are passed through untouched")
def device_aux(aux: Mapping[str, object]) -> dict:
    """Stage a compiled program's aux tables (dict masks, gather tables)
    on the device, skipping values that already live there — the aux
    dict crosses every fragment boundary, and re-staging device-resident
    arrays on each hop costs a transfer for nothing."""
    from ydb_tpu.analysis import memsan  # deferred: import graph
    out = {}
    staged = 0
    with memsan.seam("staging"):
        for k, v in aux.items():
            if isinstance(v, jax.Array):
                out[k] = v
            else:
                out[k] = jnp.asarray(v)
                staged += int(getattr(out[k], "nbytes", 0) or 0)
    if staged and memsan.armed():
        memsan.charge(staged, "staging", owner="device_aux")
    return out


@host_ok("host-side concat for readers/tests; the warm scan path"
         " merges on device (merge_blocks_device) instead")
def concat_blocks(blocks: list[TableBlock], capacity: int | None = None) -> TableBlock:
    """Host-side concat of live rows into one block (used by readers/tests)."""
    if not blocks:
        raise ValueError("concat of no blocks")
    schema = blocks[0].schema
    if len(blocks) > 1:
        # a row may come from any branch, so a column is nullable as
        # soon as ANY branch's is (branch schemas share names/types)
        schema = dtypes.Schema(tuple(
            dtypes.Field(
                f.name, f.type,
                any(b.schema.field(f.name).nullable for b in blocks))
            for f in schema.fields))
    arrays: dict[str, np.ndarray] = {}
    validity: dict[str, np.ndarray] = {}
    for name in schema.names:
        arrays[name] = np.concatenate(
            [b.to_numpy()[name] for b in blocks]
        )
        validity[name] = np.concatenate(
            [b.validity_numpy()[name] for b in blocks]
        )
    return TableBlock.from_numpy(arrays, schema, validity, capacity=capacity)
