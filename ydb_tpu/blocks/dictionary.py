"""Host-side string dictionaries.

TPUs have no varlen byte strings; string columns live on device as int32
dictionary ids (SURVEY.md §7.2 hard part #1). The dictionary — id -> bytes —
stays on host and is consulted at *plan time*: string predicates (==, LIKE,
prefix) are evaluated once over the dictionary values producing a small
per-id mask/array that ships to the device as a kernel input, turning string
compute into an int gather. This mirrors how the reference's columnar engine
keeps Arrow dictionary arrays and evaluates kernels over them
(ydb/core/formats/arrow/custom_registry.cpp) — redesigned for the TPU split.

Id conventions:
  * ids are dense [0, len(values))
  * NULL is carried by the validity mask, not by a sentinel id
"""

from __future__ import annotations

import fnmatch
import re

import numpy as np


class Dictionary:
    """Append-only bytes <-> dense int32 id mapping for one column."""

    __slots__ = ("values", "_index")

    def __init__(self, values=()):
        self.values: list[bytes] = []
        self._index: dict[bytes, int] = {}
        for v in values:
            self.add(_as_bytes(v))

    def __len__(self) -> int:
        return len(self.values)

    def add(self, value) -> int:
        value = _as_bytes(value)
        idx = self._index.get(value)
        if idx is None:
            idx = len(self.values)
            self.values.append(value)
            self._index[value] = idx
        return idx

    def get(self, value) -> int | None:
        return self._index.get(_as_bytes(value))

    def encode(self, values) -> np.ndarray:
        """Encode an iterable of str/bytes to int32 ids, adding new entries."""
        return np.fromiter(
            (self.add(v) for v in values), dtype=np.int32, count=len(values)
        )

    def decode(self, ids: np.ndarray) -> list[bytes]:
        vals = self.values
        return [vals[i] for i in np.asarray(ids)]

    # -- plan-time predicate evaluation (produces device-shippable arrays) --

    def eq_id(self, literal) -> int:
        """Id of literal, or -1 if absent (predicate is constant-false)."""
        idx = self.get(literal)
        return -1 if idx is None else idx

    def match_mask(self, predicate) -> np.ndarray:
        """bool[len(dict)] mask of ids whose value satisfies predicate(bytes)."""
        return np.fromiter(
            (bool(predicate(v)) for v in self.values),
            dtype=np.bool_, count=len(self.values),
        )

    def like_mask(self, pattern: str | bytes) -> np.ndarray:
        """SQL LIKE (%, _) evaluated over the dictionary."""
        pat = _as_bytes(pattern).decode("utf-8", "surrogateescape")
        rx = re.compile(
            "^" + re.escape(pat).replace("%", ".*").replace("_", ".") + "$",
            re.S,
        )
        return self.match_mask(
            lambda v: rx.match(v.decode("utf-8", "surrogateescape")) is not None
        )

    def prefix_mask(self, prefix) -> np.ndarray:
        p = _as_bytes(prefix)
        return self.match_mask(lambda v: v.startswith(p))

    def sort_rank(self) -> np.ndarray:
        """int32[len(dict)]: lexicographic rank of each id.

        Lets ORDER BY / min / max on a string column run on device as an int
        op over rank[id].
        """
        order = sorted(range(len(self.values)), key=lambda i: self.values[i])
        rank = np.empty(len(self.values), dtype=np.int32)
        for r, i in enumerate(order):
            rank[i] = r
        return rank

    def glob_mask(self, pattern: str) -> np.ndarray:
        return self.match_mask(
            lambda v: fnmatch.fnmatchcase(
                v.decode("utf-8", "surrogateescape"), pattern
            )
        )


def _as_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode("utf-8")
    return bytes(v)


class DictionarySet:
    """Dictionaries for all string columns of a table, keyed by column name."""

    def __init__(self):
        self._dicts: dict[str, Dictionary] = {}

    def for_column(self, name: str) -> Dictionary:
        d = self._dicts.get(name)
        if d is None:
            d = self._dicts[name] = Dictionary()
        return d

    def __contains__(self, name: str) -> bool:
        return name in self._dicts

    def __getitem__(self, name: str) -> Dictionary:
        return self._dicts[name]

    def columns(self):
        return self._dicts.keys()
