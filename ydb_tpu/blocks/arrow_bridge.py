"""Arrow RecordBatch ⇄ TableBlock bridge.

This is the TPU analog of the reference's Arrow glue (ydb/core/formats/arrow):
the ColumnShard stores/ships Arrow batches; the device executes fixed-shape
blocks. Encoding rules follow ydb_tpu.dtypes:

  * string/binary columns dictionary-encode against a table-level
    ``DictionarySet`` (host), shipping int32 ids;
  * decimal128(p, s) → int64 unscaled (values must fit 64 bits — TPC-H/DS do);
  * date32 → int32 days, timestamp[us] → int64;
  * nulls → validity masks (null slots get 0, masked out by kernels).

Numeric buffers transfer zero-copy where numpy/dlpack allows (Arrow numeric
arrays without nulls expose their data buffer directly).
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import TableBlock
from ydb_tpu.blocks.dictionary import DictionarySet

_ARROW_TO_KIND = {
    pa.int8(): dtypes.Kind.INT8,
    pa.int16(): dtypes.Kind.INT16,
    pa.int32(): dtypes.Kind.INT32,
    pa.int64(): dtypes.Kind.INT64,
    pa.uint8(): dtypes.Kind.UINT8,
    pa.uint16(): dtypes.Kind.UINT16,
    pa.uint32(): dtypes.Kind.UINT32,
    pa.uint64(): dtypes.Kind.UINT64,
    pa.float32(): dtypes.Kind.FLOAT,
    pa.float64(): dtypes.Kind.DOUBLE,
    pa.bool_(): dtypes.Kind.BOOL,
    pa.date32(): dtypes.Kind.DATE,
}


def schema_from_arrow(asch: pa.Schema) -> dtypes.Schema:
    fields = []
    for f in asch:
        t = f.type
        if t in _ARROW_TO_KIND:
            lt = dtypes.LogicalType(_ARROW_TO_KIND[t])
        elif pa.types.is_timestamp(t):
            lt = dtypes.TIMESTAMP
        elif pa.types.is_decimal(t):
            lt = dtypes.decimal(t.scale)
        elif (
            pa.types.is_string(t)
            or pa.types.is_large_string(t)
            or pa.types.is_binary(t)
            or pa.types.is_large_binary(t)
            or pa.types.is_dictionary(t)
        ):
            lt = dtypes.STRING
        else:
            raise NotImplementedError(f"arrow type {t} for column {f.name}")
        fields.append(dtypes.Field(f.name, lt, f.nullable))
    return dtypes.Schema(tuple(fields))


def _column_to_numpy(
    arr: pa.ChunkedArray | pa.Array,
    field: dtypes.Field,
    dicts: DictionarySet,
) -> tuple[np.ndarray, np.ndarray]:
    """Return (physical values, validity) for one column."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    validity = np.ones(n, dtype=np.bool_) if arr.null_count == 0 else np.asarray(
        arr.is_valid()
    )
    t = field.type
    if t.is_string:
        d = dicts.for_column(field.name)
        if pa.types.is_dictionary(arr.type):
            # Remap the batch-local dictionary into the table-level one.
            local = arr.dictionary.to_pylist()
            remap = np.fromiter(
                (d.add(v if v is not None else b"") for v in local),
                dtype=np.int32, count=len(local),
            )
            idx = np.asarray(arr.indices.fill_null(0), dtype=np.int32)
            vals = remap[idx] if len(local) else np.zeros(n, np.int32)
        else:
            py = arr.to_pylist()
            vals = np.fromiter(
                (d.add(v) if v is not None else 0 for v in py),
                dtype=np.int32, count=n,
            )
        return vals, validity
    if t.is_decimal:
        # decimal128 → scaled int64; arrow gives Decimal objects host-side.
        py = arr.to_pylist()
        scale = 10 ** t.scale
        vals = np.fromiter(
            (
                int(v.scaleb(t.scale).to_integral_value()) if v is not None else 0
                for v in py
            ),
            dtype=np.int64, count=n,
        )
        del scale
        return vals, validity
    if pa.types.is_timestamp(arr.type):
        arr = arr.cast(pa.timestamp("us"))
        vals = np.asarray(arr.fill_null(0), dtype="datetime64[us]").astype(np.int64)
        return vals, validity
    if pa.types.is_date32(arr.type):
        vals = np.asarray(arr.fill_null(0), dtype="datetime64[D]").astype(np.int32)
        return vals, validity
    fill = False if pa.types.is_boolean(arr.type) else 0
    vals = np.asarray(arr.fill_null(fill)).astype(t.physical, copy=False)
    return vals, validity


def record_batch_to_block(
    batch: pa.RecordBatch | pa.Table,
    dicts: DictionarySet,
    schema: dtypes.Schema | None = None,
    capacity: int | None = None,
) -> TableBlock:
    if schema is None:
        schema = schema_from_arrow(batch.schema)
    arrays: dict[str, np.ndarray] = {}
    validity: dict[str, np.ndarray] = {}
    for f in schema.fields:
        col = batch.column(f.name)
        arrays[f.name], validity[f.name] = _column_to_numpy(col, f, dicts)
    return TableBlock.from_numpy(arrays, schema, validity, capacity=capacity)


def block_to_record_batch(
    block: TableBlock, dicts: DictionarySet | None = None
) -> pa.RecordBatch:
    """Materialize live rows back into an Arrow RecordBatch (host)."""
    import decimal as pydec

    data = block.to_numpy()
    valid = block.validity_numpy()
    out = []
    names = []
    for f in block.schema.fields:
        v = data[f.name]
        mask = ~valid[f.name]
        t = f.type
        if t.is_string:
            if dicts is not None and f.name in dicts:
                vals = dicts[f.name].decode(v)
                arr = pa.array(
                    [None if m else s for s, m in zip(vals, mask)],
                    type=pa.binary(),
                )
            else:
                arr = pa.array(v, mask=mask, type=pa.int32())
        elif t.is_decimal:
            q = pydec.Decimal(1).scaleb(-t.scale)
            arr = pa.array(
                [
                    None if m else pydec.Decimal(int(x)).scaleb(-t.scale).quantize(q)
                    for x, m in zip(v, mask)
                ],
                type=pa.decimal128(38, t.scale),
            )
        elif t.kind == dtypes.Kind.DATE:
            arr = pa.array(v.astype("datetime64[D]"), mask=mask)
        elif t.kind == dtypes.Kind.TIMESTAMP:
            arr = pa.array(v.astype("datetime64[us]"), mask=mask)
        else:
            arr = pa.array(v, mask=mask)
        out.append(arr)
        names.append(f.name)
    return pa.RecordBatch.from_arrays(out, names=names)
