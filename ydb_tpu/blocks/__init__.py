from ydb_tpu.blocks.block import Column, TableBlock  # noqa: F401
from ydb_tpu.blocks.dictionary import Dictionary, DictionarySet  # noqa: F401
