"""Logical column types and their device representations.

The reference models column types with NScheme type ids and Arrow types
(ydb/core/formats/arrow/arrow_helpers.cpp). On TPU every column must be a
fixed-shape numeric array, so each logical type maps to a *physical* jnp dtype
plus optional side metadata (decimal scale, string dictionary):

  INT8/16/32/64, UINT*        -> same-width ints (device)
  FLOAT, DOUBLE               -> float32 / float64
  BOOL                        -> bool_
  DATE                        -> int32 (days since epoch)
  TIMESTAMP                   -> int64 (microseconds since epoch)
  DECIMAL(p, s)               -> int64 scaled by 10**s   (exact arithmetic)
  STRING / UTF8               -> int32 dictionary ids; the dictionary itself
                                 stays on host (ydb_tpu.blocks.dictionary)

This file has no jax dependency at import time beyond dtype names; it is the
schema vocabulary shared by host (Arrow) and device (blocks) code.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np


class Kind(enum.Enum):
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT = "float32"
    DOUBLE = "float64"
    BOOL = "bool"
    DATE = "date"            # int32 days
    TIMESTAMP = "timestamp"  # int64 micros
    DECIMAL = "decimal"      # int64 scaled
    STRING = "string"        # int32 dict id


_PHYSICAL = {
    Kind.INT8: np.int8,
    Kind.INT16: np.int16,
    Kind.INT32: np.int32,
    Kind.INT64: np.int64,
    Kind.UINT8: np.uint8,
    Kind.UINT16: np.uint16,
    Kind.UINT32: np.uint32,
    Kind.UINT64: np.uint64,
    Kind.FLOAT: np.float32,
    Kind.DOUBLE: np.float64,
    Kind.BOOL: np.bool_,
    Kind.DATE: np.int32,
    Kind.TIMESTAMP: np.int64,
    Kind.DECIMAL: np.int64,
    Kind.STRING: np.int32,
}


@dataclasses.dataclass(frozen=True)
class LogicalType:
    """A logical column type. Hashable; used as static jit metadata."""

    kind: Kind
    # DECIMAL scale: value = unscaled / 10**scale. Ignored otherwise.
    scale: int = 0

    @property
    def physical(self) -> np.dtype:
        return np.dtype(_PHYSICAL[self.kind])

    @property
    def is_string(self) -> bool:
        return self.kind == Kind.STRING

    @property
    def is_decimal(self) -> bool:
        return self.kind == Kind.DECIMAL

    @property
    def is_floating(self) -> bool:
        return self.kind in (Kind.FLOAT, Kind.DOUBLE)

    @property
    def is_integer(self) -> bool:
        return self.kind in (
            Kind.INT8, Kind.INT16, Kind.INT32, Kind.INT64,
            Kind.UINT8, Kind.UINT16, Kind.UINT32, Kind.UINT64,
            Kind.DATE, Kind.TIMESTAMP,
        )

    def __repr__(self) -> str:
        if self.kind == Kind.DECIMAL:
            return f"decimal(s={self.scale})"
        return self.kind.value


INT8 = LogicalType(Kind.INT8)
INT16 = LogicalType(Kind.INT16)
INT32 = LogicalType(Kind.INT32)
INT64 = LogicalType(Kind.INT64)
UINT8 = LogicalType(Kind.UINT8)
UINT16 = LogicalType(Kind.UINT16)
UINT32 = LogicalType(Kind.UINT32)
UINT64 = LogicalType(Kind.UINT64)
FLOAT = LogicalType(Kind.FLOAT)
DOUBLE = LogicalType(Kind.DOUBLE)
BOOL = LogicalType(Kind.BOOL)
DATE = LogicalType(Kind.DATE)
TIMESTAMP = LogicalType(Kind.TIMESTAMP)
STRING = LogicalType(Kind.STRING)


def decimal(scale: int) -> LogicalType:
    return LogicalType(Kind.DECIMAL, scale=scale)


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    type: LogicalType
    nullable: bool = True


@dataclasses.dataclass(frozen=True)
class Schema:
    """Ordered, hashable column schema (static under jit)."""

    fields: tuple[Field, ...]

    def __post_init__(self):
        object.__setattr__(self, "fields", tuple(self.fields))

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(f"no column {name!r} in schema {self.names}")

    def __contains__(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def select(self, names) -> "Schema":
        return Schema(tuple(self.field(n) for n in names))

    def with_field(self, f: Field) -> "Schema":
        return Schema(self.fields + (f,))


def schema(*cols: tuple) -> Schema:
    """schema(("a", INT32), ("b", STRING, False), ...)"""
    fields = []
    for c in cols:
        if len(c) == 2:
            fields.append(Field(c[0], c[1]))
        else:
            fields.append(Field(c[0], c[1], c[2]))
    return Schema(tuple(fields))
