"""ydb_tpu — a TPU-native distributed SQL data framework.

A ground-up rebuild of the capabilities of YDB (reference: rohankumardubey/ydb)
designed TPU-first on JAX/XLA: columnar SSA programs execute as fused XLA
kernels over fixed-shape device column blocks, inter-shard shuffles map onto
``all_to_all``/``psum`` over the ICI mesh, and the host runtime (tablets,
transactions, control plane) stays on CPU where it belongs.

Planes (see SURVEY.md §7.0):
  * ``ydb_tpu.blocks``   — Arrow ⇄ device column-block bridge
  * ``ydb_tpu.ssa``      — SSA scan program model + JAX kernel registry
                           (reference: ydb/core/protos/ssa.proto,
                           ydb/core/formats/arrow/program.h)
  * ``ydb_tpu.engine``   — column engine: portions, granules, MVCC snapshots,
                           insert/compaction/TTL (reference:
                           ydb/core/tx/columnshard/engines/)
  * ``ydb_tpu.dq``       — distributed dataflow: tasks, channels, runners
                           (reference: ydb/library/yql/dq/)
  * ``ydb_tpu.parallel`` — mesh, shardings, collective shuffle/aggregate
  * ``ydb_tpu.sql``      — SQL frontend + planner (reference: ydb/core/kqp)
  * ``ydb_tpu.runtime``  — actor shim, counters, tracing, config knobs
"""

import jax

# Decimal columns are scaled int64; aggregate accumulators must not silently
# truncate to 32 bits (reference keeps exact i64/i128 decimal sums —
# ydb/library/yql/minikql/comp_nodes/mkql_block_agg.cpp). TPU emulates int64
# on the VPU; hot kernels opt back into int32 pairs explicitly where measured.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
