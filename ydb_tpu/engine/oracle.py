"""CPU reference engine (the correctness oracle and default engine).

The reference keeps a CPU MiniKQL engine as the default with the
accelerator runner plugged in behind a factory seam (SURVEY.md §2.9,
TComputationNodeFactory mkql_factory.cpp:360). This module is that default
engine for SSA programs: a straightforward numpy evaluator with identical
semantics to the JAX lowering (nulls, Kleene logic, decimal scaling,
group-by, sort). Deliberately implemented independently of
ydb_tpu.ssa.kernels so tests can cross-check the two.
"""

from __future__ import annotations

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.ssa.ops import Agg, Op
from ydb_tpu.ssa.program import (
    AssignStep,
    Call,
    Col,
    Const,
    DictPredicate,
    FilterStep,
    GroupByStep,
    ProjectStep,
    Program,
    SortStep,
    WindowStep,
    agg_result_type,
    infer_type,
)

Array = np.ndarray
ColT = tuple[Array, Array]  # (values, validity)


class OracleTable:
    """Host columnar table: name -> (values, validity)."""

    def __init__(self, cols: dict[str, ColT], schema: dtypes.Schema):
        self.cols = cols
        self.schema = schema
        self.dicts = None  # attached by the session for string decode

    @property
    def num_rows(self) -> int:
        if not self.cols:
            return 0
        return len(next(iter(self.cols.values()))[0])

    def column(self, name: str):
        return self.cols[name][0]

    def validity(self, name: str):
        return self.cols[name][1]

    def strings(self, name: str, dicts=None) -> list[bytes]:
        """Decode a dictionary-encoded string column to bytes values."""
        dicts = dicts if dicts is not None else self.dicts
        if dicts is None:
            raise ValueError("no DictionarySet attached for decode")
        return dicts[name].decode(np.asarray(self.cols[name][0]))

    @staticmethod
    def from_block(block) -> "OracleTable":
        # one batched device fetch for data + validity together: each
        # separate fetch costs a device-link round trip
        data, valid = block.host_columns()
        return OracleTable(
            {n: (data[n], valid[n]) for n in data}, block.schema
        )


def run_oracle(
    program: Program,
    table: OracleTable,
    dicts: DictionarySet | None = None,
) -> OracleTable:
    cols = dict(table.cols)
    types = {f.name: f.type for f in table.schema.fields}
    n = table.num_rows
    mask = np.ones(n, dtype=bool)
    names = list(cols.keys())

    for step in program.steps:
        if isinstance(step, AssignStep):
            cols[step.name] = _eval(step.expr, cols, types, dicts, n)
            types[step.name] = infer_type(step.expr, table.schema, types)
            if step.name not in names:
                names.append(step.name)
        elif isinstance(step, FilterStep):
            v, ok = _eval(step.expr, cols, types, dicts, n)
            mask = mask & (v.astype(bool) & ok)
        elif isinstance(step, ProjectStep):
            names = list(step.names)
        elif isinstance(step, GroupByStep):
            cols, types, names = _group_by(step, cols, types, mask, dicts,
                                           table.schema)
            n = len(next(iter(cols.values()))[0]) if cols else 0
            mask = np.ones(n, dtype=bool)
        elif isinstance(step, SortStep):
            cols = {nm: (c[0][mask], c[1][mask]) for nm, c in cols.items()}
            n = int(mask.sum())
            mask = np.ones(n, dtype=bool)
            order = _sort_order(step, cols, types, dicts)
            cols = {nm: (c[0][order], c[1][order]) for nm, c in cols.items()}
            if step.limit is not None:
                cols = {nm: (c[0][:step.limit], c[1][:step.limit])
                        for nm, c in cols.items()}
                n = min(n, step.limit)
                mask = np.ones(n, dtype=bool)
        elif isinstance(step, WindowStep):
            # deliberately DIFFERENT algorithm from the device plane:
            # python sort + per-partition scan (vs lexsort + segment
            # cummax), so the cross-check is independent
            live_idx = np.flatnonzero(mask)

            def keyval(col, i):
                v = cols[col][0][i]
                t = types[col]
                if t.is_string:
                    return int(dicts[col].sort_rank()[int(v)])
                return v

            def sort_key(i):
                parts = [keyval(k, i) for k in step.partition]
                orders = [
                    -keyval(k, i) if dsc else keyval(k, i)
                    for k, dsc in zip(
                        step.order_keys,
                        step.descending
                        or (False,) * len(step.order_keys))]
                return (parts, orders)

            ranked = sorted(live_idx.tolist(),
                            key=lambda i: tuple(
                                map(tuple, sort_key(i))))
            out = np.zeros(len(mask), dtype=np.int64)
            prev_part = prev_order = None
            rown = rank = dense = 0
            for i in ranked:
                parts, orders = sort_key(i)
                if parts != prev_part:
                    rown = rank = dense = 0
                    prev_order = None
                rown += 1
                if orders != prev_order:
                    rank = rown
                    dense += 1
                out[i] = {"row_number": rown, "rank": rank,
                          "dense_rank": dense}[step.func]
                prev_part, prev_order = parts, orders
            cols[step.out_name] = (out, mask.copy())
            types[step.out_name] = dtypes.INT64
            if step.out_name not in names:
                names.append(step.out_name)
        else:
            raise NotImplementedError(step)

    out_cols = {nm: (cols[nm][0][mask], cols[nm][1][mask]) for nm in names}
    out_schema = dtypes.Schema(
        tuple(dtypes.Field(nm, types[nm]) for nm in names)
    )
    return OracleTable(out_cols, out_schema)


def _const_array(c: Const, n: int) -> ColT:
    if c.value is None:  # typed NULL (CASE without ELSE)
        return (
            np.zeros(n, dtype=c.type.physical),
            np.zeros(n, dtype=bool),
        )
    return (
        np.full(n, c.value, dtype=c.type.physical),
        np.ones(n, dtype=bool),
    )


def _eval(expr, cols, types, dicts, n) -> ColT:
    from ydb_tpu.ssa.program import DictMap, UdfCall

    if isinstance(expr, Col):
        return cols[expr.name]
    if isinstance(expr, Const):
        return _const_array(expr, n)
    if isinstance(expr, UdfCall):
        args = [_eval(a, cols, types, dicts, n) for a in expr.args]
        valid = args[0][1].copy()
        for _, ok in args[1:]:
            valid &= ok
        out = np.asarray(expr.fn(*[v for v, _ in args]),
                         dtype=expr.out_type.physical)
        return out, valid
    if isinstance(expr, DictMap):
        from ydb_tpu.ssa.compiler import dict_map_table

        d = dicts[expr.column]
        out_d = dicts.for_column(expr.out_column)
        table = dict_map_table(d, out_d, expr.kind, expr.args)
        ids, ok = cols[expr.column]
        return table[np.clip(ids, 0, len(table) - 1)], ok.copy()
    if isinstance(expr, DictPredicate):
        d = dicts[expr.column]
        ids, ok = cols[expr.column]
        if expr.kind in ("eq", "ne"):
            table = np.zeros(max(len(d), 1), dtype=bool)
            i = d.eq_id(expr.pattern)
            if i >= 0:
                table[i] = True
            if expr.kind == "ne":
                table = ~table
        elif expr.kind == "like":
            table = d.like_mask(expr.pattern)
        elif expr.kind == "prefix":
            table = d.prefix_mask(expr.pattern)
        elif expr.kind in ("in_set", "not_in_set"):
            table = np.zeros(max(len(d), 1), dtype=bool)
            for v in expr.pattern:
                i = d.eq_id(v)
                if i >= 0:
                    table[i] = True
            if expr.kind == "not_in_set":
                table = ~table
        elif expr.kind == "custom":
            from ydb_tpu.ssa.compiler import _custom_dict_mask

            table = _custom_dict_mask(d, expr.pattern)
        else:
            raise NotImplementedError(expr.kind)
        if len(table) == 0:
            table = np.zeros(1, dtype=bool)
        return table[np.clip(ids, 0, len(table) - 1)], ok.copy()
    assert isinstance(expr, Call)
    op = expr.op
    args = [_eval(a, cols, types, dicts, n) for a in expr.args]
    ts = [infer_type(a, None, types) if not isinstance(a, Const) else a.type
          for a in expr.args]
    return _apply_op(op, expr, args, ts, cols, types, dicts, n)


def _align_dec(op, args, ts):
    if len(ts) != 2 or not (ts[0].is_decimal or ts[1].is_decimal):
        return args
    sa = ts[0].scale if ts[0].is_decimal else 0
    sb = ts[1].scale if ts[1].is_decimal else 0
    if sa == sb:
        return args
    t = max(sa, sb)
    out = list(args)
    for i, s in enumerate((sa, sb)):
        if s < t:
            v, ok = out[i]
            if np.issubdtype(v.dtype, np.floating):
                out[i] = (np.round(v * 10 ** (t - s)).astype(np.int64), ok)
            else:
                out[i] = (v.astype(np.int64) * 10 ** (t - s), ok)
    return out


def _descale_mixed_np(args, ts):
    """decimal op float -> both float (matches compiler._descale_mixed)."""
    if len(ts) != 2:
        return args, ts
    a, b = ts
    if not ((a.is_decimal and b.is_floating)
            or (b.is_decimal and a.is_floating)):
        return args, ts
    out = list(args)
    t_out = list(ts)
    for i, t in enumerate(ts):
        if t.is_decimal:
            v, ok = out[i]
            out[i] = (v.astype(np.float64) / 10.0 ** t.scale, ok)
            t_out[i] = dtypes.DOUBLE
    return out, t_out


_F_UN = {Op.SQRT: np.sqrt, Op.EXP: np.exp, Op.LN: np.log,
         Op.LOG10: np.log10, Op.FLOOR: np.floor, Op.CEIL: np.ceil,
         Op.ROUND: np.round, Op.SIGN: np.sign, Op.SIN: np.sin,
         Op.COS: np.cos, Op.TAN: np.tan, Op.ASIN: np.arcsin,
         Op.ACOS: np.arccos, Op.ATAN: np.arctan, Op.SINH: np.sinh,
         Op.COSH: np.cosh, Op.TANH: np.tanh, Op.ASINH: np.arcsinh,
         Op.ACOSH: np.arccosh, Op.ATANH: np.arctanh,
         Op.CBRT: np.cbrt, Op.LOG2: np.log2, Op.EXP2: np.exp2,
         Op.TRUNC: np.trunc, Op.RINT: np.round,
         Op.RADIANS: np.deg2rad, Op.DEGREES: np.rad2deg}
# ops computed in float64 (everything but the shape-preserving four)
_F_UN_FLOAT = frozenset(_F_UN) - {Op.FLOOR, Op.CEIL, Op.ROUND, Op.SIGN}


def _apply_op(op, expr, args, ts, cols, types, dicts, n) -> ColT:
    # decimal MUL multiplies unscaled values (scales add); only additive and
    # comparison ops align operand scales
    if op in (Op.ADD, Op.SUB, Op.MUL, Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT,
              Op.GE, Op.DIV, Op.GREATEST, Op.LEAST):
        args, ts = _descale_mixed_np(args, ts)
    if op in (Op.ADD, Op.SUB, Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT,
              Op.GE, Op.MOD, Op.GREATEST, Op.LEAST):
        args = _align_dec(op, args, ts)
    simple = {
        Op.EQ: np.equal, Op.NE: np.not_equal, Op.LT: np.less,
        Op.LE: np.less_equal, Op.GT: np.greater, Op.GE: np.greater_equal,
        Op.ADD: np.add, Op.SUB: np.subtract, Op.MUL: np.multiply,
        Op.XOR: np.bitwise_xor,
        Op.GREATEST: np.maximum, Op.LEAST: np.minimum,
    }
    if op in simple:
        (a, va), (b, vb) = args
        return simple[op](a, b), va & vb
    if op is Op.AND:
        (a, va), (b, vb) = args
        return a & b, ((~a & va) | (~b & vb) | (va & vb))
    if op is Op.OR:
        (a, va), (b, vb) = args
        return a | b, ((a & va) | (b & vb) | (va & vb))
    if op is Op.NOT:
        a, va = args[0]
        return ~a, va
    if op in (Op.NEG,):
        a, va = args[0]
        return -a, va
    if op is Op.ABS:
        a, va = args[0]
        return np.abs(a), va
    if op is Op.DIV:
        (a, va), (b, vb) = args
        ta, tb = ts
        zero = b == 0
        denom = np.where(zero, 1, b)
        if ta.is_floating or tb.is_floating or ta.is_decimal or tb.is_decimal:
            fa = a.astype(np.float64) / (10.0 ** ta.scale if ta.is_decimal else 1)
            fb = denom.astype(np.float64) / (10.0 ** tb.scale if tb.is_decimal else 1)
            fb = np.where(fb == 0, 1.0, fb)
            return fa / fb, va & vb & ~zero
        # SQL integer division truncates toward zero
        q = np.floor_divide(a, denom)
        q = np.where((a - q * denom != 0) & ((a < 0) ^ (denom < 0)), q + 1, q)
        return q, va & vb & ~zero
    if op is Op.MOD:
        (a, va), (b, vb) = args
        zero = b == 0
        denom = np.where(zero, 1, b)
        q = np.floor_divide(a, denom)
        q = np.where((a - q * denom != 0) & ((a < 0) ^ (denom < 0)), q + 1, q)
        return a - denom * q, va & vb & ~zero
    if op is Op.IS_NULL:
        a, va = args[0]
        return ~va, np.ones(len(va), dtype=bool)
    if op is Op.IS_NOT_NULL:
        a, va = args[0]
        return va.copy(), np.ones(len(va), dtype=bool)
    if op is Op.COALESCE:
        data, valid = args[-1]
        data, valid = data.copy(), valid.copy()
        for a, va in reversed(args[:-1]):
            data = np.where(va, a, data)
            valid = va | valid
        return data, valid
    if op is Op.IF:
        (c, vc), (a, va), (b, vb) = args
        take = c.astype(bool) & vc
        return np.where(take, a, b), vc & np.where(take, va, vb)
    if op in (Op.CAST_INT32, Op.CAST_INT64, Op.CAST_FLOAT,
              Op.CAST_DOUBLE, Op.CAST_INT8, Op.CAST_INT16,
              Op.CAST_UINT64, Op.CAST_BOOL):
        a, va = args[0]
        ta = ts[0]
        target = {
            Op.CAST_INT32: np.int32, Op.CAST_INT64: np.int64,
            Op.CAST_FLOAT: np.float32, Op.CAST_DOUBLE: np.float64,
            Op.CAST_INT8: np.int8, Op.CAST_INT16: np.int16,
            Op.CAST_UINT64: np.uint64, Op.CAST_BOOL: np.bool_,
        }[op]
        if ta.is_decimal:
            if np.issubdtype(target, np.floating):
                return (a.astype(np.float64) / 10 ** ta.scale).astype(target), va
            return (a // 10 ** ta.scale).astype(target), va
        return a.astype(target), va
    if op in (Op.YEAR, Op.MONTH, Op.DAY):
        a, va = args[0]
        ta = ts[0]
        days = a // 86_400_000_000 if ta.kind == dtypes.Kind.TIMESTAMP else a
        dt = days.astype("datetime64[D]")
        if op is Op.YEAR:
            return dt.astype("datetime64[Y]").astype(int) + 1970, va
        if op is Op.MONTH:
            m = (dt.astype("datetime64[M]").astype(int) % 12) + 1
            return m.astype(np.int32), va
        dom = (dt - dt.astype("datetime64[M]")).astype(int) + 1
        return dom.astype(np.int32), va
    if op in (Op.HOUR, Op.MINUTE, Op.SECOND):
        a, va = args[0]
        if ts[0].kind != dtypes.Kind.TIMESTAMP:
            # identical semantics to the JAX lowering: sub-day parts
            # of a DATE are an error, not silent zeros
            raise TypeError(f"{op} needs a timestamp operand")
        div = {Op.HOUR: 3_600_000_000, Op.MINUTE: 60_000_000,
               Op.SECOND: 1_000_000}[op]
        mod = 24 if op is Op.HOUR else 60
        return ((a // div) % mod).astype(np.int32), va
    if op in (Op.DAY_OF_WEEK, Op.DAY_OF_YEAR, Op.WEEK, Op.QUARTER):
        a, va = args[0]
        days = (a // 86_400_000_000
                if ts[0].kind == dtypes.Kind.TIMESTAMP else a)
        days = days.astype(np.int64)
        if op is Op.DAY_OF_WEEK:
            return ((days + 4) % 7).astype(np.int32), va
        dt = days.astype("datetime64[D]")
        if op is Op.QUARTER:
            m = (dt.astype("datetime64[M]").astype(int) % 12) + 1
            return ((m - 1) // 3 + 1).astype(np.int32), va
        jan1 = dt.astype("datetime64[Y]").astype("datetime64[D]")
        doy = (dt - jan1).astype(int) + 1
        if op is Op.DAY_OF_YEAR:
            return doy.astype(np.int32), va
        return ((doy - 1) // 7 + 1).astype(np.int32), va
    if op in _F_UN:
        a, va = args[0]
        f = _F_UN[op]
        if op in _F_UN_FLOAT:
            with np.errstate(all="ignore"):
                return f(a.astype(np.float64)), va
        return f(a), va

    if op is Op.ERF:
        import math

        a, va = args[0]
        return np.vectorize(math.erf)(a.astype(np.float64)), va
    if op in (Op.ATAN2, Op.HYPOT):
        (a, va), (b, vb) = args
        f = np.arctan2 if op is Op.ATAN2 else np.hypot
        return f(a.astype(np.float64), b.astype(np.float64)), va & vb
    if op in (Op.BIT_AND, Op.BIT_OR, Op.BIT_XOR, Op.SHIFT_LEFT,
              Op.SHIFT_RIGHT):
        (a, va), (b, vb) = args
        f = {Op.BIT_AND: np.bitwise_and, Op.BIT_OR: np.bitwise_or,
             Op.BIT_XOR: np.bitwise_xor,
             Op.SHIFT_LEFT: np.left_shift,
             Op.SHIFT_RIGHT: np.right_shift}[op]
        return f(a, b), va & vb
    if op is Op.BIT_NOT:
        a, va = args[0]
        return np.bitwise_not(a), va
    if op is Op.DIV_INT:
        (a, va), (b, vb) = args
        ta, tb = ts[0], ts[1]
        zero = b == 0
        if (ta.is_decimal or tb.is_decimal or ta.is_floating
                or tb.is_floating):
            sa = 10.0 ** ta.scale if ta.is_decimal else 1.0
            sb = 10.0 ** tb.scale if tb.is_decimal else 1.0
            av = a.astype(np.float64) / sa
            bv = np.where(zero, 1.0, b.astype(np.float64) / sb)
            return np.trunc(av / bv).astype(np.int64), va & vb & ~zero
        denom = np.where(zero, 1, b)
        q = np.sign(a) * np.sign(denom) * (np.abs(a) // np.abs(denom))
        return q, va & vb & ~zero
    if op is Op.NULLIF:
        (a, va), (b, vb) = args
        ta, tb = ts[0], ts[1]
        sa = ta.scale if ta.is_decimal else 0
        sb = tb.scale if tb.is_decimal else 0
        if ta.is_floating or tb.is_floating:
            av = a.astype(np.float64) / 10.0 ** sa
            bv = b.astype(np.float64) / 10.0 ** sb
            equal = (av == bv) & vb
        else:
            m = max(sa, sb)
            equal = (a * 10 ** (m - sa) == b * 10 ** (m - sb)) & vb
        return a, va & ~equal
    if op is Op.POW:
        (a, va), (b, vb) = args
        return np.power(a.astype(np.float64), b.astype(np.float64)), va & vb
    if op is Op.IN_SET:
        a, va = args[0]
        hit = np.zeros(len(a), dtype=bool)
        for cst in expr.args[1:]:
            hit |= a == cst.value
        return hit, va
    raise NotImplementedError(op)


def _group_by(step: GroupByStep, cols, types, mask, dicts, schema):
    import numpy as np

    key_vals = []
    for k in step.keys:
        v, ok = cols[k]
        key_vals.append(np.where(ok, v, 0))
        key_vals.append(ok)
    nrows = len(mask)
    if step.keys:
        stacked = np.rec.fromarrays(key_vals)
        live_keys = stacked[mask]
        uniq, inv = np.unique(live_keys, return_inverse=True)
        ngroups = len(uniq)
    else:
        ngroups = 1
        inv = np.zeros(int(mask.sum()), dtype=np.int64)

    out_cols: dict[str, ColT] = {}
    out_types: dict[str, dtypes.LogicalType] = {}
    for i, k in enumerate(step.keys):
        v, ok = cols[k]
        lv, lok = v[mask], ok[mask]
        kd = np.zeros(ngroups, dtype=v.dtype)
        kv = np.zeros(ngroups, dtype=bool)
        kd[inv] = lv
        kv[inv] = lok
        out_cols[k] = (kd, kv)
        out_types[k] = types[k]

    for spec in step.aggs:
        t = agg_result_type(spec, schema, types)
        out_types[spec.out_name] = t
        if spec.func is Agg.COUNT_ALL:
            data = np.bincount(inv, minlength=ngroups).astype(np.int64)
            valid = (
                np.ones(ngroups, dtype=bool)
                if not step.keys
                else data >= 0
            )
            out_cols[spec.out_name] = (data, valid)
            continue
        v, ok = cols[spec.column]
        lv, lok = v[mask], ok[mask]
        nn = np.bincount(inv[lok], minlength=ngroups).astype(np.int64)
        if spec.func is Agg.COUNT:
            out_cols[spec.out_name] = (
                nn,
                np.ones(ngroups, dtype=bool) if not step.keys else nn >= 0,
            )
            continue
        if spec.func is Agg.SUM:
            acc = np.zeros(ngroups, dtype=t.physical)
            np.add.at(acc, inv[lok], lv[lok].astype(t.physical))
            out_cols[spec.out_name] = (acc, nn > 0)
        elif spec.func is Agg.AVG:
            src_t = types[spec.column]
            acc = np.zeros(ngroups, dtype=np.float64)
            np.add.at(acc, inv[lok], lv[lok].astype(np.float64))
            if src_t.is_decimal:
                acc /= 10.0 ** src_t.scale
            out_cols[spec.out_name] = (
                acc / np.maximum(nn, 1), nn > 0
            )
        elif spec.func in (Agg.VAR_SAMP, Agg.STDDEV_SAMP):
            # deliberately DIFFERENT algorithm from the device plane:
            # stable two-pass np.var per group, so the oracle
            # cross-check detects the linear-state formula's
            # catastrophic-cancellation regime instead of sharing it
            src_t = types[spec.column]
            v = lv[lok].astype(np.float64)
            if src_t.is_decimal:
                v = v / 10.0 ** src_t.scale
            gi = inv[lok]
            var = np.zeros(ngroups, dtype=np.float64)
            for gidx in range(ngroups):
                vals = v[gi == gidx]
                if len(vals) >= 2:
                    var[gidx] = np.var(vals, ddof=1)
            out = np.sqrt(var) if spec.func is Agg.STDDEV_SAMP else var
            out_cols[spec.out_name] = (out, nn > 1)
        elif spec.func in (Agg.MIN, Agg.MAX):
            src_t = types[spec.column]
            vals = lv
            if src_t.is_string:
                rank = dicts[spec.column].sort_rank()
                vals = rank[lv].astype(np.int64) << 32 | lv.astype(np.int64)
            red = np.minimum if spec.func is Agg.MIN else np.maximum
            if np.issubdtype(vals.dtype, np.floating):
                init = np.inf if spec.func is Agg.MIN else -np.inf
            else:
                ii = np.iinfo(vals.dtype)
                init = ii.max if spec.func is Agg.MIN else ii.min
            acc = np.full(ngroups, init, dtype=vals.dtype)
            red.at(acc, inv[lok], vals[lok])
            if src_t.is_string:
                acc = (acc & 0xFFFFFFFF).astype(np.int32)
            out_cols[spec.out_name] = (acc, nn > 0)
        elif spec.func is Agg.SOME:
            acc = np.zeros(ngroups, dtype=lv.dtype)
            acc[inv[lok][::-1]] = lv[lok][::-1]
            out_cols[spec.out_name] = (acc, nn > 0)
        else:
            raise NotImplementedError(spec.func)

    names = list(step.keys) + [s.out_name for s in step.aggs]
    return out_cols, out_types, names


def _sort_order(step: SortStep, cols, types, dicts):
    desc = step.descending or (False,) * len(step.keys)
    sort_keys = []
    for k, dsc in zip(reversed(step.keys), reversed(desc)):
        v, ok = cols[k]
        t = types[k]
        if t.is_string and dicts is not None and k in dicts:
            v = dicts[k].sort_rank()[v]
        d = v
        if dsc:
            if d.dtype == np.bool_:
                d = ~d
            elif np.issubdtype(d.dtype, np.integer):
                d = ~d
            else:
                d = -d
        sort_keys.append(d)
        sort_keys.append(~ok)
    return np.lexsort(tuple(sort_keys))
