"""Single-shard scan execution: stream column blocks through a compiled
SSA program with partial/final aggregation.

This is the minimum end-to-end slice of the reference's ColumnShard scan
(SURVEY.md §3.3): portions → assemble → program steps → merged result.
Here: a host column source is tiled into fixed-capacity device blocks; the
*partial* program (filters + assigns + partial group-by) runs jitted per
block (one XLA compile for all blocks — identical shapes); the small
partial results are merged by the *final* program. Programs without a
GROUP BY concatenate block outputs directly.

The per-block loop is the host-side analog of the scan iterator
(engines/reader/plain_reader/iterator/iterator.h:53) — flow control,
prefetch and credit windows attach here (ydb_tpu.dq channels reuse it).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.chaos import deadline as statement_deadline
from ydb_tpu.blocks.block import (
    Column,
    TableBlock,
    concat_blocks,
    device_aux,
)
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.ssa import kernels, twophase
from ydb_tpu.ssa.compiler import compile_program
from ydb_tpu.ssa.program import Program

DEFAULT_BLOCK_ROWS = 1 << 20


@dataclasses.dataclass
class ColumnSource:
    """A host-resident columnar table (one shard's worth of data)."""

    columns: dict[str, np.ndarray]
    schema: dtypes.Schema
    dicts: DictionarySet | None = None
    validity: dict[str, np.ndarray] | None = None

    @property
    def num_rows(self) -> int:
        return len(next(iter(self.columns.values()))) if self.columns else 0

    def n_blocks(self, block_rows: int = DEFAULT_BLOCK_ROWS) -> int:
        n = self.num_rows
        cap = min(block_rows, max(n, 1))
        return len(range(0, max(n, 1), cap))

    def blocks(
        self, block_rows: int = DEFAULT_BLOCK_ROWS,
        columns: tuple[str, ...] | None = None,
        start_block: int = 0,
    ) -> Iterator[TableBlock]:
        """Tile into equal-capacity blocks (last one padded).
        ``start_block`` seeks without materializing skipped blocks
        (checkpoint-resume path)."""
        names = columns if columns is not None else self.schema.names
        sch = self.schema.select(names)
        n = self.num_rows
        cap = min(block_rows, max(n, 1))
        for off in range(start_block * cap, max(n, 1), cap):
            hi = min(off + cap, n)
            arrays = {m: self.columns[m][off:hi] for m in names}
            validity = None
            if self.validity:
                validity = {
                    m: self.validity[m][off:hi]
                    for m in names if m in self.validity
                }
            yield TableBlock.from_numpy(arrays, sch, validity, capacity=cap)


def merge_blocks_device(blocks: list[TableBlock]) -> TableBlock:
    """Trace-time concat of blocks (live rows compacted to the front).

    The device twin of ``concat_blocks``: everything stays on the chip —
    no host round trip, which matters enormously when the device sits
    behind a network tunnel (each to_numpy costs a full RTT)."""
    if len(blocks) == 1:
        return blocks[0]
    schema = blocks[0].schema
    live = jnp.concatenate([b.row_mask() for b in blocks])
    cols = {}
    for n in schema.names:
        data = jnp.concatenate([b.columns[n].data for b in blocks])
        val = jnp.concatenate([b.columns[n].validity for b in blocks])
        cols[n] = Column(data, val)
    # live rows sit at each segment's start, not in one prefix: give the
    # concat full-capacity length so compact's row_mask covers them all
    blk = TableBlock(cols, jnp.int32(live.shape[0]), schema)
    return kernels.compact(blk, live)


def required_columns(program: Program, schema: dtypes.Schema) -> tuple[str, ...]:
    """Input columns the program actually reads (scan projection pushdown)."""
    from ydb_tpu.ssa.program import (
        AssignStep, Call, Col, DictMap, DictPredicate, FilterStep,
        GroupByStep, ProjectStep, SortStep, UdfCall,
    )

    used: set[str] = set()
    assigned: set[str] = set()

    def walk(e):
        if isinstance(e, Col):
            if e.name not in assigned:
                used.add(e.name)
        elif isinstance(e, (Call, UdfCall)):
            for a in e.args:
                walk(a)
        elif isinstance(e, (DictPredicate, DictMap)):
            if e.column not in assigned:
                used.add(e.column)

    for s in program.steps:
        if isinstance(s, AssignStep):
            walk(s.expr)
            assigned.add(s.name)
        elif isinstance(s, FilterStep):
            walk(s.expr)
        elif isinstance(s, GroupByStep):
            for k in s.keys:
                if k not in assigned:
                    used.add(k)
            for a in s.aggs:
                if a.column is not None and a.column not in assigned:
                    used.add(a.column)
        elif isinstance(s, SortStep):
            for k in s.keys:
                if k not in assigned:
                    used.add(k)
        elif isinstance(s, ProjectStep):
            for nm in s.names:
                if nm not in assigned:
                    used.add(nm)
    if not used:
        # pure COUNT(*)-style programs still need one column for the row
        # count; read the narrowest physical column (the reference reads a
        # system column)
        if not schema.fields:
            return ()
        cheapest = min(
            schema.fields, key=lambda f: f.type.physical.itemsize
        )
        return (cheapest.name,)
    return tuple(n for n in schema.names if n in used)


class ScanExecutor:
    """Compiles a program against a source and executes block-streamed.

    Memory discipline (the TChunksLimiter credit idea,
    ydb/library/chunks_limiter/chunks_limiter.h:7, re-expressed for XLA's
    async dispatch): the block loop keeps at most ``inflight_blocks``
    dispatched-but-unfinished device computations — each in-flight
    execution pins its input block's buffers, so an unbounded dispatch
    queue (slow device / starved host) would retain the whole table.
    Aggregation partials additionally fold incrementally every
    ``combine_every`` blocks through the associative combine program
    (twophase.combine_of) whenever the group layout is shape-stable, so
    the partials list never grows with the table either.
    """

    def __init__(
        self,
        program: Program,
        source: ColumnSource,
        block_rows: int = DEFAULT_BLOCK_ROWS,
        key_spaces: dict[str, int] | None = None,
        inflight_blocks: int = 4,
        combine_every: int = 8,
        group_est: float | None = None,
    ):
        self.source = source
        self.block_rows = block_rows
        self.inflight_blocks = inflight_blocks
        self.combine_every = combine_every
        # advisory NDV-based group-count estimate (stats.cost): steers
        # the PARTIAL program's group-by tier choice; the final/combine
        # programs run over small partial blocks and keep their own
        # sizing
        self.group_est = group_est
        # first dispatch of each jitted program (partial / combine /
        # final) = jit trace + XLA compile; measured once per program
        # and summed into first_trace_seconds so scan sites can
        # attribute the compile-vs-execute split that separates cold
        # from warm runs (finalize compiles too — attributing only the
        # partial would leak its compile into "execute")
        self.first_trace_seconds: float | None = None
        self._partial_traced = False
        self._combine_traced = False
        self._finalize_traced = False
        self.read_cols = required_columns(program, source.schema)
        in_schema = source.schema.select(self.read_cols)
        # verify the ORIGINAL program before the two-phase rewrite:
        # diagnostics then point at the caller's step indices, not at
        # synthesized partial/final steps (which compile_program still
        # re-checks as its own precondition). Its nullability also
        # types the RESULT schema below: the original program knows
        # keyed AVG over a non-null input is never NULL, while the
        # rewritten final program only sees a division fixup.
        from ydb_tpu.analysis.verify import check_program

        self._out_nullable = check_program(program, in_schema).out_nullable
        self.partial_prog, self.final_prog = twophase.split(program)
        self.partial = compile_program(
            self.partial_prog, in_schema, source.dicts, key_spaces,
            group_est=group_est,
        )
        self._partial_jit = jax.jit(self.partial.run)
        self._partial_aux = device_aux(self.partial.aux)
        self._combine_jit = None
        self._combine_aux = {}
        if self.final_prog is not None and self.partial.group_layout[0] in (
            "keyless", "dense", "dense_slots"
        ):
            combine_prog = twophase.combine_of(program)
            comb = compile_program(
                combine_prog, self.partial.out_schema, source.dicts,
                key_spaces,
                dict_aliases=twophase.dict_aliases(self.partial_prog),
            )
            comb_run = comb.run

            @jax.jit
            def _combine(parts, aux):
                return comb_run(merge_blocks_device(list(parts)), aux)

            self._combine_jit = _combine
            self._combine_aux = device_aux(comb.aux)
        if self.final_prog is not None:
            self.final = compile_program(
                self.final_prog, self.partial.out_schema, source.dicts,
                key_spaces,
                dict_aliases=twophase.dict_aliases(self.partial_prog),
            )
            self._final_jit = jax.jit(self.final.run)
            self._final_aux = device_aux(self.final.aux)
            self.out_schema = self._stamp_nullability(
                self.final.out_schema)
            final_run = self.final.run

            @jax.jit
            def _finalize(parts, aux):
                return final_run(merge_blocks_device(list(parts)), aux)

            self._finalize_jit = _finalize
        else:
            self.final = None
            self.out_schema = self._stamp_nullability(
                self.partial.out_schema)
            self._final_aux = {}
            self._finalize_jit = jax.jit(
                lambda parts, aux: merge_blocks_device(list(parts)))

    def detach(self) -> "ScanExecutor":
        """Drop the source reference: compiled state only. Callers that
        cache executors across source replacements (plan executor) must
        not pin the original table's arrays."""
        self.source = None
        return self

    def _timed_first(self, flag: str, fn, *args):
        """A program's first dispatch runs jit trace + XLA compile:
        time it synchronously, once (one-off sync; warm stays async),
        accumulating into ``first_trace_seconds``."""
        if getattr(self, flag):
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        # one-off sync: times the first dispatch's trace+compile (the
        # warm arm above stays async)
        # ydb-lint: disable=H001
        jax.block_until_ready(out)
        setattr(self, flag, True)
        self.first_trace_seconds = (
            (self.first_trace_seconds or 0.0)
            + time.perf_counter() - t0)
        return out

    def run_block(self, block: TableBlock) -> TableBlock:
        return self._timed_first("_partial_traced", self._partial_jit,
                                 block, self._partial_aux)

    def finalize(self, partials: list[TableBlock]) -> TableBlock:
        """Merge per-block partial results and run the final program —
        one jitted device computation end to end."""
        if self.final is None and len(partials) == 1:
            return partials[0]
        return self._timed_first("_finalize_traced", self._finalize_jit,
                                 tuple(partials), self._final_aux)

    def run_stream(self, blocks, timer=None,
                   consumed_cb=None) -> TableBlock:
        """Drive a block stream with bounded in-flight work; returns the
        result block (merged partials finalized, or concatenated rows).

        The stream contract admits out-of-order-READY production: a
        morsel pipeline (engine.stream_sched) may complete blocks in
        any order underneath, as long as the iterator delivers them in
        order — this loop consumes strictly in order and, via
        ``consumed_cb`` (called once per admitted block), returns the
        in-order consumption credit that lets the producer account its
        double-buffered slabs.

        ``timer`` (obs.probes.StageTimer) charges device dispatch +
        backpressure waits to the "compute" stage; time spent PULLING
        from ``blocks`` (the staging pipeline) is charged by the
        producer side, so the two stages expose their overlap."""
        import collections
        import contextlib

        window: collections.deque = collections.deque()
        partials: list[TableBlock] = []

        def computing():
            return (timer.stage("compute") if timer is not None
                    else contextlib.nullcontext())

        def admit(out):
            partials.append(out)
            window.append(out)
            if len(window) > self.inflight_blocks:
                # deliberate backpressure: sync ONLY the oldest
                # in-flight block once the window fills — bounded by
                # inflight_blocks, not rows
                # ydb-lint: disable=H001
                jax.block_until_ready(window.popleft())

        # the morsel driver loop: iterations are bounded by block
        # count (capacity-quantized morsels), never by rows; each
        # iteration is one async device dispatch
        # ydb-lint: disable=H006
        for b in blocks:
            # block-boundary cancellation point (no-op when the
            # statement carries no deadline)
            statement_deadline.check_current("scan")
            with computing():
                admit(self.run_block(b))
                if (
                    self._combine_jit is not None
                    and len(partials) >= self.combine_every
                ):
                    merged = self._timed_first(
                        "_combine_traced", self._combine_jit,
                        tuple(partials), self._combine_aux)
                    partials = []
                    admit(merged)
            if consumed_cb is not None:
                consumed_cb()
        with computing():
            if self.final is None:
                # pure filter/project program: block outputs concatenate
                out = (partials[0] if len(partials) == 1
                       else concat_blocks(partials))
            else:
                out = self.finalize(partials)
            from ydb_tpu.obs import timeline
            if timeline.timeline_enabled():
                # movement observatory runs materialize here so the
                # async tail lands on the compute stage interval, not
                # on whichever caller first touches the arrays —
                # occupancy attribution stays exact. Default path
                # stays lazy (cross-query dispatch pipelining).
                # ydb-lint: disable=H001
                jax.block_until_ready(out.columns)
            return self._retype(out)

    def _stamp_nullability(self, sch: dtypes.Schema) -> dtypes.Schema:
        """Original-program nullability over a rewritten-program schema
        (the two-phase rewrite's fixups would widen it: AVG restated as
        a division fixup loses never-NULL knowledge)."""
        return dtypes.Schema(tuple(
            dtypes.Field(f.name, f.type,
                         self._out_nullable.get(f.name, f.nullable))
            for f in sch.fields))

    def _retype(self, blk: TableBlock) -> TableBlock:
        sch = self._stamp_nullability(blk.schema)
        if sch == blk.schema:
            return blk
        return TableBlock(blk.columns, blk.length, sch)

    def execute(self) -> OracleTable:
        return OracleTable.from_block(self.run_stream(
            self.source.blocks(self.block_rows, self.read_cols)
        ))


def execute_scan(
    program: Program,
    source: ColumnSource,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    key_spaces: dict[str, int] | None = None,
) -> OracleTable:
    return ScanExecutor(program, source, block_rows, key_spaces).execute()
