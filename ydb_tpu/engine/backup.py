"""Table backup / export-import against any blob store.

The reference exports tables to external storage as data files plus a
scheme manifest and imports them back
(ydb/core/tx/datashard/export_s3.cpp, schemeshard import/export ops;
SURVEY §2.14 backup row). TPU-era equivalent, against the BlobStore
abstraction (point it at a DirBlobStore for local files or an object
store adapter for S3/GCS):

  * ``export_table``  — at ONE consistent snapshot, stream every shard
    through the PK-merge/dedup reader (logical rows: shadowed versions
    drop, so a backup doubles as a full compaction) into chunked part
    blobs + a JSON manifest (schema, pk, sharding, dictionaries).
  * ``import_table``  — recreate a ShardedTable from the manifest and
    bulk-load the parts through the normal routed insert path, so the
    target may use a different shard count.

Every part blob carries row data with the SAME chunked container format
as portions (engine/portion.py), not a private encoding.
"""

from __future__ import annotations

import json

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.engine.portion import read_portion_blob, write_portion_blob


def schema_to_json(schema: dtypes.Schema) -> list:
    return [
        {"name": f.name, "kind": f.type.kind.value,
         "scale": f.type.scale, "nullable": f.nullable}
        for f in schema.fields
    ]


def schema_from_json(spec: list) -> dtypes.Schema:
    return dtypes.Schema(tuple(
        dtypes.Field(
            s["name"],
            dtypes.LogicalType(dtypes.Kind(s["kind"]), s["scale"]),
            s["nullable"],
        )
        for s in spec
    ))


def export_table(table, dest: BlobStore, name: str,
                 snap: int | None = None,
                 part_rows: int = 1 << 20) -> dict:
    """Export a ShardedTable at one snapshot. Returns the manifest."""
    from ydb_tpu.engine.reader import (
        PortionStreamSource,
        plan_clusters,
        rechunk,
    )

    snap = table.coordinator.read_snapshot() if snap is None else snap
    parts: list[dict] = []
    total_rows = 0
    for si, shard in enumerate(table.shards):
        src = PortionStreamSource(shard, shard.visible_portions(snap))
        names = shard.schema.names
        clusters_payloads = src.payload_stream(
            plan_clusters(src.metas, src.dedup), names)
        for pi, (cols, valid) in enumerate(
                rechunk(clusters_payloads, names, part_rows)):
            blob_id = f"backup/{name}/part/{si:04d}/{pi:06d}"
            write_portion_blob(dest, blob_id, cols, valid,
                               chunk_rows=part_rows)
            n = len(next(iter(cols.values())))
            parts.append({"blob_id": blob_id, "rows": n, "shard": si})
            total_rows += n
    manifest = {
        "name": name,
        "snapshot": snap,
        "schema": schema_to_json(table.schema),
        "pk_column": table.pk_column,
        "ttl_column": table.shards[0].ttl_column,
        "upsert": table.upsert,
        "n_shards": len(table.shards),
        "rows": total_rows,
        "parts": parts,
        "dicts": {
            col: [v.decode("latin1") for v in table.dicts[col].values]
            for col in table.dicts.columns()
        },
    }
    dest.put(f"backup/{name}/manifest",
             json.dumps(manifest).encode())
    return manifest


def read_manifest(src: BlobStore, name: str) -> dict:
    return json.loads(src.get(f"backup/{name}/manifest").decode())


def import_table(src: BlobStore, name: str, store: BlobStore,
                 coordinator, table_name: str | None = None,
                 n_shards: int | None = None, config=None):
    """Recreate a ShardedTable from a backup (possibly resharded)."""
    from ydb_tpu.blocks.dictionary import DictionarySet
    from ydb_tpu.tx.sharded import ShardedTable

    man = read_manifest(src, name)
    schema = schema_from_json(man["schema"])
    dicts = DictionarySet()
    for col, values in man["dicts"].items():
        d = dicts.for_column(col)
        for v in values:
            d.add(v.encode("latin1"))
    table = ShardedTable(
        table_name or man["name"], schema, store, coordinator,
        n_shards=n_shards or man["n_shards"],
        pk_column=man["pk_column"], upsert=man["upsert"],
        ttl_column=man.get("ttl_column"),
        dicts=dicts, config=config,
    )
    for part in man["parts"]:
        cols, valid = read_portion_blob(src, part["blob_id"])
        validity = valid if valid else None
        table.insert(cols, validity)
    return table
