"""ColumnShard: the OLAP partition tablet (host state plane).

Mirror of the reference's ColumnShard + column engine
(tx/columnshard/columnshard_impl.h:134; TColumnEngineForLogs
engines/column_engine_logs.h:40; SURVEY.md §2.7), redesigned for the TPU
split: ALL durable state is host-side (TPUs never own durability —
SURVEY.md §7.0 plane 3); scans hand device-ready blocks to the kernel
plane.

State machine:
  * ``write(batch)``       — buffered rows under a write id (insert table,
                             columnshard__write.cpp shape)
  * ``commit(write_ids)``  — assigns the next snapshot, flushes buffered
                             rows into an immutable *portion* (blob +
                             meta) and logs the change
  * ``scan(program, snap)``— plans visible portions at the snapshot (MVCC
                             window + PK-range pruning), streams blocks
                             through the compiled program
                             (ydb_tpu.engine.scan)
  * ``compact()``          — merges small portions into one, sorted by PK
                             (general_compaction.cpp analog); old portions
                             get removed_snap, readers at older snapshots
                             still see them
  * ``evict_ttl(cutoff)``  — drops rows older than the TTL cutoff by
                             rewriting affected portions (ttl.cpp analog)
  * durability             — every mutation appends a WAL record; periodic
                             ``checkpoint()`` writes a full-state snapshot;
                             ``ColumnShard.boot`` = snapshot + WAL replay
                             (tablet_flat boot logic, flat_boot_*.h analog)

Local write ids stand in for the reference's long-tx writes; the
distributed coordinator (ydb_tpu.tx) supplies cross-shard snapshots.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import OrderedDict

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.analysis import sanitizer
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.engine.blockcache import DeviceBlockCache
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.portion import (
    PortionMeta,
    column_stats,
    read_portion_blob,
    write_portion_blob,
)
from ydb_tpu.engine.scan import ColumnSource, ScanExecutor
from ydb_tpu.obs.probes import probe
from ydb_tpu.ssa.program import Program

_P_COMMIT = probe("columnshard.commit")
_P_SCAN = probe("columnshard.scan")
_P_SCAN_STAGES = probe("columnshard.scan.stages")
_P_SCAN_PRUNING = probe("columnshard.scan.pruning")
_P_COMPACT = probe("columnshard.compact")


@dataclasses.dataclass
class ShardConfig:
    # compaction triggers when this many live portions exist
    compact_portion_threshold: int = 8
    # checkpoint every N WAL records
    checkpoint_interval: int = 64
    scan_block_rows: int = 1 << 20
    # compaction output portions are capped at this many rows so the
    # streaming reader's working set stays bounded (out-of-core scans)
    max_portion_rows: int = 1 << 20
    # row-group chunk size inside portion blobs: the K-way merge buffers
    # O(overlapping_portions x chunk_rows) rows, so smaller chunks bound
    # memory tighter for heavily-overlapping (random-upsert) workloads
    portion_chunk_rows: int = 1 << 16
    # device-resident decoded-block cache for repeated scans: the shared
    # page cache analog (shared_sausagecache.cpp:194) lifted into
    # accelerator HBM — warm scans skip blob IO, decode, PK merge and
    # the host->device transfer entirely. Keyed by the visible portion
    # ids (immutable blobs), so every commit/compaction/TTL rewrite
    # changes the key and stale entries age out by LRU; dictionary codes
    # are append-only, so cached code arrays stay valid as dicts grow.
    # None = auto (on for tpu/gpu backends, off on CPU where "device"
    # memory is host RSS); 0 = off; >0 = byte budget.
    scan_cache_bytes: int | None = None
    # compiled-executor cache cap (LRU entries): each entry pins a
    # traced XLA executable per distinct (program, key_spaces); ad-hoc
    # query workloads would otherwise grow it without bound
    scan_cache_entries: int = 32
    # HBM-resident column tier budget (engine.resident): per-(portion,
    # column) decoded device arrays shared across every scan shape.
    # None = auto (YDB_TPU_RESIDENT_BYTES env valve, else on for
    # accelerator backends); 0 = off; >0 = byte budget.
    resident_bytes: int | None = None


class ColumnShard:
    def __init__(
        self,
        shard_id: str,
        schema: dtypes.Schema,
        store: BlobStore,
        pk_column: str | None = None,
        ttl_column: str | None = None,
        config: ShardConfig | None = None,
        dicts: DictionarySet | None = None,
        upsert: bool = False,
    ):
        self.shard_id = shard_id
        self.schema = schema
        self.store = store
        self.pk_column = pk_column
        self.ttl_column = ttl_column
        # upsert: PK semantics — a re-written key shadows the old row;
        # scans merge portions by PK with newest-wins dedup
        # (plain_reader/iterator/merge.cpp:10 NArrow::NMerger analog)
        if upsert and not pk_column:
            raise ValueError("upsert semantics require a pk_column")
        self.upsert = upsert
        self.config = config or ShardConfig()
        # dicts may be shared table-wide across shards (ids must agree for
        # cross-shard merges); sharing implies single-process ingest
        self.dicts = dicts if dicts is not None else DictionarySet()
        # when part of a coordinated shard group, background operations
        # take their snapshots from the global plan-step clock so local
        # bumps never collide with coordinator-assigned steps
        self.snap_source = None  # Optional[Callable[[], int]]

        # schema evolution state (set by the owning table on ALTER):
        # current version + the version at which each column was added
        # (absent = original column, version 1)
        self.schema_version: int = 1
        self.column_added: dict[str, int] = {}

        self.snap: int = 0           # last committed snapshot
        self.next_portion_id = 1
        self.portions: dict[int, PortionMeta] = {}
        # bumped whenever a portion id VANISHES from the map (gc): lets
        # cluster-level cache pruning skip work while the set is stable
        self.meta_gen = 0
        # WAL-replay holding pen for staged compaction outputs: they only
        # activate when the cluster's compact_commit record arrives, so a
        # crash mid-compaction loses nothing and duplicates nothing
        self._staged: dict[int, PortionMeta] = {}
        self._in_compaction = False
        self._insert_buffer: dict[int, dict] = {}  # write_id -> batch
        self._next_write_id = 1
        # compiled-scan cache: (program, key_spaces) -> (executor, sizes)
        # LRU-bounded at config.scan_cache_entries: compiled executors
        # pin XLA executables, and ad-hoc workloads mint a fresh key per
        # distinct program — unbounded, that's a leak. Under
        # YDB_TPU_TSAN=1 the cache and its lock are sanitizer-tracked
        # (the PR 3 touch/evict race regression runs against this).
        # per-INSTANCE state names (shard_id alone would fuse lockset
        # state across a reboot or two clusters reusing shard ids)
        self._scan_cache = sanitizer.share(
            OrderedDict(),
            f"columnshard.{shard_id}.{id(self):x}._scan_cache")
        self._scan_cache_lock = sanitizer.make_lock(
            f"columnshard.{shard_id}.{id(self):x}._scan_cache_lock")
        # stage snapshot of the most recent scan (read/merge/stage/
        # compute seconds) — obs surface for bench + the viewer
        self.last_scan_stages: dict = {}
        # morsel-pipeline stat snapshot of the most recent scan
        # (engine.stream_sched); None when the serialized path ran
        self.last_scan_pipeline: "dict | None" = None
        # pruning effectiveness of the most recent scan plus cumulative
        # totals (obs: columnshard.scan.pruning probe, sys_scan_pruning
        # view). Guarded by _stats_lock: concurrent scans update both.
        self._stats_lock = sanitizer.make_lock(
            f"columnshard.{shard_id}.{id(self):x}._stats_lock")
        self.last_scan_pruning: dict = {}
        self.pruning_totals: dict = sanitizer.share(
            {"scans": 0, "portions_total": 0, "portions_skipped": 0,
             "chunks_read": 0, "chunks_skipped": 0,
             "chunks_fastpath": 0, "filters_dropped": 0},
            f"columnshard.{shard_id}.{id(self):x}.pruning_totals")
        # HBM-resident decoded-block cache for warm scans, keyed by the
        # immutable (portion ids, read cols, block rows)
        self.block_cache = DeviceBlockCache(
            budget=self.config.scan_cache_bytes)
        # HBM-resident column tier (engine.resident): per-(portion,
        # column) decoded device arrays serving every scan shape —
        # where the block cache above keys whole streams on (portion
        # set, read cols, geometry, predicates) and rebuilds from host
        # bytes for any new combination. Per-shard so ROADMAP item 3
        # can slice it per-device.
        from ydb_tpu.engine.resident import ResidentStore

        self.resident = ResidentStore(
            f"{shard_id}.{id(self):x}",
            budget=self.config.resident_bytes)
        # meta_gen stamp of the last cache prune (the Cluster
        # snapshot_db pattern): entries only die when a portion id
        # vanishes from the map, so steady-state scans skip the
        # every-entry prune walk entirely. Guarded by _meta_lock.
        self._prune_gen: "int | None" = None
        # serializes metadata mutations (portion map, WAL seq, snapshot)
        # so conveyor-driven background work (compaction/TTL/GC) can run
        # concurrently with foreground scans: critical sections cover
        # metadata only, never blob IO or merging
        self._meta_lock = threading.RLock()
        # serializes whole background OPERATIONS against each other:
        # compaction and TTL both rewrite the same visible portions, and
        # overlapping them would merge rows the other just evicted
        self._bg_lock = threading.Lock()
        self._wal_seq = 0
        self._records_since_checkpoint = 0
        # per-column dictionary size already made durable; portions carry
        # dict ids, so dictionary growth must be WAL-logged with the
        # portion that introduced it
        self._dict_durable_sizes: dict[str, int] = {}

    # ---------------- write path ----------------

    def write(
        self,
        columns: dict[str, np.ndarray],
        validity: dict[str, np.ndarray] | None = None,
    ) -> int:
        """Buffer a batch; returns the write id (uncommitted, invisible)."""
        for f in self.schema.fields:
            if f.name not in columns:
                raise KeyError(f"missing column {f.name}")
        n = len(next(iter(columns.values())))
        for name, arr in columns.items():
            if len(arr) != n:
                raise ValueError("ragged batch")
        batch = {
            "columns": {
                k: np.asarray(v, dtype=self.schema.field(k).type.physical)
                for k, v in columns.items()
            },
            "validity": {k: np.asarray(v) for k, v in (validity or {}).items()},
        }
        # id allocation + buffer insert share the metadata lock:
        # concurrent API sessions writing one shard must never mint the
        # same write id or interleave with a commit's buffer drain
        with self._meta_lock:
            wid = self._next_write_id
            self._next_write_id += 1
            self._insert_buffer[wid] = batch
        return wid

    def encode_strings(
        self, columns: dict[str, np.ndarray | list]
    ) -> dict[str, np.ndarray]:
        """Dictionary-encode raw bytes/str values for string columns."""
        out = {}
        for name, vals in columns.items():
            f = self.schema.field(name)
            if f.type.is_string and not (
                isinstance(vals, np.ndarray) and vals.dtype.kind == "i"
            ):
                out[name] = self.dicts.for_column(name).encode(list(vals))
            else:
                out[name] = np.asarray(vals)
        return out

    # -- distributed-commit participant protocol (ydb_tpu.tx.Coordinator) --

    def prepare(self, write_ids: list[int]) -> list[int]:
        """Validate and lock write ids for a coordinated commit."""
        missing = [w for w in write_ids if w not in self._insert_buffer]
        if missing:
            raise KeyError(f"unknown write ids {missing}")
        return list(write_ids)

    def commit_at(self, write_ids: list[int], step: int) -> int:
        """Commit prepared writes at a coordinator-assigned plan step."""
        return self._commit(write_ids, step)

    def abort(self, write_ids: list[int]) -> None:
        with self._meta_lock:
            for w in write_ids:
                self._insert_buffer.pop(w, None)

    def commit(self, write_ids: list[int]) -> int:
        """Single-shard commit at the next local snapshot. Do not mix with
        coordinated commit_at on the same shard group — the coordinator
        owns global time there."""
        return self._commit(write_ids, None)

    def _commit(self, write_ids: list[int], snap: "int | None") -> int:
        # snapshot allocation, validation and advance happen in ONE
        # critical section: two concurrent commits reading snap outside
        # the lock would mint the same snapshot id, and background
        # compaction/TTL bump the same counter under _meta_lock
        with self._meta_lock:
            if snap is None:
                snap = self.snap + 1
            elif snap <= self.snap:
                raise ValueError(
                    f"plan step {snap} not ahead of shard snapshot "
                    f"{self.snap}")
            batches = [self._insert_buffer.pop(w) for w in write_ids]
            self.snap = snap
        if _P_COMMIT:
            _P_COMMIT.fire(shard=self.shard_id, snap=snap,
                           writes=len(write_ids))
        if not batches:
            self._log({"op": "noop", "snap": snap})
            return snap
        cols = {
            f.name: np.concatenate([b["columns"][f.name] for b in batches])
            for f in self.schema.fields
        }
        validity = {}
        for f in self.schema.fields:
            parts = []
            any_mask = False
            for b in batches:
                n = len(next(iter(b["columns"].values())))
                v = b["validity"].get(f.name)
                if v is None:
                    v = np.ones(n, dtype=bool)
                else:
                    any_mask = True
                parts.append(v)
            if any_mask:
                validity[f.name] = np.concatenate(parts)
        self._add_portion(cols, validity, snap)
        return snap

    def _add_portion(self, cols, validity, snap, removed=None,
                     staged=False) -> PortionMeta:
        # portions are PK-sorted on disk (the reference sorts at
        # indexation) so scans can K-way merge them without re-sorting;
        # under upsert, equal keys within one commit collapse last-wins
        if self.pk_column and self.pk_column in cols and \
                len(cols[self.pk_column]):
            pk = cols[self.pk_column]
            order = np.argsort(pk, kind="stable")
            if self.upsert:
                sorted_pk = pk[order]
                keep = np.r_[sorted_pk[1:] != sorted_pk[:-1], True]
                order = order[keep]
            cols = {n: a[order] for n, a in cols.items()}
            validity = {n: a[order] for n, a in (validity or {}).items()}
        with self._meta_lock:
            pid = self.next_portion_id
            self.next_portion_id += 1
        blob_id = f"{self.shard_id}/portion/{pid}"
        write_portion_blob(self.store, blob_id, cols, validity,
                           chunk_rows=self.config.portion_chunk_rows,
                           pk_column=self.pk_column)
        meta = PortionMeta(
            portion_id=pid,
            blob_id=blob_id,
            num_rows=len(next(iter(cols.values()))) if cols else 0,
            commit_snap=snap,
            schema_version=self.schema_version,
        )
        # portion-level zone maps for ALL columns (vectorized one-pass
        # min/max/null-count per column): planning prunes portions and
        # plans dense group tiers without touching blob storage
        from ydb_tpu.stats.zonemap import column_zones

        if cols:
            meta.zones = column_zones(cols, validity)
        if self.pk_column and self.pk_column in cols:
            meta.pk_min, meta.pk_max = column_stats(cols[self.pk_column])
        if self.ttl_column and self.ttl_column in cols:
            meta.ttl_min, meta.ttl_max = column_stats(cols[self.ttl_column])
        with self._meta_lock:
            self.portions[pid] = meta
            rec = {"op": "add_portion", "meta": meta.to_json(),
                   "snap": snap, "removed": removed or [],
                   "dict_delta": self._dict_delta()}
            if staged:
                rec["staged"] = True
            self._log(rec)
        # eager resident promotion (write path AND compaction output):
        # the decoded columns are already in memory — pin them on the
        # device asynchronously so the FIRST scan is already warm.
        # Budget pressure evicts cold portions; a full valve spills.
        if self.resident.enabled() and meta.num_rows:
            pcols, pvalid = cols, validity

            def from_memory():
                return pcols, pvalid

            self.resident.promote_async(pid, meta.num_rows, from_memory)
        return meta

    def _dict_delta(self) -> dict:
        """New dictionary entries since last durable point (WAL payload)."""
        delta = {}
        for col in self.dicts.columns():
            d = self.dicts[col]
            done = self._dict_durable_sizes.get(col, 0)
            if len(d) > done:
                delta[col] = [
                    v.decode("latin1") for v in d.values[done:]
                ]
                self._dict_durable_sizes[col] = len(d)
        return delta

    # ---------------- scan path ----------------

    def visible_portions(
        self, snap: int | None = None,
        pk_range: tuple[int | None, int | None] | None = None,
        preds=None,
    ) -> list[PortionMeta]:
        """Portions visible at ``snap``, pruned by metadata statistics.

        ``pk_range`` is the legacy spelling of the general path: it
        lowers to ge/le predicates on the PK column and runs through
        the same zone intersection as ``preds`` (stats.zonemap.Pred
        conjuncts from a program's filters). Pre-stats portions carry
        only pk_min/pk_max — those still serve the PK case; other
        predicates read them unpruned (conservative)."""
        with self._meta_lock:
            snap = self.snap if snap is None else snap
            metas = list(self.portions.values())
        all_preds = list(preds or [])
        if pk_range and self.pk_column:
            from ydb_tpu.stats.zonemap import Pred

            lo, hi = pk_range
            if lo is not None:
                all_preds.append(Pred(self.pk_column, "ge", lo))
            if hi is not None:
                all_preds.append(Pred(self.pk_column, "le", hi))
        out = []
        for meta in metas:
            if not meta.visible_at(snap):
                continue
            if all_preds and self._portion_pruned(meta, all_preds):
                continue
            out.append(meta)
        return sorted(out, key=lambda m: m.portion_id)

    def _meta_zones(self, meta: PortionMeta) -> dict | None:
        """A portion's zone dict for predicate matching. v0 metadata
        (pre-stats checkpoints) synthesizes the PK zone from
        pk_min/pk_max so old portions keep PK pruning through the
        general path."""
        zones = dict(meta.zones) if meta.zones else {}
        if self.pk_column and self.pk_column not in zones \
                and meta.pk_min is not None:
            # null count unknown on v0 metadata: claim "maybe all NULL"
            # so skip decisions (which ignore nulls) still fire but the
            # all-match fast path (which requires zero NULLs) never
            # trusts a synthesized zone
            zones[self.pk_column] = [meta.pk_min, meta.pk_max,
                                     meta.num_rows]
        return zones or None

    def _portion_pruned(self, meta: PortionMeta, preds) -> bool:
        """True when zone metadata proves no row of the portion can
        satisfy every conjunct."""
        from ydb_tpu.stats.zonemap import zones_decide

        skip, _all = zones_decide(self._meta_zones(meta), preds)
        return skip

    def _materialize(
        self, metas: list[PortionMeta], columns: tuple[str, ...] | None = None
    ) -> tuple[dict, dict]:
        names = columns if columns is not None else self.schema.names
        cols = {n: [] for n in names}
        valid = {n: [] for n in names}
        for meta in metas:
            c, v = read_portion_blob(self.store, meta.blob_id)
            n_rows = len(next(iter(c.values()))) if c else 0
            for n in names:
                if n in c and meta.schema_version >= \
                        self.column_added.get(n, 1):
                    cols[n].append(c[n])
                    valid[n].append(
                        v.get(n, np.ones(len(c[n]), dtype=bool))
                    )
                else:
                    # column added by ALTER after this portion was
                    # written: old rows read as NULL
                    cols[n].append(np.zeros(
                        n_rows, dtype=self.schema.field(n).type.physical))
                    valid[n].append(np.zeros(n_rows, dtype=bool))
        out_c = {n: np.concatenate(cols[n]) if cols[n] else
                 np.empty(0, dtype=self.schema.field(n).type.physical)
                 for n in names}
        out_v = {n: np.concatenate(valid[n]) if valid[n] else
                 np.empty(0, dtype=bool) for n in names}
        return out_c, out_v

    def source_at(
        self, snap: int | None = None,
        columns: tuple[str, ...] | None = None,
        pk_range=None,
    ) -> ColumnSource:
        metas = self.visible_portions(snap, pk_range)
        cols, valid = self._materialize(metas, columns)
        sch = self.schema if columns is None else self.schema.select(columns)
        return ColumnSource(cols, sch, self.dicts, valid)

    def scan(
        self, program: Program, snap: int | None = None,
        key_spaces: dict[str, int] | None = None,
        table_stats=None,
    ) -> OracleTable:
        from ydb_tpu.obs import tracing

        # profile surface: when a query trace is active the scan's
        # stage seconds / pruning counters / compile-cache status ride
        # a "shard.scan" span (the same numbers the probes fire)
        with tracing.span("shard.scan") as sp:
            return self._scan_profiled(program, snap, key_spaces,
                                       table_stats, sp)

    def _scan_profiled(
        self, program: Program, snap: int | None,
        key_spaces: dict[str, int] | None, table_stats, sp,
    ) -> OracleTable:
        """Streamed scan: portion-granular fetch -> (PK merge/dedup) ->
        fixed-capacity device blocks -> compiled program. Host memory is
        bounded by the largest PK-overlap cluster, not the table
        (fetching.h/scanner.h analog; ydb_tpu.engine.reader).

        Statistics consumption (YDB_TPU_STATS=0 disables, results stay
        bit-identical either way):

          * the program's conjunctive filter predicates evaluate against
            portion zone maps BEFORE any blob is touched — non-matching
            portions never stream, and chunk zones skip chunk fetches
            inside surviving portions (ydb_tpu.stats.zonemap);
          * a FilterStep every surviving portion provably all-matches
            (zones inside the predicate, zero NULLs) is dropped from the
            compiled program — the skip-the-filter-kernel fast path;
          * integer group-by keys gain EXACT cardinality bounds from the
            zone maps (key_spaces), enabling the dense group tier, and
            ``table_stats`` (aggregator NDV) sizes the group capacity /
            tier choice (ssa.compiler group_est).

        Value-predicate portion pruning is skipped under upsert
        semantics: a pruned newer portion could resurrect the older row
        version it shadows. Chunk pruning stays safe there — it only
        runs on single-portion clusters, whose PKs are unique.

        Compiled executors cache per (program, key_spaces, hints) — the
        pattern-cache analog (mkql_computation_pattern_cache.h) — and
        invalidate when any dictionary grows (plan-time dict tables bake
        into the compiled aux)."""
        from ydb_tpu import stats as stats_mod
        from ydb_tpu.engine.reader import PortionStreamSource
        from ydb_tpu.engine.scan import ScanExecutor, required_columns
        from ydb_tpu.obs.probes import StageTimer
        from ydb_tpu.stats import zonemap

        timer = StageTimer()
        use_stats = stats_mod.stats_enabled()
        preds: list = []
        full_steps: set = set()
        if use_stats:
            preds, full_steps = zonemap.extract_predicates(
                program, self.schema, self.dicts)
        visible = self.visible_portions(snap)
        metas = visible
        dropped: set = set()
        if preds and not self.upsert:
            metas = []
            all_steps = set(full_steps)
            for m in visible:
                skip, alls = zonemap.zones_decide(
                    self._meta_zones(m), preds)
                if skip:
                    # zone-skipped portions are poor HBM citizens: a
                    # resident copy would have served zero rows. Feed
                    # the eviction policy so they go first.
                    self.resident.note_pruned(m.portion_id)
                    continue
                metas.append(m)
                all_steps &= alls
            # fast path: a filter every SURVIVING portion all-matches
            # contributes nothing — drop it from the compiled program
            # (bit-identical: all its rows pass, and 'all' required
            # zero NULLs on the tested columns). Only for programs
            # whose output a GroupByStep pins: a bare-filter program's
            # implicit output IS its read set, and dropping the filter
            # would narrow it.
            dropped = all_steps if metas and \
                program.group_by is not None else set()
        eff_program = zonemap.drop_filter_steps(program, dropped)
        cols = required_columns(eff_program, self.schema)
        src = PortionStreamSource(
            self, metas, columns=cols, timer=timer, preds=preds
        )
        src.portions_skipped += len(visible) - len(metas)
        key_spaces = dict(key_spaces or {})
        group_est = None
        if use_stats and eff_program.group_by is not None:
            group_est = self._group_hints(
                eff_program, metas, key_spaces, table_stats)
        key = (eff_program, tuple(sorted(key_spaces.items())), group_est)
        sizes = tuple(
            (c, len(self.dicts[c])) for c in sorted(self.dicts.columns())
        )
        # the LRU bookkeeping (move_to_end / eviction) needs a lock:
        # concurrent scans race a hit-path touch against another
        # thread's eviction popitem; the expensive executor trace stays
        # OUTSIDE it (duplicate compiles on a racing miss are wasteful
        # but correct — last insert wins)
        with self._scan_cache_lock:
            hit = self._scan_cache.get(key)
            if hit is not None and hit[1] == sizes:
                self._scan_cache.move_to_end(key)
        fresh = not (hit is not None and hit[1] == sizes)
        if not fresh:
            ex = hit[0]
        else:
            ex = ScanExecutor(
                eff_program, src, self.config.scan_block_rows,
                key_spaces, group_est=group_est,
            ).detach()
            with self._scan_cache_lock:
                self._scan_cache[key] = (ex, sizes)
                self._scan_cache.move_to_end(key)
                while len(self._scan_cache) > max(
                        1, self.config.scan_cache_entries):
                    self._scan_cache.popitem(last=False)
        cache_key = None
        hit_before = self.block_cache.hits
        # the resident tier subsumes the whole-stream device cache:
        # caching the assembled stream AND pinning its source columns
        # would hold the same bytes twice against two budgets
        use_block_cache = (self.block_cache.budget() > 0
                           and not self.resident.enabled())
        if use_block_cache or self.resident.enabled():
            # entries referencing a portion that no longer exists
            # (compacted/TTL'd away and dropped from the portion map)
            # can never be keyed again by any snapshot: free their
            # device memory now instead of waiting for LRU. meta_gen
            # only moves when gc_blobs drops portions (the
            # Cluster.snapshot_db stamp pattern), so the steady state
            # is one int compare per scan instead of a full cache walk.
            with self._meta_lock:
                gen = self.meta_gen
                stale = gen != self._prune_gen
                live = set(self.portions) if stale else None
            if stale:
                self.block_cache.prune(lambda k: set(k[0]) <= live)
                self.resident.prune(live)
                # stamp with the gen read BEFORE pruning: a gc racing
                # us just forces one extra (harmless) re-prune
                with self._meta_lock:
                    self._prune_gen = gen
        if use_block_cache:
            # the predicate fingerprint is part of the identity: a
            # pruned stream holds fewer rows than an unpruned one over
            # the same portion set
            cache_key = (tuple(m.portion_id for m in src.metas),
                         tuple(ex.read_cols),
                         self.config.scan_block_rows,
                         zonemap.preds_fingerprint(preds))
        out = OracleTable.from_block(ex.run_stream(
            self.block_cache.stream(
                cache_key,
                lambda: src.blocks(self.config.scan_block_rows,
                                   ex.read_cols)),
            timer=timer, consumed_cb=src.note_block_consumed))
        # per-scan stage attribution (read/merge/stage/compute seconds);
        # bench.py surfaces this as metric extras
        self.last_scan_stages = timer.snapshot()
        # morsel-pipeline attribution (engine.stream_sched): stats are
        # set when the pipelined stream finishes; None on the
        # serialized path (YDB_TPU_STREAM_PIPELINE=0) and cache replays
        self.last_scan_pipeline = src.last_pipeline
        pruning = {
            "portions_total": len(visible),
            "portions_skipped": src.portions_skipped,
            "chunks_read": src.chunks_read,
            "chunks_skipped": src.chunks_skipped,
            # with a zone-proven filter dropped, every chunk read took
            # the skip-the-filter-kernel fast path
            "chunks_fastpath": src.chunks_read if dropped else 0,
            "filters_dropped": len(dropped),
        }
        with self._stats_lock:
            self.last_scan_pruning = pruning
            self.pruning_totals["scans"] += 1
            for k, v in pruning.items():
                if k != "portions_total":
                    self.pruning_totals[k] += v
            self.pruning_totals["portions_total"] += len(visible)
        if _P_SCAN_PRUNING:
            _P_SCAN_PRUNING.fire(shard=self.shard_id, **pruning)
        if _P_SCAN_STAGES:
            _P_SCAN_STAGES.fire(shard=self.shard_id,
                                **self.last_scan_stages)
        if _P_SCAN:
            _P_SCAN.fire(shard=self.shard_id,
                         portions=len(src.metas),
                         chunks_read=src.chunks_read,
                         compiled_fresh=fresh,
                         block_cache_hit=self.block_cache.hits
                         > hit_before,
                         resident_portions=src.resident_hits,
                         resident_rows=src.resident_rows)
        if sp.recording:
            sp.set(shard=self.shard_id, rows=int(out.num_rows),
                   compile_cache=("miss" if fresh else "hit"),
                   resident_portions=src.resident_hits,
                   resident_rows=src.resident_rows,
                   **{f"stage_{k}": v
                      for k, v in self.last_scan_stages.items()},
                   **pruning)
            if self.last_scan_pipeline is not None:
                sp.set(**{f"pipe_{k}": v
                          for k, v in self.last_scan_pipeline.items()})
            if fresh and ex.first_trace_seconds:
                sp.set(first_trace_seconds=round(
                    ex.first_trace_seconds, 6))
        return out

    def _group_hints(self, program: Program, metas, key_spaces: dict,
                     table_stats) -> float | None:
        """Stats-derived group-by planning hints, mutating key_spaces.

        Exact integer key bounds come from the zone maps of the
        portions this scan will actually read (max value over their
        union — a hard cardinality bound, so the dense tier stays
        exact); the advisory group-count estimate comes from aggregator
        NDV (table_stats) and only picks between equally-exact tiers.
        """
        from ydb_tpu.stats import cost

        gb = program.group_by
        for k in gb.keys:
            if k in key_spaces or k not in self.schema:
                continue
            t = self.schema.field(k).type
            if not t.is_integer:
                continue  # strings bound via their dictionary already
            bound = 0
            ok = bool(metas)
            for m in metas:
                zone = (m.zones or {}).get(k)
                if zone is None or zone[0] is None or zone[0] < 0:
                    ok = False
                    break
                bound = max(bound, int(zone[1]))
            # cap: a huge bound would explode the dense mixed-radix
            # space; past it the sorted tier is the right plan anyway.
            # key_spaces bounds are EXCLUSIVE (cardinality-style:
            # values live in [0, b-1]), so the inclusive zone max
            # shifts by one.
            if ok and bound < (1 << 20):
                key_spaces[k] = bound + 1
        if table_stats is None:
            return None
        est = cost.estimate_group_count(gb.keys, table_stats)
        if est is None:
            return None
        # 2-significant-figure bucket: the executor cache keys on the
        # hint, and a raw NDV float would mint a fresh compile per
        # aggregator refresh
        return float(f"{est:.2g}")

    # ---------------- background: compaction / TTL ----------------

    def maybe_compact(self) -> bool:
        if len(self.visible_portions()) >= self.config.compact_portion_threshold:
            self.compact()
            return True
        return False

    def _advance_snap(self) -> int:
        with self._meta_lock:
            if self.snap_source is not None:
                s = self.snap_source()
                if s <= self.snap:
                    raise ValueError(
                        f"snapshot source went backwards: {s} <="
                        f" {self.snap}"
                    )
            else:
                s = self.snap + 1
            self.snap = s
            return s

    def compact(self) -> None:
        """Merge visible portions cluster-by-cluster, PK-sorted, into
        output portions of at most ``max_portion_rows`` rows.

        Only one PK-overlap cluster is resident at a time (the
        general_compaction.cpp granule-local pattern), so compaction is
        as out-of-core as the scan path; under upsert semantics the
        merge drops shadowed row versions for good. Background
        operations (compaction/TTL) serialize per shard via _bg_lock:
        overlapping them would merge rows the other just rewrote.
        """
        with self._bg_lock:
            self._compact_locked()

    def _compact_locked(self) -> None:
        from ydb_tpu.engine.reader import PortionStreamSource, plan_clusters

        metas = self.visible_portions()
        if len(metas) <= 1:
            return
        cap = self.config.max_portion_rows
        # pack PK-adjacent clusters into jobs of ~cap rows: overlapping
        # clusters must merge, and runs of small disjoint portions
        # coalesce into fewer, bigger portions (small-portion merge)
        jobs: list[list] = []
        cur: list = []
        cur_rows = 0
        for c in plan_clusters(metas, dedup=bool(self.pk_column)):
            rows = sum(m.num_rows for m in c)
            if cur and cur_rows + rows > cap:
                jobs.append(cur)
                cur, cur_rows = [], 0
            cur.extend(c)
            cur_rows += rows
        if cur:
            jobs.append(cur)
        clusters = [
            job for job in jobs
            if len(job) > 1 or any(m.num_rows > cap for m in job)
        ]
        if not clusters:
            return  # every portion already compact and bounded
        from ydb_tpu.engine.reader import rechunk

        self._in_compaction = True
        snap = self._advance_snap()
        if _P_COMPACT:
            _P_COMPACT.fire(shard=self.shard_id, snap=snap,
                            clusters=len(clusters),
                            portions=len(metas))
        # output portions are WAL-staged and only activate at the
        # cluster's compact_commit record, which also carries the removal
        # tombstones: a crash anywhere mid-stream replays to the exact
        # pre-compaction state (no lost rows, no duplicates). Checkpoints
        # are deferred while staged records are in flight.
        try:
            for cluster in clusters:
                reader = PortionStreamSource(
                    self, cluster, dedup=self.upsert, prefetch=False
                )
                names = self.schema.names
                if self.upsert and self.pk_column:
                    # streamed merge: payloads arrive globally PK-ordered,
                    # so output portions of <= cap rows are cut
                    # incrementally — an all-overlapping cluster never
                    # materializes
                    payloads = reader.payload_stream([cluster], names)
                else:
                    # append path: job size is bounded by cap (plan
                    # above), so a host sort of the materialized job is
                    # fine
                    cols, valid = reader._load_cluster(cluster, names)
                    if self.pk_column:
                        order = np.argsort(cols[self.pk_column],
                                           kind="stable")
                        cols = {n: a[order] for n, a in cols.items()}
                        valid = {n: a[order] for n, a in valid.items()}
                    payloads = iter([(cols, valid)])
                added = [
                    self._add_portion(chunk_c, chunk_v, snap,
                                      staged=True).portion_id
                    for chunk_c, chunk_v in rechunk(payloads, names, cap)
                ]
                removed = [m.portion_id for m in cluster]
                with self._meta_lock:
                    for m in cluster:
                        m.removed_snap = snap
                    self._log({"op": "compact_commit", "snap": snap,
                               "adds": added, "removed": removed})
        finally:
            self._in_compaction = False
        if self._records_since_checkpoint >= self.config.checkpoint_interval:
            self.checkpoint()

    def evict_ttl(self, cutoff: int) -> int:
        """Drop rows whose TTL column < cutoff. Returns rows evicted."""
        with self._bg_lock:
            return self._evict_ttl_locked(cutoff)

    def _evict_ttl_locked(self, cutoff: int) -> int:
        if not self.ttl_column:
            return 0
        evicted = 0
        metas = [
            m for m in self.visible_portions()
            if m.ttl_min is not None and m.ttl_min < cutoff
        ]
        if not metas:
            return 0
        snap = self._advance_snap()
        for meta in metas:
            cols, valid = self._materialize([meta])
            keep = cols[self.ttl_column] >= cutoff
            evicted += int((~keep).sum())
            if keep.any():
                kept_c = {n: a[keep] for n, a in cols.items()}
                kept_v = {n: a[keep] for n, a in valid.items()}
                # tombstone + replacement under ONE meta-lock section: a
                # concurrent scan must never see neither portion
                with self._meta_lock:
                    meta.removed_snap = snap
                    self._add_portion(kept_c, kept_v, snap,
                                      removed=[meta.portion_id])
            else:
                with self._meta_lock:
                    meta.removed_snap = snap
                    self._log({"op": "remove_portion", "snap": snap,
                               "portion_id": meta.portion_id})
        return evicted

    def evict_to_cold(self, max_snap: int) -> int:
        """Move blobs of portions committed at/before ``max_snap`` to the
        cold tier (the TTL/age-driven tier eviction of tx/tiering).
        Requires a TieredBlobStore; scans keep working transparently
        (reads fall through hot -> cold). Returns blobs moved."""
        from ydb_tpu.engine.blobs import TieredBlobStore

        store = self.store
        # unwrap a page cache if one fronts the tiers
        base = getattr(store, "base", None)
        tiered = store if isinstance(store, TieredBlobStore) else (
            base if isinstance(base, TieredBlobStore) else None)
        if tiered is None:
            return 0
        ids = {
            m.blob_id for m in self.visible_portions()
            if m.commit_snap <= max_snap
        }
        return tiered.evict(lambda bid: bid in ids)

    def gc_blobs(self, keep_snap: int) -> int:
        """Delete blobs of portions invisible at and after keep_snap
        (BlobStorage collect-garbage analog). Returns blobs deleted."""
        # ONE critical section from the dead-list to the metadata drop:
        # a concurrent gc_blobs computing the same list would double-log
        # and KeyError on the second delete
        with self._meta_lock:
            dead = [
                pid for pid, m in self.portions.items()
                if m.removed_snap is not None
                and m.removed_snap <= keep_snap
            ]
            if not dead:
                return 0
            # log BEFORE deleting: a crash in between leaks blobs
            # (re-collected later) instead of leaving metadata pointing
            # at deleted blobs
            self._log({"op": "gc", "portions": dead, "snap": self.snap})
            blob_ids = [self.portions[pid].blob_id for pid in dead]
            for pid in dead:
                del self.portions[pid]
            self.meta_gen += 1
        for bid in blob_ids:
            self.store.delete(bid)
        # GC'd portion ids can never be named by any snapshot again:
        # free their resident device arrays now (outside _meta_lock —
        # the stores keep no lock-order edge between them)
        self.resident.invalidate(dead)
        return len(dead)

    # ---------------- durability: WAL + checkpoint + boot ----------------

    def _log(self, record: dict) -> None:
        with self._meta_lock:
            self._wal_seq += 1
            record["seq"] = self._wal_seq
            self.store.put(
                f"{self.shard_id}/wal/{self._wal_seq:012d}",
                json.dumps(record).encode(),
            )
            self._records_since_checkpoint += 1
            if self._records_since_checkpoint >= \
                    self.config.checkpoint_interval and \
                    not self._in_compaction:
                # a checkpoint between a staged add and its compact_commit
                # would persist half a compaction; defer until commit
                self.checkpoint()

    def checkpoint(self) -> None:
        with self._meta_lock:
            state = {
                "snap": self.snap,
                "next_portion_id": self.next_portion_id,
                "wal_seq": self._wal_seq,
                "portions": [
                    m.to_json() for m in self.portions.values()
                ],
                "dicts": {
                    col: [v.decode("latin1") for v in
                          self.dicts[col].values]
                    for col in self.dicts.columns()
                },
            }
            self.store.put(
                f"{self.shard_id}/checkpoint",
                json.dumps(state).encode(),
            )
            # WAL records up to wal_seq are now redundant
            for bid in self.store.list(f"{self.shard_id}/wal/"):
                self.store.delete(bid)
            self._records_since_checkpoint = 0
            for col in self.dicts.columns():
                self._dict_durable_sizes[col] = len(self.dicts[col])

    @staticmethod
    def boot(
        shard_id: str,
        schema: dtypes.Schema,
        store: BlobStore,
        pk_column: str | None = None,
        ttl_column: str | None = None,
        config: ShardConfig | None = None,
        dicts: DictionarySet | None = None,
    ) -> "ColumnShard":
        """Recover shard state: checkpoint + WAL replay (flat_boot analog).

        With ``dicts`` supplied (a table/cluster-shared DictionarySet the
        caller recovered from its own journal — Cluster's dict log), the
        shard trusts it and skips replaying its private dict state: ids
        must come from the shared global assignment order, not this
        shard's local view of it.
        """
        shard = ColumnShard(shard_id, schema, store, pk_column, ttl_column,
                            config, dicts=dicts)
        external_dicts = dicts is not None
        shard._external_dicts = external_dicts
        ckpt_id = f"{shard_id}/checkpoint"
        base_seq = 0
        if store.exists(ckpt_id):
            state = json.loads(store.get(ckpt_id).decode())
            shard.snap = state["snap"]
            shard.next_portion_id = state["next_portion_id"]
            shard._wal_seq = state["wal_seq"]
            base_seq = state["wal_seq"]
            for mj in state["portions"]:
                m = PortionMeta.from_json(mj)
                shard.portions[m.portion_id] = m
            if not external_dicts:
                for col, values in state.get("dicts", {}).items():
                    d = shard.dicts.for_column(col)
                    for v in values:
                        d.add(v.encode("latin1"))
        # replay WAL after the checkpoint
        for bid in store.list(f"{shard_id}/wal/"):
            rec = json.loads(store.get(bid).decode())
            if rec["seq"] <= base_seq:
                continue
            shard._replay(rec)
        for col in shard.dicts.columns():
            shard._dict_durable_sizes[col] = len(shard.dicts[col])
        # orphaned staged outputs = a compaction that never committed:
        # drop their blobs, the old portions are still fully live
        for meta in shard._staged.values():
            store.delete(meta.blob_id)
        shard._staged = {}
        return shard

    def _replay(self, rec: dict) -> None:
        # boot-time replay is single-threaded, but the metadata it
        # rewrites is the same state scans/compaction guard with
        # _meta_lock — holding it keeps the guard discipline uniform
        # (and replay-into-a-live-shard safe), at RLock cost only
        with self._meta_lock:
            self._replay_locked(rec)

    def _replay_locked(self, rec: dict) -> None:
        op = rec["op"]
        self._wal_seq = max(self._wal_seq, rec["seq"])
        self.snap = max(self.snap, rec.get("snap", 0))
        if op == "add_portion":
            meta = PortionMeta.from_json(rec["meta"])
            if rec.get("staged"):
                # compaction output: inert until compact_commit arrives
                self._staged[meta.portion_id] = meta
            else:
                self.portions[meta.portion_id] = meta
            self.next_portion_id = max(self.next_portion_id,
                                       meta.portion_id + 1)
            for pid in rec.get("removed", []):
                if pid in self.portions:
                    self.portions[pid].removed_snap = rec["snap"]
            if not getattr(self, "_external_dicts", False):
                for col, values in rec.get("dict_delta", {}).items():
                    d = self.dicts.for_column(col)
                    for v in values:
                        d.add(v.encode("latin1"))
        elif op == "compact_commit":
            for pid in rec["adds"]:
                meta = self._staged.pop(pid, None)
                if meta is not None:
                    self.portions[pid] = meta
            for pid in rec["removed"]:
                if pid in self.portions:
                    self.portions[pid].removed_snap = rec["snap"]
        elif op == "remove_portion":
            pid = rec["portion_id"]
            if pid in self.portions:
                self.portions[pid].removed_snap = rec["snap"]
        elif op == "gc":
            for pid in rec["portions"]:
                self.portions.pop(pid, None)
        elif op == "noop":
            pass
        else:
            raise ValueError(f"unknown WAL op {op}")
