"""Morsel-driven streaming pipeline for out-of-core scans.

The serialized OOC read path runs blob read -> decode -> stage ->
compute as one chain per portion: a single conveyor producer does all
the movement work while the consumer computes, so scan throughput is
the SUM of the stage times. Theseus's thesis (PAPERS.md) says it should
be the MAX: every data-movement stage overlapped, throughput bounded
only by the slowest one. This module is that architecture for the
ColumnShard scan:

  * surviving portion clusters decompose into fixed-byte-budget
    **morsels** (``YDB_TPU_MORSEL_BYTES`` of decoded data each; chunk
    pruning happens at planning time so skipped chunks never become
    work);
  * IO morsels run **out of order** on a dedicated conveyor pool
    (``runtime.conveyor.stream_conveyor``) — blob fetch + decode +
    schema projection for morsels k+1..k+d proceed while morsel k is
    consumed — and are consumed **in order** by the assembly stage, so
    payload order (and with it every block boundary) is exactly the
    serialized path's;
  * the in-order item stream feeds ``resident.mixed_blocks`` and then
    ``reader.pump_blocks``: the depth-bounded block queue IS the
    double-buffered device slab — H2D transfer of block k+1 overlaps
    compute on block k;
  * placement is resident-tier-aware: HBM-resident portions yield
    device items (zero movement) while cold portions stream behind
    them, with the same heat/promotion bookkeeping as
    ``resident.scan_items``;
  * admission back-pressures on a byte budget (``YDB_TPU_STREAM_BYTES``
    of estimated decoded bytes in flight), so peak host memory stays
    inside the OOC valve no matter how many portions survive pruning.

Deadlock freedom is by **work stealing**, not queue sizing: every
flight is a small state machine (PENDING/RUNNING/DONE/CANCELLED) and
the in-order consumer claims and runs the head morsel inline whenever
its worker task has not started — under a saturated or stalled pool the
pipeline degrades to exactly the serialized path instead of waiting on
a task that cannot run. K-way dedup merges stay inline in the assembly
stage (their cursors are inherently sequential); their chunk reads
still ride the retry policy.

Gates: ``YDB_TPU_STREAM_PIPELINE=0`` is the escape hatch back to the
serialized path (the A/B bit-identity switch); ``PIPELINE_FORCE`` is
the in-process override for tests/bench, same contract as
``FUSE_FORCE``/``RESIDENT_FORCE``. Results are bit-identical either
way: the pipeline reuses the serialized path's chunk reader, payload
boundaries, ``rechunk`` re-cutting and block assembly, only the
threads change.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading

from ydb_tpu.analysis import leaksan, sanitizer
from ydb_tpu.chaos import deadline as statement_deadline
from ydb_tpu.engine.portion import (_TRANSIENT_READ, PortionChunkReader,
                                    project_chunk)
from ydb_tpu.obs import timeline

#: test/bench override: True/False forces the gate, None = environment
PIPELINE_FORCE: "bool | None" = None


def pipeline_enabled() -> bool:
    """Morsel-pipeline gate, default ON (YDB_TPU_STREAM_PIPELINE=0 is
    the serialized-path escape hatch for A/B and emergencies)."""
    if PIPELINE_FORCE is not None:
        return PIPELINE_FORCE
    return os.environ.get("YDB_TPU_STREAM_PIPELINE", "1") \
        not in ("0", "", "off")


def morsel_bytes() -> int:
    """Decoded-byte budget of ONE morsel: big enough that per-task
    overhead vanishes, small enough that a portion splits into units
    the pool can spread."""
    try:
        return max(1 << 16,
                   int(os.environ.get("YDB_TPU_MORSEL_BYTES",
                                      str(16 << 20))))
    except ValueError:
        return 16 << 20


def stream_budget() -> int:
    """Estimated decoded bytes allowed in flight (admitted but not yet
    consumed) — the back-pressure valve that keeps pipeline RSS
    bounded regardless of portion count."""
    try:
        return max(1 << 20,
                   int(os.environ.get("YDB_TPU_STREAM_BYTES",
                                      str(128 << 20))))
    except ValueError:
        return 128 << 20


# ---------------- morsel planning ----------------


class _DevMorsel:
    """An HBM-resident portion: ready instantly, zero movement."""

    __slots__ = ("entries", "rows")

    def __init__(self, entries, rows):
        self.entries = entries
        self.rows = rows


class _MergeMorsel:
    """A K-way dedup cluster: executed inline in the assembly stage
    (the merge cursors are sequential by nature)."""

    __slots__ = ("source", "cluster")

    def __init__(self, source, cluster):
        self.source = source
        self.cluster = cluster


class _IoMorsel:
    """A run of surviving chunks of one cold portion: blob fetch +
    decode + projection, executable on any worker (or stolen)."""

    __slots__ = ("source", "meta", "reader", "chunks", "est_bytes")

    def __init__(self, source, meta, reader, chunks, est_bytes):
        self.source = source
        self.meta = meta
        self.reader = reader
        self.chunks = chunks
        self.est_bytes = est_bytes


def _open_reader(store, blob_id) -> PortionChunkReader:
    """Header read with one extra outer attempt on top of the reader's
    own RetryPolicy. Planning draws fault injections concurrently with
    worker IO, so a transient burst the serialized path would meet
    spread across many calls can land wholly on one header read; a
    second fresh retry budget absorbs any burst shorter than twice the
    policy's attempts."""
    try:
        return PortionChunkReader(store, blob_id)
    except _TRANSIENT_READ:
        return PortionChunkReader(store, blob_id)


def _row_width(schema, names) -> int:
    """Estimated decoded bytes per row (payload + validity byte)."""
    return sum(schema.field(n).type.physical.itemsize + 1
               for n in names) or 1


def plan_morsels(parts, names):
    """Lazily decompose ``[(source, clusters)]`` into morsels, in
    exactly the serialized path's consumption order.

    Pulled incrementally by the scheduler's admission loop, so header
    reads and resident lookups happen only as far ahead as the byte
    budget allows. Chunk pruning (PK range + zone predicates) and the
    resident-tier heat/promotion bookkeeping happen here, identical to
    ``_iter_plain`` / ``resident.scan_items`` — pruned chunks never
    become flights."""
    from ydb_tpu.engine import resident as resident_mod
    from ydb_tpu.engine.reader import _chunk_selected

    cap = morsel_bytes()
    for source, clusters in parts:
        shard = source.shard
        store = getattr(shard, "resident", None)
        on = store is not None and store.enabled()
        pk = shard.pk_column
        width = _row_width(shard.schema.select(names), names)
        for cl in clusters:
            if source.dedup and pk is not None and len(cl) > 1:
                yield _MergeMorsel(source, cl)
                continue
            for m in cl:
                if on:
                    ent = store.lookup(m.portion_id, names)
                    if ent is not None:
                        source.resident_hits += 1
                        source.resident_rows += m.num_rows
                        timeline.add_bytes("resident_bytes", sum(
                            e.nbytes for e in ent.values()))
                        yield _DevMorsel(ent, m.num_rows)
                        continue
                    if store.record_miss(m.portion_id):
                        store.promote_async(
                            m.portion_id, m.num_rows,
                            resident_mod.portion_loader(shard, m))
                rd = _open_reader(shard.store, m.blob_id)
                sel: list[int] = []
                est = 0
                for i in range(rd.n_chunks):
                    cm = rd.chunk_meta(i)
                    if not _chunk_selected(cm, source.pk_range,
                                           source.preds):
                        source.chunks_skipped += 1
                        continue
                    rows = cm.get("rows") or m.num_rows or 1
                    sel.append(i)
                    est += rows * width
                    if est >= cap:
                        yield _IoMorsel(source, m, rd, tuple(sel), est)
                        sel, est = [], 0
                if sel:
                    yield _IoMorsel(source, m, rd, tuple(sel), est)


# ---------------- flights + scheduler ----------------

_PENDING, _RUNNING, _DONE, _FAILED, _CANCELLED = range(5)


class _FlightSlot:
    """One admitted IO morsel crossing threads. State transitions are
    guarded by the scheduler lock; ``event`` fires on any terminal
    worker outcome. The leaksan handle opens at admission and closes
    exactly once at retire (consume or cancel) — consumer-owned, so a
    worker never races the close."""

    __slots__ = ("morsel", "state", "payloads", "error", "event",
                 "leak", "retired", "idx")

    def __init__(self, morsel, leak, idx):
        self.morsel = morsel
        self.state = _PENDING
        self.payloads = None
        self.error = None
        self.event = threading.Event()
        self.leak = leak
        self.retired = False
        self.idx = idx


class StreamScheduler:
    """Admission + in-order consumption over the morsel plan.

    Thread model: the plan iterator and the in-order queue are touched
    ONLY by the assembly thread (the pump_blocks producer); the lock
    guards flight state, the in-flight byte ledger and the stat
    counters that workers and the block consumer also touch."""

    def __init__(self, parts, names, timer=None):
        self.names = tuple(names)
        self.timer = timer
        self._plan = plan_morsels(parts, self.names)
        self._plan_done = False
        self._queue: collections.deque = collections.deque()
        self._lock = sanitizer.make_lock(
            f"stream_sched.{id(self):x}.lock")
        self._budget = stream_budget()
        self._inflight_bytes = 0
        self._inflight_io = 0
        self._next_idx = 0
        self._closed = False
        # stats surfaced on the scan span / bench extras
        self.stats = {
            "morsels_io": 0, "morsels_dev": 0, "morsels_merge": 0,
            "stolen": 0, "ready_out_of_order": 0, "reruns": 0,
            "peak_inflight_bytes": 0, "est_bytes": 0,
            "blocks_emitted": 0, "blocks_consumed": 0,
            "peak_live_blocks": 0,
        }

    # ---- admission (assembly thread only) ----

    def _admit(self) -> None:
        """Pull the plan and launch IO flights while the byte budget
        holds. The head of an empty pipeline always admits (one morsel
        larger than the whole budget must still run), and planning runs
        PAST non-IO morsels so cold portions behind a resident run or a
        merge already stream while those are consumed."""
        while not self._plan_done:
            with self._lock:
                if self._closed:
                    return  # torn down mid-admission: launch nothing
                full = (self._inflight_io > 0
                        and self._inflight_bytes >= self._budget)
            if full:
                return
            m = next(self._plan, None)
            if m is None:
                self._plan_done = True
                return
            if isinstance(m, _DevMorsel):
                with self._lock:
                    self.stats["morsels_dev"] += 1
                self._queue.append(m)
            elif isinstance(m, _MergeMorsel):
                with self._lock:
                    self.stats["morsels_merge"] += 1
                self._queue.append(m)
            else:
                self._queue.append(self._launch(m))

    def _launch(self, m: _IoMorsel) -> _FlightSlot:
        from ydb_tpu.runtime.conveyor import stream_conveyor

        fl = _FlightSlot(m, leaksan.track("stream.morsel", m.meta.blob_id),
                         self._next_idx)
        self._next_idx += 1
        with self._lock:
            self._inflight_bytes += m.est_bytes
            self._inflight_io += 1
            self.stats["morsels_io"] += 1
            self.stats["est_bytes"] += m.est_bytes
            # fixed key set (initialized in __init__), counters only —
            # bounded by construction  # ydb-lint: disable=R007
            self.stats["peak_inflight_bytes"] = max(
                self.stats["peak_inflight_bytes"], self._inflight_bytes)
        try:
            stream_conveyor().submit("stream_morsel", self._run_flight,
                                     fl)
        except RuntimeError:
            # pool shut down (tests teardown): the consumer steals it
            pass
        return fl

    # ---- execution (worker threads or stolen inline) ----

    def _run_flight(self, fl: _FlightSlot, claimed: bool = False) -> None:
        if not claimed:
            with self._lock:
                if fl.state != _PENDING:
                    return  # stolen by the consumer, or cancelled
                fl.state = _RUNNING
        try:
            payloads = self._execute_io(fl)
        except BaseException as e:  # noqa: BLE001 - relayed via slot
            with self._lock:
                if fl.state == _RUNNING:
                    fl.state = _FAILED
                    fl.error = e
        else:
            with self._lock:
                if fl.state == _RUNNING:
                    fl.state = _DONE
                    fl.payloads = payloads
        finally:
            fl.event.set()

    def _execute_io(self, fl: _FlightSlot) -> list:
        """Fetch + decode + project every chunk of one morsel (same
        retry policy, chunk order and projection as ``_iter_plain``;
        one payload per chunk so payload boundaries match exactly)."""
        m = fl.morsel
        shard = m.source.shard
        out = []
        for i in m.chunks:
            with self._lock:
                cancelled = fl.state == _CANCELLED
            if cancelled:
                break
            statement_deadline.check_current("read")
            ctx = (self.timer.stage("read", morsel=fl.idx)
                   if self.timer is not None
                   else contextlib.nullcontext())
            with ctx:
                c, v = m.reader.read_chunk(i, zero_copy=True)
                out.append(project_chunk(shard.schema,
                                         shard.column_added,
                                         m.meta, self.names, c, v))
        return out

    # ---- in-order consumption (assembly thread only) ----

    def _collect(self, fl: _FlightSlot) -> list:
        """Block until the head flight is done, stealing it inline if
        its worker task has not started — guaranteed progress under any
        pool state. Retires the flight (budget credit + leak close) on
        every path."""
        try:
            with self._lock:
                steal = fl.state == _PENDING
                if steal:
                    fl.state = _RUNNING
                    self.stats["stolen"] += 1
                elif fl.state != _DONE and any(
                        isinstance(q, _FlightSlot)
                        and q.state in (_DONE, _FAILED)
                        for q in self._queue):
                    # a later morsel finished before this head: the
                    # out-of-order readiness the in-order queue absorbs
                    self.stats["ready_out_of_order"] += 1
            if steal:
                self._run_flight(fl, claimed=True)
            else:
                while not fl.event.wait(0.05):
                    # consumer-side cancellation while a worker runs
                    statement_deadline.check_current("read")
            with self._lock:
                state, err, payloads = fl.state, fl.error, fl.payloads
            if state == _FAILED and isinstance(err, _TRANSIENT_READ):
                # the worker's RetryPolicy drowned in a fault burst
                # (concurrent flights split the injection/outage window
                # across retry budgets): re-run the morsel inline ONCE
                # with a fresh budget before surrendering the scan
                with self._lock:
                    fl.state = _RUNNING
                    fl.error = None
                    self.stats["reruns"] += 1
                self._run_flight(fl, claimed=True)
                with self._lock:
                    state, err, payloads = \
                        fl.state, fl.error, fl.payloads
            if state == _FAILED:
                raise err
            if state != _DONE:
                raise RuntimeError("morsel flight cancelled mid-scan")
            fl.morsel.source.chunks_read += len(fl.morsel.chunks)
            return payloads
        finally:
            self._retire(fl)

    def _retire(self, fl: _FlightSlot) -> None:
        """Idempotent terminal accounting: exactly one budget credit
        and one leak close per flight, no matter which of consume /
        cancel / close gets there first."""
        with self._lock:
            if fl.retired:
                return
            fl.retired = True
            self._inflight_bytes -= fl.morsel.est_bytes
            self._inflight_io -= 1
            lk, fl.leak = fl.leak, None
        leaksan.close(lk)

    def items(self):
        """The in-order ('dev'/'host') item stream for
        ``resident.mixed_blocks`` — identical item order and payload
        boundaries to ``resident.scan_items`` over the same clusters
        (and, with no resident store, to ``payload_stream``)."""
        try:
            while True:
                self._admit()
                if not self._queue:
                    return
                m = self._queue.popleft()
                if isinstance(m, _FlightSlot):
                    payloads = self._collect(m)
                    # refill the window BEFORE yielding: downstream
                    # staging/compute runs while fresh flights fly
                    self._admit()
                    for cols, valid in payloads:
                        yield ("host", cols, valid)
                elif isinstance(m, _DevMorsel):
                    yield ("dev", m.entries, m.rows)
                else:
                    # inline K-way merge: its blob reads/merge charge
                    # the usual stages; cold portions AFTER it (already
                    # admitted above) stream meanwhile
                    for cols, valid in m.source._iter_merged(
                            m.cluster, self.names):
                        yield ("host", cols, valid)
        finally:
            self.close()

    # ---- cancellation / teardown ----

    def close(self) -> None:
        """Cancel every admitted flight and retire it: pending tasks
        become no-ops, running workers notice and stop between chunks,
        and every leaksan handle closes — a mid-scan deadline or an
        abandoned stream drains to zero. Re-entrant, not just
        idempotent: a flight admitted concurrently with an earlier
        close (the consumer-abandon race) is swept by the next call —
        every exit path calls close, so the last one wins."""
        with self._lock:
            self._closed = True
            flights = [q for q in self._queue
                       if isinstance(q, _FlightSlot)]
            for fl in flights:
                if fl.state in (_PENDING, _RUNNING):
                    fl.state = _CANCELLED
        self._queue.clear()
        for fl in flights:
            self._retire(fl)

    # ---- consumption credit (any thread) ----

    def note_emitted(self) -> None:
        with self._lock:
            self.stats["blocks_emitted"] += 1
            self.stats["peak_live_blocks"] = max(
                self.stats["peak_live_blocks"],
                self.stats["blocks_emitted"]
                - self.stats["blocks_consumed"])

    def note_consumed(self) -> None:
        """In-order consumption credit from the executor
        (scan.run_stream): tracks how many emitted blocks are still
        live on the device side — the measured double-buffer depth."""
        with self._lock:
            self.stats["blocks_consumed"] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)


def stream_pipeline(parts, names, sch, cap, timer=None, prefetch=True,
                    owner=None):
    """Morsel-pipelined block stream over ``[(source, clusters)]``.

    The assembly generator (mixed_blocks over the scheduler's in-order
    items) runs on the shared conveyor via ``pump_blocks`` — its
    depth-bounded queue is the double-buffered device slab stage — and
    the scheduler's dedicated pool runs the IO morsels underneath it.
    ``owner`` (the stream source) gets ``attach_pipeline(sched)`` while
    the stream runs (so the executor's in-order consumption credit
    reaches ``note_consumed``) and ``finish_pipeline(sched)`` when it
    ends or is abandoned (the stat snapshot for the scan span)."""
    from ydb_tpu.engine import resident as resident_mod
    from ydb_tpu.engine.reader import pump_blocks

    sched = StreamScheduler(parts, names, timer=timer)
    if owner is not None:
        owner.attach_pipeline(sched)

    def gen():
        try:
            for blk in resident_mod.mixed_blocks(
                    sched.items(), sched.names, sch, cap, timer=timer):
                sched.note_emitted()
                yield blk
        finally:
            sched.close()
            if owner is not None:
                owner.finish_pipeline(sched)
    return pump_blocks(gen(), prefetch=prefetch)
