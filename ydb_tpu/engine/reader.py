"""Portion-granular streaming scan pipeline with PK merge + MVCC dedup.

The out-of-core read path of the ColumnShard — the analog of the
reference's scan fetching script + K-way PK merge
(engines/reader/plain_reader/iterator/fetching.h:12, scanner.h:69,
merge.cpp:10 NArrow::NMerger):

  * portions are planned into **clusters** by PK-range overlap; only a
    cluster is ever resident at once, so host memory is bounded by the
    largest cluster (compaction keeps clusters small), not the table;
  * within a cluster, rows merge by PK with newest-wins dedup (portions
    ordered oldest -> newest by commit snapshot; the native
    ``ydbtpu_kway_merge`` or its numpy twin does the heavy lifting —
    ydb_tpu/native/src/ydbtpu_native.cpp);
  * the next cluster's blobs are prefetched on a worker thread while the
    current one streams to the device (the conveyor-offload pattern,
    tx/conveyor/service/service.h:73);
  * output blocks all share one fixed capacity, so a single compiled
    program serves the whole stream.
"""

from __future__ import annotations

import concurrent.futures
from typing import Iterator

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import TableBlock
from ydb_tpu.engine.portion import PortionMeta, read_portion_blob
from ydb_tpu import native


def plan_clusters(
    metas: list[PortionMeta], dedup: bool
) -> list[list[PortionMeta]]:
    """Group portions into PK-overlap clusters (granule planning analog).

    Without dedup every portion streams independently. With dedup,
    portions whose [pk_min, pk_max] ranges overlap must merge together;
    portions with no PK stats (empty or statless) conservatively join
    one cluster with everything they might overlap.
    """
    if not dedup:
        return [[m] for m in metas]
    statless = [m for m in metas if m.pk_min is None]
    ranged = sorted(
        (m for m in metas if m.pk_min is not None),
        key=lambda m: (m.pk_min, m.pk_max, m.portion_id),
    )
    clusters: list[list[PortionMeta]] = []
    cur: list[PortionMeta] = []
    cur_max: int | None = None
    for m in ranged:
        if cur and m.pk_min > cur_max:
            clusters.append(cur)
            cur, cur_max = [], None
        cur.append(m)
        cur_max = m.pk_max if cur_max is None else max(cur_max, m.pk_max)
    if cur:
        clusters.append(cur)
    if statless:
        # merge everything into one cluster: no stats, no pruning
        flat = statless + [m for c in clusters for m in c]
        return [sorted(flat, key=lambda m: m.portion_id)]
    return clusters


class PortionStreamSource:
    """ColumnSource-compatible streaming reader over shard portions.

    Duck-types the ``ColumnSource`` surface that ``ScanExecutor`` uses:
    ``schema``, ``dicts``, ``num_rows`` (pre-dedup upper bound) and
    ``blocks()``.
    """

    def __init__(
        self,
        shard,
        metas: list[PortionMeta],
        columns: tuple[str, ...] | None = None,
        dedup: bool | None = None,
        prefetch: bool = True,
    ):
        self.shard = shard
        self.metas = list(metas)
        names = columns if columns is not None else shard.schema.names
        self.columns_read = tuple(names)
        self.schema = shard.schema.select(self.columns_read)
        self.dicts = shard.dicts
        self.dedup = (
            dedup if dedup is not None
            else bool(shard.upsert and shard.pk_column)
        )
        self.prefetch = prefetch

    @property
    def num_rows(self) -> int:
        """Upper bound (pre-dedup): callers size block capacity with it."""
        return sum(m.num_rows for m in self.metas)

    # ---- cluster loading (host side, bounded) ----

    def _read_portion(self, meta: PortionMeta, names) -> tuple[dict, dict]:
        """One portion's columns + validity with schema-evolution nulls
        (same semantics as ColumnShard._materialize)."""
        c, v = read_portion_blob(self.shard.store, meta.blob_id)
        n_rows = len(next(iter(c.values()))) if c else meta.num_rows
        cols, valid = {}, {}
        for n in names:
            if n in c and meta.schema_version >= \
                    self.shard.column_added.get(n, 1):
                cols[n] = c[n]
                valid[n] = v.get(n, np.ones(len(c[n]), dtype=bool))
            else:
                cols[n] = np.zeros(
                    n_rows, dtype=self.shard.schema.field(n).type.physical)
                valid[n] = np.zeros(n_rows, dtype=bool)
        return cols, valid

    def _load_cluster(self, cluster: list[PortionMeta], names):
        """Materialize ONE cluster, merged + deduped when required."""
        pk = self.shard.pk_column
        need_pk = self.dedup and len(cluster) > 0 and pk is not None
        read_names = tuple(names)
        if need_pk and pk not in read_names:
            read_names = read_names + (pk,)
        if not (self.dedup and pk is not None):
            # plain streaming: portions emit in portion order
            parts = [self._read_portion(m, read_names) for m in cluster]
            cols = {n: np.concatenate([p[0][n] for p in parts])
                    for n in read_names} if parts else {}
            valid = {n: np.concatenate([p[1][n] for p in parts])
                     for n in read_names} if parts else {}
            return ({n: cols[n] for n in names},
                    {n: valid[n] for n in names})
        # newest-wins merge: runs ordered oldest -> newest
        ordered = sorted(cluster, key=lambda m: (m.commit_snap,
                                                 m.portion_id))
        parts = [self._read_portion(m, read_names) for m in ordered]
        runs = [np.ascontiguousarray(p[0][pk], dtype=np.int64)
                for p in parts]
        run_idx, row_idx = native.kway_merge(runs, dedup=True)
        offsets = np.cumsum([0] + [len(r) for r in runs])[:-1]
        gidx = offsets[run_idx] + row_idx
        cols = {n: np.concatenate([p[0][n] for p in parts])[gidx]
                for n in names}
        valid = {n: np.concatenate([p[1][n] for p in parts])[gidx]
                 for n in names}
        return cols, valid

    # ---- block stream ----

    def blocks(
        self,
        block_rows: int,
        columns: tuple[str, ...] | None = None,
        start_block: int = 0,
    ) -> Iterator[TableBlock]:
        names = columns if columns is not None else self.columns_read
        sch = self.shard.schema.select(names)
        cap = min(block_rows, max(self.num_rows, 1))
        clusters = plan_clusters(self.metas, self.dedup)

        def gen_rows():
            """Yield (cols, valid) cluster payloads with 1-deep prefetch."""
            if not self.prefetch or len(clusters) <= 1:
                for cl in clusters:
                    yield self._load_cluster(cl, names)
                return
            with concurrent.futures.ThreadPoolExecutor(1) as pool:
                fut = pool.submit(self._load_cluster, clusters[0], names)
                for nxt in clusters[1:]:
                    cur = fut.result()
                    fut = pool.submit(self._load_cluster, nxt, names)
                    yield cur
                yield fut.result()

        # re-chunk cluster payloads into fixed-capacity blocks
        buf_c: list[dict] = []
        buf_n = 0
        emitted = 0

        def make_block(cols, valid):
            nonlocal emitted
            emitted += 1
            if emitted - 1 < start_block:
                return None  # checkpoint-resume seek: skip cheaply
            return TableBlock.from_numpy(cols, sch, valid, capacity=cap)

        for cols, valid in gen_rows():
            n = len(next(iter(cols.values()))) if cols else 0
            off = 0
            while off < n:
                take = min(cap - buf_n, n - off)
                buf_c.append((
                    {m: cols[m][off:off + take] for m in names},
                    {m: valid[m][off:off + take] for m in names},
                ))
                buf_n += take
                off += take
                if buf_n == cap:
                    cc = {m: np.concatenate([b[0][m] for b in buf_c])
                          for m in names}
                    vv = {m: np.concatenate([b[1][m] for b in buf_c])
                          for m in names}
                    blk = make_block(cc, vv)
                    if blk is not None:
                        yield blk
                    buf_c, buf_n = [], 0
        if buf_n or emitted == 0:
            cc = {m: (np.concatenate([b[0][m] for b in buf_c]) if buf_c
                      else np.empty(0, dtype=sch.field(m).type.physical))
                  for m in names}
            vv = {m: (np.concatenate([b[1][m] for b in buf_c]) if buf_c
                      else np.empty(0, dtype=bool))
                  for m in names}
            blk = make_block(cc, vv)
            if blk is not None:
                yield blk

    # NOTE deliberately no n_blocks(): with dedup the emitted block count
    # is only known after merging, so any count-based resume arithmetic
    # (DQ checkpoint seek) must count actual emissions, not estimate.
