"""Portion-granular streaming scan pipeline with PK merge + MVCC dedup.

The out-of-core read path of the ColumnShard — the analog of the
reference's scan fetching script + K-way PK merge
(engines/reader/plain_reader/iterator/fetching.h:12, scanner.h:69,
merge.cpp:10 NArrow::NMerger):

  * portions are planned into **clusters** by PK-range overlap; within a
    cluster rows merge by PK with newest-wins dedup (portions ordered
    oldest -> newest by commit snapshot; the native ``ydbtpu_kway_merge``
    or its numpy twin does the batch merging —
    ydb_tpu/native/src/ydbtpu_native.cpp);
  * the merge is **incremental**: each portion blob is chunk-indexed
    (engine/portion.py) and a per-run cursor keeps only a couple of
    chunks buffered, so host memory is bounded by
    O(runs x chunk_rows) even when every portion overlaps every other
    (uniform-random upserts) — the interval-bounded merge of the
    reference's TScanHead (plain_reader/iterator/scanner.h:69), not a
    cluster materialization;
  * the next payload is prefetched on a worker thread while the current
    one streams to the device (the conveyor-offload pattern,
    tx/conveyor/service/service.h:73);
  * output blocks all share one fixed capacity, so a single compiled
    program serves the whole stream.
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
from typing import Iterator

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import TableBlock
from ydb_tpu.chaos import deadline as statement_deadline
from ydb_tpu.engine.portion import (
    PortionChunkReader,
    PortionMeta,
    project_chunk,
    read_portion_blob,
)
from ydb_tpu import native


def _chunk_in_range(meta: dict, pk_range) -> bool:
    """Chunk-level PK pruning off the blob header bounds."""
    if pk_range is None:
        return True
    lo, hi = pk_range
    cmin, cmax = meta.get("pk_min"), meta.get("pk_max")
    if lo is not None and cmax is not None and cmax < lo:
        return False
    if hi is not None and cmin is not None and cmin > hi:
        return False
    return True


def _chunk_selected(meta: dict, pk_range, preds) -> bool:
    """General chunk pruning: PK range plus conjunctive filter
    predicates against the chunk's v1-header zone maps (the PK check is
    just the oldest special case of the zone path). Conservative: a
    chunk without zones (v0 header) is always read."""
    if not _chunk_in_range(meta, pk_range):
        return False
    if preds:
        from ydb_tpu.stats.zonemap import zones_decide

        skip, _all = zones_decide(meta.get("zones") if meta else None,
                                  preds)
        if skip:
            return False
    return True


def rechunk(payloads, names, cap: int):
    """Re-cut a stream of (cols, valid) payloads into exactly-``cap``-row
    pieces (last piece partial). Shared by the block stream and
    compaction output cutting.

    Low-copy: a payload whose boundary already aligns with ``cap``
    passes its arrays through untouched (the common case once portion
    chunk sizes divide the block size), and a single buffered piece
    flushes as its own slice views — ``np.concatenate`` only runs when
    a block genuinely straddles payloads."""
    buf: list[tuple[dict, dict]] = []
    buf_n = 0

    def flush():
        if len(buf) == 1:
            return buf[0]
        return ({m: np.concatenate([b[0][m] for b in buf]) for m in names},
                {m: np.concatenate([b[1][m] for b in buf]) for m in names})

    for cols, valid in payloads:
        n = len(next(iter(cols.values()))) if cols else 0
        if not buf_n and n == cap:
            # aligned payload: no buffering, no copy — pass through
            yield ({m: cols[m] for m in names},
                   {m: valid[m] for m in names})
            continue
        off = 0
        while off < n:
            take = min(cap - buf_n, n - off)
            if take == n:
                # whole payload in one piece: keep the original arrays
                # (a [0:n] slice would demote them to views, costing the
                # device-transfer aliasing fast path downstream)
                buf.append(({m: cols[m] for m in names},
                            {m: valid[m] for m in names}))
            else:
                buf.append((
                    {m: cols[m][off:off + take] for m in names},
                    {m: valid[m][off:off + take] for m in names},
                ))
            buf_n += take
            off += take
            if buf_n == cap:
                yield flush()
                buf, buf_n = [], 0
    if buf_n:
        yield flush()


def plan_clusters(
    metas: list[PortionMeta], dedup: bool
) -> list[list[PortionMeta]]:
    """Group portions into PK-overlap clusters (granule planning analog).

    Without dedup every portion streams independently. With dedup,
    portions whose [pk_min, pk_max] ranges overlap must merge together;
    portions with no PK stats (empty or statless) conservatively join
    one cluster with everything they might overlap.
    """
    if not dedup:
        return [[m] for m in metas]
    statless = [m for m in metas if m.pk_min is None]
    ranged = sorted(
        (m for m in metas if m.pk_min is not None),
        key=lambda m: (m.pk_min, m.pk_max, m.portion_id),
    )
    clusters: list[list[PortionMeta]] = []
    cur: list[PortionMeta] = []
    cur_max: int | None = None
    for m in ranged:
        if cur and m.pk_min > cur_max:
            clusters.append(cur)
            cur, cur_max = [], None
        cur.append(m)
        cur_max = m.pk_max if cur_max is None else max(cur_max, m.pk_max)
    if cur:
        clusters.append(cur)
    if statless:
        # merge everything into one cluster: no stats, no pruning
        flat = statless + [m for c in clusters for m in c]
        return [sorted(flat, key=lambda m: m.portion_id)]
    return clusters


class _RunCursor:
    """Chunk-granular cursor over one PK-sorted portion (a merge run).

    Buffers whole chunks; ``pop`` releases merged rows from the front.
    Schema-evolution nulls match ColumnShard._materialize: a column only
    reads from portions at least as new as the version that added it.
    """

    def __init__(self, source: "PortionStreamSource", meta: PortionMeta,
                 names: tuple[str, ...]):
        self.source = source
        self.meta = meta
        self.names = names
        self.reader = PortionChunkReader(source.shard.store, meta.blob_id)
        self.next_chunk = 0
        self.cols = {n: [] for n in names}   # buffered chunk slices
        self.valid = {n: [] for n in names}
        self.pk_buf = np.empty(0, dtype=np.int64)

    @property
    def done(self) -> bool:
        return self.next_chunk >= self.reader.n_chunks

    @property
    def size(self) -> int:
        return len(self.pk_buf)

    @property
    def last_pk(self) -> int:
        return int(self.pk_buf[-1])

    def _read_chunk(self, i: int) -> tuple[dict, dict]:
        t = self.source.timer
        ctx = (t.stage("read") if t is not None
               else contextlib.nullcontext())
        with ctx:
            c, v = self.reader.read_chunk(i)
            self.source.chunks_read += 1
            shard = self.source.shard
            return project_chunk(shard.schema, shard.column_added,
                                 self.meta, self.names, c, v)

    def fill_more(self) -> None:
        """Append the next chunk to the buffer (PK-pruned chunks skip).

        Only the PK range prunes here: this cursor feeds the K-way
        newest-wins merge, where value-predicate skips could resurrect
        shadowed row versions (see PortionStreamSource.preds)."""
        i = self.next_chunk
        self.next_chunk += 1
        if not _chunk_in_range(self.reader.chunk_meta(i),
                               self.source.pk_range):
            self.source.chunks_skipped += 1
            return
        cols, valid = self._read_chunk(i)
        for n in self.names:
            self.cols[n].append(cols[n])
            self.valid[n].append(valid[n])
        pk = self.source.shard.pk_column
        self.pk_buf = np.concatenate([
            self.pk_buf,
            np.ascontiguousarray(cols[pk], dtype=np.int64),
        ])

    def fill(self) -> None:
        """Ensure the buffer is non-empty (or the run is exhausted)."""
        while self.size == 0 and not self.done:
            self.fill_more()

    def take(self, bound: int | None) -> int:
        """Rows at the buffer front with pk <= bound (all when None)."""
        if bound is None:
            return self.size
        return int(np.searchsorted(self.pk_buf, bound, side="right"))

    def slices(self, k: int) -> tuple[dict, dict]:
        cat_c = {n: (np.concatenate(a) if len(a) != 1 else a[0])
                 for n, a in self.cols.items()}
        cat_v = {n: (np.concatenate(a) if len(a) != 1 else a[0])
                 for n, a in self.valid.items()}
        self.cols = {n: [cat_c[n]] for n in self.names}
        self.valid = {n: [cat_v[n]] for n in self.names}
        return ({n: cat_c[n][:k] for n in self.names},
                {n: cat_v[n][:k] for n in self.names})

    def pop(self, k: int) -> None:
        self.cols = {n: [self.cols[n][0][k:]] for n in self.names}
        self.valid = {n: [self.valid[n][0][k:]] for n in self.names}
        self.pk_buf = self.pk_buf[k:]


class PortionStreamSource:
    """ColumnSource-compatible streaming reader over shard portions.

    Duck-types the ``ColumnSource`` surface that ``ScanExecutor`` uses:
    ``schema``, ``dicts``, ``num_rows`` (pre-dedup upper bound) and
    ``blocks()``.
    """

    def __init__(
        self,
        shard,
        metas: list[PortionMeta],
        columns: tuple[str, ...] | None = None,
        dedup: bool | None = None,
        prefetch: bool = True,
        pk_range: tuple[int | None, int | None] | None = None,
        timer=None,
        preds=None,
    ):
        self.shard = shard
        self.metas = list(metas)
        # chunk-granular PK pruning window (coarse: callers still filter)
        self.pk_range = pk_range
        # conjunctive filter predicates (stats.zonemap.Pred) for
        # chunk-granular zone pruning. Only the NON-merging read path
        # consults them: inside a K-way dedup merge a skipped newer
        # chunk could resurrect the older row version it shadows, so
        # merged clusters read every in-PK-range chunk. Single-portion
        # clusters are always safe — portions hold unique PKs.
        self.preds = list(preds or [])
        self.chunks_read = 0  # observability: chunk fetches actually done
        self.chunks_skipped = 0  # chunks zone/PK-pruned without a fetch
        self.portions_skipped = 0  # whole portions pruned by zone maps
        # per-scan stage accounting (obs.probes.StageTimer): blob reads
        # charge "read", K-way merging "merge"; None = untimed
        self.timer = timer
        names = columns if columns is not None else shard.schema.names
        self.columns_read = tuple(names)
        self.schema = shard.schema.select(self.columns_read)
        self.dicts = shard.dicts
        self.dedup = (
            dedup if dedup is not None
            else bool(shard.upsert and shard.pk_column)
        )
        self.prefetch = prefetch
        # HBM-resident tier attribution: portions/rows served from
        # decoded device arrays instead of the staged host path
        # (engine.resident; sys_resident_store + shard.scan spans)
        self.resident_hits = 0
        self.resident_rows = 0
        # morsel-pipeline attribution (engine.stream_sched): the live
        # scheduler while a pipelined stream runs, kept after it ends
        # for the stat snapshot (shard.scan spans / bench extras)
        self._pipeline = None
        self._finished_pipeline = None

    # ---- morsel-pipeline hooks (engine.stream_sched owner surface) ----

    def attach_pipeline(self, sched) -> None:
        self._pipeline = sched

    def finish_pipeline(self, sched) -> None:
        self._pipeline = None
        self._finished_pipeline = sched

    @property
    def last_pipeline(self) -> "dict | None":
        """Stat snapshot of the last pipelined stream, taken lazily —
        the producer finishes the pipeline while the consumer is still
        draining queued blocks, so an eager snapshot would undercount
        ``blocks_consumed``."""
        s = self._finished_pipeline
        return None if s is None else s.snapshot()

    def note_block_consumed(self) -> None:
        """In-order consumption credit from the executor (run_stream):
        forwarded to the scheduler's slab accounting (live or finished
        — the tail blocks outlive the producer); a no-op on the
        serialized path."""
        p = self._pipeline or self._finished_pipeline
        if p is not None:
            p.note_consumed()

    @property
    def num_rows(self) -> int:
        """Upper bound (pre-dedup): callers size block capacity with it."""
        return sum(m.num_rows for m in self.metas)

    # ---- cluster loading (host side, bounded) ----

    def _read_portion(self, meta: PortionMeta, names) -> tuple[dict, dict]:
        """One portion's columns + validity with schema-evolution nulls
        (same semantics as ColumnShard._materialize)."""
        c, v = read_portion_blob(self.shard.store, meta.blob_id)
        return project_chunk(self.shard.schema, self.shard.column_added,
                             meta, names, c, v)

    def _iter_merged(self, cluster: list[PortionMeta], names):
        """Incremental K-way newest-wins merge over a PK-overlap cluster.

        Yields bounded (cols, valid) payloads in global PK order. At each
        step the *bound* is the smallest last-buffered PK over unfinished
        runs: every row with pk <= bound is provably buffered (runs are
        PK-sorted, and runs still at the bound are extended first), so a
        batch merge of the <=bound prefixes is final — the incremental
        analog of the reference's interval merge (scanner.h:69).
        """
        pk = self.shard.pk_column
        read_names = tuple(names)
        if pk not in read_names:
            read_names = read_names + (pk,)
        ordered = sorted(cluster, key=lambda m: (m.commit_snap,
                                                 m.portion_id))
        cursors = [_RunCursor(self, m, read_names) for m in ordered]
        while True:
            for c in cursors:
                c.fill()
            if not any(c.size for c in cursors):
                return
            not_done = [c for c in cursors if not c.done]
            bound = (min(c.last_pk for c in not_done)
                     if not_done else None)
            if bound is not None:
                # duplicates of the bound key may straddle a chunk edge:
                # extend runs until their buffers pass the bound
                for c in cursors:
                    while not c.done and c.last_pk <= bound:
                        c.fill_more()
            mctx = (self.timer.stage("merge") if self.timer is not None
                    else contextlib.nullcontext())
            with mctx:
                takes = [c.take(bound) for c in cursors]
                parts = []
                runs = []
                for c, k in zip(cursors, takes):
                    if k == 0:
                        continue
                    parts.append(c.slices(k))
                    runs.append(c.pk_buf[:k])
                run_idx, row_idx = native.kway_merge(runs, dedup=True)
                # gather per-run instead of concatenate-then-gather:
                # with dedup the merged output is SMALLER than the
                # buffered input, so materializing a concatenated copy
                # of every run just to index it wastes the difference;
                # per-run fancy gathers write each output row exactly
                # once
                out_n = len(run_idx)
                sels = [np.flatnonzero(run_idx == r)
                        for r in range(len(parts))]
                rsels = [row_idx[s] for s in sels]
                cols = {}
                valid = {}
                for n in names:
                    first = parts[0][0][n]
                    oc = np.empty(out_n, dtype=first.dtype)
                    ov = np.empty(out_n, dtype=np.bool_)
                    for p, s, rs in zip(parts, sels, rsels):
                        oc[s] = p[0][n][rs]
                        ov[s] = p[1][n][rs]
                    cols[n] = oc
                    valid[n] = ov
                for c, k in zip(cursors, takes):
                    if k:
                        c.pop(k)
            yield cols, valid

    def _iter_plain(self, cluster: list[PortionMeta], names):
        """No-merge streaming: portion chunks emit in portion order.
        Chunk-granular pruning (PK range + zone-map predicates) happens
        here — skipped chunks are never fetched from the store."""
        for m in cluster:
            rd = PortionChunkReader(self.shard.store, m.blob_id)
            for i in range(rd.n_chunks):
                if not _chunk_selected(rd.chunk_meta(i), self.pk_range,
                                       self.preds):
                    self.chunks_skipped += 1
                    continue
                rctx = (self.timer.stage("read")
                        if self.timer is not None
                        else contextlib.nullcontext())
                with rctx:
                    c, v = rd.read_chunk(i)
                    self.chunks_read += 1
                    out = project_chunk(self.shard.schema,
                                        self.shard.column_added,
                                        m, names, c, v)
                yield out

    def payload_stream(self, clusters, names):
        """All clusters as a stream of bounded (cols, valid) payloads."""
        pk = self.shard.pk_column
        for cl in clusters:
            if self.dedup and pk is not None and len(cl) > 1:
                yield from self._iter_merged(cl, names)
            else:
                yield from self._iter_plain(cl, names)

    def _load_cluster(self, cluster: list[PortionMeta], names):
        """Materialize ONE cluster (compaction of bounded jobs; tests).
        The scan path streams via payload_stream instead."""
        pk = self.shard.pk_column
        if self.dedup and pk is not None and len(cluster) > 1:
            payloads = list(self._iter_merged(cluster, names))
        else:
            payloads = list(self._iter_plain(cluster, names))
        if not payloads:
            empty_c = {n: np.empty(
                0, dtype=self.shard.schema.field(n).type.physical)
                for n in names}
            return empty_c, {n: np.empty(0, dtype=bool) for n in names}
        cols = {n: np.concatenate([p[0][n] for p in payloads])
                for n in names}
        valid = {n: np.concatenate([p[1][n] for p in payloads])
                 for n in names}
        return cols, valid

    # ---- block stream ----

    def blocks(
        self,
        block_rows: int,
        columns: tuple[str, ...] | None = None,
        start_block: int = 0,
    ) -> Iterator[TableBlock]:
        names = columns if columns is not None else self.columns_read
        sch = self.shard.schema.select(names)
        cap = min(block_rows, max(self.num_rows, 1))
        clusters = plan_clusters(self.metas, self.dedup)
        if start_block == 0:
            from ydb_tpu.engine import stream_sched

            if stream_sched.pipeline_enabled():
                # morsel-driven pipeline: out-of-order IO/decode on the
                # stream conveyor, in-order assembly, double-buffered
                # slabs — resident-tier placement folded in. Count-based
                # resume (start_block) keeps the serialized path: its
                # block arithmetic must not depend on pipeline state.
                yield from stream_sched.stream_pipeline(
                    [(self, clusters)], names, sch, cap,
                    timer=self.timer, prefetch=self.prefetch,
                    owner=self)
                return
        res = getattr(self.shard, "resident", None)
        if start_block == 0 and res is not None and res.enabled():
            # HBM-resident fast path: portions with pinned decoded
            # columns assemble blocks device-side; the rest stage
            # through the host path mid-stream. Count-based resume
            # (start_block) stays on the host path — its block
            # boundaries must not depend on what happens to be
            # resident at resume time.
            from ydb_tpu.engine import resident as resident_mod

            yield from resident_mod.stream_resident(
                self, clusters, names, sch, cap,
                timer=self.timer, prefetch=self.prefetch)
            return
        yield from stream_blocks(
            self.payload_stream(clusters, names), names, sch, cap,
            start_block=start_block, prefetch=self.prefetch,
            timer=self.timer,
        )

    # NOTE deliberately no n_blocks(): with dedup the emitted block count
    # is only known after merging, so any count-based resume arithmetic
    # (DQ checkpoint seek) must count actual emissions, not estimate.


#: test/bench override for the staging lookahead: an int forces the
#: depth, None reads the (cached) environment — the FUSE_FORCE pattern
PREFETCH_DEPTH_FORCE: "int | None" = None

#: cached YDB_TPU_PREFETCH_DEPTH: the env var is configuration, not a
#: per-stream knob, and re-reading the environment on every stream put
#: a getenv on the hot scan path. None = not read yet.
_prefetch_depth_env: "int | None" = None
_prefetch_depth_lock = threading.Lock()


def _prefetch_depth() -> int:
    """Staging lookahead (device blocks buffered ahead of the consumer).
    Depth 2 keeps one block in transfer while one waits, without pinning
    unbounded host/device memory. Read from the environment ONCE;
    ``PREFETCH_DEPTH_FORCE`` is the in-process override seam."""
    global _prefetch_depth_env
    if PREFETCH_DEPTH_FORCE is not None:
        return PREFETCH_DEPTH_FORCE
    depth = _prefetch_depth_env
    if depth is None:
        with _prefetch_depth_lock:
            if _prefetch_depth_env is None:
                try:
                    _prefetch_depth_env = int(
                        os.environ.get("YDB_TPU_PREFETCH_DEPTH", "2"))
                except ValueError:
                    _prefetch_depth_env = 2
            depth = _prefetch_depth_env
    return depth


def stream_blocks(payloads, names, sch, cap: int,
                  start_block: int = 0,
                  prefetch: bool = True,
                  depth: int | None = None,
                  timer=None) -> Iterator[TableBlock]:
    """(cols, valid) payload stream -> fixed-capacity TableBlocks.

    The staging pipeline: a producer task on the SHARED conveyor pool
    (runtime.conveyor.shared_conveyor — no per-scan executor churn)
    drains the payload stream, re-cuts it (``rechunk``), builds device
    blocks (``TableBlock.from_numpy`` issues the host->device transfer),
    and parks them in a ``depth``-bounded queue. Blob IO, host merge AND
    the next blocks' device transfers all overlap the consumer's device
    compute; ``depth`` bounds how far the producer runs ahead.

    ``timer`` (obs.probes.StageTimer) charges block building to the
    "stage" stage. Always emits at least one (possibly empty) block:
    consumers size their compiled programs off the stream. Abandoning
    the generator (close/GC) stops the producer promptly — the bounded
    put is stop-aware, so no task leaks on the shared pool.
    """
    def build(cols, valid):
        from ydb_tpu.obs import timeline

        ctx = (timer.stage("stage") if timer is not None
               else contextlib.nullcontext())
        with ctx:
            blk = TableBlock.from_numpy(cols, sch, valid, capacity=cap)
        # staged/H2D movement: padded device bytes this block shipped
        timeline.add_bytes("staged_bytes", sum(
            c.data.nbytes + c.validity.nbytes
            for c in blk.columns.values()))
        return blk

    pieces = rechunk(payloads, names, cap)

    def gen():
        emitted = 0
        for cols, valid in pieces:
            # per-piece cancellation: the conveyor carried the
            # statement deadline onto the producer thread, so an
            # expired statement stops staging (the error relays to the
            # consumer and the worker slot frees)
            statement_deadline.check_current("stage")
            emitted += 1
            if emitted - 1 < start_block:
                continue  # checkpoint-resume seek: skips BEFORE staging
            yield build(cols, valid)
        if emitted == 0 and start_block == 0:
            yield build(
                {m: np.empty(0, dtype=sch.field(m).type.physical)
                 for m in names},
                {m: np.empty(0, dtype=bool) for m in names})

    return pump_blocks(gen(), prefetch=prefetch, depth=depth)


def pump_blocks(blocks, prefetch: bool = True,
                depth: int | None = None) -> Iterator[TableBlock]:
    """Drain a block generator on the SHARED conveyor pool ahead of the
    consumer (the staging producer shape shared by the host payload
    path and the resident tier's mixed stream). With no idle worker —
    or prefetch off — the generator runs inline on the consumer."""
    depth = _prefetch_depth() if depth is None else depth
    if not prefetch or depth <= 0:
        yield from blocks
        return

    from ydb_tpu.runtime.conveyor import shared_conveyor

    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def put(item) -> bool:
        """Stop-aware bounded put: an abandoned consumer sets ``stop``
        and the producer exits instead of parking forever."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        # the conveyor re-activated the consumer's span on this worker
        # thread; the producer span proves (and tests assert) the
        # trace id crossed the pool
        from ydb_tpu.obs import tracing

        emitted = 0
        try:
            with tracing.span("scan.producer") as psp:
                psp.set(thread=threading.get_ident())
                for blk in blocks:
                    if stop.is_set():
                        return
                    emitted += 1
                    if not put(("blk", blk)):
                        return
                psp.set(blocks=emitted)
            put(("end", emitted))
        except BaseException as e:  # noqa: BLE001 - relayed to consumer
            put(("err", e))
        finally:
            # abandoned consumer (stop set): a bare return here would
            # strand the generator's finally blocks — the morsel
            # scheduler's teardown (stream_sched.close) lives there, so
            # close it on THIS thread, the one iterating it
            close = getattr(blocks, "close", None)
            if close is not None:
                close()

    # atomic free-worker admission: a producer must never QUEUE behind
    # other parked producers (its consumer would starve waiting on a
    # task that cannot start) — with no idle worker, stage inline
    handle = shared_conveyor().submit_if_free("scan_prefetch", produce)
    if handle is None:
        yield from blocks
        return
    try:
        while True:
            # consumer-side cancellation: raising here runs the finally
            # below — stop is set, the queue drains, the producer exits
            # and its conveyor slot frees (no leaked tasks)
            statement_deadline.check_current("scan")
            try:
                kind, payload = q.get(timeout=0.05)
            except queue.Empty:
                if handle.done.is_set() and q.empty():
                    # producer finished without a terminal message:
                    # cancelled during pool shutdown — surface that
                    handle.wait(0)
                    raise RuntimeError("block staging producer vanished")
                continue
            if kind == "blk":
                yield payload
            elif kind == "end":
                return
            else:
                raise payload
    finally:
        stop.set()
        with contextlib.suppress(queue.Empty):
            while True:
                q.get_nowait()


class MultiShardStreamSource:
    """Streaming ColumnSource over every shard of a sharded table at one
    snapshot — the SQL path's scan source. Per-shard portion streams
    (PK-merged + deduped under upsert) concatenate into one
    fixed-capacity block stream; nothing materializes beyond the merge
    working set, so SELECTs inherit the same out-of-core bound as direct
    shard scans (the KQP scan fan-out shape, kqp_scan_executer.cpp)."""

    def __init__(self, shards, schema, dicts, snap=None,
                 columns: tuple[str, ...] | None = None,
                 timer=None):
        names = columns if columns is not None else schema.names
        self.columns_read = tuple(names)
        self._base_schema = schema
        self.schema = schema.select(self.columns_read)
        self.dicts = dicts
        self.timer = timer
        self._shards = list(shards)
        self._snap = snap
        self.preds: tuple = ()
        self._pipeline = None
        self._finished_pipeline = None
        self.subs = [
            PortionStreamSource(s, s.visible_portions(snap),
                                columns=self.columns_read, timer=timer)
            for s in shards
        ]

    def attach_timer(self, timer) -> "MultiShardStreamSource":
        """Late-bind a StageTimer (the SQL scan path creates the source
        at snapshot time, before any program — and with it any profile
        span — exists)."""
        self.timer = timer
        for sub in self.subs:
            sub.timer = timer
        return self

    # ---- morsel-pipeline hooks (engine.stream_sched owner surface) ----

    def attach_pipeline(self, sched) -> None:
        self._pipeline = sched

    def finish_pipeline(self, sched) -> None:
        self._pipeline = None
        self._finished_pipeline = sched

    @property
    def last_pipeline(self) -> "dict | None":
        s = self._finished_pipeline
        return None if s is None else s.snapshot()

    def note_block_consumed(self) -> None:
        p = self._pipeline or self._finished_pipeline
        if p is not None:
            p.note_consumed()

    def with_predicates(self, preds) -> "MultiShardStreamSource":
        """A pruned VIEW of this source for one program's conjunctive
        filter predicates (stats.zonemap.Pred): portion-level zone
        pruning for shards whose rows never shadow (non-upsert), plus
        chunk-granular pruning inside every sub-stream. The base source
        stays untouched — other programs over the same snapshot keep
        their unpruned streams — and ``device_cache_key`` carries the
        predicate fingerprint so pruned block streams never collide
        with unpruned ones in the device cache."""
        from ydb_tpu.stats.zonemap import preds_fingerprint, zones_decide

        view = MultiShardStreamSource(
            self._shards, self._base_schema, self.dicts, self._snap,
            columns=self.columns_read, timer=self.timer)
        view.preds = preds_fingerprint(preds)
        for sub in view.subs:
            sub.preds = list(preds)
            if not getattr(sub.shard, "upsert", False):
                kept = []
                res = getattr(sub.shard, "resident", None)
                for m in sub.metas:
                    skip, _all = zones_decide(m.zones, sub.preds)
                    if skip:
                        sub.portions_skipped += 1
                        if res is not None:
                            # zone-pruned portions have no resident
                            # value: feed the eviction policy
                            res.note_pruned(m.portion_id)
                    else:
                        kept.append(m)
                sub.metas = kept
        return view

    @property
    def num_rows(self) -> int:
        """Pre-dedup upper bound across all shards."""
        return sum(sub.num_rows for sub in self.subs)

    def device_cache_key(self, read_cols, block_rows: int):
        """Identity of this source's block stream for the device block
        cache: per-shard (shard id, visible portion ids) plus the block
        geometry AND the pruning-predicate fingerprint (a pruned stream
        holds fewer rows than an unpruned one over the same portions —
        serving one for the other would drop data). Portions are
        immutable, so equal keys produce equal streams; any
        commit/compaction changes some shard's portion set and with it
        the key."""
        return (
            tuple((sub.shard.shard_id,
                   tuple(m.portion_id for m in sub.metas))
                  for sub in self.subs),
            tuple(read_cols), block_rows, self.preds,
        )

    @property
    def chunks_read(self) -> int:
        return sum(sub.chunks_read for sub in self.subs)

    @property
    def chunks_skipped(self) -> int:
        return sum(sub.chunks_skipped for sub in self.subs)

    @property
    def portions_skipped(self) -> int:
        return sum(sub.portions_skipped for sub in self.subs)

    @property
    def resident_hits(self) -> int:
        return sum(sub.resident_hits for sub in self.subs)

    @property
    def resident_rows(self) -> int:
        return sum(sub.resident_rows for sub in self.subs)

    def blocks(
        self,
        block_rows: int,
        columns: tuple[str, ...] | None = None,
        start_block: int = 0,
    ) -> Iterator[TableBlock]:
        names = columns if columns is not None else self.columns_read
        sch = self._base_schema.select(names)
        cap = min(block_rows, max(self.num_rows, 1))
        if start_block == 0:
            from ydb_tpu.engine import stream_sched

            if stream_sched.pipeline_enabled():
                # one scheduler spans ALL shards: IO morsels of shard
                # k+1 fly while shard k's blocks are consumed, under a
                # single byte budget and one block capacity (one
                # compiled program)
                yield from stream_sched.stream_pipeline(
                    [(sub, plan_clusters(sub.metas, sub.dedup))
                     for sub in self.subs],
                    names, sch, cap, timer=self.timer, owner=self)
                return
        if start_block == 0 and any(
                getattr(sub.shard, "resident", None) is not None
                and sub.shard.resident.enabled() for sub in self.subs):
            # resident-aware SQL path: one mixed item stream across all
            # shards keeps a single block capacity (one compiled
            # program), while each shard's portions serve from its own
            # resident store or stage through the host path
            from ydb_tpu.engine import resident as resident_mod

            def items():
                for sub in self.subs:
                    clusters = plan_clusters(sub.metas, sub.dedup)
                    yield from resident_mod.scan_items(sub, clusters,
                                                       names)

            yield from pump_blocks(resident_mod.mixed_blocks(
                items(), names, sch, cap, timer=self.timer))
            return

        def payloads():
            for sub in self.subs:
                clusters = plan_clusters(sub.metas, sub.dedup)
                yield from sub.payload_stream(clusters, names)

        yield from stream_blocks(payloads(), names, sch, cap,
                                 start_block=start_block,
                                 timer=self.timer)
