"""Per-scan single-flight staging share (the batch tier's scan attach).

The DeviceBlockCache (engine/blockcache.py) single-flights the DECODE
of a portion stream per cache key; this module lifts the same
single-flight discipline one level up, to the fused executor's STAGED
block — the shape-class-padded device block a whole scan site stages
into (``plan_fuse.fit_blocks`` / ``TableBlock.from_numpy``). N
concurrent statements scanning the same hot table under the same
snapshot attach to ONE in-flight staging instead of each merging and
padding their own copy: the first arrival stages, everyone else waits
on the flight and reads the same device block.

Keys must capture everything that shapes the staged block: table,
pushdown program (pruning derives from it), read columns, shape-class
capacity, and the source's ``device_cache_key`` (per-shard visible
portion ids — a commit mints a new key, so stale entries are never
served; they just stop being asked for). Sources without a device cache
key (host ColumnSources outside a cluster) don't share — the caller
passes ``key=None`` and stages privately.

Entries are single-flight ONLY: an entry exists while its staging is in
flight and for the short tail while waiters collect it; completed
entries age out after ``linger_seconds``. Persistence across statements
belongs to the layers below (DeviceBlockCache, the resident tier) —
this share must never become a second cache holding HBM bytes twice.

Shared blocks are handed to NON-DONATING dispatches only
(``FusedPlan.run_shared``; ``run_stacked`` copies via ``jnp.stack``):
donating a shared buffer would let one statement's dispatch scribble
over a block a batchmate is about to read.
"""

from __future__ import annotations

import threading
import time

from ydb_tpu.analysis import leaksan, sanitizer

#: a filler stuck past this (wedged blob store) stops blocking
#: attachers — they stage privately instead (blockcache idiom)
FLIGHT_WAIT_SECONDS = 30.0


class _Flight:
    __slots__ = ("event", "block", "error", "done_at")

    def __init__(self):
        self.event = threading.Event()
        self.block = None
        self.error = None
        self.done_at = None


class ScanShare:
    """Single-flight map: scan identity -> in-flight staged block."""

    def __init__(self, linger_seconds: float = 0.05):
        self.linger_seconds = linger_seconds
        self._lock = sanitizer.make_lock(f"scanshare.{id(self):x}")
        self._flights = sanitizer.share(
            {}, f"scanshare.{id(self):x}.flights")
        self.staged = 0    # stage_fn actually ran
        self.attached = 0  # served from another statement's flight

    def get_or_stage(self, key, stage_fn):
        """The staged block for ``key``: the first caller runs
        ``stage_fn()`` (outside the lock) and publishes; concurrent
        callers wait on the flight and share the result. ``key=None``
        bypasses sharing entirely. A failed staging propagates its
        error to every attacher of THAT flight, then clears."""
        if key is None:
            return stage_fn()
        now = time.monotonic()
        with self._lock:
            self._sweep(now)
            fl = self._flights.get(key)
            if fl is None:
                fl = _Flight()
                self._flights[key] = fl
                filler = True
            else:
                filler = False
                self.attached += 1
        if not filler:
            if not fl.event.wait(FLIGHT_WAIT_SECONDS):
                # wedged filler: stage privately rather than stall
                return stage_fn()
            if fl.error is not None:
                raise fl.error
            return fl.block
        lk = leaksan.track("scanshare.flight", str(key)[:80])
        try:
            fl.block = stage_fn()
            self.staged += 1
            return fl.block
        except BaseException as e:
            fl.error = e
            raise
        finally:
            fl.done_at = time.monotonic()
            if fl.error is not None:
                # failed flights clear immediately: the next statement
                # must retry the staging, not inherit the error
                with self._lock:
                    self._flights.pop(key, None)
            fl.event.set()
            leaksan.close(lk)

    def _sweep(self, now: float) -> None:
        # drop completed flights past their linger window (under _lock)
        dead = [k for k, fl in self._flights.items()
                if fl.done_at is not None
                and now - fl.done_at > self.linger_seconds]
        for k in dead:
            self._flights.pop(k, None)

    def snapshot(self) -> dict:
        with self._lock:
            return {"staged": self.staged, "attached": self.attached,
                    "inflight": sum(
                        1 for fl in self._flights.values()
                        if fl.done_at is None)}

    def clear(self) -> None:
        """Drop completed flights (DDL invalidation is not needed —
        keys are snapshot-scoped — but tests want a clean slate)."""
        with self._lock:
            for k in [k for k, fl in self._flights.items()
                      if fl.done_at is not None]:
                self._flights.pop(k, None)
