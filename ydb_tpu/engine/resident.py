"""HBM-resident column tier: decoded portion columns pinned on device.

The third level of the storage hierarchy (blob store -> host blocks ->
device-resident columns) and the engine-side answer to ROADMAP item 1:
the kernel tier runs Q1 at billions of rows/s because its blocks ALREADY
live in device memory, while the engine path re-ingests from host bytes
on every scan. Theseus's thesis (PAPERS.md) is that accelerator query
efficiency comes from *not moving data*, and TQP shows a device-resident
table representation is what makes whole-query tensor execution pay off
— this module is that representation for the ColumnShard.

Unlike ``DeviceBlockCache`` (whole block STREAMS keyed by portion set +
read columns + geometry + predicate fingerprint — any new column subset
or predicate rebuilds from host bytes), the resident store pins
per-(portion, column) decoded device arrays. Portions are immutable, so
one promoted portion serves EVERY scan shape: scans assemble
fixed-capacity ``TableBlock``s directly from resident arrays
(device-side slice + pad, zero host decode or transfer), and portions
not yet resident fall through to the staged host path mid-stream — a
partially resident table still wins on its resident fraction.

Promotion is asynchronous on the shared conveyor ("resident_promote"
queue): eager at portion write/compaction output (the columns are
already in memory) and heat-driven from scan access counters (a portion
read twice from the host path is worth pinning). Eviction is
budget-bounded (``YDB_TPU_RESIDENT_BYTES`` valve, same semantics as the
scan-cache valve) with zone-map-informed victim choice: portions the
zone maps keep pruning away deliver no resident value and go first,
then cold-by-access portions (LRU heat). Invalidation is by immutable
portion id: compaction/TTL rewrites mint NEW ids, old ids keep serving
readers at old snapshots until GC drops them from the portion map.

Gates: ``YDB_TPU_RESIDENT=0`` disables the tier everywhere (scans take
exactly the pre-tier path — the A/B bit-identity switch); ``=1`` forces
it on even on CPU backends (where the default budget is 0 because
"device" memory is host RSS). ``RESIDENT_FORCE`` is the in-process
override for tests/bench A/B without environment mutation.
"""

from __future__ import annotations

import contextlib
import os
import threading

import numpy as np

from ydb_tpu import chaos
from ydb_tpu.analysis import leaksan, memsan, sanitizer
from ydb_tpu.blocks.block import Column, TableBlock
from ydb_tpu.chaos import deadline as statement_deadline
from ydb_tpu.obs import timeline
from ydb_tpu.obs.probes import probe

_P_PROMOTE = probe("resident.promote")
_P_EVICT = probe("resident.evict")

#: test/bench override: True/False forces the gate, None = environment
RESIDENT_FORCE: "bool | None" = None

AUTO_BYTES = 4 << 30

#: host-path reads of one portion before heat promotion triggers
PROMOTE_HEAT = 2

#: concurrent promotion tasks per store: promotions ride the SHARED
#: conveyor next to scan-prefetch producers, so a flood of queued
#: promotions must never starve staging admission (submit_if_free turns
#: producers away whenever the heap is non-empty)
MAX_INFLIGHT = 4


def _gate() -> "bool | None":
    """Tri-state tier gate: False = off, True = forced on, None = auto
    (budget decides — on for accelerator backends, off on CPU)."""
    if RESIDENT_FORCE is not None:
        return RESIDENT_FORCE
    env = os.environ.get("YDB_TPU_RESIDENT")
    if env is None:
        return None
    return env not in ("0", "", "off")


def default_budget() -> int:
    """Auto budget mirrors the scan cache: on for accelerator backends,
    off on CPU (there "device" memory is host RSS and the out-of-core
    tests own that bound)."""
    import jax

    return (AUTO_BYTES
            if jax.default_backend() in ("tpu", "axon", "gpu") else 0)


class _Entry:
    """One resident column of one portion: decoded device arrays at
    portion length (un-padded; scans slice/pad to block capacity)."""

    __slots__ = ("data", "validity", "nbytes")

    def __init__(self, data, validity):
        self.data = data
        self.validity = validity
        self.nbytes = int(data.nbytes) + int(validity.nbytes)


class ResidentStore:
    """Per-shard device-resident portion store.

    Structured per-shard deliberately: ROADMAP item 3 (multi-device
    scan parallelism) slices tables shard-per-device, so a per-shard
    store maps 1:1 onto a per-device resident set later.

    Thread model: one lock guards ALL mutable state (entry map, portion
    info, heat counters, in-flight set, byte ledger, stat counters).
    Device work (jnp array construction) always happens OUTSIDE the
    lock; promotion single-flights per portion id via ``_inflight``.
    """

    def __init__(self, name: str, budget: "int | None" = None):
        self.name = name
        self._budget = budget
        # sanitizer-tracked under YDB_TPU_TSAN=1; per-instance names so
        # distinct stores never share lockset state
        self._lock = sanitizer.make_lock(f"resident.{name}.lock")
        # (portion_id, column) -> _Entry
        self._cols = sanitizer.share({}, f"resident.{name}.cols")
        # portion_id -> {rows, nbytes, cols, heat, tick, zskips}
        self._info: dict = {}
        # portion_id -> host-path access count (heat toward promotion)
        self._miss_heat: dict = {}
        self._inflight: set = set()
        self._pending: list = []  # conveyor TaskHandles (drain support)
        # mesh device slice (set_device_slice): when the cluster mesh is
        # on, each shard's store binds to ONE mesh device — promotions
        # place arrays there and the budget narrows to the device share,
        # so mesh scans read columns already resident on the device that
        # computes them (no cross-device pull at dispatch)
        self._slice_slot: "int | None" = None
        self._slice_device = None
        self._slice_budget: "int | None" = None
        self._nbytes = 0
        self._tick = 0
        # counters (the sys_resident_store / viewer surface)
        self.hits = 0
        self.misses = 0
        self.promotions = 0
        self.evictions = 0
        self.spills = 0
        self.invalidations = 0
        self.errors = 0

    # ---- gates ----

    def budget(self) -> int:
        """YDB_TPU_RESIDENT_BYTES overrides EVERYTHING (the operator's
        emergency valve for HBM pressure; malformed values disable
        rather than poison the read path). Otherwise the constructor
        budget; else AUTO when the gate is forced on (so CPU tests and
        bench get a real budget), else the backend default."""
        env = os.environ.get("YDB_TPU_RESIDENT_BYTES")
        if env is not None:
            try:
                return int(env)
            except ValueError:
                return 0
        if self._slice_budget is not None:
            return self._slice_budget
        return self._base_budget()

    def _base_budget(self) -> int:
        """Budget ignoring any mesh device slice: what this store may
        hold when it owns its device alone (assign_device_slices divides
        this across the shards sharing one mesh device)."""
        if self._budget is not None:
            return self._budget
        if _gate() is True:
            return AUTO_BYTES
        return default_budget()

    # ---- mesh device slices ----

    def set_device_slice(self, slot: int, device, budget: int) -> None:
        """Bind this store to one mesh device: promotions land on
        ``device`` and the budget narrows to the per-device share (the
        ledger evicts down immediately — a store that grew under the
        full budget must not keep over-occupying its device)."""
        with self._lock:
            self._slice_slot = slot
            self._slice_device = device
            self._slice_budget = int(budget)
            self._evict_to_budget_locked(self._slice_budget)

    def clear_device_slice(self) -> None:
        with self._lock:
            self._slice_slot = None
            self._slice_device = None
            self._slice_budget = None

    def enabled(self) -> bool:
        g = _gate()
        if g is False:
            return False
        return self.budget() > 0

    # ---- read path ----

    def lookup(self, portion_id: int, names) -> "dict | None":
        """All-or-nothing: every requested column resident -> the entry
        dict (and a heat/LRU touch); any gap -> None (the scan falls
        through to the host path and ``record_miss`` counts the heat)."""
        if not names:
            return None
        if chaos.hit("resident.lookup", portion=portion_id) is not None:
            # injected device-memory fault: served as a miss, so the
            # scan degrades mid-stream to the staged host path
            chaos.note_fallback("resident.lookup")
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            self._tick += 1
            out = {}
            for n in names:
                e = self._cols.get((portion_id, n))
                if e is None:
                    self.misses += 1
                    return None
                out[n] = e
            info = self._info.get(portion_id)
            if info is not None:
                info["heat"] += 1
                info["tick"] = self._tick
            self.hits += 1
            return out

    def record_miss(self, portion_id: int) -> bool:
        """Host-path access bookkeeping. True when the portion just
        crossed the heat threshold and is worth promoting now."""
        with self._lock:
            self._tick += 1
            if len(self._miss_heat) > 4096 and \
                    portion_id not in self._miss_heat:
                # bound the heat map for ad-hoc workloads that scan a
                # long tail of portions exactly once
                self._miss_heat.clear()
            n = self._miss_heat.get(portion_id, 0) + 1
            self._miss_heat[portion_id] = n
            return n == PROMOTE_HEAT and portion_id not in self._inflight

    def note_pruned(self, portion_id: int) -> None:
        """A scan's zone maps pruned this portion entirely: its resident
        bytes served nothing. Eviction sends such portions first."""
        with self._lock:
            info = self._info.get(portion_id)
            if info is not None:
                info["zskips"] += 1

    # ---- promotion ----

    def promote(self, portion_id: int, rows: int, cols: dict,
                valid: "dict | None") -> bool:
        """Synchronous promote: decode-free device put of host arrays.
        Device array construction runs OUTSIDE the lock; insertion,
        accounting and budget eviction inside it."""
        if not self.enabled():
            return False
        import jax.numpy as jnp

        budget = self.budget()
        dev = self._slice_device
        entries = {}
        total = 0
        valid = valid or {}
        with memsan.seam("resident"):
            for n, a in cols.items():
                v = valid.get(n)
                if v is None:
                    v = np.ones(len(a), dtype=np.bool_)
                if dev is not None:
                    import jax

                    e = _Entry(jax.device_put(np.asarray(a), dev),
                               jax.device_put(
                                   np.asarray(v, dtype=np.bool_), dev))
                else:
                    e = _Entry(jnp.asarray(a),
                               jnp.asarray(v, dtype=jnp.bool_))
                entries[n] = e
                total += e.nbytes
        if total > budget:
            # a single portion larger than the whole valve can never be
            # resident: spill — the host path keeps serving it
            with self._lock:
                self.spills += 1
            return False
        with self._lock:
            info = self._info.get(portion_id)
            if info is None:
                info = {"rows": rows, "nbytes": 0, "cols": set(),
                        "heat": self._miss_heat.pop(portion_id, 0),
                        "tick": self._tick, "zskips": 0}
                self._info[portion_id] = info
            added = 0
            for n, e in entries.items():
                if (portion_id, n) in self._cols:
                    continue  # concurrent promotion landed first
                self._cols[(portion_id, n)] = e
                info["cols"].add(n)
                info["nbytes"] += e.nbytes
                added += e.nbytes
            self._nbytes += added
            if added:
                self.promotions += 1
                if memsan.armed():
                    info.setdefault("tickets", []).append(
                        memsan.charge(added, "resident",
                                      owner=portion_id))
            evicted = self._evict_to_budget_locked(budget,
                                                   keep=portion_id)
        if _P_PROMOTE and added:
            _P_PROMOTE.fire(store=self.name, portion=portion_id,
                            nbytes=added, evicted=evicted)
        return added > 0

    def _evict_to_budget_locked(self, budget: int, keep=None) -> int:
        """Drop whole portions until the ledger fits the budget. Victim
        order: zone-pruned-away portions first (their zone maps keep
        proving scans don't need them — zero resident value), then
        coldest by (access heat, LRU tick). Caller holds the lock."""
        evicted = 0
        while self._nbytes > budget and self._info:
            candidates = [p for p in self._info if p != keep]
            if not candidates:
                break
            victim = min(
                candidates,
                key=lambda p: (-self._info[p]["zskips"],
                               self._info[p]["heat"],
                               self._info[p]["tick"]))
            self._drop_locked(victim)
            self.evictions += 1
            evicted += 1
        if evicted and _P_EVICT:
            _P_EVICT.fire(store=self.name, portions=evicted,
                          nbytes=self._nbytes)
        return evicted

    def _drop_locked(self, portion_id: int) -> None:
        info = self._info.pop(portion_id, None)
        if info is None:
            return
        for n in info["cols"]:
            e = self._cols.pop((portion_id, n), None)
            if e is not None:
                self._nbytes -= e.nbytes
        for t in info.get("tickets", ()):
            memsan.release(t, evicted=True)

    def promote_async(self, portion_id: int, rows: int, loader) -> bool:
        """Queue a promotion on the shared conveyor. ``loader()`` runs
        on a worker and returns (cols, valid) host dicts — either the
        in-memory arrays of a fresh portion write (eager path) or a
        blob-store read (heat path). Single-flight per portion id;
        bounded in-flight so queued promotions never crowd out scan
        prefetch admission."""
        if not self.enabled():
            return False
        with self._lock:
            if portion_id in self._inflight or \
                    len(self._inflight) >= MAX_INFLIGHT:
                return False
            self._inflight.add(portion_id)
            fh = leaksan.track("resident.flight",
                               f"{self.name}:{portion_id}")
            # compact finished handles while here (drain bookkeeping)
            self._pending = [h for h in self._pending
                             if not h.done.is_set()]

        def task():
            try:
                cols, valid = loader()
                self.promote(portion_id, rows, cols, valid)
            except Exception:
                # best-effort: a GC'd blob or a shrunk budget mid-task
                # is not a scan error — the host path still serves
                with self._lock:
                    self.errors += 1
            finally:
                with self._lock:
                    self._inflight.discard(portion_id)
                leaksan.close(fh)

        from ydb_tpu.runtime.conveyor import shared_conveyor

        try:
            # promotions are background work owned by the STORE, not the
            # statement that triggered them: submit outside the
            # statement's deadline so a cancelled query can never strand
            # the _inflight entry (its discard lives in task()'s finally)
            with statement_deadline.activate(None):
                h = shared_conveyor().submit("resident_promote", task,
                                             priority=20)
        except RuntimeError:  # conveyor shut down (tests teardown)
            with self._lock:
                self._inflight.discard(portion_id)
            leaksan.close(fh)
            return False
        with self._lock:
            self._pending.append(h)
        return True

    def drain(self, timeout: float = 30.0) -> None:
        """Wait for every queued promotion (tests/bench determinism).
        Bounded: a wedged conveyor stops the wait at ``timeout``, it
        never wedges the caller."""
        import time

        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                pending = [h for h in self._pending
                           if not h.done.is_set()]
                self._pending = pending
            left = deadline - time.monotonic()
            if not pending or left <= 0:
                return
            pending[0].done.wait(left)

    # ---- invalidation ----

    def invalidate(self, portion_ids) -> None:
        """Drop by immutable portion id (GC'd portions that no snapshot
        can ever name again — compaction/TTL tombstones keep serving
        old-snapshot readers until then)."""
        with self._lock:
            for pid in portion_ids:
                if pid in self._info:
                    self._drop_locked(pid)
                    self.invalidations += 1
                self._miss_heat.pop(pid, None)

    def prune(self, live) -> None:
        """Keep only portions in ``live`` (the shard's portion map)."""
        with self._lock:
            for pid in [p for p in self._info if p not in live]:
                self._drop_locked(pid)
                self.invalidations += 1
            for pid in [p for p in self._miss_heat if p not in live]:
                del self._miss_heat[pid]

    def clear(self) -> None:
        with self._lock:
            self._cols.clear()
            self._info.clear()
            self._miss_heat.clear()
            self._nbytes = 0

    # ---- observability ----

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "portions": len(self._info),
                "columns": len(self._cols),
                "bytes": self._nbytes,
                "budget": self.budget(),
                "hits": self.hits,
                "misses": self.misses,
                "promotions": self.promotions,
                "evictions": self.evictions,
                "spills": self.spills,
                "invalidations": self.invalidations,
                "errors": self.errors,
                "inflight": len(self._inflight),
                "device_slot": self._slice_slot,
            }


def assign_device_slices(stores, n_devices: int, devices=None,
                         per_device_budget: "int | None" = None) -> None:
    """Bind a table's per-shard ResidentStores onto mesh devices.

    Stores group round-robin (``stores[d::n_devices]``) — the SAME
    grouping mesh_exec.device_partitions uses for scan sources, so a
    shard's resident columns live exactly where its rows are scanned.
    Shards sharing one device split the device budget evenly; the base
    is ``per_device_budget`` when given, else each store's own un-sliced
    budget standing in for the device's HBM share."""
    for d in range(n_devices):
        group = stores[d::n_devices]
        if not group:
            continue
        dev = devices[d] if devices is not None else None
        for st in group:
            base = (per_device_budget if per_device_budget is not None
                    else st._base_budget())
            st.set_device_slice(d, dev, max(base // len(group), 0))


def clear_device_slices(stores) -> None:
    for st in stores:
        st.clear_device_slice()


# ---------------- scan-side block assembly ----------------


def portion_loader(shard, meta):
    """Blob-store loader for heat promotions: full current schema, with
    schema-evolution NULLs projected exactly as the host path would."""
    names = tuple(shard.schema.names)

    def load():
        from ydb_tpu.engine.portion import project_chunk, read_portion_blob

        c, v = read_portion_blob(shard.store, meta.blob_id)
        return project_chunk(shard.schema, shard.column_added, meta,
                             names, c, v)

    return load


def scan_items(source, clusters, names):
    """One shard's scan stream as ('dev', entries, rows) /
    ('host', cols, valid) items, preserving global row order.

    Resident portions serve decoded device arrays; everything else
    (K-way dedup merges, cold portions, disabled stores) falls through
    to the existing host payload path mid-stream. Host-path portions
    count heat; crossing the threshold queues an async promotion so the
    NEXT scan finds them resident."""
    shard = source.shard
    store = getattr(shard, "resident", None)
    on = store is not None and store.enabled()
    pk = shard.pk_column
    for cl in clusters:
        if source.dedup and pk is not None and len(cl) > 1:
            # a K-way newest-wins merge rewrites rows; its output is
            # not any single portion's columns — host path only
            for cols, valid in source._iter_merged(cl, names):
                yield ("host", cols, valid)
            continue
        for m in cl:
            if on:
                ent = store.lookup(m.portion_id, names)
                if ent is not None:
                    source.resident_hits += 1
                    source.resident_rows += m.num_rows
                    # bytes served straight from HBM — the movement the
                    # resident tier SAVED the staged pipeline
                    timeline.add_bytes("resident_bytes", sum(
                        e.nbytes for e in ent.values()))
                    yield ("dev", ent, m.num_rows)
                    continue
                if store.record_miss(m.portion_id):
                    store.promote_async(m.portion_id, m.num_rows,
                                        portion_loader(shard, m))
            for cols, valid in source._iter_plain([m], names):
                yield ("host", cols, valid)


def _device_blocks(run, names, sch, cap, timer):
    """Cut a RUN of consecutive resident portions into
    capacity-``cap`` TableBlocks by device-side slice + concat.

    Coalescing across portion boundaries matters as much as skipping
    the host stage: emitting one padded block per small portion would
    hand the executor mostly-padding blocks and multiply compute by
    the portion count. The aligned case (one portion exactly filling a
    block) reuses the resident arrays as-is — zero device work."""
    import jax.numpy as jnp

    stage = (timer.stage if timer is not None else None)
    starts = []
    total = 0
    for _, rows in run:
        starts.append(total)
        total += rows
    for off in range(0, total, cap):
        take = min(cap, total - off)
        # resident pieces overlapping [off, off+take), local coords
        parts = []
        for (entries, rows), s in zip(run, starts):
            lo = max(off, s) - s
            hi = min(off + take, s + rows) - s
            if lo < hi:
                parts.append((entries, lo, hi, rows))
        ctx = stage("stage") if stage is not None \
            else contextlib.nullcontext()
        with ctx:
            whole = (len(parts) == 1 and parts[0][1] == 0
                     and parts[0][2] == parts[0][3] == cap)
            cols = {}
            for n in names:
                if whole:
                    e = parts[0][0][n]
                    d, v = e.data, e.validity
                else:
                    ds, vs = [], []
                    for entries, lo, hi, _rows in parts:
                        e = entries[n]
                        ds.append(e.data[lo:hi])
                        vs.append(e.validity[lo:hi])
                    if take < cap:
                        # tail-only pad; padding validity stays False
                        ds.append(jnp.zeros(cap - take,
                                            dtype=ds[0].dtype))
                        vs.append(jnp.zeros(cap - take,
                                            dtype=jnp.bool_))
                    d = ds[0] if len(ds) == 1 else jnp.concatenate(ds)
                    v = vs[0] if len(vs) == 1 else jnp.concatenate(vs)
                cols[n] = Column(d, v)
            blk = TableBlock(cols, jnp.asarray(take, dtype=jnp.int32),
                             sch)
        yield blk


def mixed_blocks(items, names, sch, cap, timer=None):
    """('dev'/'host') item stream -> fixed-capacity TableBlocks.

    Host runs pack through ``reader.rechunk`` (the same low-copy
    re-cutting as the pure host path); a device item flushes the
    pending host run as a partial block first, so row ORDER is exactly
    the host path's. Block BOUNDARIES may differ from the pure host
    stream (partial flushes at tier transitions) — programs are
    boundary-agnostic (fixed capacity + masked padding), only row order
    matters. Always emits at least one (possibly empty) block:
    consumers size their compiled programs off the stream."""
    from ydb_tpu.engine.reader import rechunk

    def build(cols, valid):
        ctx = (timer.stage("stage") if timer is not None
               else contextlib.nullcontext())
        with ctx:
            blk = TableBlock.from_numpy(cols, sch, valid, capacity=cap)
        timeline.add_bytes("staged_bytes", sum(
            c.data.nbytes + c.validity.nbytes
            for c in blk.columns.values()))
        return blk

    it = iter(items)
    emitted = 0
    pending = None
    while True:
        item = pending if pending is not None else next(it, None)
        pending = None
        if item is None:
            break
        if item[0] == "dev":
            # absorb the whole consecutive resident run so blocks
            # coalesce across portion boundaries
            dev_run = [(item[1], item[2])]
            for nxt in it:
                if nxt[0] != "dev":
                    pending = nxt
                    break
                dev_run.append((nxt[1], nxt[2]))
            for blk in _device_blocks(dev_run, names, sch, cap, timer):
                emitted += 1
                yield blk
            continue

        def host_run(first=item):
            nonlocal pending
            yield first[1], first[2]
            for nxt in it:
                if nxt[0] != "host":
                    pending = nxt
                    return
                yield nxt[1], nxt[2]

        for cols, valid in rechunk(host_run(), names, cap):
            emitted += 1
            yield build(cols, valid)
    if emitted == 0:
        yield build(
            {m: np.empty(0, dtype=sch.field(m).type.physical)
             for m in names},
            {m: np.empty(0, dtype=bool) for m in names})


def stream_resident(source, clusters, names, sch, cap,
                    timer=None, prefetch=True):
    """Resident-aware block stream for one PortionStreamSource, with the
    same conveyor-prefetch shape as ``reader.stream_blocks``: blob IO,
    host staging AND device assembly all run on a worker ahead of the
    consumer's compute."""
    from ydb_tpu.engine.reader import pump_blocks

    gen = mixed_blocks(scan_items(source, clusters, names), names, sch,
                       cap, timer=timer)
    return pump_blocks(gen, prefetch=prefetch)
