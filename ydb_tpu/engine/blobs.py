"""Blob storage behind a narrow Put/Get/Delete interface.

The reference's BlobStorage is a distributed erasure-coded store reached
through per-group DSProxy actors (TEvPut/TEvGet, dsproxy_put.cpp:29;
SURVEY.md §2.3). The TPU-era equivalent (§2.3 header) is a persistent
object store behind the same narrow surface: tablets never see disks,
only blob ids. Backends:

  * ``MemBlobStore``  — in-process fake for deterministic tests (the
    pattern of the reference's fake storages, e.g. wrappers/fake_storage.h)
  * ``DirBlobStore``  — local filesystem directory (one file per blob),
    crash-safe via write-to-temp + atomic rename

A real deployment points this at an object store (GCS/S3); the interface
is deliberately async-free here — the host runtime wraps calls in worker
threads (conveyor analog) when overlap matters.
"""

from __future__ import annotations

import bisect
import os
import tempfile


class BlobStore:
    def put(self, blob_id: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, blob_id: str) -> bytes:
        raise NotImplementedError

    def get_range(self, blob_id: str, off: int, length: int) -> bytes:
        """Ranged read (the DSProxy TEvGet shift/size analog). Backends
        that can seek override this; the default slices a full get."""
        return self.get(blob_id)[off:off + length]

    def delete(self, blob_id: str) -> None:
        raise NotImplementedError

    def exists(self, blob_id: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        raise NotImplementedError


class MemBlobStore(BlobStore):
    """In-memory store with a sorted key index: ``list(prefix)`` is
    O(log n + matches), not a full scan — every hot path above this
    (DSProxy versions, WAL replay ranges, portion listings) leans on
    prefix listing."""

    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._keys: list[str] = []  # sorted key index

    def put(self, blob_id, data):
        if blob_id not in self._data:
            bisect.insort(self._keys, blob_id)
        self._data[blob_id] = bytes(data)

    def get(self, blob_id):
        return self._data[blob_id]

    def delete(self, blob_id):
        if blob_id in self._data:
            del self._data[blob_id]
            i = bisect.bisect_left(self._keys, blob_id)
            if i < len(self._keys) and self._keys[i] == blob_id:
                self._keys.pop(i)

    def exists(self, blob_id):
        return blob_id in self._data

    def list(self, prefix=""):
        if not prefix:
            return list(self._keys)
        lo = bisect.bisect_left(self._keys, prefix)
        hi = bisect.bisect_left(self._keys, prefix + "￿")
        return self._keys[lo:hi]


class DirBlobStore(BlobStore):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, blob_id: str) -> str:
        from urllib.parse import quote

        return os.path.join(self.root, quote(blob_id, safe=""))

    def put(self, blob_id, data):
        # temp + rename: a crash mid-write never leaves a torn blob
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp.")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(blob_id))
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def get(self, blob_id):
        with open(self._path(blob_id), "rb") as f:
            return f.read()

    def get_range(self, blob_id, off, length):
        with open(self._path(blob_id), "rb") as f:
            f.seek(off)
            return f.read(length)

    def delete(self, blob_id):
        try:
            os.unlink(self._path(blob_id))
        except FileNotFoundError:
            pass

    def exists(self, blob_id):
        return os.path.exists(self._path(blob_id))

    def list(self, prefix=""):
        from urllib.parse import quote, unquote

        enc_prefix = quote(prefix, safe="")
        out = []
        for name in os.listdir(self.root):
            if name.startswith(".tmp."):
                continue
            if name.startswith(enc_prefix):
                out.append(unquote(name))
        return sorted(out)
