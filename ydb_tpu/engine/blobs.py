"""Blob storage behind a narrow Put/Get/Delete interface.

The reference's BlobStorage is a distributed erasure-coded store reached
through per-group DSProxy actors (TEvPut/TEvGet, dsproxy_put.cpp:29;
SURVEY.md §2.3). The TPU-era equivalent (§2.3 header) is a persistent
object store behind the same narrow surface: tablets never see disks,
only blob ids. Backends:

  * ``MemBlobStore``  — in-process fake for deterministic tests (the
    pattern of the reference's fake storages, e.g. wrappers/fake_storage.h)
  * ``DirBlobStore``  — local filesystem directory (one file per blob),
    crash-safe via write-to-temp + atomic rename

A real deployment points this at an object store (GCS/S3); the interface
is deliberately async-free here — the host runtime wraps calls in worker
threads (conveyor analog) when overlap matters.
"""

from __future__ import annotations

import bisect
import os
import tempfile

from ydb_tpu import chaos


def _chaos_io(op: str, blob_id: str,
              data: bytes | None = None) -> bytes | None:
    """Chaos injection on the real IO surface (sites ``blob.put`` /
    ``blob.get`` / ``blob.get_range``): latency spikes sleep here,
    ``io_error`` raises :class:`chaos.InjectedIOError` (an OSError, so
    the retry plane treats it as the real thing), ``torn`` returns a
    short read whose decode failure exercises the re-fetch path.
    Disarmed cost: one bool check inside ``chaos.hit``."""
    f = chaos.hit(op, blob=blob_id)
    if f is None:
        return data
    f.sleep()
    if f.kind == "io_error":
        raise chaos.InjectedIOError(
            f"injected {op} failure on {blob_id!r}")
    if f.kind == "torn" and data is not None:
        return data[:len(data) // 2]
    return data


class BlobStore:
    def put(self, blob_id: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, blob_id: str) -> bytes:
        raise NotImplementedError

    def get_range(self, blob_id: str, off: int, length: int) -> bytes:
        """Ranged read (the DSProxy TEvGet shift/size analog). Backends
        that can seek override this; the default slices a full get."""
        return self.get(blob_id)[off:off + length]

    def delete(self, blob_id: str) -> None:
        raise NotImplementedError

    def exists(self, blob_id: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        raise NotImplementedError


    def size(self, blob_id: str) -> int:
        """Stored byte size; default reads the blob (backends with a
        cheap stat override this)."""
        return len(self.get(blob_id))

class MemBlobStore(BlobStore):
    """In-memory store with a sorted key index: ``list(prefix)`` is
    O(log n + matches), not a full scan — every hot path above this
    (DSProxy versions, WAL replay ranges, portion listings) leans on
    prefix listing. Thread-safe: conveyor background jobs (compaction
    blob writes, GC deletes) run concurrently with foreground commits."""

    def __init__(self):
        import threading

        self._data: dict[str, bytes] = {}
        self._keys: list[str] = []  # sorted key index
        self._lock = threading.Lock()

    def size(self, blob_id: str) -> int:
        with self._lock:
            return len(self._data[blob_id])

    def put(self, blob_id, data):
        _chaos_io("blob.put", blob_id)
        with self._lock:
            if blob_id not in self._data:
                bisect.insort(self._keys, blob_id)
            self._data[blob_id] = bytes(data)

    def get(self, blob_id):
        return _chaos_io("blob.get", blob_id, self._data[blob_id])

    def get_range(self, blob_id, off, length):
        return _chaos_io("blob.get_range", blob_id,
                         self._data[blob_id][off:off + length])

    def delete(self, blob_id):
        with self._lock:
            if blob_id in self._data:
                del self._data[blob_id]
                i = bisect.bisect_left(self._keys, blob_id)
                if i < len(self._keys) and self._keys[i] == blob_id:
                    self._keys.pop(i)

    def exists(self, blob_id):
        return blob_id in self._data

    def list(self, prefix=""):
        with self._lock:
            if not prefix:
                return list(self._keys)
            lo = bisect.bisect_left(self._keys, prefix)
            hi = bisect.bisect_left(self._keys, prefix + "￿")
            return self._keys[lo:hi]


class DirBlobStore(BlobStore):
    def size(self, blob_id: str) -> int:
        return os.path.getsize(self._path(blob_id))

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, blob_id: str) -> str:
        from urllib.parse import quote

        return os.path.join(self.root, quote(blob_id, safe=""))

    def put(self, blob_id, data):
        _chaos_io("blob.put", blob_id)
        # temp + rename: a crash mid-write never leaves a torn blob
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp.")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(blob_id))
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def get(self, blob_id):
        with open(self._path(blob_id), "rb") as f:
            return _chaos_io("blob.get", blob_id, f.read())

    def get_range(self, blob_id, off, length):
        with open(self._path(blob_id), "rb") as f:
            f.seek(off)
            return _chaos_io("blob.get_range", blob_id, f.read(length))

    def delete(self, blob_id):
        try:
            os.unlink(self._path(blob_id))
        except FileNotFoundError:
            pass

    def exists(self, blob_id):
        return os.path.exists(self._path(blob_id))

    def list(self, prefix=""):
        from urllib.parse import quote, unquote

        enc_prefix = quote(prefix, safe="")
        out = []
        for name in os.listdir(self.root):
            if name.startswith(".tmp."):
                continue
            if name.startswith(enc_prefix):
                out.append(unquote(name))
        return sorted(out)


class CachedBlobStore(BlobStore):
    """Shared page cache over any backend (SURVEY §2.4 row 'shared page
    cache'; reference ydb/core/tablet_flat shared_cache.cpp): a node-wide
    byte-budget LRU over blob reads, shared by every shard on the node so
    hot portions/chunks are fetched once. Writes/deletes invalidate
    (write-through); ranged reads cache per (blob, off, len) page — the
    chunk-granular scan reader hits exactly these.

    Thread-safe: conveyor background jobs and foreground scans share it.
    """

    def __init__(self, base: BlobStore, capacity_bytes: int = 256 << 20):
        import threading
        from collections import OrderedDict

        self.base = base
        self.capacity_bytes = capacity_bytes
        # the operator-configured budget: the grow ceiling for
        # pressure recovery; explicit resize() re-bases it
        self._configured_capacity = capacity_bytes
        self._lru: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._by_blob: dict[str, set] = {}  # blob_id -> cached keys
        self._bytes = 0
        self._lock = threading.Lock()
        # GLOBAL invalidation generation, bumped by every put/delete: a
        # fill whose read STARTED before any invalidation is rejected,
        # closing the read-miss / write / stale-fill TOCTOU race. One
        # counter (not per-blob) keeps memory O(1); the cost is a
        # conservatively-skipped fill when an unrelated blob was
        # rewritten during the read — a missed optimization, never a
        # stale result.
        self._gen = 0
        self.hits = 0
        self.misses = 0

    # -- cache core --

    def _cache_get(self, key):
        with self._lock:
            data = self._lru.get(key)
            if data is not None:
                self._lru.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return data, self._gen

    def _cache_put(self, key, data: bytes, gen: int):
        if len(data) > self.capacity_bytes:
            return  # larger than the whole budget: never cache
        with self._lock:
            if self._gen != gen:
                return  # an invalidation raced the fill: maybe stale
            old = self._lru.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._lru[key] = data
            self._by_blob.setdefault(key[0], set()).add(key)
            self._bytes += len(data)
            self._evict_to_fit()

    def _evict_to_fit(self) -> None:
        """LRU eviction to the budget (caller holds the lock)."""
        while self._bytes > self.capacity_bytes and self._lru:
            k, evicted = self._lru.popitem(last=False)
            self._bytes -= len(evicted)
            keys = self._by_blob.get(k[0])
            if keys is not None:
                keys.discard(k)
                if not keys:
                    del self._by_blob[k[0]]

    def resize(self, capacity_bytes: int,
               rebase: bool = True) -> None:
        """Shrink/grow the byte budget, evicting LRU pages to fit.
        ``rebase`` (an explicit operator resize) moves the configured
        grow ceiling too; pressure reactions pass rebase=False."""
        with self._lock:
            self.capacity_bytes = max(0, capacity_bytes)
            if rebase:
                self._configured_capacity = self.capacity_bytes
            self._evict_to_fit()

    def react_to_pressure(self, used_fraction: float,
                          high: float = 0.85,
                          low: float = 0.6) -> str:
        """Memory-pressure integration (the shared_sausagecache +
        memory-controller contract, shared_sausagecache.cpp:194):
        above the ``high`` watermark the budget HALVES (floor 4 KiB);
        below ``low`` it doubles back toward the configured maximum.
        Returns "shrink" | "grow" | "steady" for observability."""
        if used_fraction > high:
            self.resize(max(self.capacity_bytes // 2, 4096),
                        rebase=False)
            return "shrink"
        if used_fraction < low and \
                self.capacity_bytes < self._configured_capacity:
            self.resize(min(self.capacity_bytes * 2,
                            self._configured_capacity),
                        rebase=False)
            return "grow"
        return "steady"

    def _invalidate(self, blob_id: str):
        with self._lock:
            self._gen += 1
            for key in self._by_blob.pop(blob_id, ()):
                data = self._lru.pop(key, None)
                if data is not None:
                    self._bytes -= len(data)

    # -- BlobStore surface --

    def put(self, blob_id, data):
        self.base.put(blob_id, data)
        self._invalidate(blob_id)

    def get(self, blob_id):
        key = (blob_id, None, None)
        data, gen = self._cache_get(key)
        if data is None:
            data = self.base.get(blob_id)
            self._cache_put(key, data, gen)
        return data

    def get_range(self, blob_id, off, length):
        key = (blob_id, off, length)
        data, gen = self._cache_get(key)
        if data is None:
            data = self.base.get_range(blob_id, off, length)
            self._cache_put(key, data, gen)
        return data

    def delete(self, blob_id):
        self.base.delete(blob_id)
        self._invalidate(blob_id)

    def exists(self, blob_id):
        return self.base.exists(blob_id)

    def list(self, prefix=""):
        return self.base.list(prefix)

    def stats(self) -> dict:
        with self._lock:
            return {"bytes": self._bytes, "entries": len(self._lru),
                    "hits": self.hits, "misses": self.misses}


class TieredBlobStore(BlobStore):
    """Hot/cold tiering behind the flat BlobStore surface (SURVEY §2.7
    blob-abstraction-and-tiering row; reference ydb/core/tx/tiering +
    S3 external storage): writes land in the hot tier; ``evict``
    migrates blobs matching a predicate to the cold tier (an object
    store in a real deployment — any BlobStore here); reads fall
    through hot -> cold transparently, so portion metadata never
    changes when data changes temperature. ``promote`` moves a hot-read
    candidate back.
    """

    def __init__(self, hot: BlobStore, cold: BlobStore):
        self.hot = hot
        self.cold = cold

    def put(self, blob_id, data):
        self.hot.put(blob_id, data)
        # a rewrite supersedes any cold copy (stale tier shadowing)
        if self.cold.exists(blob_id):
            self.cold.delete(blob_id)

    def get(self, blob_id):
        if self.hot.exists(blob_id):
            return self.hot.get(blob_id)
        return self.cold.get(blob_id)

    def get_range(self, blob_id, off, length):
        if self.hot.exists(blob_id):
            return self.hot.get_range(blob_id, off, length)
        return self.cold.get_range(blob_id, off, length)

    def delete(self, blob_id):
        self.hot.delete(blob_id)
        self.cold.delete(blob_id)

    def exists(self, blob_id):
        return self.hot.exists(blob_id) or self.cold.exists(blob_id)

    def list(self, prefix=""):
        merged = set(self.hot.list(prefix)) | set(self.cold.list(prefix))
        return sorted(merged)

    # -- tier management --

    def evict(self, predicate) -> int:
        """Move hot blobs with predicate(blob_id)=True to the cold tier
        (the TTL-driven tier eviction shape, tx/tiering). Copy-then-
        delete: a crash in between leaves a harmless duplicate (reads
        prefer hot; the next evict pass re-deletes)."""
        moved = 0
        for bid in self.hot.list(""):
            if not predicate(bid):
                continue
            self.cold.put(bid, self.hot.get(bid))
            self.hot.delete(bid)
            moved += 1
        return moved

    def promote(self, blob_id) -> bool:
        """Bring a cold blob back to the hot tier (read-heat feedback)."""
        if self.hot.exists(blob_id) or not self.cold.exists(blob_id):
            return False
        self.hot.put(blob_id, self.cold.get(blob_id))
        self.cold.delete(blob_id)
        return True

    def tier_of(self, blob_id) -> str | None:
        if self.hot.exists(blob_id):
            return "hot"
        if self.cold.exists(blob_id):
            return "cold"
        return None
