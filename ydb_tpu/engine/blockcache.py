"""Device-resident decoded-block cache.

The TPU lift of the reference's shared page cache
(ydb/core/tablet_flat/shared_sausagecache.cpp:194): warm scans reuse
decoded column blocks pinned in accelerator HBM, skipping blob IO, the
host-side decode/PK-merge, and the host->device transfer. Entries key on
IMMUTABLE inputs (portion ids + read columns + block geometry), so a
commit/compaction/TTL rewrite simply produces a different key: old
snapshots keep hitting their own entries, and entries whose portions are
gone free eagerly via ``prune``.

Used by ColumnShard.scan (single-shard scans) and by the plan executor's
TableScan over MultiShardStreamSource (the SQL path, one cache per
Cluster).
"""

from __future__ import annotations

import collections
import os

from ydb_tpu.analysis import leaksan, sanitizer

#: single-flight wait bound: a filler stuck past this (wedged blob
#: store, debugger) stops blocking waiters — they fill uncached instead
FLIGHT_WAIT_SECONDS = 30.0


def default_budget() -> int:
    """Auto budget: on for accelerator backends, off on CPU (there the
    "device" is host RSS and the out-of-core tests own that bound)."""
    import jax

    return (DeviceBlockCache.AUTO_BYTES
            if jax.default_backend() in ("tpu", "axon", "gpu") else 0)


class DeviceBlockCache:
    AUTO_BYTES = 4 << 30
    MAX_ENTRIES = 32

    def __init__(self, budget: "int | None" = None):
        # budget None = resolve default_budget() per use (it can change
        # with the environment in tests)
        self._budget = budget
        # sanitizer-tracked under YDB_TPU_TSAN=1 (a per-instance name:
        # distinct caches must not share lockset state)
        self._entries = sanitizer.share(
            collections.OrderedDict(), f"blockcache.{id(self):x}")
        self._nbytes = 0
        self._lock = sanitizer.make_lock(f"blockcache.{id(self):x}.lock")
        # key -> threading.Event: per-key in-flight fills (single-flight
        # dedup — concurrent scans missing the same key must not both
        # decode and both tee)
        self._flights = sanitizer.share(
            {}, f"blockcache.{id(self):x}.flights")
        self.hits = 0
        self.misses = 0
        self.flight_waits = 0

    def budget(self) -> int:
        """YDB_TPU_SCAN_CACHE_BYTES overrides EVERYTHING (including an
        explicitly configured budget — the operator's emergency valve
        for HBM pressure); malformed values disable rather than poison
        the read path. Otherwise the constructor budget, else auto."""
        env = os.environ.get("YDB_TPU_SCAN_CACHE_BYTES")
        if env is not None:
            try:
                return int(env)
            except ValueError:
                return 0
        return self._budget if self._budget is not None \
            else default_budget()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._nbytes = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __iter__(self):
        return iter(list(self._entries))

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def get(self, key):
        """Cached block list or None; hit refreshes LRU order."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return ent[0]

    def prune(self, alive) -> None:
        """Drop entries whose key fails ``alive(key)`` — e.g. entries
        referencing GC'd portions that no snapshot can name again."""
        with self._lock:
            for k in [k for k in self._entries if not alive(k)]:
                self._nbytes -= self._entries.pop(k)[1]

    def tee(self, blocks, key):
        """Yield ``blocks`` unchanged while collecting them for the
        cache. Collection aborts (releasing already-pinned blocks) the
        moment the running size exceeds the budget, so an over-budget
        scan never pins more device memory than an uncached one."""
        budget = self.budget()
        collected: "list | None" = []
        nbytes = 0
        # pass-through collection loop: bounded by BLOCK count (the
        # morsel stream), device refs only — no per-row work, no copy
        # ydb-lint: disable=H006
        for b in blocks:
            if collected is not None:
                nbytes += sum(
                    int(c.data.nbytes) + int(c.validity.nbytes)
                    for c in b.columns.values())
                if nbytes > budget:
                    collected = None
                else:
                    collected.append(b)
            yield b
        if collected is not None:
            with self._lock:
                old = self._entries.pop(key, None)
                if old is not None:
                    self._nbytes -= old[1]
                self._entries[key] = (collected, nbytes)
                self._nbytes += nbytes
                # byte budget + entry cap: commit-heavy workloads mint a
                # fresh key per commit; stale-but-live entries must not
                # pile up in device memory
                while ((self._nbytes > budget
                        or len(self._entries) > self.MAX_ENTRIES)
                       and len(self._entries) > 1):
                    _, (_, nb) = self._entries.popitem(last=False)
                    self._nbytes -= nb

    def stream(self, key, make_blocks):
        """Cached stream for ``key``: the cached blocks when present,
        else ``make_blocks()`` teed into the cache with per-key
        single-flight dedup — the first scan to miss fills; concurrent
        scans on the same key wait for its entry instead of each
        decoding and teeing their own copy. When the budget is off, the
        raw stream passes through untouched."""
        if self.budget() <= 0 or key is None:
            return make_blocks()
        return self._stream_gen(key, make_blocks)

    def _stream_gen(self, key, make_blocks):
        """Flight registration happens INSIDE the generator body (on
        first next()): a generator handed back but never iterated runs
        no ``finally``, so registering before returning it could strand
        the flight and wedge every waiter."""
        import threading

        while True:
            with self._lock:
                ent = self._entries.get(key)
                if ent is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    blocks = ent[0]
                    ev = None
                elif key not in self._flights:
                    # we are the filler
                    self._flights[key] = threading.Event()
                    fh = leaksan.track("blockcache.flight",
                                       str(key)[:80])
                    blocks = None
                    ev = None
                else:
                    ev = self._flights[key]
                    self.flight_waits += 1
            if ev is not None:
                if not ev.wait(FLIGHT_WAIT_SECONDS):
                    # wedged filler: serve uncached rather than stall
                    with self._lock:
                        self.misses += 1
                    yield from make_blocks()
                    return
                continue  # filler done — re-check the entry
            if blocks is not None:
                yield from blocks
                return
            try:
                with self._lock:
                    self.misses += 1
                yield from self.tee(make_blocks(), key)
            finally:
                # wake waiters whether the fill landed, overflowed the
                # budget, or the consumer abandoned the stream early —
                # they re-check and fill (or wait) themselves
                with self._lock:
                    ev = self._flights.pop(key, None)
                leaksan.close(fh)
                if ev is not None:
                    ev.set()
            return
