"""Portions: immutable columnar data units with PK stats.

Reference: a ColumnShard's data is a set of *portions* — per-column blobs
plus metadata (row count, PK min/max, snapshot) grouped into granules
(TPortionInfo, engines/portion_info.h; SURVEY.md §2.7). Scans plan by
intersecting portion PK ranges with the query range at a snapshot.

Here a portion serializes into one blob of PK-consecutive row-group
*chunks* (each chunk an npz of the column slices + validity masks), with
a JSON header indexing {offset, rows, pk_min, pk_max} per chunk so
readers can fetch one chunk at a time via ranged gets — the streaming
K-way merge (ydb_tpu.engine.reader) keeps at most a few chunks per
portion resident, never a whole portion. Metadata lives in the shard's
WAL/snapshot (not in the blob), so planning never touches blob storage.
Column data is the *physical* encoding (dict ids, scaled decimals) —
dictionaries are table-level state owned by the shard.
"""

from __future__ import annotations

import dataclasses
import io
import json
import struct
import time
import zipfile

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.chaos.retry import RetryPolicy
from ydb_tpu.engine.blobs import BlobStore

#: One policy for every portion-blob read. Each retry re-fetches AND
#: re-decodes, so torn/short reads (decode blows up, not the get) heal
#: the same way IO errors do. Backoff respects the statement deadline.
READ_RETRY = RetryPolicy(max_attempts=4, base_delay=0.002)
#: What a transient blob read looks like: IO failure, or the decode
#: errors a truncated payload produces (npz blobs are zip containers).
_TRANSIENT_READ = (OSError, EOFError, ValueError, zipfile.BadZipFile,
                   struct.error)


@dataclasses.dataclass
class PortionMeta:
    portion_id: int
    blob_id: str
    num_rows: int
    # MVCC window: visible when commit_snap <= snap < removed_snap
    commit_snap: int
    removed_snap: int | None = None
    # PK range stats for scan pruning (min/max of the first PK column)
    pk_min: int | None = None
    pk_max: int | None = None
    # min/max of the TTL column, for eviction planning
    ttl_min: int | None = None
    ttl_max: int | None = None
    # per-column zone map: {column: [vmin, vmax, null_count]} over the
    # WHOLE portion (union of its chunk zones; ydb_tpu.stats.zonemap) —
    # scan planning prunes portions against filter predicates without
    # touching blob storage. None on pre-stats portions (v0 metadata).
    zones: dict | None = None
    # table schema version this portion was written under: a column only
    # reads from portions at least as new as the version that (re)added
    # it — DROP then ADD of the same name must not resurrect old bytes
    schema_version: int = 1

    def visible_at(self, snap: int) -> bool:
        if self.commit_snap > snap:
            return False
        return self.removed_snap is None or snap < self.removed_snap

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "PortionMeta":
        return PortionMeta(**d)


PORTION_MAGIC = b"YDBP0001"
DEFAULT_CHUNK_ROWS = 1 << 16
#: blob header format version: v1 adds per-chunk column zone maps
#: ("zones" per chunk entry). v0 headers (no "version" key) read fine —
#: they simply carry no zones, so scans fall back to unpruned reads.
HEADER_VERSION = 1


def _pack_chunk(columns, validity, lo, hi) -> bytes:
    buf = io.BytesIO()
    payload = {n: a[lo:hi] for n, a in columns.items()}
    if validity:
        for name, v in validity.items():
            payload[f"__valid__{name}"] = v[lo:hi]
    np.savez(buf, **payload)
    return buf.getvalue()


def _unpack_chunk(data: bytes) -> tuple[dict, dict]:
    with np.load(io.BytesIO(data)) as z:
        cols, valid = {}, {}
        for name in z.files:
            if name.startswith("__valid__"):
                valid[name[len("__valid__"):]] = z[name]
            else:
                cols[name] = z[name]
    return cols, valid


def _unpack_chunk_view(data: bytes) -> tuple[dict, dict]:
    """Zero-copy chunk decode: read-only array VIEWS into ``data``.

    ``np.savez`` stores members uncompressed (ZIP_STORED), so every
    npy's payload is a contiguous slice of the blob bytes already in
    hand — ``np.load`` still pays a ZipExtFile + CRC + copy per member,
    which measures ~10x the cost of the underlying memcpy and holds the
    GIL throughout (it is what serializes the morsel pipeline's decode
    stage). Here we walk the zip directory, parse each npy header, and
    ``np.frombuffer`` straight into the fetched buffer: no copy, no
    CRC pass, a few microseconds per member. Torn payloads still fail
    (zip directory/npy header parses raise ``_TRANSIENT_READ`` kinds),
    so the fetch+decode retry contract is unchanged; anything this fast
    path cannot prove safe (compressed member, object dtype, truncated
    payload) falls back to ``np.load``. Callers get READ-ONLY arrays —
    every downstream consumer (rechunk, block build, merge cursors)
    copies rather than mutates."""
    buf = io.BytesIO(data)
    cols, valid = {}, {}
    with zipfile.ZipFile(buf) as z:
        for zi in z.infolist():
            if zi.compress_type != zipfile.ZIP_STORED:
                return _unpack_chunk(data)
            # local header: 26..30 hold filename/extra lengths; the
            # member payload follows both
            ho = zi.header_offset
            fn_len, ex_len = struct.unpack_from("<HH", data, ho + 26)
            start = ho + 30 + fn_len + ex_len
            end = start + zi.file_size
            if end > len(data):
                raise ValueError("torn npz member")
            m = io.BytesIO(data[start:min(end, start + 256)])
            version = np.lib.format.read_magic(m)
            shape, fortran, dtype = \
                np.lib.format._read_array_header(m, version)
            if dtype.hasobject or fortran:
                return _unpack_chunk(data)
            n = int(np.prod(shape, dtype=np.int64))
            if m.tell() + n * dtype.itemsize > zi.file_size:
                raise ValueError("torn npz member payload")
            a = np.frombuffer(data, dtype, n,
                              offset=start + m.tell()).reshape(shape)
            name = zi.filename
            if name.endswith(".npy"):
                name = name[:-4]
            if name.startswith("__valid__"):
                valid[name[len("__valid__"):]] = a
            else:
                cols[name] = a
    return cols, valid


def write_portion_blob(
    store: BlobStore,
    blob_id: str,
    columns: dict[str, np.ndarray],
    validity: dict[str, np.ndarray] | None = None,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
    pk_column: str | None = None,
    stats: bool = True,
) -> None:
    """Serialize columns as a chunk-indexed blob.

    Layout: MAGIC | u64 header_len | header JSON | chunk payloads.
    Chunks are consecutive row slices; when ``pk_column`` is given (and
    rows are PK-sorted, which the shard guarantees) each chunk's header
    entry carries PK bounds so ranged scans can skip whole chunks
    (reader._chunk_in_range) without fetching them.

    With ``stats`` (v1 headers, the default) each chunk entry also
    carries per-column zone maps — ``{"zones": {col: [vmin, vmax,
    null_count]}}``, dtype-aware (ints, floats, scaled decimals,
    dict-encoded string ids) — computed vectorized at write time so
    scans can skip chunks that no conjunctive filter predicate can
    match (ydb_tpu.stats.zonemap). ``stats=False`` writes v0 headers
    (the pre-stats format, still fully readable).
    """
    from ydb_tpu.stats.zonemap import column_zones

    n = len(next(iter(columns.values()))) if columns else 0
    chunks = []
    payloads = []
    off = 0
    for lo in range(0, max(n, 1), chunk_rows):
        hi = min(lo + chunk_rows, n)
        if hi <= lo and n > 0:
            break
        data = _pack_chunk(columns, validity, lo, hi)
        entry = {"off": off, "len": len(data), "rows": hi - lo}
        if pk_column is not None and pk_column in columns and hi > lo:
            pk = columns[pk_column]
            if np.issubdtype(pk.dtype, np.integer):
                entry["pk_min"] = int(pk[lo])
                entry["pk_max"] = int(pk[hi - 1])
        if stats and hi > lo:
            entry["zones"] = column_zones(columns, validity, lo, hi)
        chunks.append(entry)
        payloads.append(data)
        off += len(data)
        if n == 0:
            break
    head: dict = {"chunks": chunks}
    if stats:
        head["version"] = HEADER_VERSION
    header = json.dumps(head).encode()
    blob = b"".join([PORTION_MAGIC, struct.pack("<Q", len(header)),
                     header] + payloads)
    store.put(blob_id, blob)


class PortionChunkReader:
    """Chunk-granular reader over one portion blob (ranged gets)."""

    def __init__(self, store: BlobStore, blob_id: str):
        self.store = store
        self.blob_id = blob_id
        def _head():
            h = store.get_range(blob_id, 0, 16)
            if h[:8] == PORTION_MAGIC and len(h) < 16:
                raise EOFError(f"short header read on {blob_id!r}")
            return h

        head = READ_RETRY.call(_head, site="blob.get_range",
                               retry_on=_TRANSIENT_READ)
        if head[:8] != PORTION_MAGIC:
            # legacy single-npz blob: treat as one chunk
            self._legacy = READ_RETRY.call(
                lambda: store.get(blob_id),
                site="blob.get", retry_on=_TRANSIENT_READ)
            self.chunks = [None]
            self._base = 0
            self.version = 0
            return
        self._legacy = None
        (hlen,) = struct.unpack("<Q", head[8:16])
        header = READ_RETRY.call(
            lambda: json.loads(
                store.get_range(blob_id, 16, hlen).decode()),
            site="blob.get_range", retry_on=_TRANSIENT_READ)
        self.chunks = header["chunks"]
        # v0 headers predate zone maps: absent "version" reads as 0 and
        # chunk entries simply have no "zones" (scans stay unpruned)
        self.version = header.get("version", 0)
        self._base = 16 + hlen

    @property
    def n_chunks(self) -> int:
        return len(self.chunks)

    def chunk_meta(self, i: int) -> dict:
        c = self.chunks[i]
        return {"rows": None, "pk_min": None, "pk_max": None} \
            if c is None else c

    def read_chunk(self, i: int, *,
                   zero_copy: bool = False) -> tuple[dict, dict]:
        """One chunk's (columns, validity). ``zero_copy`` decodes to
        read-only views into the fetched buffer (the morsel pipeline's
        decode discipline — see ``_unpack_chunk_view``); the default
        copies via ``np.load`` (the legacy serialized-path decode,
        kept bit-for-bit as the ``YDB_TPU_STREAM_PIPELINE=0``
        reference)."""
        from ydb_tpu.obs import timeline

        unpack = _unpack_chunk_view if zero_copy else _unpack_chunk

        # fetch + decode retried as ONE unit: a torn/short read fails in
        # the decode, and only re-fetching can heal it
        def _fetch_decode():
            if self._legacy is not None:
                data = self._legacy
            else:
                c = self.chunks[i]
                with timeline.event("blob.read", "blob.read",
                                    timeline.current_trace_id(),
                                    bytes=c["len"]):
                    data = self.store.get_range(
                        self.blob_id, self._base + c["off"], c["len"])
            timeline.add_bytes("blob_read_bytes", len(data))
            t0 = time.perf_counter()
            cols, valid = unpack(data)
            decoded = sum(a.nbytes for a in cols.values()) + sum(
                v.nbytes for v in valid.values())
            timeline.add_bytes("decoded_bytes", decoded)
            timeline.record("decode", "decode", t0, time.perf_counter(),
                            timeline.current_trace_id(), bytes=decoded)
            return cols, valid

        return READ_RETRY.call(_fetch_decode, site="blob.get_range",
                               retry_on=_TRANSIENT_READ)


def read_portion_blob(
    store: BlobStore, blob_id: str
) -> tuple[dict[str, np.ndarray], dict[str, np.ndarray]]:
    """Whole-portion read: all chunks concatenated."""
    rd = PortionChunkReader(store, blob_id)
    parts = [rd.read_chunk(i) for i in range(rd.n_chunks)]
    if len(parts) == 1:
        return parts[0]
    cols = {n: np.concatenate([p[0][n] for p in parts])
            for n in parts[0][0]}
    valid_names = set()
    for p in parts:
        valid_names.update(p[1])
    valid = {}
    for n in valid_names:
        valid[n] = np.concatenate([
            p[1].get(n, np.ones(len(next(iter(p[0].values()))), dtype=bool))
            for p in parts
        ])
    return cols, valid


def column_stats(
    arr: np.ndarray, validity: np.ndarray | None = None,
) -> tuple:
    """Typed (min, max) of a column, dtype-aware.

    Ints (incl. dict ids, scaled decimals, dates) return ints; floats
    return floats (no silent ``int()`` truncation); NULL rows are
    excluded when ``validity`` is given. ``(None, None)`` for empty or
    unstatable input. Zone maps reuse this for every scan column —
    ydb_tpu.stats.zonemap.zone_of carries the shared implementation.
    """
    from ydb_tpu.stats.zonemap import zone_of

    vmin, vmax, _nulls = zone_of(arr, validity)
    return vmin, vmax


def project_chunk(
    schema,
    column_added: dict[str, int],
    meta: PortionMeta,
    names,
    cols_raw: dict[str, np.ndarray],
    valid_raw: dict[str, np.ndarray],
) -> tuple[dict, dict]:
    """Project raw chunk columns to ``names`` with schema-evolution nulls.

    The single home of the rule: a column only reads from portions at
    least as new as the schema version that (re)added it — DROP then ADD
    of the same name must not resurrect old bytes; older portions read
    the column as NULL.
    """
    n_rows = len(next(iter(cols_raw.values()))) if cols_raw else 0
    cols, valid = {}, {}
    for n in names:
        if n in cols_raw and meta.schema_version >= column_added.get(n, 1):
            cols[n] = cols_raw[n]
            valid[n] = valid_raw.get(
                n, np.ones(len(cols_raw[n]), dtype=bool))
        else:
            cols[n] = np.zeros(n_rows, dtype=schema.field(n).type.physical)
            valid[n] = np.zeros(n_rows, dtype=bool)
    return cols, valid
