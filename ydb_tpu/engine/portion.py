"""Portions: immutable columnar data units with PK stats.

Reference: a ColumnShard's data is a set of *portions* — per-column blobs
plus metadata (row count, PK min/max, snapshot) grouped into granules
(TPortionInfo, engines/portion_info.h; SURVEY.md §2.7). Scans plan by
intersecting portion PK ranges with the query range at a snapshot.

Here a portion serializes all columns into one npz blob (validity masks
included for nullable columns); metadata lives in the shard's WAL/snapshot
(not in the blob), so planning never touches blob storage. Column data is
the *physical* encoding (dict ids, scaled decimals) — dictionaries are
table-level state owned by the shard.
"""

from __future__ import annotations

import dataclasses
import io

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import BlobStore


@dataclasses.dataclass
class PortionMeta:
    portion_id: int
    blob_id: str
    num_rows: int
    # MVCC window: visible when commit_snap <= snap < removed_snap
    commit_snap: int
    removed_snap: int | None = None
    # PK range stats for scan pruning (min/max of the first PK column)
    pk_min: int | None = None
    pk_max: int | None = None
    # min/max of the TTL column, for eviction planning
    ttl_min: int | None = None
    ttl_max: int | None = None
    # table schema version this portion was written under: a column only
    # reads from portions at least as new as the version that (re)added
    # it — DROP then ADD of the same name must not resurrect old bytes
    schema_version: int = 1

    def visible_at(self, snap: int) -> bool:
        if self.commit_snap > snap:
            return False
        return self.removed_snap is None or snap < self.removed_snap

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "PortionMeta":
        return PortionMeta(**d)


def write_portion_blob(
    store: BlobStore,
    blob_id: str,
    columns: dict[str, np.ndarray],
    validity: dict[str, np.ndarray] | None = None,
) -> None:
    buf = io.BytesIO()
    payload = dict(columns)
    if validity:
        for name, v in validity.items():
            payload[f"__valid__{name}"] = v
    np.savez(buf, **payload)
    store.put(blob_id, buf.getvalue())


def read_portion_blob(
    store: BlobStore, blob_id: str
) -> tuple[dict[str, np.ndarray], dict[str, np.ndarray]]:
    with np.load(io.BytesIO(store.get(blob_id))) as z:
        cols = {}
        valid = {}
        for name in z.files:
            if name.startswith("__valid__"):
                valid[name[len("__valid__"):]] = z[name]
            else:
                cols[name] = z[name]
    return cols, valid


def column_stats(arr: np.ndarray) -> tuple[int | None, int | None]:
    if arr.size == 0 or not np.issubdtype(arr.dtype, np.integer):
        return None, None
    return int(arr.min()), int(arr.max())
