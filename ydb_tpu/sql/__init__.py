from ydb_tpu.sql.parser import parse  # noqa: F401
from ydb_tpu.sql.planner import plan_select  # noqa: F401
