"""SQL AST → logical plan (binding, pushdown, join + aggregate planning).

The compact analog of the reference's KQP compile pipeline (SURVEY.md
§3.2): name binding and type derivation (kqp_type_ann), predicate
pushdown into table scans (the OLAP pushdown shape,
opt/physical/kqp_opt_phy_olap_filter.cpp), join planning over FK->PK
lookup joins vs N:M expansion (CBO-lite: keyed on catalog primary keys),
aggregate/HAVING/ORDER BY lowering into SSA programs, projection naming.

Output is a ydb_tpu.plan tree; the same tree drives the single-chip and
mesh executors.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.plan.nodes import ExpandJoin, LookupJoin, TableScan, Transform
from ydb_tpu.sql import ast
from ydb_tpu.ssa.ops import Agg, Op
from ydb_tpu.ssa.program import (
    AggSpec,
    AssignStep,
    Call,
    Col,
    Const,
    DictPredicate,
    FilterStep,
    GroupByStep,
    Program,
    ProjectStep,
    SortStep,
    infer_type,
)

_AGG_FUNCS = {
    "sum": Agg.SUM, "avg": Agg.AVG, "min": Agg.MIN, "max": Agg.MAX,
    "count": Agg.COUNT, "some": Agg.SOME,
}

_CMP = {"eq": Op.EQ, "ne": Op.NE, "lt": Op.LT, "le": Op.LE, "gt": Op.GT,
        "ge": Op.GE}
_ARITH = {"add": Op.ADD, "sub": Op.SUB, "mul": Op.MUL, "div": Op.DIV,
          "mod": Op.MOD}


@dataclasses.dataclass
class Catalog:
    schemas: dict[str, dtypes.Schema]
    primary_keys: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict)
    dicts: DictionarySet | None = None


class PlanError(Exception):
    pass


# ---------------- binding ----------------


@dataclasses.dataclass
class _Binding:
    """alias -> table; column -> owning alias (unique or qualified)."""

    tables: list[tuple[str, str]]  # (alias, table) in FROM order
    col_owner: dict[str, str]      # unqualified column -> alias
    ambiguous: set[str]
    catalog: Catalog

    def resolve(self, name: ast.Name) -> tuple[str, str]:
        """-> (alias, column)"""
        if len(name.parts) == 2:
            alias, col = name.parts
            for a, t in self.tables:
                if a == alias:
                    if col not in self.catalog.schemas[t]:
                        raise PlanError(f"no column {col} in {t}")
                    return a, col
            raise PlanError(f"unknown table alias {alias}")
        col = name.parts[0]
        if col in self.ambiguous:
            raise PlanError(f"ambiguous column {col}")
        if col not in self.col_owner:
            raise PlanError(f"unknown column {col}")
        return self.col_owner[col], col

    def column_type(self, col: str) -> dtypes.LogicalType:
        alias = self.col_owner[col]
        table = dict(self.tables)[alias]
        return self.catalog.schemas[table].field(col).type


def _flatten_from(f: ast.FromItem) -> tuple[list[ast.TableRef], list]:
    """-> ([tables in order], [(right_index, on_expr, kind)])"""
    if isinstance(f, ast.TableRef):
        return [f], []
    tables, joins = _flatten_from(f.left)
    tables.append(f.right)
    joins.append((len(tables) - 1, f.on, f.kind))
    return tables, joins


def _bind(sel: ast.Select, catalog: Catalog) -> tuple[_Binding, list, list]:
    if sel.from_ is None:
        raise PlanError("SELECT without FROM is not supported")
    refs, join_specs = _flatten_from(sel.from_)
    tables = []
    for r in refs:
        if r.name not in catalog.schemas:
            raise PlanError(f"unknown table {r.name}")
        tables.append((r.alias or r.name, r.name))
    seen: dict[str, str] = {}
    ambiguous: set[str] = set()
    for alias, t in tables:
        for f in catalog.schemas[t].fields:
            if f.name in seen and seen[f.name] != alias:
                ambiguous.add(f.name)
            else:
                seen[f.name] = alias
    return _Binding(tables, seen, ambiguous, catalog), refs, join_specs


# ---------------- expression lowering ----------------


def _conjuncts(e: ast.Expr | None) -> list[ast.Expr]:
    if e is None:
        return []
    if isinstance(e, ast.BinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _expr_columns(e: ast.Expr, binding: _Binding) -> set[str]:
    """Aliases of tables referenced by an expression."""
    out: set[str] = set()

    def walk(x):
        if isinstance(x, ast.Name):
            out.add(binding.resolve(x)[0])
        elif isinstance(x, ast.BinOp):
            walk(x.left); walk(x.right)
        elif isinstance(x, ast.UnOp):
            walk(x.operand)
        elif isinstance(x, ast.FuncCall):
            for a in x.args:
                walk(a)
        elif isinstance(x, ast.Between):
            walk(x.expr); walk(x.low); walk(x.high)
        elif isinstance(x, ast.InList):
            walk(x.expr)
            for a in x.items:
                walk(a)
        elif isinstance(x, (ast.Like, ast.IsNull)):
            walk(x.expr)
        elif isinstance(x, ast.Case):
            for c, v in x.whens:
                walk(c); walk(v)
            if x.else_ is not None:
                walk(x.else_)

    walk(e)
    return out


def _days(s: str) -> int:
    return int(np.datetime64(s, "D").astype(np.int32))


class _Lower:
    """AST expr -> SSA expr against a column-type environment."""

    def __init__(self, types: dict[str, dtypes.LogicalType],
                 dicts: DictionarySet | None):
        self.types = types
        self.dicts = dicts

    def type_of(self, e) -> dtypes.LogicalType | None:
        try:
            return infer_type(e, None, self.types)
        except Exception:
            return None

    def lower(self, e: ast.Expr):
        if isinstance(e, ast.Name):
            col = e.column
            if col not in self.types:
                raise PlanError(f"column {col} not in scope")
            return Col(col)
        if isinstance(e, ast.Literal):
            return self._literal(e)
        if isinstance(e, ast.UnOp):
            if e.op == "not":
                return Call(Op.NOT, self.lower(e.operand))
            if e.op == "neg":
                return Call(Op.NEG, self.lower(e.operand))
            raise PlanError(f"unary {e.op}")
        if isinstance(e, ast.BinOp):
            return self._binop(e)
        if isinstance(e, ast.Between):
            lo = ast.BinOp("ge", e.expr, e.low)
            hi = ast.BinOp("le", e.expr, e.high)
            both = Call(Op.AND, self._binop(lo), self._binop(hi))
            return Call(Op.NOT, both) if e.negated else both
        if isinstance(e, ast.InList):
            return self._in_list(e)
        if isinstance(e, ast.Like):
            col = self._string_col(e.expr, "LIKE")
            p = DictPredicate(col, "like", e.pattern)
            return Call(Op.NOT, p) if e.negated else p
        if isinstance(e, ast.IsNull):
            inner = self.lower(e.expr)
            return Call(Op.IS_NOT_NULL if e.negated else Op.IS_NULL, inner)
        if isinstance(e, ast.Case):
            if e.else_ is None:
                raise PlanError("CASE without ELSE is not supported yet")
            out = self.lower(e.else_)
            for cond, val in reversed(e.whens):
                out = Call(Op.IF, self.lower(cond), self.lower(val), out)
            return out
        if isinstance(e, ast.FuncCall):
            return self._func(e)
        raise PlanError(f"cannot lower {e}")

    def _literal(self, e: ast.Literal):
        if e.kind == "int":
            return Const(e.value, dtypes.INT64)
        if e.kind == "decimal":
            from ydb_tpu.ssa.program import decimal_lit

            scale = len(e.value.split(".")[1]) if "." in e.value else 0
            return decimal_lit(e.value, scale)
        if e.kind == "bool":
            return Const(e.value, dtypes.BOOL)
        if e.kind == "string":
            raise PlanError(
                f"string literal {e.value!r} outside a string comparison"
            )
        raise PlanError(f"literal {e.kind}")

    def _string_col(self, e: ast.Expr, what: str) -> str:
        if isinstance(e, ast.Name) and self.types.get(
                e.column, dtypes.INT64).is_string:
            return e.column
        raise PlanError(f"{what} needs a string column operand")

    def _binop(self, e: ast.BinOp):
        if e.op in ("and", "or"):
            return Call(Op.AND if e.op == "and" else Op.OR,
                        self.lower(e.left), self.lower(e.right))
        if e.op in _CMP:
            # string column vs string literal -> dictionary predicate
            lit_side = col_side = None
            if isinstance(e.right, ast.Literal) and e.right.kind == "string":
                col_side, lit_side, op = e.left, e.right, e.op
            elif isinstance(e.left, ast.Literal) and e.left.kind == "string":
                col_side, lit_side = e.right, e.left
                op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(
                    e.op, e.op)
            if lit_side is not None:
                col = self._string_col(col_side, "string comparison")
                if op == "eq":
                    return DictPredicate(col, "eq", lit_side.value)
                if op == "ne":
                    return DictPredicate(col, "ne", lit_side.value)
                # ordered string compare: lowered by the compiler via a
                # plan-time dictionary mask (_custom_dict_mask)
                if not (self.dicts and col in self.dicts):
                    raise PlanError(
                        f"ordered string compare on {col} needs dictionary")
                val = lit_side.value.encode() if isinstance(
                    lit_side.value, str) else lit_side.value
                return DictPredicate(col, "custom", ("ord", op, val))
            return Call(_CMP[e.op], self.lower(e.left), self.lower(e.right))
        if e.op in _ARITH:
            return Call(_ARITH[e.op], self.lower(e.left),
                        self.lower(e.right))
        raise PlanError(f"binop {e.op}")

    def _in_list(self, e: ast.InList):
        if all(isinstance(i, ast.Literal) and i.kind == "string"
               for i in e.items):
            col = self._string_col(e.expr, "IN")
            kind = "not_in_set" if e.negated else "in_set"
            return DictPredicate(col, kind,
                                 tuple(i.value for i in e.items))
        inner = self.lower(e.expr)
        consts = []
        for i in e.items:
            c = self.lower(i)
            if not isinstance(c, Const):
                raise PlanError("IN items must be literals")
            consts.append(c)
        call = Call(Op.IN_SET, inner, *consts)
        return Call(Op.NOT, call) if e.negated else call

    def _func(self, e: ast.FuncCall):
        if e.name in _AGG_FUNCS or (e.name == "count" and e.star):
            raise PlanError(f"aggregate {e.name} in scalar context")
        if e.name == "date":
            return Const(_days(e.args[0].value), dtypes.DATE)
        if e.name == "interval":
            n = int(e.args[0].value)
            unit = e.args[1].value
            days = {"day": 1, "week": 7}.get(unit)
            if days is None:
                raise PlanError(f"interval unit {unit}")
            return Const(n * days, dtypes.INT32)
        if e.name in ("year", "month"):
            op = Op.YEAR if e.name == "year" else Op.MONTH
            return Call(op, self.lower(e.args[0]))
        if e.name.startswith("cast_"):
            target = e.name[5:]
            op = {"int32": Op.CAST_INT32, "int64": Op.CAST_INT64,
                  "bigint": Op.CAST_INT64, "float": Op.CAST_FLOAT,
                  "double": Op.CAST_DOUBLE}.get(target)
            if op is None:
                raise PlanError(f"cast to {target}")
            return Call(op, self.lower(e.args[0]))
        simple = {"abs": Op.ABS, "sqrt": Op.SQRT, "exp": Op.EXP,
                  "ln": Op.LN, "floor": Op.FLOOR, "ceil": Op.CEIL,
                  "round": Op.ROUND, "coalesce": Op.COALESCE}
        if e.name in simple:
            return Call(simple[e.name], *[self.lower(a) for a in e.args])
        raise PlanError(f"unknown function {e.name}")


def _contains_agg(e: ast.Expr) -> bool:
    if isinstance(e, ast.FuncCall):
        if e.name in _AGG_FUNCS or (e.name == "count" and e.star):
            return True
        return any(_contains_agg(a) for a in e.args)
    if isinstance(e, ast.BinOp):
        return _contains_agg(e.left) or _contains_agg(e.right)
    if isinstance(e, ast.UnOp):
        return _contains_agg(e.operand)
    if isinstance(e, ast.Between):
        return any(_contains_agg(x) for x in (e.expr, e.low, e.high))
    if isinstance(e, (ast.Like, ast.IsNull)):
        return _contains_agg(e.expr)
    if isinstance(e, ast.InList):
        return _contains_agg(e.expr)
    if isinstance(e, ast.Case):
        return any(
            _contains_agg(c) or _contains_agg(v) for c, v in e.whens
        ) or (e.else_ is not None and _contains_agg(e.else_))
    return False


# ---------------- the planner ----------------


def plan_select(sel: ast.Select, catalog: Catalog):
    binding, refs, join_specs = _bind(sel, catalog)
    alias_to_table = dict(binding.tables)

    # right sides of LEFT JOINs: WHERE on them filters AFTER the join
    # (pushing into the scan would keep NULL-extended rows WHERE should
    # drop), so their single-table conjuncts stay residual
    left_right_aliases = {
        binding.tables[idx][0]
        for idx, _, kind in join_specs if kind == "left"
    }

    # classify WHERE conjuncts
    pushdown: dict[str, list[ast.Expr]] = {a: [] for a, _ in binding.tables}
    join_conds: list[tuple[str, str, str, str]] = []  # (la, lc, ra, rc)
    residual: list[ast.Expr] = []
    for c in _conjuncts(sel.where):
        aliases = _expr_columns(c, binding)
        if len(aliases) <= 1:
            target = next(iter(aliases)) if aliases else binding.tables[0][0]
            if target in left_right_aliases:
                residual.append(c)
                continue
            pushdown[target].append(c)
        elif (
            len(aliases) == 2
            and isinstance(c, ast.BinOp) and c.op == "eq"
            and isinstance(c.left, ast.Name)
            and isinstance(c.right, ast.Name)
        ):
            la, lc = binding.resolve(c.left)
            ra, rc = binding.resolve(c.right)
            if la in left_right_aliases or ra in left_right_aliases:
                # folding a WHERE equi-cond into a LEFT JOIN's ON would
                # keep NULL-extended rows that WHERE must drop
                residual.append(c)
            else:
                join_conds.append((la, lc, ra, rc))
        else:
            residual.append(c)

    # explicit ON conditions
    on_conds: dict[int, list[tuple[str, str, str, str]]] = {}
    for idx, on, kind in join_specs:
        conds = []
        for c in _conjuncts(on):
            if not (isinstance(c, ast.BinOp) and c.op == "eq"
                    and isinstance(c.left, ast.Name)
                    and isinstance(c.right, ast.Name)):
                raise PlanError("JOIN ON supports equi-conditions only")
            la, lc = binding.resolve(c.left)
            ra, rc = binding.resolve(c.right)
            conds.append((la, lc, ra, rc))
        on_conds[idx] = conds

    # column demand per table: everything referenced anywhere
    demand: dict[str, set[str]] = {a: set() for a, _ in binding.tables}

    def demand_expr(e):
        for x in _walk_names(e):
            a, c = binding.resolve(x)
            demand[a].add(c)

    out_aliases = {
        _item_name(item, i) for i, item in enumerate(sel.items)
    }
    for item in sel.items:
        demand_expr(item.expr)
    for e in sel.group_by:
        demand_expr(e)
    for o in sel.order_by:
        # ORDER BY may reference select aliases, which are not table columns
        if isinstance(o.expr, ast.Name) and o.expr.parts[-1] in out_aliases:
            continue
        demand_expr(o.expr)
    if sel.having is not None:
        demand_expr(sel.having)
    for e in residual:
        demand_expr(e)
    for la, lc, ra, rc in join_conds:
        demand[la].add(lc)
        demand[ra].add(rc)
    for conds in on_conds.values():
        for la, lc, ra, rc in conds:
            demand[la].add(lc)
            demand[ra].add(rc)

    # per-table scan with pushdown
    def scan_for(alias: str) -> TableScan:
        table = alias_to_table[alias]
        sch = catalog.schemas[table]
        types = {f.name: f.type for f in sch.fields}
        low = _Lower(types, catalog.dicts)
        steps = []
        for c in pushdown[alias]:
            steps.append(FilterStep(low.lower(c)))
        cols = tuple(
            n for n in sch.names
            if n in demand[alias]
        ) or sch.names[:1]
        steps.append(ProjectStep(cols))
        return TableScan(table, Program(tuple(steps)))

    # left-deep join tree in FROM order
    joined_aliases = [binding.tables[0][0]]
    plan = scan_for(joined_aliases[0])
    types: dict[str, dtypes.LogicalType] = {}
    # joined output columns are keyed by bare name; owner tracks which
    # alias a carried name actually came from so residual predicates can
    # reject silent cross-alias mis-resolution on name collisions
    owner: dict[str, str] = {}
    a0, t0 = binding.tables[0]
    for n in demand[a0] or set(catalog.schemas[t0].names[:1]):
        types[n] = catalog.schemas[t0].field(n).type
        owner[n] = a0

    pending = join_conds[:]
    for i in range(1, len(binding.tables)):
        alias, table = binding.tables[i]
        # orient every condition (ON or WHERE-derived) as
        # (joined-side alias/col, new-table alias/col)
        conds = []
        for la, lc, ra, rc in on_conds.get(i, []):
            if ra == alias and la in joined_aliases:
                conds.append((la, lc, ra, rc))
            elif la == alias and ra in joined_aliases:
                conds.append((ra, rc, la, lc))
            else:
                raise PlanError(
                    f"ON condition does not connect {alias} to the joined"
                    f" tables: {la}.{lc} = {ra}.{rc}"
                )
        still = []
        for la, lc, ra, rc in pending:
            if ra == alias and la in joined_aliases:
                conds.append((la, lc, ra, rc))
            elif la == alias and ra in joined_aliases:
                conds.append((ra, rc, la, lc))
            else:
                still.append((la, lc, ra, rc))
        pending = still
        if not conds:
            raise PlanError(
                f"no equi-join condition connects {alias}; cross joins are"
                " not supported"
            )
        probe_keys = tuple(lc for la, lc, ra, rc in conds)
        build_keys = tuple(rc for la, lc, ra, rc in conds)
        kind = dict((j[0], j[2]) for j in join_specs).get(i, "inner")
        payload = tuple(
            n for n in catalog.schemas[table].names
            if n in demand[alias] and n not in build_keys
        )
        # keep join keys when referenced downstream
        payload += tuple(
            n for n in build_keys
            if n in demand[alias] and n not in payload
            and n not in types  # probe side may already carry same name
        )
        pk = catalog.primary_keys.get(table)
        unique_build = pk is not None and set(pk) <= set(build_keys)
        if kind == "left" and not unique_build:
            raise PlanError(
                f"LEFT JOIN with non-unique build side {table} is not"
                " supported yet (N:M left expansion)"
            )
        if not payload and kind == "inner" and unique_build:
            # pure filtering join: multiplicity can't change (<=1 match)
            plan = LookupJoin(plan, scan_for(alias), probe_keys, build_keys,
                              (), "semi")
        elif unique_build or kind == "left":
            plan = LookupJoin(plan, scan_for(alias), probe_keys, build_keys,
                              payload, kind)
        else:
            # non-unique build changes row multiplicity: expand exactly
            probe_payload = tuple(types.keys())
            plan = ExpandJoin(plan, scan_for(alias), probe_keys, build_keys,
                              probe_payload, payload)
        for n in payload:
            types[n] = catalog.schemas[table].field(n).type
            owner[n] = alias
        joined_aliases.append(alias)
    if pending:
        raise PlanError(f"unplaced join conditions {pending}")

    # final transform: residual filters, aggregation, having, order, project
    if len(binding.tables) > 1:
        for c in residual:
            for x in _walk_names(c):
                a, col = binding.resolve(x)
                if col not in types or owner.get(col, a) != a:
                    raise PlanError(
                        f"predicate references {a}.{col}, which is not"
                        " carried through the join under that name (name"
                        " collision with another table); rename the column"
                    )
    low = _Lower(types, catalog.dicts)
    steps: list = []
    for c in residual:
        steps.append(FilterStep(low.lower(c)))

    has_agg = any(_contains_agg(i.expr) for i in sel.items) or (
        sel.having is not None and _contains_agg(sel.having)
    ) or bool(sel.group_by)

    out_names: list[str] = []
    if has_agg:
        if sel.distinct:
            raise PlanError("SELECT DISTINCT with aggregates is redundant"
                            " or unsupported; drop DISTINCT")
        steps, out_names = _plan_aggregate(sel, low, steps, binding)
    else:
        for idx, item in enumerate(sel.items):
            name = _item_name(item, idx)
            if isinstance(item.expr, ast.Name) and (
                    item.alias is None
                    or item.alias == item.expr.column):
                out_names.append(item.expr.column)
            else:
                steps.append(AssignStep(name, low.lower(item.expr)))
                out_names.append(name)
        steps.append(ProjectStep(tuple(out_names)))
        if sel.distinct:
            # DISTINCT == group by every output column, no aggregates
            steps.append(GroupByStep(tuple(out_names), ()))

    if sel.order_by:
        keys = []
        desc = []
        for o in sel.order_by:
            if isinstance(o.expr, ast.Name) and o.expr.parts[-1] in out_names:
                keys.append(o.expr.parts[-1])
            else:
                raise PlanError(
                    "ORDER BY must reference output columns/aliases")
            desc.append(o.descending)
        steps.append(SortStep(tuple(keys), tuple(desc), sel.limit))
    elif sel.limit is not None:
        steps.append(SortStep((), (), sel.limit))

    return Transform(plan, Program(tuple(steps)))


def _walk_names(e):
    if isinstance(e, ast.Name):
        yield e
    elif isinstance(e, ast.BinOp):
        yield from _walk_names(e.left)
        yield from _walk_names(e.right)
    elif isinstance(e, ast.UnOp):
        yield from _walk_names(e.operand)
    elif isinstance(e, ast.FuncCall):
        for a in e.args:
            yield from _walk_names(a)
    elif isinstance(e, ast.Between):
        yield from _walk_names(e.expr)
        yield from _walk_names(e.low)
        yield from _walk_names(e.high)
    elif isinstance(e, (ast.Like, ast.IsNull)):
        yield from _walk_names(e.expr)
    elif isinstance(e, ast.InList):
        yield from _walk_names(e.expr)
        for i in e.items:
            yield from _walk_names(i)
    elif isinstance(e, ast.Case):
        for c, v in e.whens:
            yield from _walk_names(c)
            yield from _walk_names(v)
        if e.else_ is not None:
            yield from _walk_names(e.else_)


def _item_name(item: ast.SelectItem, idx: int) -> str:
    if item.alias:
        return item.alias
    if isinstance(item.expr, ast.Name):
        return item.expr.column
    return f"column{idx}"


def _plan_aggregate(sel: ast.Select, low: _Lower, steps: list, binding):
    """Lower GROUP BY + aggregates + HAVING into SSA steps."""
    # group keys: plain columns stay; computed keys get pre-assigns
    key_names: list[str] = []
    key_exprs: dict = {}  # ast expr -> key column name
    for i, g in enumerate(sel.group_by):
        if isinstance(g, ast.Name):
            key_names.append(g.column)
            key_exprs[g] = g.column
        else:
            name = f"__key{i}"
            steps.append(AssignStep(name, low.lower(g)))
            low.types[name] = infer_type(
                steps[-1].expr, None, low.types)
            key_names.append(name)
            key_exprs[g] = name

    agg_specs: list[AggSpec] = []
    agg_map: dict = {}  # ast.FuncCall (by repr) -> out name

    def register_agg(fc: ast.FuncCall) -> str:
        key = repr(fc)
        if key in agg_map:
            return agg_map[key]
        name = f"__agg{len(agg_specs)}"
        if fc.name == "count" and fc.star:
            agg_specs.append(AggSpec(Agg.COUNT_ALL, None, name))
        else:
            func = _AGG_FUNCS[fc.name]
            arg = fc.args[0]
            if isinstance(arg, ast.Name):
                col = arg.column
            else:
                col = f"__arg{len(agg_specs)}"
                assign = AssignStep(col, low.lower(arg))
                steps.append(assign)
                low.types[col] = infer_type(assign.expr, None, low.types)
            agg_specs.append(AggSpec(func, col, name))
        agg_map[key] = name
        return name

    def rewrite(e: ast.Expr) -> ast.Expr:
        """Replace group-key expressions and aggregate calls with
        references to their group-by outputs (SQL: every select expr is a
        function of group keys and aggregates)."""
        if e in key_exprs:
            return ast.Name((key_exprs[e],))
        if isinstance(e, ast.FuncCall) and (
                e.name in _AGG_FUNCS or (e.name == "count" and e.star)):
            return ast.Name((register_agg(e),))
        if isinstance(e, ast.BinOp):
            return ast.BinOp(e.op, rewrite(e.left), rewrite(e.right))
        if isinstance(e, ast.UnOp):
            return ast.UnOp(e.op, rewrite(e.operand))
        if isinstance(e, ast.FuncCall):
            return ast.FuncCall(e.name, tuple(rewrite(a) for a in e.args),
                                e.star)
        return e

    post_items: list[tuple[str, ast.Expr]] = []
    out_names: list[str] = []
    for idx, item in enumerate(sel.items):
        name = _item_name(item, idx)
        if isinstance(item.expr, ast.Name):
            col = item.expr.column
            if col not in key_names:
                raise PlanError(
                    f"column {col} is neither aggregated nor a group key")
            out_names.append(col if item.alias in (None, col) else name)
            post_items.append((out_names[-1], item.expr))
            continue
        out_names.append(name)
        post_items.append((name, rewrite(item.expr)))
    having_rw = rewrite(sel.having) if sel.having is not None else None

    steps.append(GroupByStep(tuple(key_names), tuple(agg_specs)))
    # post-aggregation scope: keys + agg outputs
    from ydb_tpu.ssa.program import agg_result_type

    post_types = {k: low.types[k] for k in key_names}
    for spec in agg_specs:
        post_types[spec.out_name] = agg_result_type(spec, None, low.types)
    post_low = _Lower(post_types, low.dicts)

    if having_rw is not None:
        steps.append(FilterStep(post_low.lower(having_rw)))
    for name, e in post_items:
        if isinstance(e, ast.Name) and e.parts[-1] == name:
            continue
        steps.append(AssignStep(name, post_low.lower(e)))
        post_low.types[name] = infer_type(steps[-1].expr, None,
                                          post_low.types)
    steps.append(ProjectStep(tuple(out_names)))
    return steps, out_names
