"""SQL AST → logical plan (binding, pushdown, join + subquery planning).

The compact analog of the reference's KQP compile pipeline (SURVEY.md
§3.2): name binding and type derivation (kqp_type_ann), predicate
pushdown into table scans (the OLAP pushdown shape,
opt/physical/kqp_opt_phy_olap_filter.cpp), join planning over FK->PK
lookup joins vs N:M expansion (CBO-lite: keyed on catalog primary keys),
subquery planning — EXISTS/IN lower to semi/anti joins, correlated
scalar subqueries decorrelate into aggregate joins, uncorrelated ones
execute eagerly as a prior phase (the kqp "precompute" phase shape,
kqp_opt_phy_precompute.cpp) — derived tables / CTEs compose as plan
subtrees, aggregate/HAVING/ORDER BY lowering into SSA programs.

Output is a ydb_tpu.plan tree; the same tree drives the single-chip and
mesh executors.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.blocks.dictionary import _as_bytes as _as_b
from ydb_tpu.plan.nodes import (
    Concat, ExpandJoin, LookupJoin, TableScan, Transform,
)
from ydb_tpu.sql import ast
from ydb_tpu.ssa.ops import Agg, Op
from ydb_tpu.ssa.program import (
    AggSpec,
    AssignStep,
    Call,
    Col,
    Const,
    DictMap,
    DictPredicate,
    FilterStep,
    GroupByStep,
    Program,
    ProjectStep,
    SortStep,
    WindowStep,
    infer_type,
)

_AGG_FUNCS = {
    "sum": Agg.SUM, "avg": Agg.AVG, "min": Agg.MIN, "max": Agg.MAX,
    "count": Agg.COUNT, "some": Agg.SOME,
    "stddev_samp": Agg.STDDEV_SAMP, "stddev": Agg.STDDEV_SAMP,
    "var_samp": Agg.VAR_SAMP, "variance": Agg.VAR_SAMP,
}

_CMP = {"eq": Op.EQ, "ne": Op.NE, "lt": Op.LT, "le": Op.LE, "gt": Op.GT,
        "ge": Op.GE}
_ARITH = {"add": Op.ADD, "sub": Op.SUB, "mul": Op.MUL, "div": Op.DIV,
          "mod": Op.MOD}


@dataclasses.dataclass
class Catalog:
    schemas: dict[str, dtypes.Schema]
    primary_keys: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict)
    dicts: DictionarySet | None = None
    # table -> estimated row count (statistics service feed,
    # obs/sysview.table_stats): drives CBO-lite join ordering — among
    # connectable candidates, smaller estimated sides join first
    row_counts: dict[str, int] = dataclasses.field(default_factory=dict)
    # table -> stats.cost.TableStats from the StatisticsAggregator:
    # per-column NDV / null fractions / value bounds. Fills row-count
    # gaps for join ordering and feeds downstream estimators.
    table_stats: dict = dataclasses.field(default_factory=dict)
    # registered scalar UDFs: name -> (vectorized fn, result LogicalType)
    udfs: dict[str, tuple] = dataclasses.field(default_factory=dict)


# PlanError now lives with the static-analysis diagnostics (the
# verifier raises VerificationError, a PlanError subclass, so the SQL
# surface reports one error family); re-exported here for compatibility.
from ydb_tpu.analysis.diagnostics import PlanError  # noqa: E402,F401


@dataclasses.dataclass
class PlannedQuery:
    """A planned SELECT with its statically-derived output description."""

    plan: object
    out_names: tuple[str, ...]
    out_types: dict[str, dtypes.LogicalType]
    dict_aliases: dict[str, str]  # out column -> dictionary source column
    unique_key: tuple[str, ...] | None  # cols the output is unique on
    # True when an uncorrelated scalar subquery was executed eagerly and
    # its RESULT baked into the plan as a constant: such plans are bound
    # to the planning-time snapshot and must not be cached across writes
    used_scalar_exec: bool = False


# ---------------- helpers ----------------


def _conjuncts(e: ast.Expr | None) -> list[ast.Expr]:
    if e is None:
        return []
    if isinstance(e, ast.BinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _days(s: str) -> int:
    return int(np.datetime64(s, "D").astype(np.int32))


def _walk_names(e):
    if isinstance(e, ast.Name):
        yield e
    elif isinstance(e, ast.BinOp):
        yield from _walk_names(e.left)
        yield from _walk_names(e.right)
    elif isinstance(e, ast.UnOp):
        yield from _walk_names(e.operand)
    elif isinstance(e, ast.FuncCall):
        for a in e.args:
            yield from _walk_names(a)
    elif isinstance(e, ast.Between):
        yield from _walk_names(e.expr)
        yield from _walk_names(e.low)
        yield from _walk_names(e.high)
    elif isinstance(e, (ast.Like, ast.IsNull)):
        yield from _walk_names(e.expr)
    elif isinstance(e, ast.InList):
        yield from _walk_names(e.expr)
        for i in e.items:
            yield from _walk_names(i)
    elif isinstance(e, ast.Case):
        for c, v in e.whens:
            yield from _walk_names(c)
            yield from _walk_names(v)
        if e.else_ is not None:
            yield from _walk_names(e.else_)
    elif isinstance(e, ast.WindowCall):
        for p in e.partition:
            yield from _walk_names(p)
        for o in e.order:
            yield from _walk_names(o.expr)


def _contains_agg(e) -> bool:
    if isinstance(e, ast.FuncCall):
        if e.name in _AGG_FUNCS or (e.name == "count" and e.star):
            return True
        return any(_contains_agg(a) for a in e.args)
    if isinstance(e, ast.BinOp):
        return _contains_agg(e.left) or _contains_agg(e.right)
    if isinstance(e, ast.UnOp):
        return _contains_agg(e.operand)
    if isinstance(e, ast.Between):
        return any(_contains_agg(x) for x in (e.expr, e.low, e.high))
    if isinstance(e, (ast.Like, ast.IsNull)):
        return _contains_agg(e.expr)
    if isinstance(e, ast.InList):
        return _contains_agg(e.expr)
    if isinstance(e, ast.Case):
        return any(
            _contains_agg(c) or _contains_agg(v) for c, v in e.whens
        ) or (e.else_ is not None and _contains_agg(e.else_))
    return False


def _contains_window(e) -> bool:
    """A WindowCall anywhere in the expression tree (not descending into
    subqueries — those plan themselves and run their own check)."""
    if isinstance(e, ast.WindowCall):
        return True
    if isinstance(e, ast.BinOp):
        return _contains_window(e.left) or _contains_window(e.right)
    if isinstance(e, ast.UnOp):
        return _contains_window(e.operand)
    if isinstance(e, ast.FuncCall):
        return any(_contains_window(a) for a in e.args)
    if isinstance(e, ast.Between):
        return any(_contains_window(x) for x in (e.expr, e.low, e.high))
    if isinstance(e, (ast.Like, ast.IsNull)):
        return _contains_window(e.expr)
    if isinstance(e, ast.InList):
        return _contains_window(e.expr) or any(
            _contains_window(i) for i in e.items)
    if isinstance(e, ast.Case):
        return any(
            _contains_window(c) or _contains_window(v) for c, v in e.whens
        ) or (e.else_ is not None and _contains_window(e.else_))
    return False


def _reject_nested_windows(sel: ast.Select) -> None:
    """Window functions are supported only as whole top-level select
    items; anything else (rank() + 1, windows in WHERE/HAVING/GROUP
    BY/ORDER BY) must fail with a targeted message, not a late generic
    'cannot lower' (ADVICE round 5, planner has_window)."""
    for item in sel.items:
        if isinstance(item.expr, ast.Star) or isinstance(
                item.expr, ast.WindowCall):
            continue
        if _contains_window(item.expr):
            raise PlanError(
                "window functions are only allowed as top-level select"
                " items; compute rank() in a subquery and transform it"
                " in the outer SELECT")
    for clause, e in (("WHERE", sel.where), ("HAVING", sel.having)):
        if e is not None and _contains_window(e):
            raise PlanError(
                f"window functions are not allowed in {clause}; rank in"
                " a subquery and filter the outer SELECT")
    for e in sel.group_by:
        if _contains_window(e):
            raise PlanError(
                "window functions are not allowed in GROUP BY")
    for o in sel.order_by:
        if _contains_window(o.expr):
            raise PlanError(
                "window functions are not allowed in ORDER BY; ORDER BY"
                " the aliased select item instead")


def _contains_subquery(e) -> bool:
    if isinstance(e, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
        return True
    if isinstance(e, ast.BinOp):
        return _contains_subquery(e.left) or _contains_subquery(e.right)
    if isinstance(e, ast.UnOp):
        return _contains_subquery(e.operand)
    if isinstance(e, ast.FuncCall):
        return any(_contains_subquery(a) for a in e.args)
    if isinstance(e, ast.Between):
        return any(_contains_subquery(x) for x in (e.expr, e.low, e.high))
    if isinstance(e, (ast.Like, ast.IsNull)):
        return _contains_subquery(e.expr)
    if isinstance(e, ast.InList):
        return _contains_subquery(e.expr)
    if isinstance(e, ast.Case):
        return any(
            _contains_subquery(c) or _contains_subquery(v)
            for c, v in e.whens
        ) or (e.else_ is not None and _contains_subquery(e.else_))
    return False


def _item_name(item: ast.SelectItem, idx: int) -> str:
    if item.alias:
        return item.alias
    if isinstance(item.expr, ast.Name):
        return item.expr.column
    return f"column{idx}"


def _try_const_date(e) -> int | None:
    """Fold date '...' ± interval '...' unit chains to an int day count
    at plan time (month/year intervals only exist inside such folds —
    days-since-epoch columns cannot shift by calendar units at runtime)."""
    if isinstance(e, ast.FuncCall) and e.name == "date":
        return _days(e.args[0].value)
    if isinstance(e, ast.BinOp) and e.op in ("add", "sub"):
        base = _try_const_date(e.left)
        if base is None:
            return None
        iv = e.right
        if not (isinstance(iv, ast.FuncCall) and iv.name == "interval"):
            return None
        n = int(iv.args[0].value)
        unit = iv.args[1].value
        if e.op == "sub":
            n = -n
        d = np.datetime64("1970-01-01", "D") + base
        if unit in ("day", "week"):
            out = d + n * (7 if unit == "week" else 1)
        elif unit == "month":
            m = d.astype("datetime64[M]")
            day_in_month = (d - m.astype("datetime64[D]")).astype(int)
            out = (m + n).astype("datetime64[D]") + int(day_in_month)
        elif unit == "year":
            y = d.astype("datetime64[Y]")
            day_in_year = (d - y.astype("datetime64[D]")).astype(int)
            out = (y + n).astype("datetime64[D]") + int(day_in_year)
        else:
            return None
        return int(out.astype("datetime64[D]").astype(np.int32))
    return None


def _strip_decimal_zeros(value: int, scale: int) -> tuple[int, int]:
    while scale > 0 and value % 10 == 0:
        value //= 10
        scale -= 1
    return value, scale


# ---------------- scopes & binding ----------------


@dataclasses.dataclass
class _Scope:
    """One FROM source: a base table or a planned derived query."""

    alias: str
    names: tuple[str, ...]
    types: dict[str, dtypes.LogicalType]
    dict_src: dict[str, str]       # col -> dictionary source column
    table: str | None = None       # base table name
    sub: PlannedQuery | None = None
    pk: tuple[str, ...] | None = None


@dataclasses.dataclass
class _Binding:
    scopes: list[_Scope]
    col_owner: dict[str, str]
    ambiguous: set[str]

    def scope(self, alias: str) -> _Scope:
        for s in self.scopes:
            if s.alias == alias:
                return s
        raise PlanError(f"unknown table alias {alias}")

    def resolve(self, name: ast.Name) -> tuple[str, str]:
        """-> (alias, column)"""
        if len(name.parts) == 2:
            alias, col = name.parts
            s = self.scope(alias)
            if col not in s.types:
                raise PlanError(f"no column {col} in {alias}")
            return alias, col
        col = name.parts[0]
        if col in self.ambiguous:
            raise PlanError(f"ambiguous column {col}")
        if col not in self.col_owner:
            raise PlanError(f"unknown column {col}")
        return self.col_owner[col], col

    def try_resolve(self, name: ast.Name):
        try:
            return self.resolve(name)
        except PlanError:
            return None


def _flatten_from(f: ast.FromItem):
    """-> ([TableRef|SubquerySource in order], [(right_idx, on, kind)])"""
    if isinstance(f, (ast.TableRef, ast.SubquerySource)):
        return [f], []
    tables, joins = _flatten_from(f.left)
    tables.append(f.right)
    joins.append((len(tables) - 1, f.on, f.kind))
    return tables, joins


# ---------------- expression lowering ----------------


class _Lower:
    """AST expr -> SSA expr against a named-column environment.

    ``resolve``  maps an ast.Name to the in-scope SSA column name.
    ``dict_src`` maps in-scope string columns to the column whose
                 dictionary carries their values (rename tracking).
    ``emit``     appends auxiliary AssignSteps (hidden columns for
                 string transforms like substring)."""

    def __init__(self, types: dict[str, dtypes.LogicalType],
                 dicts: DictionarySet | None,
                 dict_src: dict[str, str] | None = None,
                 resolve=None, emit=None, udfs=None):
        self.types = types
        self.dicts = dicts
        self.dict_src = dict_src if dict_src is not None else {}
        self._resolve = resolve
        self._emit = emit
        self.udfs = udfs or {}

    def name_of(self, e: ast.Name) -> str:
        if self._resolve is not None:
            return self._resolve(e)
        col = e.column
        if col not in self.types:
            raise PlanError(f"column {col} not in scope")
        return col

    def dictionary_of(self, col: str):
        src = self.dict_src.get(col, col)
        if self.dicts is not None and src in self.dicts:
            return self.dicts[src]
        return None

    def emit_assign(self, name: str, expr, t: dtypes.LogicalType):
        if self._emit is None:
            raise PlanError(
                "string transform needs an assignment context")
        self._emit(AssignStep(name, expr))
        self.types[name] = t

    def type_of(self, e) -> dtypes.LogicalType | None:
        try:
            return infer_type(e, None, self.types)
        except Exception:
            return None

    # -- string-column helpers --

    # FuncCalls producing a (dictionary-encoded) string column
    _STRING_FUNCS = frozenset({
        "substring", "upper", "lower", "trim", "ltrim", "rtrim",
        "replace", "concat", "gethost", "cutwww",
    })

    def _as_string_col(self, e, what: str) -> str:
        """Column name of a string-valued operand; lowers string
        transforms (substring/upper/...) to hidden DictMap columns on
        the fly."""
        if isinstance(e, ast.Name):
            col = self.name_of(e)
            if not self.types.get(col, dtypes.INT64).is_string:
                raise PlanError(f"{what} needs a string column operand")
            return col
        if isinstance(e, ast.FuncCall) and e.name in self._STRING_FUNCS:
            lowered = self.lower(e)  # DictMap assign via emit
            assert isinstance(lowered, Col)
            return lowered.name
        raise PlanError(f"{what} needs a string column operand")

    def _is_string_operand(self, e) -> bool:
        if isinstance(e, ast.Name):
            try:
                col = self.name_of(e)
            except PlanError:
                return False
            return self.types.get(col, dtypes.INT64).is_string
        return isinstance(e, ast.FuncCall) and \
            e.name in self._STRING_FUNCS

    def _dict_map(self, col: str, kind: str, args: tuple,
                  out_type=dtypes.STRING) -> Col:
        """Hidden column holding a plan-time dictionary transform of
        ``col`` (substr/upper/replace/strlen/... — every string op is
        an id-indexed table built once over the dictionary)."""
        if args:
            # collision-free tag: short args stay readable, anything
            # long/exotic goes through a stable digest
            import hashlib

            rep = repr(args)
            tag = (rep if len(rep) <= 32 else
                   hashlib.blake2b(rep.encode(),
                                   digest_size=6).hexdigest())
            tag = "".join(c if c.isalnum() else "_" for c in tag)
            hidden = f"__{kind}_{col}_{tag}"
        else:
            hidden = f"__{kind}_{col}"
        if hidden not in self.types:
            self.emit_assign(
                hidden, DictMap(col, kind, args, hidden), out_type)
            if out_type.is_string:
                # the output dictionary populates at compile time;
                # register it now so downstream plan steps (xrank
                # comparisons, nested transforms) see it exists
                self.dict_src[hidden] = hidden
                if self.dicts is not None:
                    self.dicts.for_column(hidden)
        return Col(hidden)

    def _xrank(self, e, peer) -> Col:
        """Hidden int column: e's dictionary ids translated to ranks in
        the union of e's and peer's dictionaries (see "xrank" in
        ssa/compiler.dict_map_table)."""
        col = self._as_string_col(e, "string comparison")
        peer_col = self._as_string_col(peer, "string comparison")
        p_src = self.dict_src.get(peer_col, peer_col)
        if self.dictionary_of(col) is None \
                or self.dictionary_of(peer_col) is None:
            raise PlanError(
                "string column comparison needs dictionaries")
        # keyed on the operand COLUMNS (not dictionary sources): a
        # self-join compares two columns that share one dictionary
        hidden = f"__xrank_{col}_{peer_col}"
        if hidden not in self.types:
            self.emit_assign(
                hidden, DictMap(col, "xrank", (), p_src), dtypes.INT32)
        return Col(hidden)

    def _string_case(self, e: ast.Case) -> Col:
        """CASE whose branches are string columns / string literals:
        lowers to an IF over dictionary ids in ONE shared dictionary
        (all column branches must share a dictionary source; literal
        branches encode into it), emitted as a hidden string column so
        downstream group-bys/projections see a normal dict-encoded
        column (ClickBench q39's IF(..., Referer, '') AS Src shape)."""
        import hashlib

        branches = [v for _c, v in e.whens]
        if e.else_ is not None:
            branches.append(e.else_)
        src = None
        for b in branches:
            if self._is_string_operand(b):
                col = self._as_string_col(b, "CASE")
                s = self.dict_src.get(col, col)
                if src is None:
                    src = s
                elif s != src:
                    raise PlanError(
                        f"string CASE branches must share one dictionary"
                        f" ({src} vs {s})")
        if src is None:
            raise PlanError(
                "string CASE needs at least one string column branch")
        d = self.dicts[src] if (self.dicts is not None
                                and src in self.dicts) else None
        if d is None:
            raise PlanError(f"string CASE needs a dictionary for {src}")

        def enc(b):
            if isinstance(b, ast.Literal) and b.kind == "string":
                val = b.value.encode() if isinstance(b.value, str) \
                    else b.value
                return Const(int(d.add(val)), dtypes.STRING)
            return Col(self._as_string_col(b, "CASE"))

        out = enc(e.else_) if e.else_ is not None \
            else Const(None, dtypes.STRING)
        for cond, val in reversed(e.whens):
            out = Call(Op.IF, self.lower(cond), enc(val), out)
        tag = hashlib.blake2b(repr(e).encode(),
                              digest_size=6).hexdigest()
        hidden = f"__strcase_{tag}"
        if hidden not in self.types:
            self.emit_assign(hidden, out, dtypes.STRING)
            self.dict_src[hidden] = src
        return Col(hidden)

    def lower(self, e: ast.Expr):
        if isinstance(e, ast.Name):
            return Col(self.name_of(e))
        if isinstance(e, ast.Literal):
            return self._literal(e)
        if isinstance(e, ast.UnOp):
            if e.op == "not":
                return Call(Op.NOT, self.lower(e.operand))
            if e.op == "neg":
                return Call(Op.NEG, self.lower(e.operand))
            raise PlanError(f"unary {e.op}")
        if isinstance(e, ast.BinOp):
            return self._binop(e)
        if isinstance(e, ast.Between):
            lo = ast.BinOp("ge", e.expr, e.low)
            hi = ast.BinOp("le", e.expr, e.high)
            both = Call(Op.AND, self._binop(lo), self._binop(hi))
            return Call(Op.NOT, both) if e.negated else both
        if isinstance(e, ast.InList):
            return self._in_list(e)
        if isinstance(e, ast.Like):
            col = self._as_string_col(e.expr, "LIKE")
            p = DictPredicate(col, "like", e.pattern)
            return Call(Op.NOT, p) if e.negated else p
        if isinstance(e, ast.IsNull):
            inner = self.lower(e.expr)
            return Call(Op.IS_NOT_NULL if e.negated else Op.IS_NULL, inner)
        if isinstance(e, ast.Case):
            branches = [v for _c, v in e.whens]
            if e.else_ is not None:
                branches.append(e.else_)
            if any(self._is_string_operand(b)
                   or (isinstance(b, ast.Literal) and b.kind == "string")
                   for b in branches):
                return self._string_case(e)
            if e.else_ is None:
                first = self.lower(e.whens[0][1])
                t = infer_type(first, None, self.types)
                out = Const(None, t)  # CASE without ELSE -> typed NULL
            else:
                out = self.lower(e.else_)
            for cond, val in reversed(e.whens):
                out = Call(Op.IF, self.lower(cond), self.lower(val), out)
            return out
        if isinstance(e, ast.FuncCall):
            return self._func(e)
        if isinstance(e, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
            raise PlanError(
                "subquery in an unsupported position (must be a WHERE/"
                "HAVING conjunct or a comparison operand)")
        raise PlanError(f"cannot lower {e}")

    def _literal(self, e: ast.Literal):
        if e.kind == "int":
            return Const(e.value, dtypes.INT64)
        if e.kind == "typed":  # planner-internal: pre-typed constant
            value, t = e.value
            return Const(value, t)
        if e.kind == "decimal":
            from ydb_tpu.ssa.program import decimal_lit

            scale = len(e.value.split(".")[1]) if "." in e.value else 0
            return decimal_lit(e.value, scale)
        if e.kind == "bool":
            return Const(e.value, dtypes.BOOL)
        if e.kind == "string":
            raise PlanError(
                f"string literal {e.value!r} outside a string comparison"
            )
        raise PlanError(f"literal {e.kind}")

    def _binop(self, e: ast.BinOp):
        if e.op in ("and", "or"):
            return Call(Op.AND if e.op == "and" else Op.OR,
                        self.lower(e.left), self.lower(e.right))
        if e.op in _CMP:
            # string column vs string literal -> dictionary predicate
            lit_side = col_side = None
            if isinstance(e.right, ast.Literal) and e.right.kind == "string":
                col_side, lit_side, op = e.left, e.right, e.op
            elif isinstance(e.left, ast.Literal) and e.left.kind == "string":
                col_side, lit_side = e.right, e.left
                op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(
                    e.op, e.op)
            if lit_side is not None:
                col = self._as_string_col(col_side, "string comparison")
                if op == "eq":
                    return DictPredicate(col, "eq", lit_side.value)
                if op == "ne":
                    return DictPredicate(col, "ne", lit_side.value)
                # ordered string compare: lowered by the compiler via a
                # plan-time dictionary mask (_custom_dict_mask)
                if self.dictionary_of(col) is None:
                    raise PlanError(
                        f"ordered string compare on {col} needs dictionary")
                val = lit_side.value.encode() if isinstance(
                    lit_side.value, str) else lit_side.value
                return DictPredicate(col, "custom", ("ord", op, val))
            # string column vs string column: translate both sides into
            # the rank space of their dictionaries' union (plan-time
            # "xrank" DictMap), then integer-compare — correct across
            # different per-column dictionaries (TPC-DS q19 zip compare)
            if self._is_string_operand(e.left) \
                    and self._is_string_operand(e.right):
                return Call(_CMP[e.op],
                            self._xrank(e.left, e.right),
                            self._xrank(e.right, e.left))
            return Call(_CMP[e.op], self.lower(e.left), self.lower(e.right))
        if e.op in _ARITH:
            folded = _try_const_date(e)
            if folded is not None:
                return Const(folded, dtypes.DATE)
            return Call(_ARITH[e.op], self.lower(e.left),
                        self.lower(e.right))
        raise PlanError(f"binop {e.op}")

    def _in_list(self, e: ast.InList):
        if all(isinstance(i, ast.Literal) and i.kind == "string"
               for i in e.items):
            col = self._as_string_col(e.expr, "IN")
            kind = "not_in_set" if e.negated else "in_set"
            return DictPredicate(col, kind,
                                 tuple(i.value for i in e.items))
        inner = self.lower(e.expr)
        consts = []
        for i in e.items:
            c = self.lower(i)
            if isinstance(c, Call) and c.op is Op.NEG and \
                    isinstance(c.args[0], Const):
                # fold negated literals: IN (-1, 6)
                c = Const(-c.args[0].value, c.args[0].type)
            if not isinstance(c, Const):
                raise PlanError("IN items must be literals")
            consts.append(c)
        call = Call(Op.IN_SET, inner, *consts)
        return Call(Op.NOT, call) if e.negated else call

    def _func(self, e: ast.FuncCall):
        if e.name in _AGG_FUNCS or (e.name == "count" and e.star):
            raise PlanError(f"aggregate {e.name} in scalar context")
        if e.name == "date":
            return Const(_days(e.args[0].value), dtypes.DATE)
        if e.name == "interval":
            n = int(e.args[0].value)
            unit = e.args[1].value
            days = {"day": 1, "week": 7}.get(unit)
            if days is None:
                raise PlanError(
                    f"interval unit {unit} only folds against constant"
                    " dates")
            return Const(n * days, dtypes.INT32)
        if e.name in ("year", "month", "day", "hour", "minute",
                      "second", "dayofweek", "dayofyear", "week",
                      "quarter"):
            op = {"year": Op.YEAR, "month": Op.MONTH, "day": Op.DAY,
                  "hour": Op.HOUR, "minute": Op.MINUTE,
                  "second": Op.SECOND, "dayofweek": Op.DAY_OF_WEEK,
                  "dayofyear": Op.DAY_OF_YEAR, "week": Op.WEEK,
                  "quarter": Op.QUARTER}[e.name]
            return Call(op, self.lower(e.args[0]))
        if e.name in ("greatest", "least"):
            if any(self._is_string_operand(a) for a in e.args):
                # dictionary ids carry no order; a string greatest
                # would need a union-dict gather-back, not an int max
                raise PlanError(
                    f"{e.name} on string columns is not supported")
            op = Op.GREATEST if e.name == "greatest" else Op.LEAST
            out = self.lower(e.args[0])
            for arg in e.args[1:]:  # n-ary folds into binary chains
                out = Call(op, out, self.lower(arg))
            return out
        if e.name == "substring":
            col = self._as_string_col(e.args[0], "substring")
            if not (isinstance(e.args[1], ast.Literal)
                    and isinstance(e.args[2], ast.Literal)):
                raise PlanError("substring bounds must be literals")
            start, length = int(e.args[1].value), int(e.args[2].value)
            return self._dict_map(col, "substr", (start, length))
        if e.name in ("upper", "lower", "trim", "ltrim", "rtrim",
                      "gethost", "cutwww"):
            col = self._as_string_col(e.args[0], e.name)
            return self._dict_map(col, e.name, ())
        if e.name == "replace":
            col = self._as_string_col(e.args[0], "replace")
            old, new = e.args[1], e.args[2]
            if not (isinstance(old, ast.Literal)
                    and isinstance(new, ast.Literal)):
                raise PlanError("replace patterns must be literals")
            return self._dict_map(
                col, "replace",
                (_as_b(old.value), _as_b(new.value)))
        if e.name == "concat":
            # string column ++ literal (either order): a plan-time
            # dictionary transform, like every string op here
            a, b = e.args[0], e.args[1]
            if isinstance(b, ast.Literal) and b.kind == "string":
                col = self._as_string_col(a, "concat")
                return self._dict_map(col, "concat_suffix",
                                      (_as_b(b.value),))
            if isinstance(a, ast.Literal) and a.kind == "string":
                col = self._as_string_col(b, "concat")
                return self._dict_map(col, "concat_prefix",
                                      (_as_b(a.value),))
            raise PlanError("concat needs one string literal operand")
        if e.name in ("length", "strlen"):  # byte length (String type)
            col = self._as_string_col(e.args[0], "length")
            hidden = self._dict_map(col, "strlen", (),
                                    out_type=dtypes.INT32)
            return hidden
        if e.name in ("starts_with", "ends_with"):
            col = self._as_string_col(e.args[0], e.name)
            lit = e.args[1]
            if not (isinstance(lit, ast.Literal)
                    and lit.kind == "string"):
                raise PlanError(f"{e.name} needs a string literal")
            if e.name == "starts_with":
                return DictPredicate(col, "prefix", lit.value)
            return DictPredicate(col, "custom",
                                 ("suffix", _as_b(lit.value)))
        if e.name.startswith("cast_"):
            target = e.name[5:]
            op = {"int32": Op.CAST_INT32, "int64": Op.CAST_INT64,
                  "bigint": Op.CAST_INT64, "float": Op.CAST_FLOAT,
                  "double": Op.CAST_DOUBLE, "int8": Op.CAST_INT8,
                  "int16": Op.CAST_INT16, "uint64": Op.CAST_UINT64,
                  "bool": Op.CAST_BOOL}.get(target)
            if op is None:
                raise PlanError(f"cast to {target}")
            return Call(op, self.lower(e.args[0]))
        simple = {"abs": Op.ABS, "sqrt": Op.SQRT, "exp": Op.EXP,
                  "ln": Op.LN, "log10": Op.LOG10, "floor": Op.FLOOR,
                  "ceil": Op.CEIL, "round": Op.ROUND,
                  "sign": Op.SIGN, "power": Op.POW, "pow": Op.POW,
                  "coalesce": Op.COALESCE, "sin": Op.SIN,
                  "cos": Op.COS, "tan": Op.TAN, "asin": Op.ASIN,
                  "acos": Op.ACOS, "atan": Op.ATAN, "sinh": Op.SINH,
                  "cosh": Op.COSH, "tanh": Op.TANH,
                  "asinh": Op.ASINH, "acosh": Op.ACOSH,
                  "atanh": Op.ATANH, "atan2": Op.ATAN2,
                  "hypot": Op.HYPOT, "cbrt": Op.CBRT, "erf": Op.ERF,
                  "log2": Op.LOG2, "exp2": Op.EXP2,
                  "trunc": Op.TRUNC, "rint": Op.RINT,
                  "radians": Op.RADIANS,
                  "degrees": Op.DEGREES, "nullif": Op.NULLIF,
                  "bit_and": Op.BIT_AND, "bit_or": Op.BIT_OR,
                  "bit_xor": Op.BIT_XOR, "bit_not": Op.BIT_NOT,
                  "shift_left": Op.SHIFT_LEFT,
                  "shift_right": Op.SHIFT_RIGHT,
                  "div": Op.DIV_INT}
        if e.name in simple:
            if e.name == "nullif" and any(
                    self._is_string_operand(a) for a in e.args):
                # dictionary ids from unrelated dictionaries carry no
                # cross-column equality (same reason greatest refuses)
                raise PlanError("nullif on string columns is not"
                                " supported")
            return Call(simple[e.name], *[self.lower(a) for a in e.args])
        if e.name in self.udfs:
            from ydb_tpu.ssa.program import UdfCall

            fn, out_type = self.udfs[e.name]
            if not e.args:
                raise PlanError(
                    f"UDF {e.name} needs at least one argument")
            if out_type.is_string:
                raise PlanError(
                    "UDFs cannot return strings (dictionary ids are"
                    " plan-time state)")
            lowered = tuple(self.lower(a) for a in e.args)
            for a in lowered:
                t = infer_type(a, None, self.types)
                if t.is_string:
                    raise PlanError(
                        f"UDF {e.name}: string-column arguments are not"
                        " supported (the UDF would see dictionary ids)")
            return UdfCall(e.name, lowered, out_type, fn)
        raise PlanError(f"unknown function {e.name}")


# ---------------- the planner ----------------


def plan_select(sel: ast.Select, catalog: Catalog, scalar_exec=None):
    """Plan a SELECT; returns the plan tree (back-compat surface)."""
    return plan_select_full(sel, catalog, scalar_exec).plan


def plan_select_full(
    sel: ast.Select,
    catalog: Catalog,
    scalar_exec=None,
    ctes: dict[str, PlannedQuery] | None = None,
) -> PlannedQuery:
    """Plan a SELECT fully: plan tree + output names/types/dict-aliases.

    ``scalar_exec(plan_node, out_type) -> (value, valid)`` executes an
    uncorrelated scalar subquery eagerly (the KQP precompute-phase
    analog); without it such subqueries raise PlanError.
    """
    planner = _SelectPlanner(catalog, scalar_exec, dict(ctes or {}))
    if isinstance(sel, ast.UnionAll):
        return planner.plan_union(sel)
    return planner.plan(sel)


class _SelectPlanner:
    def __init__(self, catalog: Catalog, scalar_exec, ctes):
        self.catalog = catalog
        self.scalar_exec = scalar_exec
        self.ctes: dict[str, PlannedQuery] = ctes
        self._sq_n = 0
        self.used_scalar_exec = False

    # -- recursion helper --

    def _sub(self, sel: "ast.Select | ast.UnionAll") -> PlannedQuery:
        child = _SelectPlanner(
            self.catalog, self.scalar_exec, dict(self.ctes))
        sub = (child.plan_union(sel) if isinstance(sel, ast.UnionAll)
               else child.plan(sel))
        self.used_scalar_exec |= sub.used_scalar_exec
        return sub

    # -- FROM binding --

    def _bind(self, sel: ast.Select) -> tuple[_Binding, list]:
        if sel.from_ is None:
            raise PlanError("SELECT without FROM is not supported")
        refs, join_specs = _flatten_from(sel.from_)
        scopes: list[_Scope] = []
        for r in refs:
            if isinstance(r, ast.SubquerySource):
                sub = self._sub(r.select)
                scopes.append(_Scope(
                    alias=r.alias, names=sub.out_names,
                    types=dict(sub.out_types),
                    dict_src=dict(sub.dict_aliases),
                    sub=sub, pk=sub.unique_key,
                ))
                continue
            name, alias = r.name, (r.alias or r.name)
            if name in self.ctes:
                sub = self.ctes[name]
                scopes.append(_Scope(
                    alias=alias, names=sub.out_names,
                    types=dict(sub.out_types),
                    dict_src=dict(sub.dict_aliases),
                    sub=sub, pk=sub.unique_key,
                ))
                continue
            if name not in self.catalog.schemas:
                raise PlanError(f"unknown table {name}")
            sch = self.catalog.schemas[name]
            scopes.append(_Scope(
                alias=alias, names=sch.names,
                types={f.name: f.type for f in sch.fields},
                dict_src={f.name: f.name for f in sch.fields
                          if f.type.is_string},
                table=name, pk=self.catalog.primary_keys.get(name),
            ))
        seen: dict[str, str] = {}
        ambiguous: set[str] = set()
        for s in scopes:
            for n in s.names:
                if n in seen and seen[n] != s.alias:
                    ambiguous.add(n)
                else:
                    seen[n] = s.alias
        return _Binding(scopes, seen, ambiguous), join_specs

    # -- subquery rewrites --

    def _correlations(self, sub: ast.Select, outer: _Binding,
                      allow_ne: bool = False):
        """Split inner WHERE into correlated pairs and local conjuncts.

        Correlated conjunct shape: outer_col = inner_col (either order);
        with ``allow_ne``, outer_col <> inner_col is also collected (the
        q21 shape, decorrelated via the counting rewrite).
        Returns ([(outer Name, inner col)] eq pairs,
                 [(outer Name, inner col)] ne pairs,
                 local_where_conjuncts).
        """
        inner_binding, _ = self._bind(sub)
        corr: list[tuple[ast.Name, str]] = []
        ne_corr: list[tuple[ast.Name, str]] = []
        local: list[ast.Expr] = []
        for c in _conjuncts(sub.where):
            names = list(_walk_names(c))
            outer_names = [
                n for n in names
                if inner_binding.try_resolve(n) is None
                and outer.try_resolve(n) is not None
            ]
            if not outer_names:
                local.append(c)
                continue
            ops = ("eq", "ne") if allow_ne else ("eq",)
            if not (isinstance(c, ast.BinOp) and c.op in ops
                    and isinstance(c.left, ast.Name)
                    and isinstance(c.right, ast.Name)):
                raise PlanError(
                    "correlated subquery conditions must be equality"
                    f" (got {c})")
            left_outer = inner_binding.try_resolve(c.left) is None
            o, i = (c.left, c.right) if left_outer else (c.right, c.left)
            if inner_binding.try_resolve(i) is None:
                raise PlanError(
                    "correlated condition does not reference the"
                    " subquery's tables")
            (corr if c.op == "eq" else ne_corr).append((o, i.column))
        return corr, ne_corr, local

    @staticmethod
    def _check_plain_exists(sub: ast.Select) -> None:
        """The EXISTS rewrites rebuild the inner SELECT from its FROM and
        WHERE only; refuse shapes whose dropped clauses would change the
        result instead of silently mis-evaluating them."""
        if sub.group_by or sub.having is not None or sub.limit is not None:
            raise PlanError(
                "EXISTS subqueries with GROUP BY/HAVING/LIMIT are not"
                " supported")

    def _plan_exists_like(self, sub: ast.Select, corr, local):
        """Plan an EXISTS/IN subquery body projecting its correlation
        columns. ``corr``/``local`` come from the caller's
        ``_correlations`` pass (binding the inner FROM is not repeated)."""
        self._check_plain_exists(sub)
        where = None
        for c in local:
            where = c if where is None else ast.BinOp("and", where, c)
        items = tuple(
            ast.SelectItem(ast.Name((col,)), None)
            for col in dict.fromkeys(c for _, c in corr)
        )
        rewritten = ast.Select(
            items=items, from_=sub.from_, where=where, group_by=(),
            having=None, order_by=(), limit=None, ctes=sub.ctes,
        )
        return self._sub(rewritten)

    def _rewrite_or_exists(self, c, binding, scalar_joins, synthetic,
                           new_sq_name):
        """EXISTS leaves inside an OR disjunction -> COUNT scalar joins
        compared against zero. Returns the rebuilt OR expression, or
        None when the shape doesn't qualify (some leaf is an
        unsupported subquery form — the caller then reports the usual
        unsupported-position error)."""
        leaves: list = []

        def collect(e):
            if isinstance(e, ast.BinOp) and e.op == "or":
                return collect(e.left) and collect(e.right)
            negated = False
            while isinstance(e, ast.UnOp) and e.op == "not" \
                    and isinstance(e.operand, ast.Exists):
                negated = not negated
                e = e.operand
            if isinstance(e, ast.Exists):
                leaves.append(("exists", negated != e.negated, e))
                return True
            if _contains_subquery(e):
                return False  # nested non-EXISTS subquery in the OR
            leaves.append(("plain", False, e))
            return True

        if not collect(c) or not any(
                k == "exists" for k, _n, _e in leaves):
            return None
        parts: list = []
        for kind, negated, e in leaves:
            if kind == "plain":
                parts.append(e)
                continue
            try:
                eq, ne_pairs, local = self._correlations(
                    e.select, binding)
            except PlanError:
                return None  # non-equality correlation: fall through
            if not eq or ne_pairs:
                return None
            name = new_sq_name()
            sub = self._plan_count_sub(
                e.select, local, [i for _, i in eq], name)
            scalar_joins.append((name, eq, sub))
            synthetic[name] = dtypes.INT64
            cnt = ast.FuncCall(
                "coalesce", (ast.Name((name,)), ast.Literal(0, "int")))
            parts.append(ast.BinOp("eq" if negated else "gt", cnt,
                                   ast.Literal(0, "int")))
        out = parts[0]
        for p in parts[1:]:
            out = ast.BinOp("or", out, p)
        return out

    def _plan_count_sub(self, sub: ast.Select, local, group_cols,
                        name: str) -> PlannedQuery:
        """COUNT(*) of the subquery's rows grouped by correlation columns
        (the counting decorrelation of non-equi EXISTS, q21)."""
        self._check_plain_exists(sub)
        where = None
        for c in local:
            where = c if where is None else ast.BinOp("and", where, c)
        cols = tuple(dict.fromkeys(group_cols))
        items = (
            ast.SelectItem(ast.FuncCall("count", (), star=True), name),
        ) + tuple(ast.SelectItem(ast.Name((c,)), None) for c in cols)
        rewritten = ast.Select(
            items=items, from_=sub.from_, where=where,
            group_by=tuple(ast.Name((c,)) for c in cols),
            having=None, order_by=(), limit=None, ctes=sub.ctes,
        )
        return self._sub(rewritten)

    # ---------------- main planning ----------------

    def plan_union(self, u: ast.UnionAll) -> PlannedQuery:
        """UNION [ALL] chain -> Concat node (+ dedup / sort / limit).

        Branch outputs align by POSITION to the first branch's names;
        each later branch gets a rename Transform when its names differ.
        Logical types must match exactly per position, and string
        columns must share one dictionary source across branches (the
        concatenated codes decode through a single dictionary)."""
        # a statement-level WITH parses into the FIRST branch; its CTEs
        # scope over every branch. A later branch's own WITH (non-
        # standard but parseable) stays local to that branch: _sub plans
        # it in a child planner whose cte dict is a copy, so it shadows
        # without leaking into sibling branches.
        for name, csub in u.selects[0].ctes:
            self.ctes[name] = self._sub(csub)
        subs = [self._sub(
            dataclasses.replace(b, ctes=()) if i == 0 else b)
            for i, b in enumerate(u.selects)]
        first = subs[0]
        names = first.out_names
        out_types = dict(first.out_types)
        dict_aliases = dict(first.dict_aliases)
        inputs: list = []
        for bi, sub in enumerate(subs):
            if len(sub.out_names) != len(names):
                raise PlanError(
                    f"UNION branch {bi + 1} yields "
                    f"{len(sub.out_names)} columns, expected "
                    f"{len(names)}")
            renames: list[tuple[str, str]] = []
            aliases: dict[str, str] = {}
            for src, dst in zip(sub.out_names, names):
                t_src, t_dst = sub.out_types[src], out_types[dst]
                if t_src != t_dst:
                    raise PlanError(
                        f"UNION branch {bi + 1} column {src}: type "
                        f"{t_src} does not match {dst}: {t_dst}")
                d_src = sub.dict_aliases.get(src, src)
                if t_dst.is_string:
                    d_dst = dict_aliases.get(dst, dst)
                    if bi == 0:
                        dict_aliases[dst] = d_src
                    elif d_src != d_dst:
                        raise PlanError(
                            f"UNION branches disagree on the "
                            f"dictionary for {dst}: {d_src} vs {d_dst}")
                if src != dst:
                    renames.append((src, dst))
                    if t_dst.is_string:
                        aliases[dst] = d_src
                if t_src.is_string and d_src != src:
                    aliases[src] = d_src
            if renames:
                # two-phase rename through fresh temp names: a direct
                # Assign(dst, Col(src)) sequence corrupts permuted
                # column lists (Assign a=b overwrites a before
                # Assign b=a reads it — assignments share one env)
                steps: list = []
                for t, (src, _dst) in enumerate(renames):
                    steps.append(AssignStep(f"__union_{t}", Col(src)))
                for t, (_src, dst) in enumerate(renames):
                    steps.append(AssignStep(dst, Col(f"__union_{t}")))
                steps.append(ProjectStep(names))
                inputs.append(Transform(
                    sub.plan, Program(tuple(steps)),
                    tuple(sorted(aliases.items()))))
            else:
                inputs.append(sub.plan)
        plan: object = Concat(tuple(inputs))

        post: list = []
        if u.distinct:
            post.append(GroupByStep(names, ()))
        if u.order_by:
            keys, desc = [], []
            for o in u.order_by:
                if not (isinstance(o.expr, ast.Name)
                        and o.expr.parts[-1] in names):
                    raise PlanError(
                        "UNION ORDER BY must reference output columns")
                keys.append(o.expr.parts[-1])
                desc.append(o.descending)
            post.append(SortStep(tuple(keys), tuple(desc), u.limit))
        elif u.limit is not None:
            post.append(SortStep((), (), u.limit))
        if post:
            aliases = tuple(sorted(
                (k, v) for k, v in dict_aliases.items() if k != v))
            plan = Transform(plan, Program(tuple(post)), aliases)
        return PlannedQuery(
            plan=plan,
            out_names=names,
            out_types=out_types,
            dict_aliases=dict_aliases,
            unique_key=names if u.distinct else None,
            used_scalar_exec=self.used_scalar_exec,
        )

    def plan(self, sel: ast.Select) -> PlannedQuery:
        # every SELECT — top-level, CTE, derived table, union branch —
        # funnels through here, so nested windows fail with the
        # targeted message wherever they hide
        _reject_nested_windows(sel)
        for name, sub in sel.ctes:
            self.ctes[name] = self._sub(sub)

        mixed = _rewrite_mixed_distinct(sel, self)
        if mixed is not None:
            sel = mixed

        binding, join_specs = self._bind(sel)
        scopes = binding.scopes

        # SELECT * expands to every in-scope column in FROM order
        # (ClickBench q23 shape); duplicate names across scopes surface
        # as the usual ambiguity errors downstream
        if any(isinstance(it.expr, ast.Star) for it in sel.items):
            items = []
            for it in sel.items:
                if not isinstance(it.expr, ast.Star):
                    items.append(it)
                    continue
                for s in scopes:
                    for col in s.names:
                        items.append(
                            ast.SelectItem(ast.Name((s.alias, col)), col))
            sel = dataclasses.replace(sel, items=tuple(items))

        # right sides of LEFT JOINs: WHERE on them filters AFTER the join
        left_right_aliases = {
            scopes[idx].alias for idx, _, kind in join_specs
            if kind == "left"
        }

        # --- subquery rewrites over WHERE conjuncts + HAVING ---
        semi_joins: list = []    # (kind, [(outer Name, build col)], sub)
        scalar_joins: list = []  # (name, [(outer Name, build col)], sub)
        synthetic: dict[str, dtypes.LogicalType] = {}
        syn_dict_src: dict[str, str] = {}

        def new_sq_name() -> str:
            self._sq_n += 1
            return f"__sq{self._sq_n - 1}"

        def rewrite_scalars(e):
            """Replace ScalarSubquery nodes inside an expression."""
            if isinstance(e, ast.ScalarSubquery):
                return self._rewrite_scalar(
                    e.select, binding, scalar_joins, synthetic,
                    syn_dict_src, new_sq_name)
            if isinstance(e, ast.BinOp):
                return ast.BinOp(e.op, rewrite_scalars(e.left),
                                 rewrite_scalars(e.right))
            if isinstance(e, ast.UnOp):
                return ast.UnOp(e.op, rewrite_scalars(e.operand))
            if isinstance(e, ast.FuncCall):
                return ast.FuncCall(
                    e.name, tuple(rewrite_scalars(a) for a in e.args),
                    e.star, e.distinct)
            if isinstance(e, ast.Between):
                return ast.Between(
                    rewrite_scalars(e.expr), rewrite_scalars(e.low),
                    rewrite_scalars(e.high), e.negated)
            if isinstance(e, ast.Case):
                return ast.Case(
                    tuple((rewrite_scalars(c), rewrite_scalars(v))
                          for c, v in e.whens),
                    rewrite_scalars(e.else_)
                    if e.else_ is not None else None)
            return e

        where_conjuncts: list[ast.Expr] = []
        for c in _conjuncts(sel.where):
            neg = False
            while isinstance(c, ast.UnOp) and c.op == "not" and isinstance(
                    c.operand, (ast.Exists, ast.InSubquery)):
                neg = not neg
                c = c.operand
            if isinstance(c, ast.Exists):
                negated = neg != c.negated
                eq, ne_pairs, local = self._correlations(
                    c.select, binding, allow_ne=True)
                if not eq:
                    raise PlanError(
                        "uncorrelated EXISTS is not supported (constant)")
                if ne_pairs:
                    # counting decorrelation (q21):
                    #   EXISTS(k = o.k AND j <> o.j AND f)
                    #   <=> cnt_f(k) > cnt_f(k, j=o.j)
                    name_a, name_b = new_sq_name(), new_sq_name()
                    sub_a = self._plan_count_sub(
                        c.select, local, [i for _, i in eq], name_a)
                    sub_b = self._plan_count_sub(
                        c.select, local,
                        [i for _, i in eq] + [i for _, i in ne_pairs],
                        name_b)
                    scalar_joins.append((name_a, eq, sub_a))
                    scalar_joins.append((name_b, eq + ne_pairs, sub_b))
                    synthetic[name_a] = dtypes.INT64
                    synthetic[name_b] = dtypes.INT64
                    zero = ast.Literal(0, "int")
                    ca = ast.FuncCall(
                        "coalesce", (ast.Name((name_a,)), zero))
                    cb = ast.FuncCall(
                        "coalesce", (ast.Name((name_b,)), zero))
                    where_conjuncts.append(
                        ast.BinOp("eq" if negated else "gt", ca, cb))
                    continue
                sub = self._plan_exists_like(c.select, eq, local)
                semi_joins.append(
                    ("anti" if negated else "semi", eq, sub))
                continue
            if isinstance(c, ast.InSubquery):
                negated = neg != c.negated
                if not isinstance(c.expr, ast.Name):
                    raise PlanError("IN (subquery) needs a column operand")
                sub_sel = c.select
                if len(sub_sel.items) != 1 or isinstance(
                        sub_sel.items[0].expr, ast.Star):
                    raise PlanError(
                        "IN subquery must select exactly one column")
                sub = self._plan_in_subquery(sub_sel, binding)
                build_col = sub.out_names[0]
                semi_joins.append((
                    "anti" if negated else "semi",
                    [(c.expr, build_col)], sub,
                ))
                continue
            if not neg and isinstance(c, ast.BinOp) and c.op == "or" \
                    and _contains_subquery(c):
                # EXISTS(A) OR EXISTS(B) (the q10/q35 shape): each
                # EXISTS leaf decorrelates to a per-key COUNT scalar
                # join (the q21 counting machinery), and the OR
                # rebuilds over count>0 / count==0 markers
                rewritten = self._rewrite_or_exists(
                    c, binding, scalar_joins, synthetic, new_sq_name)
                if rewritten is not None:
                    where_conjuncts.append(rewritten)
                    continue
            if neg:
                c = ast.UnOp("not", c)
            if _contains_subquery(c):
                c = rewrite_scalars(c)
            where_conjuncts.append(c)

        having = sel.having
        if having is not None and _contains_subquery(having):
            having = rewrite_scalars(having)

        # scalar subqueries may appear in SELECT items too (the
        # mixed-COUNT(DISTINCT) rewrite produces them); their synthetic
        # result columns are functions of the correlation keys, so under
        # aggregation they ride along as extra GROUP BY keys
        if any(_contains_subquery(i.expr) for i in sel.items
               if not isinstance(i.expr, ast.Star)):
            new_items = tuple(
                dataclasses.replace(i, expr=rewrite_scalars(i.expr))
                if _contains_subquery(i.expr) else i
                for i in sel.items
            )
            sel = dataclasses.replace(sel, items=new_items)
        if synthetic and (sel.group_by or any(
                _contains_agg(i.expr) for i in sel.items)):
            used = {
                n.parts[0]
                for i in sel.items
                for n in _walk_names(i.expr)
                if len(n.parts) == 1 and n.parts[0] in synthetic
            }
            if having is not None:
                used |= {
                    n.parts[0] for n in _walk_names(having)
                    if len(n.parts) == 1 and n.parts[0] in synthetic
                }
            extra = tuple(
                ast.Name((n,)) for n in sorted(used)
                if ast.Name((n,)) not in sel.group_by
            )
            if extra:
                sel = dataclasses.replace(
                    sel, group_by=tuple(sel.group_by) + extra)

        # --- classify WHERE conjuncts ---
        pushdown: dict[str, list[ast.Expr]] = {s.alias: [] for s in scopes}
        join_conds: list[tuple[str, str, str, str]] = []
        residual: list[ast.Expr] = []

        def expr_aliases(e) -> tuple[set, bool]:
            """(aliases referenced, uses_synthetic)"""
            out, syn = set(), False
            for x in _walk_names(e):
                if len(x.parts) == 1 and x.parts[0] in synthetic:
                    syn = True
                    continue
                out.add(binding.resolve(x)[0])
            return out, syn

        for c in where_conjuncts:
            aliases, syn = expr_aliases(c)
            if syn:
                residual.append(c)
                continue
            if len(aliases) <= 1:
                target = next(iter(aliases)) if aliases else scopes[0].alias
                if target in left_right_aliases:
                    residual.append(c)
                    continue
                pushdown[target].append(c)
            elif (
                len(aliases) == 2
                and isinstance(c, ast.BinOp) and c.op == "eq"
                and isinstance(c.left, ast.Name)
                and isinstance(c.right, ast.Name)
            ):
                la, lc = binding.resolve(c.left)
                ra, rc = binding.resolve(c.right)
                if la in left_right_aliases or ra in left_right_aliases:
                    residual.append(c)
                else:
                    join_conds.append((la, lc, ra, rc))
            else:
                hoisted = self._hoist_or_equi(c, binding)
                join_conds.extend(hoisted)
                residual.append(c)

        # explicit ON conditions
        on_conds: dict[int, list[tuple[str, str, str, str]]] = {}
        for idx, on, kind in join_specs:
            conds = []
            for c in _conjuncts(on):
                if (isinstance(c, ast.BinOp) and c.op == "eq"
                        and isinstance(c.left, ast.Name)
                        and isinstance(c.right, ast.Name)):
                    la, lc = binding.resolve(c.left)
                    ra, rc = binding.resolve(c.right)
                    conds.append((la, lc, ra, rc))
                    continue
                aliases, syn = expr_aliases(c)
                if syn or len(aliases) > 1:
                    raise PlanError(
                        "JOIN ON supports equi-conditions plus"
                        " single-table filters only")
                target = next(iter(aliases)) if aliases else None
                if target == scopes[idx].alias:
                    # build-side ON filter: restricts matches, which for
                    # LEFT keeps the probe row with NULLs — push into the
                    # build scan
                    pushdown[target].append(c)
                elif kind == "left":
                    raise PlanError(
                        "probe-side ON filters in LEFT JOIN are not"
                        " supported")
                elif target is not None:
                    pushdown[target].append(c)
            on_conds[idx] = conds

        # --- demand per scope ---
        demand: dict[str, set[str]] = {s.alias: set() for s in scopes}
        out_aliases = {
            _item_name(item, i) for i, item in enumerate(sel.items)
        }

        def demand_expr(e):
            for x in _walk_names(e):
                if len(x.parts) == 1 and x.parts[0] in synthetic:
                    continue
                try:
                    a, c = binding.resolve(x)
                except PlanError:
                    # select aliases (GROUP BY initial) demand nothing:
                    # the aliased expression is walked via its item
                    if len(x.parts) == 1 and x.parts[0] in out_aliases:
                        continue
                    raise
                demand[a].add(c)
        for item in sel.items:
            if isinstance(item.expr, ast.Star):
                raise PlanError("SELECT * is only allowed inside EXISTS")
            demand_expr(item.expr)
        for e in sel.group_by:
            demand_expr(e)
        for o in sel.order_by:
            if isinstance(o.expr, ast.Name) and o.expr.parts[-1] in out_aliases:
                continue
            demand_expr(o.expr)
        if having is not None:
            demand_expr(having)
        for e in residual:
            demand_expr(e)
        for la, lc, ra, rc in join_conds:
            demand[la].add(lc)
            demand[ra].add(rc)
        for conds in on_conds.values():
            for la, lc, ra, rc in conds:
                demand[la].add(lc)
                demand[ra].add(rc)
        for _, corr, _sub in semi_joins:
            for o, _ in corr:
                a, c = binding.resolve(o)
                demand[a].add(c)
        for _, corr, _sub in scalar_joins:
            for o, _ in corr:
                a, c = binding.resolve(o)
                demand[a].add(c)

        # --- per-scope scan plans (pushdown + projection) ---
        def scan_for(scope: _Scope):
            types = dict(scope.types)
            dict_src = dict(scope.dict_src)
            steps: list = []
            low = _Lower(types, self.catalog.dicts, dict_src,
                         emit=steps.append, udfs=self.catalog.udfs)
            for c in pushdown[scope.alias]:
                steps.append(FilterStep(low.lower(c)))
            cols = tuple(
                n for n in scope.names if n in demand[scope.alias]
            ) or scope.names[:1]
            steps.append(ProjectStep(cols))
            prog = Program(tuple(steps))
            if scope.table is not None:
                return TableScan(scope.table, prog)
            aliases = tuple(sorted(
                (k, v) for k, v in scope.dict_src.items() if k != v
            ))
            return Transform(scope.sub.plan, prog, aliases)

        # --- left-deep join tree with (alias, col) -> out-name map ---
        colmap: dict[tuple[str, str], str] = {}
        types: dict[str, dtypes.LogicalType] = {}
        dict_src: dict[str, str] = {}

        s0 = scopes[0]
        plan = scan_for(s0)
        first_cols = tuple(
            n for n in s0.names if n in demand[s0.alias]
        ) or s0.names[:1]
        for n in first_cols:
            colmap[(s0.alias, n)] = n
            types[n] = s0.types[n]
            if n in s0.dict_src:
                dict_src[n] = s0.dict_src[n]
        joined_aliases = [s0.alias]

        # greedy connectivity ordering (CBO-lite): FROM order may list a
        # table before the one that connects it (q2 lists supplier before
        # partsupp); always join the next FROM-ordered scope that has an
        # equi-condition into the already-joined set
        pending = join_conds[:]
        remaining = list(range(1, len(scopes)))

        def connects(i: int, joined: list[str]) -> bool:
            alias = scopes[i].alias
            on = on_conds.get(i, [])
            if on:
                # an explicit ON clause must be placeable WHOLE: every
                # conjunct's other side already joined (a partial pick
                # would raise 'ON condition does not connect' later)
                return all(
                    (la in joined) if ra == alias else
                    (ra in joined) if la == alias else False
                    for la, lc, ra, rc in on
                )
            for la, lc, ra, rc in pending:
                if (ra == alias and la in joined) or (
                        la == alias and ra in joined):
                    return True
            return False

        def est_rows(i: int) -> float:
            t = scopes[i].table
            if t is not None and t in self.catalog.row_counts:
                return float(self.catalog.row_counts[t])
            if t is not None and t in self.catalog.table_stats:
                # aggregator statistics fill row-count gaps (a table
                # whose cheap metadata count is unknown may still have
                # a sketched row count)
                return float(self.catalog.table_stats[t].rows)
            return float("inf")

        # CBO-lite: with table statistics available (and no LEFT JOINs,
        # which do not commute freely), prefer the SMALLEST connectable
        # side next — dimension tables join before fact expansions
        # (ydb/library/yql/core/cbo greedy ordering shape)
        use_stats = bool(self.catalog.row_counts
                         or self.catalog.table_stats) and not any(
            kind == "left" for _, _, kind in join_specs)

        join_order: list[int] = []
        while remaining:
            joined_now = joined_aliases + [
                scopes[j].alias for j in join_order
            ]
            connectable = [i for i in remaining
                           if connects(i, joined_now)]
            if not connectable:
                pick = remaining[0]  # will raise "no equi-join" below
            elif use_stats:
                pick = min(connectable, key=est_rows)
            else:
                pick = connectable[0]
            join_order.append(pick)
            remaining.remove(pick)

        for i in join_order:
            scope = scopes[i]
            alias = scope.alias
            conds = []
            for la, lc, ra, rc in on_conds.get(i, []):
                if ra == alias and la in joined_aliases:
                    conds.append((la, lc, ra, rc))
                elif la == alias and ra in joined_aliases:
                    conds.append((ra, rc, la, lc))
                else:
                    raise PlanError(
                        f"ON condition does not connect {alias} to the"
                        f" joined tables: {la}.{lc} = {ra}.{rc}"
                    )
            still = []
            for la, lc, ra, rc in pending:
                if ra == alias and la in joined_aliases:
                    conds.append((la, lc, ra, rc))
                elif la == alias and ra in joined_aliases:
                    conds.append((ra, rc, la, lc))
                else:
                    still.append((la, lc, ra, rc))
            pending = still
            # the same equi-cond can arrive twice (hoisted from an OR
            # plus explicit): dedupe
            conds = list(dict.fromkeys(conds))
            if not conds:
                raise PlanError(
                    f"no equi-join condition connects {alias}; cross"
                    " joins are not supported"
                )
            kind0 = dict(
                (j[0], j[2]) for j in join_specs).get(i, "inner")
            if len(conds) > 2:
                # the join kernel packs at most two key columns into one
                # int64 (ssa/join.py _key_i64); further equalities lower
                # as post-join filters on the carried build columns —
                # exact for inner joins (a NULL key fails both ways).
                # LEFT JOIN ON semantics (conditions gate the MATCH, not
                # the result row) would change, so those keep erroring.
                if kind0 == "left":
                    raise PlanError(
                        "LEFT JOIN with more than two equality"
                        " conditions is not supported")
                for la, lc, ra, rc in conds[2:]:
                    residual.append(ast.BinOp(
                        "eq", ast.Name((la, lc)), ast.Name((ra, rc))))
                conds = conds[:2]
            probe_keys = tuple(colmap[(la, lc)] for la, lc, ra, rc in conds)
            build_keys = tuple(rc for la, lc, ra, rc in conds)
            kind = kind0
            demanded = [
                n for n in scope.names
                if n in demand[alias] and n not in build_keys
            ]
            # keep build-side join keys if referenced downstream and not
            # already carried under the same name from the probe side
            demanded += [
                n for n in build_keys
                if n in demand[alias] and n not in demanded
                and n not in types
            ]
            taken = set(types)
            suffix = ""
            if any(n in taken for n in demanded):
                suffix = f"_{alias}"
            payload = tuple(demanded)
            for n in payload:
                out_n = n + suffix
                if out_n in taken:
                    raise PlanError(
                        f"cannot disambiguate column {n} from {alias}")
            unique_build = scope.pk is not None and set(scope.pk) <= set(
                build_keys)
            build_plan = scan_for(scope)
            if not payload and kind == "inner" and unique_build:
                plan = LookupJoin(plan, build_plan, probe_keys, build_keys,
                                  (), "semi")
            elif unique_build:
                plan = LookupJoin(plan, build_plan, probe_keys, build_keys,
                                  payload, kind, suffix)
            elif kind == "left":
                probe_payload = tuple(types.keys())
                plan = ExpandJoin(plan, build_plan, probe_keys, build_keys,
                                  probe_payload, payload,
                                  build_suffix=suffix, kind="left")
            else:
                probe_payload = tuple(types.keys())
                plan = ExpandJoin(plan, build_plan, probe_keys, build_keys,
                                  probe_payload, payload,
                                  build_suffix=suffix)
            for n in payload:
                out_n = n + suffix
                colmap[(alias, n)] = out_n
                types[out_n] = scope.types[n]
                if n in scope.dict_src:
                    dict_src[out_n] = scope.dict_src[n]
            # build keys equal probe keys on matched rows: make them
            # resolvable under the build alias too (inner joins only —
            # left-join NULL-extended rows diverge)
            for (la, lc, ra, rc), pk_name in zip(conds, probe_keys):
                if kind != "left" and (alias, rc) not in colmap:
                    colmap[(alias, rc)] = pk_name
            joined_aliases.append(alias)
        if pending:
            raise PlanError(f"unplaced join conditions {pending}")

        # --- scalar-subquery aggregate joins (decorrelated) ---
        for name, corr, sub in scalar_joins:
            probe_keys = tuple(
                colmap[binding.resolve(o)] for o, _ in corr
            )
            build_keys = tuple(c for _, c in corr)
            plan = LookupJoin(
                plan, sub.plan, probe_keys, build_keys,
                (name,), "left",
            )
            types[name] = synthetic[name]
            colmap[(None, name)] = name

        # --- semi/anti joins from EXISTS / IN subqueries ---
        for kind, corr, sub in semi_joins:
            probe_keys = tuple(
                colmap[binding.resolve(o)] for o, _ in corr
            )
            build_keys = tuple(c for _, c in corr)
            plan = LookupJoin(plan, sub.plan, probe_keys, build_keys,
                              (), kind)

        # --- final transform ---
        def resolve_out(x: ast.Name) -> str:
            if len(x.parts) == 1 and x.parts[0] in synthetic:
                return x.parts[0]
            a, c = binding.resolve(x)
            key = (a, c)
            if key not in colmap:
                raise PlanError(
                    f"column {a}.{c} is not carried through the joins")
            return colmap[key]

        if len(scopes) == 1:
            # single-table: everything references scan output names
            for n in first_cols:
                dict_src.setdefault(n, s0.dict_src.get(n, n))

        steps: list = []
        low = _Lower(types, self.catalog.dicts, dict_src,
                     resolve=resolve_out, emit=steps.append,
                     udfs=self.catalog.udfs)
        for c in residual:
            steps.append(FilterStep(low.lower(c)))

        has_agg = any(
            _contains_agg(i.expr) for i in sel.items
        ) or (having is not None and _contains_agg(having)) or bool(
            sel.group_by)

        out_names: list[str] = []
        out_types: dict[str, dtypes.LogicalType] = {}
        out_dict_aliases: dict[str, str] = {}
        unique_key: tuple[str, ...] | None = None
        project = None  # deferred final projection (non-agg path)
        has_window = any(
            isinstance(i.expr, ast.WindowCall) for i in sel.items)
        if has_agg and has_window:
            raise PlanError(
                "window functions cannot mix with aggregation in one"
                " SELECT; rank over a subquery of the aggregates")
        if has_agg:
            if sel.distinct:
                raise PlanError(
                    "SELECT DISTINCT with aggregates is redundant"
                    " or unsupported; drop DISTINCT")
            steps, out_names, out_types, key_outs = _plan_aggregate(
                sel, low, steps, having)
            unique_key = (
                tuple(key_outs) if key_outs and all(key_outs) else None
            )
        else:
            for idx, item in enumerate(sel.items):
                name = _item_name(item, idx)
                if isinstance(item.expr, ast.WindowCall):
                    wc = item.expr
                    if wc.func not in ("rank", "dense_rank",
                                       "row_number"):
                        raise PlanError(
                            f"unsupported window function {wc.func}")

                    def wcol(e):
                        if isinstance(e, ast.Name):
                            return resolve_out(e)
                        lowered = low.lower(e)
                        tmp = f"__w{len(steps)}"
                        steps.append(AssignStep(tmp, lowered))
                        low.types[tmp] = infer_type(
                            lowered, None, low.types)
                        return tmp

                    pcols = tuple(wcol(p) for p in wc.partition)
                    ocols, descs = [], []
                    for oi in wc.order:
                        ocols.append(wcol(oi.expr))
                        descs.append(oi.descending)
                    steps.append(WindowStep(
                        wc.func, pcols, tuple(ocols), tuple(descs),
                        name))
                    low.types[name] = dtypes.INT64
                    out_names.append(name)
                    out_types[name] = dtypes.INT64
                    continue
                if isinstance(item.expr, ast.Name):
                    src = resolve_out(item.expr)
                    if src == name:
                        out_names.append(src)
                        out_types[src] = types[src]
                        continue
                    steps.append(AssignStep(name, Col(src)))
                    low.types[name] = types[src]
                    if src in dict_src:
                        low.dict_src[name] = dict_src[src]
                    out_names.append(name)
                    out_types[name] = types[src]
                    continue
                lowered = low.lower(item.expr)
                t = infer_type(lowered, None, low.types)
                steps.append(AssignStep(name, lowered))
                low.types[name] = t
                if isinstance(lowered, Col) and lowered.name in low.dict_src:
                    low.dict_src[name] = low.dict_src[lowered.name]
                elif isinstance(lowered, DictMap):
                    low.dict_src[name] = lowered.out_column
                out_names.append(name)
                out_types[name] = t
            project = ProjectStep(tuple(out_names))
            if sel.distinct:
                steps.append(project)
                steps.append(GroupByStep(tuple(out_names), ()))
                unique_key = tuple(out_names)
                project = None

        # the aggregate path builds its own sort/limit/projection inside
        # _plan_aggregate (hidden post-agg sort columns)
        if has_agg:
            pass
        elif sel.order_by:
            keys = []
            desc = []
            hidden_sort = False
            for o in sel.order_by:
                if isinstance(o.expr, ast.Name) and \
                        o.expr.parts[-1] in out_names:
                    keys.append(o.expr.parts[-1])
                elif isinstance(o.expr, ast.Name):
                    # plain SELECT may order by a non-projected column:
                    # sort first, project after
                    keys.append(resolve_out(o.expr))
                    hidden_sort = True
                else:
                    raise PlanError(
                        "ORDER BY must reference output columns/aliases")
                desc.append(o.descending)
            sort = SortStep(tuple(keys), tuple(desc), sel.limit)
            if not sel.distinct:
                if hidden_sort:
                    steps.extend([sort, project])
                else:
                    steps.extend([project, sort])
            else:
                steps.append(sort)
        else:
            if not sel.distinct and project is not None:
                steps.append(project)
            if sel.limit is not None:
                steps.append(SortStep((), (), sel.limit))

        for n in out_names:
            if n in low.dict_src and low.dict_src[n] != n:
                out_dict_aliases[n] = low.dict_src[n]

        aliases = tuple(sorted(
            (k, v) for k, v in low.dict_src.items() if k != v
        ))
        out_plan = Transform(plan, Program(tuple(steps)), aliases)
        return PlannedQuery(
            plan=out_plan,
            out_names=tuple(out_names),
            out_types=out_types,
            dict_aliases=out_dict_aliases,
            unique_key=unique_key,
            used_scalar_exec=self.used_scalar_exec,
        )

    # -- helpers used by plan() --

    def _plan_in_subquery(self, sub_sel: ast.Select,
                          outer: _Binding) -> PlannedQuery:
        """Plan the body of IN (SELECT col ...). Correlated conjuncts are
        not supported here (TPC-H IN-subqueries are uncorrelated)."""
        return self._sub(sub_sel)

    def _rewrite_scalar(self, sub: ast.Select, binding: _Binding,
                        scalar_joins, synthetic, syn_dict_src,
                        new_sq_name):
        """ScalarSubquery -> Literal (uncorrelated, eager exec) or
        Name(__sqN) backed by a decorrelated aggregate join."""
        corr, ne_corr, local = self._correlations(sub, binding)
        if ne_corr:
            raise PlanError(
                "non-equi correlation in a scalar subquery")
        if not corr:
            if self.scalar_exec is None:
                raise PlanError(
                    "uncorrelated scalar subquery needs an executor"
                    " (scalar_exec)")
            if len(sub.items) != 1:
                raise PlanError("scalar subquery must select one value")
            self.used_scalar_exec = True
            planned = self._sub(sub)
            t = planned.out_types[planned.out_names[0]]
            value, valid = self.scalar_exec(planned.plan, t)
            if not valid:
                value = None
            elif t.is_decimal:
                value, scale = _strip_decimal_zeros(int(value), t.scale)
                t = dtypes.decimal(scale)
            return ast.Literal((value, t), "typed")
        # correlated: rewrite into GROUP BY over the correlation columns
        if len(sub.items) != 1:
            raise PlanError("scalar subquery must select one value")
        if not _contains_agg(sub.items[0].expr):
            raise PlanError(
                "correlated scalar subquery must be an aggregate")
        name = new_sq_name()
        where = None
        for c in local:
            where = c if where is None else ast.BinOp("and", where, c)
        corr_cols = list(dict.fromkeys(c for _, c in corr))
        items = (ast.SelectItem(sub.items[0].expr, name),) + tuple(
            ast.SelectItem(ast.Name((c,)), None) for c in corr_cols
        )
        rewritten = ast.Select(
            items=items, from_=sub.from_, where=where,
            group_by=tuple(ast.Name((c,)) for c in corr_cols),
            having=None, order_by=(), limit=None, ctes=sub.ctes,
        )
        planned = self._sub(rewritten)
        scalar_joins.append((name, corr, planned))
        synthetic[name] = planned.out_types[name]
        return ast.Name((name,))

    def _hoist_or_equi(self, c, binding) -> list[tuple[str, str, str, str]]:
        """For an OR-of-conjunctions where EVERY branch contains the same
        two-table equality (q19's (p=l and ...) or (p=l and ...) shape),
        hoist that equality as a join condition; the OR stays residual."""
        def branches(e):
            if isinstance(e, ast.BinOp) and e.op == "or":
                return branches(e.left) + branches(e.right)
            return [e]

        brs = branches(c)
        if len(brs) < 2:
            return []
        common: set | None = None
        for b in brs:
            eqs = set()
            for cj in _conjuncts(b):
                if (isinstance(cj, ast.BinOp) and cj.op == "eq"
                        and isinstance(cj.left, ast.Name)
                        and isinstance(cj.right, ast.Name)):
                    la = binding.try_resolve(cj.left)
                    ra = binding.try_resolve(cj.right)
                    if la and ra and la[0] != ra[0]:
                        eqs.add((la + ra))
                        eqs.add((ra + la))
            common = eqs if common is None else (common & eqs)
            if not common:
                return []
        out = []
        seen = set()
        for la, lc, ra, rc in common:
            if (ra, rc, la, lc) in seen:
                continue
            seen.add((la, lc, ra, rc))
            out.append((la, lc, ra, rc))
        return out


def _collect_aggs(e, out: list) -> None:
    if isinstance(e, ast.FuncCall):
        if e.name in _AGG_FUNCS or (e.name == "count" and e.star):
            out.append(e)
            return
        for a in e.args:
            _collect_aggs(a, out)
    elif isinstance(e, ast.BinOp):
        _collect_aggs(e.left, out)
        _collect_aggs(e.right, out)
    elif isinstance(e, ast.UnOp):
        _collect_aggs(e.operand, out)
    elif isinstance(e, ast.Case):
        for c, v in e.whens:
            _collect_aggs(c, out)
            _collect_aggs(v, out)
        if e.else_ is not None:
            _collect_aggs(e.else_, out)


def _remap_alias_names(e, mapping: dict):
    """Rewrite qualified Names whose alias is in ``mapping``."""
    if isinstance(e, ast.Name):
        if len(e.parts) == 2 and e.parts[0] in mapping:
            return ast.Name((mapping[e.parts[0]], e.parts[1]))
        return e
    if isinstance(e, ast.BinOp):
        return ast.BinOp(e.op, _remap_alias_names(e.left, mapping),
                         _remap_alias_names(e.right, mapping))
    if isinstance(e, ast.UnOp):
        return ast.UnOp(e.op, _remap_alias_names(e.operand, mapping))
    if isinstance(e, ast.FuncCall):
        return ast.FuncCall(
            e.name,
            tuple(_remap_alias_names(a, mapping) for a in e.args),
            e.star, e.distinct)
    if isinstance(e, ast.Between):
        return ast.Between(_remap_alias_names(e.expr, mapping),
                           _remap_alias_names(e.low, mapping),
                           _remap_alias_names(e.high, mapping), e.negated)
    if isinstance(e, ast.InList):
        return ast.InList(_remap_alias_names(e.expr, mapping),
                          tuple(_remap_alias_names(i, mapping)
                                for i in e.items), e.negated)
    if isinstance(e, (ast.Like, ast.IsNull)):
        return dataclasses.replace(
            e, expr=_remap_alias_names(e.expr, mapping))
    if isinstance(e, ast.Case):
        return ast.Case(
            tuple((_remap_alias_names(c, mapping),
                   _remap_alias_names(v, mapping)) for c, v in e.whens),
            _remap_alias_names(e.else_, mapping)
            if e.else_ is not None else None)
    return e


def _rename_from(f, pre: str, mapping: dict):
    if isinstance(f, ast.TableRef):
        alias = f.alias or f.name
        mapping[alias] = pre + alias
        return ast.TableRef(f.name, pre + alias)
    if isinstance(f, ast.SubquerySource):
        mapping[f.alias] = pre + f.alias
        return ast.SubquerySource(f.select, pre + f.alias)
    left = _rename_from(f.left, pre, mapping)
    right = _rename_from(f.right, pre, mapping)
    on = _remap_alias_names(f.on, mapping) if f.on is not None else None
    return ast.Join(left, right, on, f.kind)


def _rewrite_mixed_distinct(sel: ast.Select, planner):
    """COUNT(DISTINCT x) mixed with other aggregates (ClickBench Q9
    shape): each distinct aggregate becomes a correlated scalar subquery
    over a renamed copy of the FROM, correlated on the GROUP BY keys —
    the existing decorrelation machinery then turns it into a
    dedup-aggregate join. Returns the rewritten Select or None when the
    query is not the mixed shape (the single-distinct fast path and the
    'cannot mix' error stay as they were for unsupported forms)."""
    aggs: list[ast.FuncCall] = []
    for i in sel.items:
        if not isinstance(i.expr, ast.Star):
            _collect_aggs(i.expr, aggs)
    if sel.having is not None:
        _collect_aggs(sel.having, aggs)
    distinct = [a for a in aggs if a.distinct]
    plain = [a for a in aggs if not a.distinct]
    d_cols = {a.args[0].column for a in distinct
              if a.args and isinstance(a.args[0], ast.Name)}
    if not distinct or not (plain or len(d_cols) > 1):
        return None
    if any(a.name != "count" or not a.args
           or not isinstance(a.args[0], ast.Name) for a in distinct):
        return None
    if not all(isinstance(g, ast.Name) for g in sel.group_by):
        return None
    if sel.from_ is None:
        return None
    if any(_contains_subquery(c) for c in _conjuncts(sel.where)):
        # the WHERE would be copied into the dedup subqueries, and
        # nested-subquery scopes do not survive the alias renaming
        return None
    try:
        binding, _ = planner._bind(sel)
    except PlanError:
        return None

    counter = [0]

    def subquery_for(fc: ast.FuncCall) -> ast.ScalarSubquery:
        pre = f"__dd{counter[0]}_"
        counter[0] += 1
        mapping: dict = {}
        inner_from = _rename_from(sel.from_, pre, mapping)
        conjs = [
            _remap_alias_names(c, mapping)
            for c in _conjuncts(sel.where)
        ]
        # correlate on every group key: outer side stays qualified with
        # the OUTER alias (unresolvable inside -> correlation), inner
        # side uses the renamed alias
        for g in sel.group_by:
            alias, col = binding.resolve(g)
            conjs.append(ast.BinOp(
                "eq", ast.Name((alias, col)),
                ast.Name((mapping[alias], col))))
        where = None
        for c in conjs:
            where = c if where is None else ast.BinOp("and", where, c)
        inner = ast.Select(
            items=(ast.SelectItem(
                ast.FuncCall(
                    "count",
                    tuple(_remap_alias_names(a, mapping)
                          for a in fc.args),
                    distinct=True), None),),
            from_=inner_from, where=where, group_by=(), having=None,
            order_by=(), limit=None,
        )
        return ast.ScalarSubquery(inner)

    # one distinct aggregate stays INLINE (the single-distinct fast
    # path handles it) so the outer query remains an aggregation and
    # emits its mandatory row even over empty input; the rest become
    # scalar subqueries
    inline_key = repr(distinct[0]) if not plain else None
    replaced: dict = {}

    def rw(e):
        if isinstance(e, ast.FuncCall) and e.distinct:
            key = repr(e)
            if key == inline_key:
                return e
            if key not in replaced:
                replaced[key] = subquery_for(e)
            return replaced[key]
        if isinstance(e, ast.FuncCall):
            return ast.FuncCall(e.name, tuple(rw(a) for a in e.args),
                                e.star, e.distinct)
        if isinstance(e, ast.BinOp):
            return ast.BinOp(e.op, rw(e.left), rw(e.right))
        if isinstance(e, ast.UnOp):
            return ast.UnOp(e.op, rw(e.operand))
        if isinstance(e, ast.Case):
            return ast.Case(
                tuple((rw(c), rw(v)) for c, v in e.whens),
                rw(e.else_) if e.else_ is not None else None)
        return e

    new_items = tuple(
        i if isinstance(i.expr, ast.Star)
        else dataclasses.replace(i, expr=rw(i.expr))
        for i in sel.items
    )
    new_having = rw(sel.having) if sel.having is not None else None
    return dataclasses.replace(sel, items=new_items, having=new_having)


def _plan_aggregate(sel: ast.Select, low: _Lower, steps: list, having):
    """Lower GROUP BY + aggregates + HAVING into SSA steps.

    Returns (steps, out_names, out_types, group_key_out_names)."""
    # group keys may be select aliases of computed exprs (q7's l_year
    # aliases extract(...)) — resolve through the alias map
    alias_exprs = {
        item.alias: item.expr for item in sel.items if item.alias
    }

    def assign_key(name: str, expr) -> None:
        lowered = low.lower(expr)
        steps.append(AssignStep(name, lowered))
        low.types[name] = infer_type(lowered, None, low.types)
        if isinstance(lowered, Col) and lowered.name in low.dict_src:
            low.dict_src[name] = low.dict_src[lowered.name]
        elif isinstance(lowered, DictMap):
            low.dict_src[name] = lowered.out_column

    key_names: list[str] = []
    key_exprs: dict = {}
    for i, g in enumerate(sel.group_by):
        if isinstance(g, ast.Name):
            nm = g.parts[-1]
            try:
                name = low.name_of(g)
            except PlanError:
                if len(g.parts) == 1 and nm in alias_exprs:
                    expr = alias_exprs[nm]
                    if isinstance(expr, ast.Name):
                        name = low.name_of(expr)
                    else:
                        assign_key(nm, expr)
                        name = nm
                    # the aliased expression itself is this key too
                    key_exprs[expr] = name
                else:
                    raise
            key_names.append(name)
            key_exprs[g] = name
        else:
            name = f"__key{i}"
            assign_key(name, g)
            key_names.append(name)
            key_exprs[g] = name

    agg_specs: list[AggSpec] = []
    agg_map: dict = {}
    distinct_cols: list[str] = []

    def register_agg(fc: ast.FuncCall) -> str:
        key = repr(fc)
        if key in agg_map:
            return agg_map[key]
        name = f"__agg{len(agg_specs)}"
        if fc.name == "count" and fc.star:
            agg_specs.append(AggSpec(Agg.COUNT_ALL, None, name))
        else:
            func = _AGG_FUNCS[fc.name]
            arg = fc.args[0]
            if isinstance(arg, ast.Name):
                col = low.name_of(arg)
            else:
                col = f"__arg{len(agg_specs)}"
                lowered = low.lower(arg)
                steps.append(AssignStep(col, lowered))
                low.types[col] = infer_type(lowered, None, low.types)
            if fc.distinct:
                if fc.name != "count":
                    raise PlanError(
                        "DISTINCT is supported for COUNT only")
                distinct_cols.append(col)
            agg_specs.append(AggSpec(func, col, name))
        agg_map[key] = name
        return name

    def key_of_name(e: ast.Name) -> str | None:
        if len(e.parts) == 1 and e.parts[0] in key_names:
            return e.parts[0]
        try:
            nm = low.name_of(e)
        except PlanError:
            return None
        return nm if nm in key_names else None

    def rewrite(e):
        if e in key_exprs:
            return ast.Name((key_exprs[e],))
        if isinstance(e, ast.Name):
            nm = key_of_name(e)
            return ast.Name((nm,)) if nm is not None else e
        if isinstance(e, ast.FuncCall) and (
                e.name in _AGG_FUNCS or (e.name == "count" and e.star)):
            return ast.Name((register_agg(e),))
        if isinstance(e, ast.BinOp):
            return ast.BinOp(e.op, rewrite(e.left), rewrite(e.right))
        if isinstance(e, ast.UnOp):
            return ast.UnOp(e.op, rewrite(e.operand))
        if isinstance(e, ast.FuncCall):
            return ast.FuncCall(e.name, tuple(rewrite(a) for a in e.args),
                                e.star, e.distinct)
        return e

    post_items: list[tuple[str, ast.Expr]] = []
    out_names: list[str] = []
    key_out: dict[str, str] = {}  # group key -> its projected out name
    for idx, item in enumerate(sel.items):
        name = _item_name(item, idx)
        if isinstance(item.expr, ast.Name):
            col = key_of_name(item.expr)
            if col is None:
                raise PlanError(
                    f"column {item.expr.column} is neither aggregated nor"
                    " a group key")
            out_names.append(col if item.alias in (None, col) else name)
            key_out[col] = out_names[-1]
            post_items.append((out_names[-1], ast.Name((col,))))
            continue
        out_names.append(name)
        post_items.append((name, rewrite(item.expr)))
    having_rw = rewrite(having) if having is not None else None

    if distinct_cols:
        if any(s.func is not Agg.COUNT or s.column not in distinct_cols
               for s in agg_specs):
            raise PlanError(
                "COUNT(DISTINCT) cannot mix with other aggregates yet")
        if len(set(distinct_cols)) > 1:
            # one dedup pass over (keys + ALL distinct cols) would count
            # PAIRS, silently wrong per column; the mixed-distinct
            # rewrite handles the supported shapes before reaching here
            raise PlanError(
                "multiple COUNT(DISTINCT ...) columns need plain column"
                " arguments (unsupported distinct-aggregate shape)")
        # dedup pass: group by (keys + distinct cols) with no aggregates,
        # then COUNT over the deduplicated rows
        steps.append(GroupByStep(
            tuple(key_names) + tuple(dict.fromkeys(distinct_cols)), ()))
    steps.append(GroupByStep(tuple(key_names), tuple(agg_specs)))

    from ydb_tpu.ssa.program import agg_result_type

    post_types = {k: low.types[k] for k in key_names}
    post_dict_src = dict(low.dict_src)
    for spec in agg_specs:
        post_types[spec.out_name] = agg_result_type(spec, None, low.types)
    post_low = _Lower(post_types, low.dicts, post_dict_src,
                      udfs=low.udfs)
    for spec in agg_specs:
        # MIN/MAX/SOME over a string column: the output carries the
        # source column's dictionary
        if spec.column is not None and post_types[
                spec.out_name].is_string:
            post_dict_src[spec.out_name] = low.dict_src.get(
                spec.column, spec.column)

    if having_rw is not None:
        steps.append(FilterStep(post_low.lower(having_rw)))
    for name, e in post_items:
        if isinstance(e, ast.Name) and e.parts[-1] == name:
            continue
        lowered = post_low.lower(e)
        steps.append(AssignStep(name, lowered))
        post_low.types[name] = infer_type(lowered, None, post_low.types)
        if isinstance(lowered, Col) and lowered.name in post_low.dict_src:
            post_low.dict_src[name] = post_low.dict_src[lowered.name]

    # ORDER BY: output aliases directly; aggregate EXPRESSIONS (ClickBench
    # 'ORDER BY COUNT(*) DESC') lower into hidden post-agg columns sorted
    # before the final projection drops them
    if sel.order_by:
        keys, desc = [], []
        n_aggs_final = len(agg_specs)
        for i, o in enumerate(sel.order_by):
            if isinstance(o.expr, ast.Name) and \
                    o.expr.parts[-1] in out_names:
                keys.append(o.expr.parts[-1])
            else:
                if isinstance(o.expr, ast.Literal):
                    raise PlanError(
                        "ORDER BY must reference output columns/aliases"
                        " or aggregate expressions")
                rw = rewrite(o.expr)
                if len(agg_specs) != n_aggs_final:
                    # the GroupByStep (and post scope) snapshotted the
                    # aggregate list already — a NEW aggregate here would
                    # reference states that were never computed
                    raise PlanError(
                        "ORDER BY aggregate must also appear in the"
                        " SELECT list")
                if isinstance(rw, ast.Name) and rw.parts[-1] in out_names:
                    keys.append(rw.parts[-1])
                else:
                    name = f"__ord{i}"
                    lowered = post_low.lower(rw)
                    steps.append(AssignStep(name, lowered))
                    post_low.types[name] = infer_type(
                        lowered, None, post_low.types)
                    keys.append(name)
            desc.append(o.descending)
        steps.append(SortStep(tuple(keys), tuple(desc), sel.limit))
    elif sel.limit is not None:
        steps.append(SortStep((), (), sel.limit))
    steps.append(ProjectStep(tuple(out_names)))

    out_types = {n: post_low.types[n] for n in out_names}
    # propagate dictionary renames for downstream consumers
    low.dict_src.update(post_low.dict_src)
    # the output names the group keys survive under (None if projected out)
    key_outs = [key_out.get(k) for k in key_names]
    return steps, out_names, out_types, key_outs
