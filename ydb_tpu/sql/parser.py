"""Hand-rolled SQL lexer + recursive-descent parser.

Dialect: the YQL/PostgreSQL-flavored subset the engine executes — SELECT
with expressions/aggregates, multi-way JOIN ... ON, WHERE with
AND/OR/NOT/BETWEEN/IN/LIKE/IS NULL/CASE, GROUP BY, HAVING, ORDER BY ...
[ASC|DESC], LIMIT; INSERT INTO ... VALUES; CREATE TABLE with PRIMARY KEY.
Grammar is layered by precedence (or > and > not > cmp > add > mul >
unary > primary), one function per layer — the shape of the reference's
SQL grammar without the generated-parser machinery (yql/sql/v1).
"""

from __future__ import annotations

import dataclasses
import re

from ydb_tpu.sql import ast

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<number>\d+\.\d+|\.\d+|\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<name>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><=|>=|<>|!=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.)
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "between", "in", "like", "is", "null",
    "asc", "desc", "join", "inner", "left", "on", "insert", "upsert",
    "into",
    "values", "create", "table", "primary", "key", "case", "when", "then",
    "else", "end", "date", "interval", "true", "false", "distinct",
    "outer", "exists", "cast", "drop", "alter", "add", "column", "with",
    "update", "set", "delete", "extract", "substring", "for", "explain",
    "begin", "commit", "rollback", "transaction", "union", "all",
    "partition",
}


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind, value, pos):
        self.kind = kind
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.value!r}"


def tokenize(sql: str) -> list[Token]:
    out = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SyntaxError(f"bad character {sql[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        text = m.group()
        if kind == "name":
            low = text.lower()
            if low in _KEYWORDS:
                out.append(Token("kw", low, m.start()))
            else:
                out.append(Token("name", text, m.start()))
        elif kind == "string":
            out.append(Token("string", text[1:-1].replace("''", "'"),
                             m.start()))
        else:
            out.append(Token(kind, text, m.start()))
    out.append(Token("eof", "", len(sql)))
    return out


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers --

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind, value=None):
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind, value=None) -> Token:
        t = self.accept(kind, value)
        if t is None:
            got = self.peek()
            want = value or kind
            raise SyntaxError(f"expected {want!r}, got {got.value!r} at "
                              f"position {got.pos}")
        return t

    def kw(self, word) -> bool:
        return self.accept("kw", word) is not None

    # -- statements --

    def parse_statement(self) -> ast.Statement:
        if self.peek().value == "explain":
            self.next()
            # ANALYZE is a soft keyword (stays usable as a column name)
            analyze = False
            t = self.peek()
            if t.kind == "name" and t.value.lower() == "analyze":
                self.next()
                analyze = True
            stmt = ast.Explain(self.parse_select_or_union(),
                               analyze=analyze)
        elif self.peek().value in ("select", "with"):
            stmt = self.parse_select_or_union()
        elif self.peek().value in ("insert", "upsert"):
            stmt = self.parse_insert()
        elif self.peek().value == "begin":
            self.next()
            self.accept("kw", "transaction")
            stmt = ast.Begin()
        elif self.peek().value == "commit":
            self.next()
            stmt = ast.Commit()
        elif self.peek().value == "rollback":
            self.next()
            stmt = ast.Rollback()
        elif self.peek().value == "create":
            stmt = self.parse_create()
        elif self.peek().value == "drop":
            stmt = self.parse_drop()
        elif self.peek().value == "alter":
            stmt = self.parse_alter()
        elif self.peek().value == "update":
            stmt = self.parse_update()
        elif self.peek().value == "delete":
            stmt = self.parse_delete()
        else:
            raise SyntaxError(f"unsupported statement {self.peek().value!r}")
        self.expect("eof")
        return stmt

    def parse_select_or_union(self) -> "ast.Select | ast.UnionAll":
        """A SELECT, or a UNION [ALL] chain of them.

        A trailing ORDER BY / LIMIT parses into the LAST branch; per the
        SQL standard they bind to the whole set operation, so they hoist
        onto the UnionAll node. Mixing UNION and UNION ALL in one chain
        is rejected (the subset keeps one distinct flag per chain).
        """
        first = self.parse_select()
        if self.peek().value != "union":
            return first
        branches = [first]
        kinds = set()
        while self.kw("union"):
            kinds.add("all" if self.kw("all") else "distinct")
            branches.append(self.parse_select())
        if len(kinds) > 1:
            raise SyntaxError(
                "mixed UNION / UNION ALL in one chain is not supported")
        for b in branches[:-1]:
            # standard SQL only allows ORDER BY/LIMIT on the WHOLE set
            # operation (or parenthesized branches, which this subset
            # does not parse); an interior one would otherwise silently
            # stay branch-local
            if b.order_by or b.limit is not None:
                raise SyntaxError(
                    "ORDER BY/LIMIT inside a non-final UNION branch is"
                    " not supported")
        last = branches[-1]
        order, limit = last.order_by, last.limit
        if order or limit is not None:
            branches[-1] = dataclasses.replace(
                last, order_by=(), limit=None)
        return ast.UnionAll(tuple(branches), order, limit,
                            distinct=kinds == {"distinct"})

    def parse_select(self) -> ast.Select:
        ctes: list[tuple[str, ast.Select]] = []
        if self.kw("with"):
            while True:
                name = self.expect("name").value
                self.expect("kw", "as")
                self.expect("op", "(")
                ctes.append((name, self.parse_select_or_union()))
                self.expect("op", ")")
                if not self.accept("op", ","):
                    break
        self.expect("kw", "select")
        distinct = self.kw("distinct")
        items = [self.parse_select_item()]
        while self.accept("op", ","):
            items.append(self.parse_select_item())
        from_ = None
        if self.kw("from"):
            from_ = self.parse_from()
        where = self.parse_expr() if self.kw("where") else None
        group_by: tuple = ()
        if self.kw("group"):
            self.expect("kw", "by")
            gb = [self.parse_expr()]
            while self.accept("op", ","):
                gb.append(self.parse_expr())
            group_by = tuple(gb)
        having = self.parse_expr() if self.kw("having") else None
        order_by: tuple = ()
        if self.kw("order"):
            self.expect("kw", "by")
            ob = [self.parse_order_item()]
            while self.accept("op", ","):
                ob.append(self.parse_order_item())
            order_by = tuple(ob)
        limit = None
        if self.kw("limit"):
            limit = int(self.expect("number").value)
        return ast.Select(tuple(items), from_, where, group_by, having,
                          order_by, limit, distinct, tuple(ctes))

    def parse_select_item(self) -> ast.SelectItem:
        if self.peek().kind == "op" and self.peek().value == "*":
            self.next()
            return ast.SelectItem(ast.Star(), None)
        expr = self.parse_expr()
        alias = None
        if self.kw("as"):
            alias = self.expect("name").value
        elif self.peek().kind == "name":
            alias = self.next().value
        return ast.SelectItem(expr, alias)

    def parse_from(self) -> ast.FromItem:
        left: ast.FromItem = self.parse_table_ref()
        while True:
            kind = None
            if self.kw("join") or self.kw("inner") and self.kw("join"):
                kind = "inner"
            elif self.peek().value == "left":
                self.next()
                self.kw("outer")
                self.expect("kw", "join")
                kind = "left"
            elif self.accept("op", ","):
                # comma join: cross product restricted by WHERE; planner
                # requires equi-conditions there
                right = self.parse_table_ref()
                left = ast.Join(left, right, None, "inner")
                continue
            if kind is None:
                return left
            right = self.parse_table_ref()
            on = None
            if self.kw("on"):
                on = self.parse_expr()
            left = ast.Join(left, right, on, kind)

    def parse_table_ref(self) -> "ast.TableRef | ast.SubquerySource":
        if self.accept("op", "("):
            # derived table: ( SELECT ... ) [AS] alias
            sub = self.parse_select_or_union()
            self.expect("op", ")")
            self.kw("as")
            alias = self.expect("name").value
            return ast.SubquerySource(sub, alias)
        name = self.expect("name").value
        alias = None
        if self.kw("as"):
            alias = self.expect("name").value
        elif self.peek().kind == "name":
            alias = self.next().value
        return ast.TableRef(name, alias)

    def parse_order_item(self) -> ast.OrderItem:
        e = self.parse_expr()
        desc = False
        if self.kw("desc"):
            desc = True
        else:
            self.kw("asc")
        return ast.OrderItem(e, desc)

    def parse_insert(self) -> ast.Insert:
        # UPSERT INTO parses to the same node: the row stores' write
        # path is newest-wins (blind upsert), matching YQL UPSERT
        if not self.accept("kw", "upsert"):
            self.expect("kw", "insert")
        self.expect("kw", "into")
        table = self.expect("name").value
        cols = []
        if self.accept("op", "("):
            cols.append(self.expect("name").value)
            while self.accept("op", ","):
                cols.append(self.expect("name").value)
            self.expect("op", ")")
        self.expect("kw", "values")
        rows = []
        while True:
            self.expect("op", "(")
            row = [self.parse_expr()]
            while self.accept("op", ","):
                row.append(self.parse_expr())
            self.expect("op", ")")
            rows.append(tuple(row))
            if not self.accept("op", ","):
                break
        return ast.Insert(table, tuple(cols), tuple(rows))

    def parse_create(self):
        self.expect("kw", "create")
        if self.peek().kind == "name" and \
                self.peek().value.lower() == "sequence":
            self.next()
            name = self.expect("name").value
            opts = {"start": 1, "increment": 1, "cache": 100}
            while self.peek().kind == "name" and \
                    self.peek().value.lower() in ("start", "increment",
                                                  "cache"):
                key = self.next().value.lower()
                self.accept("kw", "with")
                neg = (self.peek().kind == "op"
                       and self.peek().value == "-"
                       and bool(self.next()))
                val = int(self.expect("number").value)
                opts[key] = -val if neg else val
            return ast.CreateSequence(name, opts["start"],
                                      opts["increment"], opts["cache"])
        self.expect("kw", "table")
        table = self.expect("name").value
        self.expect("op", "(")
        columns = []
        pk: tuple = ()
        while True:
            if self.kw("primary"):
                self.expect("kw", "key")
                self.expect("op", "(")
                names = [self.expect("name").value]
                while self.accept("op", ","):
                    names.append(self.expect("name").value)
                self.expect("op", ")")
                pk = tuple(names)
            else:
                name = self.expect("name").value
                t = self.next()
                if t.kind not in ("name", "kw"):
                    raise SyntaxError(f"expected type after {name}")
                typ = t.value
                if self.accept("op", "("):  # decimal(p, s)
                    p = self.expect("number").value
                    s = "0"
                    if self.accept("op", ","):
                        s = self.expect("number").value
                    self.expect("op", ")")
                    typ = f"{typ}({p},{s})"
                not_null = False
                if self.kw("not"):
                    self.expect("kw", "null")
                    not_null = True
                columns.append((name, typ, not_null))
            if not self.accept("op", ","):
                break
        self.expect("op", ")")
        options: list[tuple[str, str]] = []
        if self.kw("with"):
            self.expect("op", "(")
            while True:
                k = self.next()
                if k.kind not in ("name", "kw"):
                    raise SyntaxError("expected option name in WITH")
                self.expect("op", "=")
                v = self.next()
                if v.kind not in ("name", "kw", "number", "string"):
                    raise SyntaxError(f"bad option value for {k.value}")
                options.append((k.value.lower(), str(v.value)))
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
        return ast.CreateTable(table, tuple(columns), pk, tuple(options))

    def parse_drop(self):
        self.expect("kw", "drop")
        if self.peek().kind == "name" and \
                self.peek().value.lower() == "sequence":
            self.next()
            return ast.DropSequence(self.expect("name").value)
        self.expect("kw", "table")
        return ast.DropTable(self.expect("name").value)

    def parse_update(self) -> ast.Update:
        self.expect("kw", "update")
        table = self.expect("name").value
        self.expect("kw", "set")
        sets = []
        while True:
            name = self.expect("name").value
            self.expect("op", "=")
            sets.append((name, self.parse_expr()))
            if not self.accept("op", ","):
                break
        where = self.parse_expr() if self.kw("where") else None
        return ast.Update(table, tuple(sets), where)

    def parse_delete(self) -> ast.Delete:
        self.expect("kw", "delete")
        self.expect("kw", "from")
        table = self.expect("name").value
        where = self.parse_expr() if self.kw("where") else None
        return ast.Delete(table, where)

    def parse_alter(self) -> ast.AlterTable:
        self.expect("kw", "alter")
        self.expect("kw", "table")
        table = self.expect("name").value
        add: list[tuple[str, str]] = []
        drop: list[str] = []
        while True:
            if self.kw("add"):
                self.kw("column")
                name = self.expect("name").value
                t = self.next()
                if t.kind not in ("name", "kw"):
                    raise SyntaxError(f"expected type after {name}")
                typ = t.value
                if self.accept("op", "("):
                    p = self.expect("number").value
                    s = "0"
                    if self.accept("op", ","):
                        s = self.expect("number").value
                    self.expect("op", ")")
                    typ = f"{typ}({p},{s})"
                add.append((name, typ))
            elif self.kw("drop"):
                self.kw("column")
                drop.append(self.expect("name").value)
            else:
                raise SyntaxError("expected ADD or DROP in ALTER TABLE")
            if not self.accept("op", ","):
                break
        return ast.AlterTable(table, tuple(add), tuple(drop))

    # -- expressions by precedence --

    def parse_expr(self) -> ast.Expr:
        return self.parse_or()

    def parse_or(self) -> ast.Expr:
        e = self.parse_and()
        while self.kw("or"):
            e = ast.BinOp("or", e, self.parse_and())
        return e

    def parse_and(self) -> ast.Expr:
        e = self.parse_not()
        while self.kw("and"):
            e = ast.BinOp("and", e, self.parse_not())
        return e

    def parse_not(self) -> ast.Expr:
        if self.kw("not"):
            return ast.UnOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> ast.Expr:
        e = self.parse_additive()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "<>", "!=", "<", "<=", ">",
                                          ">="):
            self.next()
            op = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt", "<=": "le",
                  ">": "gt", ">=": "ge"}[t.value]
            return ast.BinOp(op, e, self.parse_additive())
        negated = False
        if t.kind == "kw" and t.value == "not":
            # NOT BETWEEN / NOT IN / NOT LIKE
            nxt = self.toks[self.i + 1]
            if nxt.kind == "kw" and nxt.value in ("between", "in", "like"):
                self.next()
                negated = True
                t = self.peek()
        if t.kind == "kw" and t.value == "between":
            self.next()
            low = self.parse_additive()
            self.expect("kw", "and")
            high = self.parse_additive()
            return ast.Between(e, low, high, negated)
        if t.kind == "kw" and t.value == "in":
            self.next()
            self.expect("op", "(")
            if self.peek().value in ("select", "with"):
                sub = self.parse_select()
                self.expect("op", ")")
                return ast.InSubquery(e, sub, negated)
            items = [self.parse_expr()]
            while self.accept("op", ","):
                items.append(self.parse_expr())
            self.expect("op", ")")
            return ast.InList(e, tuple(items), negated)
        if t.kind == "kw" and t.value == "like":
            self.next()
            pat = self.expect("string").value
            return ast.Like(e, pat, negated)
        if t.kind == "kw" and t.value == "is":
            self.next()
            neg = self.kw("not")
            self.expect("kw", "null")
            return ast.IsNull(e, neg)
        return e

    def parse_additive(self) -> ast.Expr:
        e = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                op = "add" if t.value == "+" else "sub"
                e = ast.BinOp(op, e, self.parse_multiplicative())
            else:
                return e

    def parse_multiplicative(self) -> ast.Expr:
        e = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                op = {"*": "mul", "/": "div", "%": "mod"}[t.value]
                e = ast.BinOp(op, e, self.parse_unary())
            else:
                return e

    def parse_unary(self) -> ast.Expr:
        if self.accept("op", "-"):
            return ast.UnOp("neg", self.parse_unary())
        if self.accept("op", "+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> ast.Expr:
        t = self.peek()
        if t.kind == "op" and t.value == "(":
            self.next()
            if self.peek().value in ("select", "with"):
                sub = self.parse_select()
                self.expect("op", ")")
                return ast.ScalarSubquery(sub)
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        if t.kind == "number":
            self.next()
            if "." in t.value:
                return ast.Literal(t.value, "decimal")
            return ast.Literal(int(t.value), "int")
        if t.kind == "string":
            self.next()
            return ast.Literal(t.value, "string")
        if t.kind == "kw":
            if t.value == "null":
                self.next()
                return ast.Literal(None, "null")
            if t.value in ("true", "false"):
                self.next()
                return ast.Literal(t.value == "true", "bool")
            if t.value == "date":
                self.next()
                s = self.expect("string").value
                return ast.FuncCall("date", (ast.Literal(s, "string"),))
            if t.value == "interval":
                self.next()
                s = self.expect("string").value
                unit = self.expect("name").value.lower()
                return ast.FuncCall(
                    "interval",
                    (ast.Literal(s, "string"), ast.Literal(unit, "string")),
                )
            if t.value == "exists":
                self.next()
                self.expect("op", "(")
                sub = self.parse_select()
                self.expect("op", ")")
                return ast.Exists(sub)
            if t.value == "extract":
                # extract(year|month from expr)
                self.next()
                self.expect("op", "(")
                part = self.next().value.lower()
                self.expect("kw", "from")
                e = self.parse_expr()
                self.expect("op", ")")
                return ast.FuncCall(part, (e,))
            if t.value == "substring":
                # substring(x, start, len) | substring(x from start for len)
                self.next()
                self.expect("op", "(")
                e = self.parse_expr()
                if self.kw("from"):
                    start = self.parse_expr()
                    self.expect("kw", "for")
                    length = self.parse_expr()
                else:
                    self.expect("op", ",")
                    start = self.parse_expr()
                    self.expect("op", ",")
                    length = self.parse_expr()
                self.expect("op", ")")
                return ast.FuncCall("substring", (e, start, length))
            if t.value == "case":
                return self.parse_case()
            if t.value == "cast":
                self.next()
                self.expect("op", "(")
                e = self.parse_expr()
                self.expect("kw", "as")
                typ = self.next().value
                self.expect("op", ")")
                return ast.FuncCall(f"cast_{typ.lower()}", (e,))
        if t.kind == "name":
            self.next()
            if self.peek().kind == "op" and self.peek().value == "(":
                self.next()
                if self.accept("op", "*"):
                    self.expect("op", ")")
                    return ast.FuncCall(t.value.lower(), (), star=True)
                distinct = self.kw("distinct")
                args = []
                if not (self.peek().kind == "op" and self.peek().value == ")"):
                    args.append(self.parse_expr())
                    while self.accept("op", ","):
                        args.append(self.parse_expr())
                self.expect("op", ")")
                fc = ast.FuncCall(t.value.lower(), tuple(args),
                                  distinct=distinct)
                if str(self.peek().value).lower() == "over":
                    if fc.name in ("rank", "dense_rank", "row_number") \
                            and (fc.args or fc.distinct or fc.star):
                        # the reference rejects these at translation
                        # time too; silently dropping the argument list
                        # would rewrite the query's meaning
                        found = ("DISTINCT" if fc.distinct else
                                 "*" if fc.star else
                                 f"{len(fc.args)} argument(s)")
                        raise SyntaxError(
                            f"window function {fc.name}() takes no"
                            f" arguments and no DISTINCT/*; found"
                            f" {found} at {t.pos}")
                    self.next()
                    self.expect("op", "(")
                    partition: list = []
                    if self.kw("partition"):
                        self.expect("kw", "by")
                        partition.append(self.parse_expr())
                        while self.accept("op", ","):
                            partition.append(self.parse_expr())
                    order: list = []
                    if self.kw("order"):
                        self.expect("kw", "by")
                        order.append(self.parse_order_item())
                        while self.accept("op", ","):
                            order.append(self.parse_order_item())
                    self.expect("op", ")")
                    return ast.WindowCall(fc.name, tuple(partition),
                                          tuple(order))
                return fc
            parts = [t.value]
            while self.accept("op", "."):
                parts.append(self.expect("name").value)
            return ast.Name(tuple(parts))
        raise SyntaxError(f"unexpected token {t.value!r} at {t.pos}")

    def parse_case(self) -> ast.Case:
        self.expect("kw", "case")
        whens = []
        while self.kw("when"):
            cond = self.parse_expr()
            self.expect("kw", "then")
            val = self.parse_expr()
            whens.append((cond, val))
        else_ = None
        if self.kw("else"):
            else_ = self.parse_expr()
        self.expect("kw", "end")
        return ast.Case(tuple(whens), else_)


def parse(sql: str) -> ast.Statement:
    return Parser(sql).parse_statement()
