"""SQL AST nodes (unresolved names; the planner binds them).

The reference parses SQL into an expression graph via NSQLTranslation →
TExprNode (SURVEY.md §2 layer 7a). This is the TPU build's lean analog: a
typed AST for the supported dialect subset, produced by
ydb_tpu.sql.parser and consumed by ydb_tpu.sql.planner.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Union


@dataclasses.dataclass(frozen=True)
class Name:
    """Possibly qualified column reference (t.col or col)."""

    parts: tuple[str, ...]

    @property
    def column(self) -> str:
        return self.parts[-1]


@dataclasses.dataclass(frozen=True)
class Literal:
    value: Any
    kind: str  # int | float | string | null | bool | decimal


@dataclasses.dataclass(frozen=True)
class BinOp:
    op: str
    left: "Expr"
    right: "Expr"


@dataclasses.dataclass(frozen=True)
class UnOp:
    op: str
    operand: "Expr"


@dataclasses.dataclass(frozen=True)
class FuncCall:
    name: str
    args: tuple["Expr", ...]
    star: bool = False  # count(*)
    distinct: bool = False  # count(distinct x)


@dataclasses.dataclass(frozen=True)
class WindowCall:
    """fn() OVER (PARTITION BY ... ORDER BY ...) — the ranking window
    subset (rank / dense_rank / row_number)."""

    func: str
    partition: tuple["Expr", ...]
    order: tuple["OrderItem", ...]


@dataclasses.dataclass(frozen=True)
class Between:
    expr: "Expr"
    low: "Expr"
    high: "Expr"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InList:
    expr: "Expr"
    items: tuple["Expr", ...]
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Like:
    expr: "Expr"
    pattern: str
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class IsNull:
    expr: "Expr"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Case:
    whens: tuple[tuple["Expr", "Expr"], ...]
    else_: "Expr | None"


@dataclasses.dataclass(frozen=True)
class ScalarSubquery:
    """(SELECT single-expr ...) used as a value. Uncorrelated ones execute
    eagerly at plan time; correlated ones decorrelate into aggregate
    joins (the DqBuildJoin-style subquery rewrites, kqp_opt_phy)."""

    select: "Select"


@dataclasses.dataclass(frozen=True)
class InSubquery:
    expr: "Expr"
    select: "Select"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Exists:
    select: "Select"
    negated: bool = False


Expr = Union[Name, Literal, BinOp, UnOp, FuncCall, Between, InList, Like,
             IsNull, Case, ScalarSubquery, InSubquery, Exists]


@dataclasses.dataclass(frozen=True)
class Star:
    """SELECT * (allowed in EXISTS subqueries and plain selects)."""


@dataclasses.dataclass(frozen=True)
class SelectItem:
    expr: "Expr | Star"
    alias: str | None


@dataclasses.dataclass(frozen=True)
class TableRef:
    name: str
    alias: str | None = None


@dataclasses.dataclass(frozen=True)
class SubquerySource:
    """Derived table: (SELECT ...) AS alias in FROM."""

    select: "Select | UnionAll"
    alias: str


@dataclasses.dataclass(frozen=True)
class Join:
    left: "FromItem"
    right: "TableRef | SubquerySource"
    on: Expr | None
    kind: str = "inner"  # inner | left


FromItem = Union[TableRef, SubquerySource, Join]


@dataclasses.dataclass(frozen=True)
class OrderItem:
    expr: Expr
    descending: bool = False


@dataclasses.dataclass(frozen=True)
class Select:
    items: tuple[SelectItem, ...]
    from_: FromItem | None
    where: Expr | None
    group_by: tuple[Expr, ...]
    having: Expr | None
    order_by: tuple[OrderItem, ...]
    limit: int | None
    distinct: bool = False
    # WITH name AS (select), ...: CTEs usable as FROM sources downstream
    ctes: tuple[tuple[str, "Select"], ...] = ()


@dataclasses.dataclass(frozen=True)
class UnionAll:
    """SELECT ... UNION ALL SELECT ... [ORDER BY ...] [LIMIT n].

    Branch outputs align by POSITION; names come from the first branch
    (SQL standard set-operation semantics). ``distinct`` True models
    plain UNION (duplicate rows collapse). The reference compiles set
    operations into an Extend/UnionAll expression node
    (yql/essentials/core/type_ann/type_ann_list.cpp UnionAll); here the
    planner lowers them to a Concat plan node.
    """

    selects: tuple["Select", ...]
    order_by: tuple[OrderItem, ...] = ()
    limit: int | None = None
    distinct: bool = False


@dataclasses.dataclass(frozen=True)
class Insert:
    table: str
    columns: tuple[str, ...]
    rows: tuple[tuple[Expr, ...], ...]


@dataclasses.dataclass(frozen=True)
class CreateTable:
    table: str
    columns: tuple[tuple[str, str, bool], ...]  # (name, type, not_null)
    primary_key: tuple[str, ...]
    # WITH (store = column|row, shards = N, ttl_column = name)
    options: tuple[tuple[str, str], ...] = ()


@dataclasses.dataclass(frozen=True)
class DropTable:
    table: str


@dataclasses.dataclass(frozen=True)
class Update:
    table: str
    sets: tuple[tuple[str, Expr], ...]
    where: Expr | None


@dataclasses.dataclass(frozen=True)
class Delete:
    table: str
    where: Expr | None


@dataclasses.dataclass(frozen=True)
class AlterTable:
    table: str
    add_columns: tuple[tuple[str, str], ...] = ()  # (name, type)
    drop_columns: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Explain:
    """EXPLAIN [ANALYZE] <select>: return the physical plan. With
    ANALYZE the query actually runs and the plan is annotated with
    measured actuals (per-stage seconds, rows, cache hits)."""

    select: Select
    analyze: bool = False


@dataclasses.dataclass(frozen=True)
class CreateSequence:
    """CREATE SEQUENCE name [START n] [INCREMENT n] [CACHE n]."""

    name: str
    start: int = 1
    increment: int = 1
    cache: int = 100


@dataclasses.dataclass(frozen=True)
class DropSequence:
    name: str


@dataclasses.dataclass(frozen=True)
class Begin:
    """BEGIN: open an interactive transaction on the session."""


@dataclasses.dataclass(frozen=True)
class Commit:
    """COMMIT: apply the transaction's buffered effects atomically."""


@dataclasses.dataclass(frozen=True)
class Rollback:
    """ROLLBACK: discard the transaction's buffered effects."""


Statement = Union[Select, UnionAll, Insert, CreateTable, DropTable,
                  AlterTable, Update, Delete, Explain, Begin, Commit,
                  Rollback, CreateSequence, DropSequence]
