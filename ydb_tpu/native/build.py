"""On-demand build of the native host library.

One g++ invocation, cached by source mtime; no toolchain (or a failed
compile) degrades to the numpy twins in ydb_tpu.native — behavior
identical, just slower (the CPU-default/plugin-engine rule the
reference enforces at its TComputationNodeFactory seam)."""

from __future__ import annotations

import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "src", "ydbtpu_native.cpp")
OUT = os.path.join(_DIR, "_build", "libydbtpu_native.so")


def ensure_built(force: bool = False) -> str | None:
    """Compile if stale; returns the .so path or None when unavailable."""
    if os.environ.get("YDB_TPU_NO_NATIVE"):
        return None
    try:
        if not force and os.path.exists(OUT) and \
                os.path.getmtime(OUT) >= os.path.getmtime(SRC):
            return OUT
        os.makedirs(os.path.dirname(OUT), exist_ok=True)
        tmp = OUT + ".tmp"
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
             "-o", tmp, SRC],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, OUT)
        return OUT
    except Exception:
        return None
