"""Native host kernels with exact numpy fallbacks.

Hot host-side operations between device programs — shuffle row hashing,
K-way PK merge with MVCC dedup, bloom filters, gathers — implemented in
C++ (src/ydbtpu_native.cpp; reference analogs cited there) and loaded
via ctypes. Every entry point has a numpy twin producing bit-identical
results, selected automatically when the library can't build; set
YDB_TPU_NO_NATIVE=1 to force the fallback (tests compare both).
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from ydb_tpu.native.build import ensure_built

_lib = None
_load_lock = threading.Lock()


def _load():
    # first call can come from any conveyor worker (shuffle hashing,
    # K-way merge in scan producers): double-checked so concurrent
    # first uses build/dlopen once instead of racing ensure_built
    global _lib
    if _lib is not None:
        return _lib if _lib is not False else None
    with _load_lock:
        if _lib is not None:
            return _lib if _lib is not False else None
        path = ensure_built()
        if path is None:
            _lib = False
            return None
        try:
            lib = ctypes.CDLL(path)
            lib.ydbtpu_kway_merge.restype = ctypes.c_int64
            _lib = lib
        except OSError:
            _lib = False
            return None
        return _lib


def available() -> bool:
    return _load() is not None


def _pp(arrs, ctype):
    """list of contiguous arrays -> C array of pointers."""
    ptrs = (ctypes.POINTER(ctype) * len(arrs))()
    for i, a in enumerate(arrs):
        ptrs[i] = a.ctypes.data_as(ctypes.POINTER(ctype))
    return ptrs


# ---- row hashing ----

def hash_rows(keys: list[np.ndarray],
              valids: list[np.ndarray]) -> np.ndarray:
    """Shuffle-routing row hash over int64 key columns (+ validity bit).

    Identical bits from the native and numpy paths — partition routing
    must agree across processes with and without the toolchain.
    """
    n = len(keys[0]) if keys else 0
    lib = _load()
    if lib is not None and n > 0:
        ks = [np.ascontiguousarray(k, dtype=np.int64) for k in keys]
        vs = [np.ascontiguousarray(v, dtype=np.uint8) for v in valids]
        out = np.empty(n, dtype=np.uint64)
        lib.ydbtpu_hash_rows(
            _pp(ks, ctypes.c_int64), _pp(vs, ctypes.c_uint8),
            ctypes.c_int32(len(ks)), ctypes.c_int64(n),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        return out
    h = np.full(n, 0x9E3779B97F4A7C15, dtype=np.uint64)
    for kv, ok in zip(keys, valids):
        v = kv.astype(np.int64).view(np.uint64) ^ (
            ok.astype(np.uint64) << np.uint64(63))
        x = h ^ v
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        h = x ^ (x >> np.uint64(31))
    return h


# ---- K-way merge ----

def kway_merge(runs: list[np.ndarray], dedup: bool = False):
    """Merge sorted int64 runs into global key order.

    Returns (run_idx int32[n], row_idx int64[n]). Stable across runs;
    with dedup=True equal keys collapse to the highest run index
    (runs ordered oldest -> newest = newest-wins MVCC dedup,
    merge.cpp/NArrow::NMerger analog).
    """
    total = int(sum(len(r) for r in runs))
    lib = _load()
    if lib is not None:
        rs = [np.ascontiguousarray(r, dtype=np.int64) for r in runs]
        lens = np.asarray([len(r) for r in rs], dtype=np.int64)
        out_run = np.empty(total, dtype=np.int32)
        out_idx = np.empty(total, dtype=np.int64)
        n = lib.ydbtpu_kway_merge(
            _pp(rs, ctypes.c_int64),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int32(len(rs)), ctypes.c_int32(1 if dedup else 0),
            out_run.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return out_run[:n], out_idx[:n]
    # numpy twin: stable sort of (key, run) then optional last-dup keep
    keys = np.concatenate([np.asarray(r, dtype=np.int64) for r in runs]) \
        if runs else np.empty(0, dtype=np.int64)
    run_of = np.concatenate([
        np.full(len(r), i, dtype=np.int32) for i, r in enumerate(runs)
    ]) if runs else np.empty(0, dtype=np.int32)
    idx_of = np.concatenate([
        np.arange(len(r), dtype=np.int64) for r in runs
    ]) if runs else np.empty(0, dtype=np.int64)
    order = np.lexsort((run_of, keys))
    keys, run_of, idx_of = keys[order], run_of[order], idx_of[order]
    if dedup and len(keys):
        # keep the LAST of each equal-key group
        last = np.r_[keys[1:] != keys[:-1], True]
        run_of, idx_of = run_of[last], idx_of[last]
    return run_of, idx_of


# ---- bloom filter ----

class BloomFilter:
    """Bloom filter over u64 hashes (part/portion pruning analog)."""

    def __init__(self, nbits: int, nprobes: int = 4,
                 bits: np.ndarray | None = None):
        self.nbits = int(nbits)
        self.nprobes = int(nprobes)
        self.bits = (bits if bits is not None else
                     np.zeros((self.nbits + 7) // 8, dtype=np.uint8))

    @staticmethod
    def for_items(n_items: int, bits_per_item: int = 10) -> "BloomFilter":
        return BloomFilter(max(64, n_items * bits_per_item))

    def add(self, hashes: np.ndarray) -> None:
        h = np.ascontiguousarray(hashes, dtype=np.uint64)
        lib = _load()
        if lib is not None:
            lib.ydbtpu_bloom_build(
                h.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ctypes.c_int64(len(h)),
                self.bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.c_int64(self.nbits), ctypes.c_int32(self.nprobes))
            return
        h2 = _mix64(h) | np.uint64(1)
        for p in range(self.nprobes):
            bit = (h + np.uint64(p) * h2) % np.uint64(self.nbits)
            np.bitwise_or.at(
                self.bits, (bit >> np.uint64(3)).astype(np.int64),
                (np.uint8(1) << (bit & np.uint64(7)).astype(np.uint8)))

    def query(self, hashes: np.ndarray) -> np.ndarray:
        h = np.ascontiguousarray(hashes, dtype=np.uint64)
        lib = _load()
        if lib is not None:
            out = np.empty(len(h), dtype=np.uint8)
            lib.ydbtpu_bloom_query(
                h.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ctypes.c_int64(len(h)),
                self.bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.c_int64(self.nbits), ctypes.c_int32(self.nprobes),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            return out.astype(bool)
        h2 = _mix64(h) | np.uint64(1)
        hit = np.ones(len(h), dtype=bool)
        for p in range(self.nprobes):
            bit = (h + np.uint64(p) * h2) % np.uint64(self.nbits)
            byte = self.bits[(bit >> np.uint64(3)).astype(np.int64)]
            hit &= ((byte >> (bit & np.uint64(7)).astype(np.uint8))
                    & np.uint8(1)).astype(bool)
        return hit


def _mix64(x: np.ndarray) -> np.ndarray:
    x = (x ^ (x >> np.uint64(33))) * np.uint64(0xFF51AFD7ED558CCD)
    x = (x ^ (x >> np.uint64(33))) * np.uint64(0xC4CEB9FE1A85EC53)
    return x ^ (x >> np.uint64(33))
