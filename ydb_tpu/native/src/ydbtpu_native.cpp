// Native host runtime kernels (C ABI, loaded via ctypes).
//
// The reference implements its host hot paths in C++: the vectorized
// block hash partitioner (dq_output_consumer.cpp:338,500), the K-way
// PK merge of sorted portion streams (plain_reader/iterator/merge.cpp,
// NArrow::NMerger) and bloom filters on local-DB parts
// (tablet_flat flat_part_*). These are their TPU-era equivalents: the
// device plane (JAX/XLA) never sees them — they run on host between
// device programs, so they are plain C++ with a stable C ABI and exact
// numpy-fallback twins in ydb_tpu/native/__init__.py (same bits out,
// so routing/merges agree across mixed deployments).
//
// Build: g++ -O3 -shared -fPIC (ydb_tpu/native/build.py).

#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

extern "C" {

// ---- row hashing (splitmix64 mix, identical to the numpy twin) ----

void ydbtpu_hash_rows(const int64_t **keys, const uint8_t **valids,
                      int32_t nkeys, int64_t nrows, uint64_t *out) {
    for (int64_t i = 0; i < nrows; ++i)
        out[i] = 0x9E3779B97F4A7C15ULL;
    for (int32_t k = 0; k < nkeys; ++k) {
        const int64_t *kv = keys[k];
        const uint8_t *ok = valids[k];
        for (int64_t i = 0; i < nrows; ++i) {
            uint64_t v = (uint64_t)kv[i] ^ ((uint64_t)(ok[i] != 0) << 63);
            uint64_t x = out[i] ^ v;
            x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
            x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
            out[i] = x ^ (x >> 31);
        }
    }
}

// ---- K-way merge of sorted runs ----
//
// Emits (run_index, row_index) pairs in globally sorted key order.
// Stable across runs: equal keys emit in run order (run 0 first), so
// with runs ordered oldest -> newest, "keep the LAST duplicate" is
// newest-wins MVCC dedup. Returns the output length (== total rows, or
// fewer when dedup=1).
int64_t ydbtpu_kway_merge(const int64_t **runs, const int64_t *lens,
                          int32_t nruns, int32_t dedup,
                          int32_t *out_run, int64_t *out_idx) {
    struct Head {
        int64_t key;
        int32_t run;
        int64_t idx;
    };
    struct Cmp {
        bool operator()(const Head &a, const Head &b) const {
            if (a.key != b.key) return a.key > b.key;
            return a.run > b.run;  // stable: lower run first
        }
    };
    std::priority_queue<Head, std::vector<Head>, Cmp> heap;
    for (int32_t r = 0; r < nruns; ++r)
        if (lens[r] > 0) heap.push({runs[r][0], r, 0});
    int64_t n_out = 0;
    bool have_prev = false;
    int64_t prev_key = 0;
    while (!heap.empty()) {
        Head h = heap.top();
        heap.pop();
        if (dedup && have_prev && h.key == prev_key) {
            // newer duplicate replaces the previously emitted row
            out_run[n_out - 1] = h.run;
            out_idx[n_out - 1] = h.idx;
        } else {
            out_run[n_out] = h.run;
            out_idx[n_out] = h.idx;
            ++n_out;
            prev_key = h.key;
            have_prev = true;
        }
        if (h.idx + 1 < lens[h.run])
            heap.push({runs[h.run][h.idx + 1], h.run, h.idx + 1});
    }
    return n_out;
}

// ---- bloom filter over u64 hashes (k probes via double hashing) ----

static inline uint64_t mix64(uint64_t x) {
    x = (x ^ (x >> 33)) * 0xFF51AFD7ED558CCDULL;
    x = (x ^ (x >> 33)) * 0xC4CEB9FE1A85EC53ULL;
    return x ^ (x >> 33);
}

void ydbtpu_bloom_build(const uint64_t *hashes, int64_t n, uint8_t *bits,
                        int64_t nbits, int32_t nprobes) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h1 = hashes[i], h2 = mix64(hashes[i]) | 1ULL;
        for (int32_t p = 0; p < nprobes; ++p) {
            uint64_t bit = (h1 + (uint64_t)p * h2) % (uint64_t)nbits;
            bits[bit >> 3] |= (uint8_t)(1u << (bit & 7));
        }
    }
}

void ydbtpu_bloom_query(const uint64_t *hashes, int64_t n,
                        const uint8_t *bits, int64_t nbits,
                        int32_t nprobes, uint8_t *out) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h1 = hashes[i], h2 = mix64(hashes[i]) | 1ULL;
        uint8_t hit = 1;
        for (int32_t p = 0; p < nprobes && hit; ++p) {
            uint64_t bit = (h1 + (uint64_t)p * h2) % (uint64_t)nbits;
            hit = (bits[bit >> 3] >> (bit & 7)) & 1u;
        }
        out[i] = hit;
    }
}

// ---- gather: out[i] = src[idx[i]] (merge materialization core) ----

void ydbtpu_gather_i64(const int64_t *src, const int64_t *idx, int64_t n,
                       int64_t *out) {
    for (int64_t i = 0; i < n; ++i) out[i] = src[idx[i]];
}

}  // extern "C"
