"""DataShard execution-unit pipeline: dependency-ordered wait/restart.

The reference drives every datashard operation through an ordered list
of ~60 execution units (execution_unit_kind.h:7; pipeline in
datashard_pipeline.cpp): each unit returns Executed / Wait / Restart,
and an operation whose dependencies are still in flight PARKS at its
current unit, restarting there when the blocker completes. This module
is that state machine at the TPU build's scale — the essential
semantics (unit trace, key-conflict dependency build, wait, restart,
completion notification) over the existing propose/prepare/commit
primitives of ``DataShard``:

    CHECK            validate the operation (schema, lock liveness)
    BUILD_DEPS       key-overlap scan against in-flight operations
    WAIT_DEPS        park until every dependency completes (restart
                     here on each completion)
    BUILD_TX         stage writes durably (DataShard.propose)
    PREPARE          lock validation point (DataShard.prepare)
    WAIT_PLAN        park until the plan step arrives (auto_plan
                     pipelines self-assign the next step)
    EXECUTE          commit at the planned step (DataShard.commit_at)
    COMPLETE         release waiters, record the result

Single-shard operations only: multi-shard transactions keep riding the
coordinator's volatile 2PC (tx/coordinator.py), exactly as the
reference splits direct vs. distributed paths.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Iterable

from ydb_tpu.datashard.shard import DataShard, RowOp, TxRejected


class Unit(enum.Enum):
    CHECK = "check"
    BUILD_DEPS = "build_deps"
    WAIT_DEPS = "wait_deps"
    BUILD_TX = "build_tx"
    PREPARE = "prepare"
    WAIT_PLAN = "wait_plan"
    EXECUTE = "execute"
    COMPLETE = "complete"


UNIT_ORDER = list(Unit)


class Status(enum.Enum):
    ACTIVE = "active"
    WAITING = "waiting"
    DONE = "done"
    ABORTED = "aborted"


@dataclasses.dataclass
class Operation:
    op_id: int
    ops: list
    lock_id: int | None
    unit: Unit = Unit.CHECK
    status: Status = Status.ACTIVE
    deps: set = dataclasses.field(default_factory=set)
    write_ids: list = dataclasses.field(default_factory=list)
    step: int | None = None
    error: str | None = None
    # every unit entry is recorded; a restarted WAIT_DEPS appears once
    # per wake-up — the observable trace of wait/restart semantics
    trace: list = dataclasses.field(default_factory=list)

    @property
    def keys(self) -> set:
        return {op.key for op in self.ops}


class ExecutionPipeline:
    """Per-shard operation driver (datashard_pipeline.cpp shape)."""

    def __init__(self, shard: DataShard, step_source=None,
                 auto_plan: bool = True):
        self.shard = shard
        # auto_plan=False models the coordinator-driven path: an op
        # parks at WAIT_PLAN until plan() delivers its step, so
        # conflicting ops genuinely overlap in flight
        self.auto_plan = auto_plan
        self._next_id = 1
        self._active: dict[int, Operation] = {}
        # bounded result history: completed ops shed their payloads
        # (rows/trace) and the oldest entries evict — a long-lived
        # pipeline must not grow with every write it ever served
        from collections import OrderedDict

        self._done: "OrderedDict[int, Operation]" = OrderedDict()
        self.done_history = 1024
        # blocker op_id -> ops parked on it
        self._waiters: dict[int, list[Operation]] = {}
        self._step = step_source or self._local_steps

    def _local_steps(self) -> int:
        return self.shard.last_step + 1

    # ---- public surface ----

    def submit(self, ops: Iterable[RowOp],
               lock_id: int | None = None) -> Operation:
        op = Operation(self._next_id, list(ops), lock_id)
        self._next_id += 1
        self._active[op.op_id] = op
        self._advance(op)
        return op

    def operation(self, op_id: int) -> Operation | None:
        return self._active.get(op_id) or self._done.get(op_id)

    @property
    def in_flight(self) -> int:
        return len(self._active)

    # ---- the unit machine ----

    def _advance(self, op: Operation) -> None:
        while op.status is Status.ACTIVE:
            op.trace.append(op.unit.value)
            handler = getattr(self, f"_unit_{op.unit.value}")
            try:
                outcome = handler(op)
            except TxRejected as e:
                self._abort(op, str(e))
                return
            if outcome == "wait":
                op.status = Status.WAITING
                return
            # executed: move to the next unit (COMPLETE finishes)
            if op.unit is Unit.COMPLETE:
                return
            op.unit = UNIT_ORDER[UNIT_ORDER.index(op.unit) + 1]

    def _unit_check(self, op: Operation) -> str:
        if not op.ops:
            raise TxRejected("empty operation")
        for row_op in op.ops:
            if row_op.row is not None:
                for col in row_op.row:
                    if col not in self.shard.schema:
                        raise TxRejected(f"unknown column {col}")
        if op.lock_id is not None and self.shard.lock_broken(op.lock_id):
            raise TxRejected(f"lock {op.lock_id} broken")
        return "executed"

    def _unit_build_deps(self, op: Operation) -> str:
        """Key-overlap scan: depend on every EARLIER in-flight
        operation touching a shared key (the reference's dependency
        graph build; conflicts with later ops are their problem)."""
        mine = op.keys
        for other in self._active.values():
            # everything in _active is in flight by construction
            if other.op_id < op.op_id and mine & other.keys:
                op.deps.add(other.op_id)
                self._waiters.setdefault(other.op_id, []).append(op)
        return "executed"

    def _unit_wait_deps(self, op: Operation) -> str:
        live = {d for d in op.deps if d in self._active}
        op.deps = live
        return "wait" if live else "executed"

    def _unit_build_tx(self, op: Operation) -> str:
        op.write_ids = [self.shard.propose(op.ops, lock_id=op.lock_id)]
        return "executed"

    def _unit_prepare(self, op: Operation) -> str:
        try:
            self.shard.prepare(op.write_ids)
        except TxRejected:
            self.shard.abort(op.write_ids)
            raise
        return "executed"

    def _unit_wait_plan(self, op: Operation) -> str:
        if op.step is not None:
            return "executed"
        if self.auto_plan:
            op.step = self._step()
            return "executed"
        return "wait"

    def plan(self, op_id: int, step: int | None = None) -> None:
        """Deliver the plan step to an op parked at WAIT_PLAN (the
        coordinator's TEvPlanStep arrival)."""
        op = self._active.get(op_id)
        if op is None or op.unit is not Unit.WAIT_PLAN:
            raise ValueError(f"op {op_id} is not awaiting a plan step")
        if step is not None and step <= self.shard.last_step:
            # a regressed step would write BENEATH already-committed
            # versions, inverting the order WAIT_DEPS just enforced
            raise ValueError(
                f"plan step {step} <= shard last step "
                f"{self.shard.last_step}")
        op.step = step if step is not None else self._step()
        op.status = Status.ACTIVE
        self._advance(op)

    def _unit_execute(self, op: Operation) -> str:
        # locks validate AT EXECUTION too: a break that lands between
        # prepare and the plan step must still abort (the reference
        # re-checks in the execute unit)
        if op.lock_id is not None and \
                self.shard.lock_broken(op.lock_id):
            self.shard.abort(op.write_ids)
            raise TxRejected(f"lock {op.lock_id} broken")
        self.shard.commit_at(op.write_ids, op.step)
        return "executed"

    def _unit_complete(self, op: Operation) -> str:
        op.status = Status.DONE
        self._retire(op)
        return "executed"

    # ---- completion / abort plumbing ----

    def _retire(self, op: Operation) -> None:
        self._active.pop(op.op_id, None)
        self._done[op.op_id] = op
        while len(self._done) > self.done_history:
            self._done.popitem(last=False)
        # wake waiters: each RESTARTS at its current unit (WAIT_DEPS),
        # re-evaluating its remaining dependencies
        for waiter in self._waiters.pop(op.op_id, []):
            if waiter.status is Status.WAITING:
                waiter.status = Status.ACTIVE
                self._advance(waiter)

    def _abort(self, op: Operation, reason: str) -> None:
        op.status = Status.ABORTED
        op.error = reason
        self._retire(op)
