"""Streaming MVCC read sessions with credit flow + continuation.

Mirror of the reference's read-iterator protocol (TEvRead /
TEvReadResult / TEvReadAck, ydb/core/tx/datashard/
datashard__read_iterator.cpp; client side kqp_read_actor.cpp:46;
SURVEY.md §2.6 row "Read iterator"): the OLTP streaming read path.

Contract mirrored:
  * a session pins one snapshot; rows stream in quota-bounded pages
    and later commits never appear mid-stream (repeatable read);
  * credit flow: the server sends at most the granted row quota and
    then stalls until the client acks more (TEvReadAck) — the
    slow-consumer backpressure that keeps server memory bounded;
  * every page carries a continuation token (the last delivered PK);
    a session can be re-opened from a token against the SAME shard or
    a REBOOTED incarnation of it and resumes exactly after the last
    delivered row — the retry contract the reference's client actor
    leans on for shard restarts/splits.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class ReadPage:
    rows: list          # [(key, row)]
    continuation: tuple | None   # last delivered PK (resume token)
    finished: bool


class ReadIterator:
    """One streaming read session over a DataShard."""

    def __init__(self, shard, snapshot: int,
                 lo: tuple | None = None, hi: tuple | None = None,
                 columns: tuple | None = None,
                 quota_rows: int = 1024,
                 continuation: tuple | None = None):
        self.shard = shard
        self.snapshot = snapshot
        self.lo = lo
        self.hi = hi
        self.columns = columns
        self.credit = quota_rows
        self.continuation = continuation
        self.finished = False

    def ack(self, quota_rows: int) -> None:
        """Grant more row quota (TEvReadAck)."""
        self.credit += quota_rows

    def next_page(self, page_rows: int = 256) -> ReadPage | None:
        """Next quota-bounded page, or None when stalled on credit.
        Raises VolatileUndecided if the range hits an undecided
        volatile tx (the reference blocks the iterator there)."""
        if self.finished:
            return ReadPage([], self.continuation, True)
        if self.credit <= 0:
            return None  # out of quota: wait for ack()
        take = min(page_rows, self.credit)
        start = self.continuation if self.continuation is not None \
            else self.lo
        rows: list = []
        for page in self.shard.read(self.snapshot, lo=start,
                                    hi=self.hi, columns=self.columns,
                                    page_rows=take + 1):
            for key, row in page:
                # lo is inclusive; a continuation resumes AFTER it
                if self.continuation is not None \
                        and key <= self.continuation:
                    continue
                rows.append((key, row))
                if len(rows) > take:
                    break
            if len(rows) > take:
                break
        more = len(rows) > take
        rows = rows[:take]
        self.credit -= len(rows)
        if rows:
            self.continuation = rows[-1][0]
        if not more:
            self.finished = True
        return ReadPage(rows, self.continuation, self.finished)

    def resume_token(self) -> dict:
        """Serializable session state for reopening elsewhere/later."""
        return {
            "snapshot": self.snapshot,
            "lo": self.lo, "hi": self.hi,
            "columns": self.columns,
            "continuation": self.continuation,
        }

    @classmethod
    def from_token(cls, shard, token: dict,
                   quota_rows: int = 1024) -> "ReadIterator":
        return cls(shard, token["snapshot"], lo=token["lo"],
                   hi=token["hi"], columns=token["columns"],
                   quota_rows=quota_rows,
                   continuation=token["continuation"])
