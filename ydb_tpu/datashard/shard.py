"""DataShard: the row-store OLTP tablet.

Mirror of the reference's DataShard (tx/datashard, SURVEY.md §2.6) on the
tablet executor: rows live in the MVCC local DB versioned by *global plan
steps* (not the tablet's own commit counter), so cross-shard reads at a
coordinator snapshot are consistent — exactly the reference's
planned-step execution (datashard_pipeline.h) without the 60-unit state
machine: the executor's single-writer discipline plus the coordinator's
step order give the same serialization.

Write path (the 2PC participant contract shared with ColumnShard, so one
Coordinator drives either):
  * ``propose(ops)``    -> write_id: durably stage the tx's effects
                           (upsert/erase rows) — the pipeline's
                           check/store units
  * ``prepare([ids])``  -> validates locks, returns the ids (2PC vote)
  * ``commit_at(ids, step)`` applies effects at version=step
  * ``abort(ids)``      drops staged effects

Read path: ``read(...)`` — MVCC range/point reads at a snapshot step with
paging (TEvRead / read-iterator analog, datashard__read_iterator.cpp).

Optimistic locks (datashard locks analog): ``acquire_lock`` records the
read ranges; any committed write intersecting them breaks the lock;
``prepare`` fails for a tx that declares a broken lock, aborting the 2PC.
Locks are in-memory only — a shard restart breaks them all, as in the
reference.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Iterator

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.tablet.executor import TabletExecutor, Transaction


class VolatileUndecided(Exception):
    """A read hit the key range of a volatile tx whose cross-shard
    decision is still outstanding; the reader must wait for the
    readset exchange to settle (the reference blocks the read iterator
    on TVolatileTxManager, datashard__read_iterator.cpp)."""


class TxRejected(Exception):
    pass


class LockBroken(TxRejected):
    pass


@dataclasses.dataclass
class RowOp:
    """One effect: row upsert (row != None) or erase (row == None)."""

    key: tuple
    row: dict | None


@dataclasses.dataclass
class _Lock:
    lock_id: int
    ranges: list[tuple[tuple | None, tuple | None]]
    points: set[tuple]
    broken: bool = False

    def covers(self, key: tuple) -> bool:
        if key in self.points:
            return True
        for lo, hi in self.ranges:
            if (lo is None or key >= lo) and (hi is None or key < hi):
                return True
        return False


class _ProposeTx(Transaction):
    def __init__(self, write_id: int, ops: list[RowOp], lock_id, expect):
        self.write_id = write_id
        self.ops = ops
        self.lock_id = lock_id
        self.expect = expect

    def execute(self, txc, tablet):
        txc.put("pending", (self.write_id,), {
            "ops": [[list(o.key), o.row] for o in self.ops],
            "lock_id": self.lock_id,
            "expect": self.expect,
        })
        # same commit as the staged tx: a crash can never reuse a write
        # id that a durable pending entry already owns
        txc.put("meta", ("next_write",), {"v": self.write_id + 1})


class _CommitTx(Transaction):
    def __init__(self, shard: "DataShard", write_ids: list[int], step: int):
        self.shard = shard
        self.write_ids = write_ids
        self.step = step

    def execute(self, txc, tablet):
        cdc = self.shard.cdc_enabled
        seq_row = txc.get("meta", ("next_change",)) if cdc else None
        change_seq = seq_row["v"] if seq_row else 0
        staged: dict[tuple, dict | None] = {}  # writes within THIS commit
        for wid in self.write_ids:
            pend = txc.get("pending", (wid,))
            if pend is None:
                raise TxRejected(f"no staged tx {wid}")
            for key_list, row in pend["ops"]:
                key = tuple(key_list)
                if cdc:
                    # change collector (change_collector.h analog): the
                    # record commits IN the data transaction, so the
                    # stream never misses or invents a change; a second
                    # write to the same key in this commit must see the
                    # first as its old image, not the committed state
                    old = (staged[key] if key in staged
                           else txc.get("data", key))
                    txc.put("changes", (change_seq,), {
                        "key": list(key), "old": old, "new": row,
                        "step": self.step,
                    })
                    change_seq += 1
                    staged[key] = row
                txc.put_at("data", key, row, self.step)
                self.shard._break_locks(key)
            txc.erase("pending", (wid,))
        if cdc:
            txc.put("meta", ("next_change",), {"v": change_seq})
        txc.put("meta", ("last_step",), {"v": self.step})


class _AbortTx(Transaction):
    def __init__(self, write_ids: list[int]):
        self.write_ids = write_ids

    def execute(self, txc, tablet):
        for wid in self.write_ids:
            txc.erase("pending", (wid,))


@dataclasses.dataclass
class _VolatileTx:
    """An optimistically-applied distributed tx awaiting peer readsets
    (TVolatileTxManager analog, volatile_tx.h:91). Effects live only
    in this in-memory record until the decision — a shard restart
    forgets undecided volatile txs, which is exactly the reference's
    contract (volatile = not yet persistent)."""

    txid: int
    step: int
    write_ids: list
    keys: set
    expected: set   # peer participant ids whose readsets are awaited
    received: dict  # peer id -> bool


class DataShard:
    def __init__(self, shard_id: str, schema: dtypes.Schema,
                 store: BlobStore, pk_columns: tuple[str, ...]):
        self.shard_id = shard_id
        self.schema = schema
        self.pk_columns = tuple(pk_columns)
        self.executor = TabletExecutor.boot(f"ds/{shard_id}", store)
        row = self.executor.db.table("meta").get(("next_write",))
        self._write_ids = itertools.count(row["v"] if row else 1)
        self._locks: dict[int, _Lock] = {}
        self._next_lock = itertools.count(1)
        self.cdc_enabled = False
        self._volatile: dict[int, _VolatileTx] = {}

    # ---- MVCC state ----

    @property
    def last_step(self) -> int:
        row = self.executor.db.table("meta").get(("last_step",))
        return row["v"] if row else 0

    # interface parity with ColumnShard (cluster boot resumes the
    # coordinator clock from max shard snapshot)
    @property
    def snap(self) -> int:
        return self.last_step

    # ---- write path (2PC participant) ----

    def propose(self, ops: list[RowOp], lock_id: int | None = None,
                expect: dict | None = None) -> int:
        """Durably stage effects; returns the write id (2PC token).

        ``expect``: optional per-key preconditions, {key: row_or_None}
        checked under the executor at prepare time — the
        read-your-locks validation for interactive INSERT (fail if
        exists) semantics.
        """
        wid = next(self._write_ids)
        exp = (
            [[list(k), v] for k, v in expect.items()]
            if expect is not None else None
        )
        self.executor.execute(_ProposeTx(wid, ops, lock_id, exp))
        return wid

    def prepare(self, write_ids: list[int]) -> list[int]:
        for wid in write_ids:
            pend = self.executor.db.table("pending").get((wid,))
            if pend is None:
                raise TxRejected(f"unknown write id {wid}")
            lock_id = pend.get("lock_id")
            if lock_id is not None:
                lock = self._locks.get(lock_id)
                if lock is None or lock.broken:
                    raise LockBroken(f"lock {lock_id} is broken")
            # an undecided volatile write to any of this tx's keys is
            # ordered BEFORE it but not yet in the data table: both
            # expect-preconditions and blind writes must wait for (or
            # conservatively reject on) the outstanding decision, like
            # the read-path fence — otherwise fail-if-exists could pass
            # against a key a decided-later volatile insert owns
            for key_list, _row in pend["ops"]:
                key = tuple(key_list)
                for vt in self._volatile.values():
                    if key in vt.keys:
                        raise TxRejected(
                            f"key {key} has an undecided volatile "
                            f"write (tx {vt.txid})")
            for key_list, want in pend.get("expect") or []:
                key = tuple(key_list)
                have = self.executor.db.table("data").get(key)
                if (have is None) != (want is None):
                    raise TxRejected(
                        f"precondition failed for key {key}")
        return list(write_ids)

    def commit_at(self, write_ids: list[int], step: int) -> int:
        self.executor.execute(_CommitTx(self, write_ids, step))
        return step

    def abort(self, write_ids: list[int]) -> None:
        self.executor.execute(_AbortTx(write_ids))

    # ---- volatile distributed commit (volatile_tx.h:91 analog) ----

    def apply_volatile(self, write_ids: list[int], txid: int,
                       step: int, expected_peers) -> bool:
        """Validate + optimistically accept a planned volatile tx
        WITHOUT waiting for peers' outcomes (no prepare round-trip):
        on success the tx is recorded undecided and its keys are
        fenced from snapshot readers until the readset exchange
        settles. Local failure aborts the staged writes immediately
        and returns False (the readset this shard sends its peers)."""
        try:
            self.prepare(write_ids)
        except TxRejected:
            self.abort(write_ids)
            return False
        keys = set()
        for wid in write_ids:
            pend = self.executor.db.table("pending").get((wid,))
            for key_list, _row in pend["ops"]:
                keys.add(tuple(key_list))
        self._volatile[txid] = _VolatileTx(
            txid, step, list(write_ids), keys,
            set(expected_peers), {})
        # conflicting optimistic readers must learn NOW, not at the
        # decision: the write is already ordered at `step`
        for key in keys:
            self._break_locks(key)
        return True

    def deliver_readset(self, txid: int, from_peer,
                        ok: bool) -> bool | None:
        """Record a peer's outcome (TEvReadSet analog). Returns the
        decision once it settles: True committed, False rolled back,
        None still undecided / unknown tx."""
        vt = self._volatile.get(txid)
        if vt is None:
            return None
        if not ok:
            self.executor.execute(_AbortTx(vt.write_ids))
            del self._volatile[txid]
            return False
        vt.received[from_peer] = True
        if set(vt.received) >= vt.expected:
            # decision: effects become durable at the planned step
            self.executor.execute(
                _CommitTx(self, vt.write_ids, vt.step))
            del self._volatile[txid]
            return True
        return None

    def abort_volatile(self, txid: int) -> None:
        """Locally roll back an undecided volatile tx (restart/timeout
        path: volatile effects are never durable before the decision)."""
        vt = self._volatile.pop(txid, None)
        if vt is not None:
            self.executor.execute(_AbortTx(vt.write_ids))

    def _volatile_fence(self, snapshot: int, lo, hi, keys) -> None:
        """Raise VolatileUndecided when the request intersects an
        undecided volatile tx ordered at or before the snapshot."""
        for vt in self._volatile.values():
            if vt.step > snapshot:
                continue
            if keys is not None:
                if vt.keys.intersection(tuple(k) for k in keys):
                    raise VolatileUndecided(
                        f"tx {vt.txid} at step {vt.step} undecided")
            else:
                for k in vt.keys:
                    if (lo is None or k >= lo) and \
                            (hi is None or k < hi):
                        raise VolatileUndecided(
                            f"tx {vt.txid} at step {vt.step} undecided")

    # ---- read path (read iterator) ----

    def read(
        self,
        snapshot: int,
        lo: tuple | None = None,
        hi: tuple | None = None,
        keys: list[tuple] | None = None,
        columns: tuple[str, ...] | None = None,
        page_rows: int = 1024,
        lock_id: int | None = None,
    ) -> Iterator[list[tuple[tuple, dict]]]:
        """Stream pages of (key, row) visible at the snapshot step.

        With ``lock_id``, the scanned range/points are recorded on the
        lock so later conflicting commits break it (optimistic tx).
        Registration happens HERE, eagerly — not when the returned
        iterator is first consumed — so a conflict in the gap between
        opening and draining the read still breaks the lock.
        """
        if lock_id is not None:
            lock = self._locks.setdefault(
                lock_id, _Lock(lock_id, [], set()))
            if keys is not None:
                lock.points.update(tuple(k) for k in keys)
            else:
                lock.ranges.append((lo, hi))
        self._volatile_fence(snapshot, lo, hi, keys)
        return self._read_pages(snapshot, lo, hi, keys, columns,
                                page_rows)

    def _read_pages(self, snapshot, lo, hi, keys, columns, page_rows):
        table = self.executor.db.table("data")
        page: list[tuple[tuple, dict]] = []
        if keys is not None:
            for key in keys:
                row = table.get(tuple(key), version=snapshot)
                if row is not None:
                    page.append((tuple(key), _project(row, columns)))
                if len(page) >= page_rows:
                    yield page
                    page = []
        else:
            for key, row in table.range(lo, hi, version=snapshot):
                page.append((key, _project(row, columns)))
                if len(page) >= page_rows:
                    yield page
                    page = []
        if page:
            yield page

    # ---- locks ----

    def acquire_lock(self) -> int:
        lock_id = next(self._next_lock)
        self._locks[lock_id] = _Lock(lock_id, [], set())
        return lock_id

    def lock_broken(self, lock_id: int) -> bool:
        lock = self._locks.get(lock_id)
        return lock is None or lock.broken

    def release_lock(self, lock_id: int) -> None:
        self._locks.pop(lock_id, None)

    def _break_locks(self, key: tuple) -> None:
        for lock in self._locks.values():
            if not lock.broken and lock.covers(key):
                lock.broken = True

    # ---- CDC change queue (change sender source) ----

    def pending_changes(self, limit: int = 1000) -> list[dict]:
        """Durable change records not yet shipped (seq-ordered)."""
        out = []
        for key, row in self.executor.db.table("changes").range():
            out.append(dict(row, seq=key[0]))
            if len(out) >= limit:
                break
        return out

    def ack_changes(self, up_to_seq: int) -> None:
        """Forget shipped change records (<= up_to_seq)."""
        shard = self

        class Tx(Transaction):
            def execute(self, txc, tablet):
                for key, _row in shard.executor.db.table(
                        "changes").range(hi=(up_to_seq + 1,)):
                    txc.erase("changes", key)

        self.executor.execute(Tx())

    # ---- maintenance ----

    def compact(self, keep_after: int) -> None:
        """Collapse row version chains invisible below keep_after."""
        self.executor.db.table("data").compact(keep_after)

    def checkpoint(self) -> None:
        self.executor.checkpoint()


def _project(row: dict, columns: tuple[str, ...] | None) -> dict:
    if columns is None:
        return row
    return {c: row.get(c) for c in columns}
