"""RowTable: a sharded row-store table (OLTP) behind the same surface as
the columnar ShardedTable, so SQL and the coordinator treat both alike.

Reference shape: DataShard tablets partitioned by PK with distributed
commits through the coordinator (SURVEY.md §2.6, §3.2 COMMIT); the
KQP-facing difference from the OLAP path is point/range row access and
in-place UPDATE/DELETE, which columnar portions don't do.

Strings are encoded through the cluster-shared DictionarySet before any
durable write (same id-agreement rule as ShardedTable), with the same
pre_commit journaling hook.
"""

from __future__ import annotations

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.analysis import host_ok
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.datashard.shard import DataShard, RowOp
from ydb_tpu.tx.coordinator import Coordinator, TxResult
from ydb_tpu.tx.sharded import _fnv_route


class RowTable:
    store_kind = "row"

    def __init__(
        self,
        name: str,
        schema: dtypes.Schema,
        store: BlobStore,
        coordinator: Coordinator,
        n_shards: int = 4,
        pk_column: str | None = None,
        pk_columns: tuple[str, ...] | None = None,
        dicts: DictionarySet | None = None,
        boot: bool = False,  # DataShard.boot is implicit (executor boot)
        ttl_column: str | None = None,
        gen: int = 0,
    ):
        self.name = name
        self.schema = schema
        self.coordinator = coordinator
        self.pk_columns = tuple(
            pk_columns if pk_columns else
            (pk_column or schema.names[0],))
        self.pk_column = self.pk_columns[0]
        self.ttl_column = ttl_column
        self.store = store
        self.gen = gen
        self.dicts = dicts if dicts is not None else DictionarySet()
        self.shards = [
            DataShard(self._shard_id(gen, i), schema, store,
                      self.pk_columns)
            for i in range(n_shards)
        ]
        self.schema_version = 1
        self.column_added: dict[str, int] = {}
        self.pre_commit = None
        # secondary indexes: name -> (column, [index DataShards keyed
        # (value, *pk)]); maintained ATOMICALLY with data writes — index
        # shards join the same 2PC (the reference maintains indeximpl
        # tables in the same distributed tx, datashard build_index /
        # change exchange for indexes)
        self.indexes: dict[str, tuple[str, list]] = {}

    def post_boot_sweep(self) -> None:
        """Crash-safe DROP COLUMN: if a prior strip (alter_schema) died
        between the scheme commit and the rewrite, stale values would
        resurrect on a later re-ADD. The cluster calls this on boot only
        when the scheme tablet holds a pending-strip marker for this
        table (and once the real coordinator clock is installed)."""
        self._strip_columns(keep=set(self.schema.names))

    def _shard_id(self, gen: int, i: int) -> str:
        return (f"{self.name}/g{gen}/{i}" if gen else f"{self.name}/{i}")

    def storage_prefixes(self) -> list[str]:
        """Blob-store prefixes owning this table's durable state —
        INDEX shards included (DROP TABLE deletes them so a same-name
        CREATE + same-name index starts empty, no resurrection)."""
        out = [f"tablet/{s.executor.tablet_id}/" for s in self.shards]
        for _, idx_shards in self.indexes.values():
            out += [f"tablet/{s.executor.tablet_id}/"
                    for s in idx_shards]
        return out

    # ---- split / merge (resharding) ----

    def reshard(self, n_new: int) -> int:
        """SPLIT/MERGE for the row store: stream every row at one
        snapshot out of the old shards into ``n_new`` new DataShards
        (generation gen+1), then swap. The CALLER records (n_new, gen)
        durably in the scheme (Cluster.reshard_table); until then a
        reboot serves the old generation and sweeps the new one.
        Secondary indexes rebuild by re-registration after the swap
        (the backfill is index-build, already online)."""
        if n_new < 1:
            raise ValueError("reshard needs n_new >= 1")
        if self.indexes:
            raise ValueError(
                "drop secondary indexes before resharding (re-add to"
                " rebuild against the new shards)")
        new_gen = self.gen + 1
        snap = self.coordinator.read_snapshot()
        new_shards = [
            DataShard(self._shard_id(new_gen, i), self.schema,
                      self.store, self.pk_columns)
            for i in range(n_new)
        ]
        ops: list[RowOp] = []

        def flush():
            proposed = _route_propose(new_shards, ops)
            if proposed:
                self.coordinator.commit(
                    [s for s, _ in proposed], [[w] for _, w in proposed])
            ops.clear()

        for shard in self.shards:
            for page in shard.read(snap):
                for key, row in page:
                    ops.append(RowOp(tuple(key), dict(row)))
                if len(ops) >= 4096:
                    flush()
        flush()
        self.shards = new_shards
        self.gen = new_gen
        return new_gen

    def drop_generation_storage(self, gen: int, n_shards: int) -> None:
        """Delete a superseded generation's tablet state."""
        for i in range(n_shards):
            prefix = f"tablet/ds/{self._shard_id(gen, i)}/"
            for bid in self.store.list(prefix):
                self.store.delete(bid)

    def sweep_stale_generations(self) -> int:
        """Boot-time sweep of shard generations other than the current
        one (crash mid-reshard orphans)."""
        keep = tuple(f"tablet/{s.executor.tablet_id}/"
                     for s in self.shards)
        for _, idx_shards in self.indexes.values():
            keep += tuple(f"tablet/{s.executor.tablet_id}/"
                          for s in idx_shards)
        swept = 0
        for bid in self.store.list(f"tablet/ds/{self.name}/"):
            if "/idx_" in bid:
                # index storage is managed by add_index/DROP TABLE, and
                # index registrations are not (yet) scheme-durable — a
                # reboot must not garbage-collect them
                continue
            if not bid.startswith(keep):
                self.store.delete(bid)
                swept += 1
        return swept

    # ---- encode helpers (shared dict ids, scaled decimals) ----

    def _encode_columns(self, columns: dict, validity=None) -> list[dict]:
        """Columnar input -> list of physical row dicts (None = NULL)."""
        n = len(next(iter(columns.values())))
        enc: dict[str, list] = {}
        for name in columns:
            f = self.schema.field(name)
            vals = columns[name]
            if f.type.is_string:
                d = self.dicts.for_column(name)
                enc[name] = [int(d.add(_as_bytes(v))) for v in vals]
            else:
                arr = np.asarray(vals)
                enc[name] = [_py(v) for v in arr]
        rows = []
        for i in range(n):
            row = {}
            for name in enc:
                ok = True
                if validity is not None and name in validity:
                    ok = bool(np.asarray(validity[name])[i])
                row[name] = enc[name][i] if ok else None
            rows.append(row)
        return rows

    def _key_of(self, row: dict) -> tuple:
        return tuple(row[c] for c in self.pk_columns)

    def _route(self, keys: list[tuple]) -> np.ndarray:
        first = np.asarray([k[0] for k in keys], dtype=np.int64)
        return _fnv_route(first, len(self.shards))

    # ---- writes (2PC across shards) ----

    @host_ok("row-store DML: routing, index maintenance and 2PC"
             " staging operate on host rows by design (the row table"
             " is the OLTP side; the analytic path never enters here)")
    def propose_ops(self, per_row_ops: list[RowOp],
                    lock_ids: dict[int, int] | None = None
                    ) -> tuple[list, list]:
        """Durably stage ops (and index maintenance) on their shards;
        returns (participants, prepare_args) for a coordinator commit.
        Interactive transactions combine several tables' proposals
        into ONE atomic commit this way."""
        if self.pre_commit is not None:
            self.pre_commit()
        route = self._route([op.key for op in per_row_ops])
        participants, prepare_args = [], []
        for i, shard in enumerate(self.shards):
            ops = [op for op, r in zip(per_row_ops, route) if r == i]
            if not ops and not (lock_ids and i in lock_ids):
                continue
            wid = shard.propose(
                ops, lock_id=lock_ids.get(i) if lock_ids else None)
            participants.append(shard)
            prepare_args.append([wid])
        if self.indexes and per_row_ops:
            # ONE old-row read serves every index
            old_rows = self.read_rows([op.key for op in per_row_ops])
            for col, idx_shards in self.indexes.values():
                idx_ops = self._index_ops(col, per_row_ops, old_rows)
                for shard, wid in _route_propose(idx_shards, idx_ops):
                    participants.append(shard)
                    prepare_args.append([wid])
        return participants, prepare_args

    def _commit_ops(self, per_row_ops: list[RowOp],
                    lock_ids: dict[int, int] | None = None) -> TxResult:
        """lock_ids: shard index -> optimistic lock the tx validated
        under; prepare fails (aborting the 2PC) if it broke."""
        participants, prepare_args = self.propose_ops(per_row_ops,
                                                      lock_ids)
        # multi-shard row commits take the volatile path: no prepare
        # round-trip under the coordinator's commit lock, outcomes
        # exchanged as readsets (volatile_tx.h; VERDICT missing #9)
        return self.coordinator.commit_volatile(participants,
                                                prepare_args)

    # ---- secondary indexes ----

    def _index_ops(self, col: str, per_row_ops, old_rows) -> list[RowOp]:
        """Index maintenance ops mirroring ``per_row_ops``: erase the
        old (value, pk) entry when the value changes or the row dies;
        put the new one. NULL values are not indexed. The same key
        appearing twice in one batch chains (last write wins, exactly
        like the data shard's apply order)."""
        idx_pk = (col,) + tuple(self.pk_columns)
        cur: dict[tuple, object] = {}  # key -> value as the batch runs
        idx_ops: list[RowOp] = []
        for op in per_row_ops:
            if op.key in cur:
                old_v = cur[op.key]
            else:
                old = old_rows.get(op.key)
                old_v = old.get(col) if old else None
            new_v = op.row.get(col) if op.row is not None else None
            cur[op.key] = new_v
            if old_v is not None and old_v != new_v:
                idx_ops.append(RowOp((old_v,) + op.key, None))
            if new_v is not None and new_v != old_v:
                idx_ops.append(
                    RowOp((new_v,) + op.key,
                          dict(zip(idx_pk, (new_v,) + op.key))))
        return idx_ops

    def add_index(self, name: str, column: str) -> None:
        """Create a global secondary index on ``column`` and backfill it
        online: the index registers FIRST (new writes maintain it), then
        existing rows backfill at a snapshot — the online index-build
        shape (datashard build_index.cpp)."""
        if column in self.pk_columns:
            raise ValueError("column is already the primary key")
        if name in self.indexes:
            raise ValueError(f"index {name} already exists")
        fields = [self.schema.field(column)] + [
            self.schema.field(c) for c in self.pk_columns
        ]
        idx_schema = dtypes.Schema(tuple(fields))
        idx_pk = (column,) + tuple(self.pk_columns)
        idx_shards = [
            DataShard(f"{self.name}/idx_{name}/{i}", idx_schema,
                      self.shards[0].executor.store, idx_pk)
            for i in range(len(self.shards))
        ]
        self.indexes[name] = (column, idx_shards)
        # online backfill at a snapshot; rows written after registration
        # are maintained by the normal write path (idempotent upserts)
        snap = self.coordinator.read_snapshot()
        backfill: list[RowOp] = []
        for shard in self.shards:
            for page in shard.read(snap):
                for key, row in page:
                    v = row.get(column)
                    if v is None:
                        continue
                    backfill.append(RowOp(
                        (v,) + key, dict(zip(idx_pk, (v,) + key))))
        proposed = _route_propose(idx_shards, backfill)
        if proposed:
            self.coordinator.commit(
                [s for s, _ in proposed], [[w] for _, w in proposed])

    def lookup_index(self, name: str, value) -> list[tuple]:
        """Primary keys of rows where the indexed column == value."""
        col, idx_shards = self.indexes[name]
        f = self.schema.field(col)
        if f.type.is_string and not isinstance(value, int):
            v = self.dicts.for_column(col).get(_as_bytes(value))
            if v is None:
                return []
        else:
            v = _py(np.asarray(value)) if not isinstance(value, int) \
                else value
        snap = self.coordinator.read_snapshot()
        shard = idx_shards[int(_fnv_route(
            np.asarray([v], dtype=np.int64), len(idx_shards))[0])]
        out = []
        for page in shard.read(snap, lo=(v,)):
            for key, _row in page:
                if key[0] != v:
                    return out
                out.append(tuple(key[1:]))
        return out

    def insert(self, columns: dict, validity=None) -> TxResult:
        """Upsert semantics (same surface as ShardedTable.insert)."""
        return self._commit_ops(self.insert_ops(columns, validity))

    def insert_ops(self, columns: dict, validity=None) -> list[RowOp]:
        """The insert's effects as RowOps, uncommitted (interactive-
        transaction buffering seam)."""
        rows = self._encode_columns(columns, validity)
        return [RowOp(self._key_of(r), r) for r in rows]

    def upsert_rows(self, rows: list[dict]) -> TxResult:
        return self._commit_ops(
            [RowOp(self._key_of(r), r) for r in rows])

    def delete_keys(self, keys: list[tuple]) -> TxResult:
        return self._commit_ops([RowOp(tuple(k), None) for k in keys])

    # ---- reads ----

    def read_row(self, key: tuple, snap: int | None = None) -> dict | None:
        rows = self.read_rows([tuple(key)], snap)
        return rows.get(tuple(key))

    def read_rows(self, keys: list[tuple],
                  snap: int | None = None) -> dict[tuple, dict]:
        """Batched point reads: one shard.read per shard, not per key."""
        snap = (self.coordinator.read_snapshot()
                if snap is None else snap)
        keys = [tuple(k) for k in keys]
        out: dict[tuple, dict] = {}
        if not keys:
            return out
        route = self._route(keys)
        for i, shard in enumerate(self.shards):
            mine = [k for k, r in zip(keys, route) if r == i]
            if not mine:
                continue
            for page in shard.read(snap, keys=mine):
                out.update(page)
        return out

    def lock_all_shards(self) -> dict[int, int]:
        """Full-range optimistic lock on every shard (the coarse
        serialization UPDATE/DELETE read-modify-write uses); returns
        shard index -> lock id."""
        locks = {}
        for i, shard in enumerate(self.shards):
            lk = shard.acquire_lock()
            shard.read(0, lock_id=lk)  # registers the (None, None) range
            locks[i] = lk
        return locks

    def release_locks(self, locks: dict[int, int]) -> None:
        for i, lk in locks.items():
            self.shards[i].release_lock(lk)

    def source_at(self, snap: int | None = None,
                  columns: tuple[str, ...] | None = None) -> ColumnSource:
        """Materialize visible rows as a ColumnSource: the seam that lets
        the OLAP scan/SSA path run over a row table."""
        snap = (self.coordinator.read_snapshot()
                if snap is None else snap)
        names = columns if columns is not None else self.schema.names
        names = tuple(n for n in names if n in self.schema)
        cols: dict[str, list] = {n: [] for n in names}
        valid: dict[str, list] = {n: [] for n in names}
        for shard in self.shards:
            for page in shard.read(snap):
                for _key, row in page:
                    for n in names:
                        v = row.get(n)  # absent (pre-ALTER row) = NULL
                        cols[n].append(0 if v is None else v)
                        valid[n].append(v is not None)
        out_c = {}
        out_v = {}
        for n in names:
            f = self.schema.field(n)
            out_c[n] = (np.asarray(cols[n], dtype=f.type.physical)
                        if cols[n] else
                        np.empty(0, dtype=f.type.physical))
            out_v[n] = (np.asarray(valid[n], dtype=bool) if valid[n]
                        else np.empty(0, dtype=bool))
        sch = self.schema.select(names)
        return ColumnSource(out_c, sch, self.dicts, out_v)

    # ---- schema evolution ----

    def alter_schema(self, schema, schema_version=1, column_added=None):
        had_drops = any(n not in schema for n in self.schema.names)
        self.schema = schema
        self.schema_version = schema_version
        self.column_added = dict(column_added or {})
        for s in self.shards:
            s.schema = schema
        # physically strip dropped columns so a later re-ADD of the name
        # cannot resurrect old values (row dicts would otherwise keep
        # them forever); the boot-time sweep repeats this if a crash
        # interrupts it here
        if had_drops:
            self._strip_columns(keep=set(schema.names))

    def _strip_columns(self, keep: set[str]) -> None:
        snap = self.coordinator.read_snapshot()
        for shard in self.shards:
            ops = []
            for page in shard.read(snap):
                for key, row in page:
                    if any(n not in keep for n in row):
                        ops.append(RowOp(
                            key,
                            {k: v for k, v in row.items() if k in keep}))
            if ops:
                # internal rewrite: must not emit changefeed events (a
                # consumer would see phantom updates that also leak the
                # dropped column's values)
                was_cdc = shard.cdc_enabled
                shard.cdc_enabled = False
                try:
                    wid = shard.propose(ops)
                    self.coordinator.commit([shard], [[wid]])
                finally:
                    shard.cdc_enabled = was_cdc

    # ---- CDC (change exchange; SURVEY.md §2.6) ----

    def enable_cdc(self) -> None:
        for s in self.shards:
            s.cdc_enabled = True

    def drain_changes_to(self, topic) -> int:
        """Change sender (change_sender*.cpp analog): ship each shard's
        durable change queue to the changefeed topic, then ack. The
        topic's producer-seqno dedup makes redelivery after a crash
        between write and ack exactly-once."""
        import json as _json

        shipped = 0
        for shard in self.shards:
            changes = shard.pending_changes()
            if not changes:
                continue
            for ch in changes:
                # per-change seqno write: shard seqs are monotonic but
                # not contiguous per partition, so no batch renumbering
                p = topic.partition_for(_json.dumps(ch["key"]))
                topic.partitions[p].write(
                    [{"data": _json.dumps({
                        "key": ch["key"], "old": ch["old"],
                        "new": ch["new"], "step": ch["step"],
                    })}],
                    producer=f"cdc/{shard.shard_id}",
                    first_seqno=ch["seq"],
                )
            shard.ack_changes(changes[-1]["seq"])
            shipped += len(changes)
        return shipped

    # ---- background ----

    def run_background(self, ttl_cutoff: int | None = None) -> dict:
        evicted = 0
        if ttl_cutoff is not None and self.ttl_column is not None:
            snap = self.coordinator.read_snapshot()
            for shard in self.shards:
                doomed = []
                for page in shard.read(snap):
                    for key, row in page:
                        v = row.get(self.ttl_column)
                        if v is not None and v < ttl_cutoff:
                            doomed.append(key)
                if doomed:
                    self.delete_keys(doomed)
                    evicted += len(doomed)
        horizon = self.coordinator.read_snapshot()
        for shard in self.shards:
            shard.compact(keep_after=horizon)
        return {"compacted": len(self.shards), "evicted": evicted}


def _route_propose(shards: list, ops: list[RowOp]) -> list[tuple]:
    """fnv-route ops by first key component and propose per shard;
    returns [(shard, write_id)] (shared by the commit path, index
    maintenance and index backfill)."""
    if not ops:
        return []
    first = np.asarray([op.key[0] for op in ops], dtype=np.int64)
    route = _fnv_route(first, len(shards))
    out = []
    for i, shard in enumerate(shards):
        mine = [op for op, r in zip(ops, route) if r == i]
        if mine:
            out.append((shard, shard.propose(mine)))
    return out


def _as_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode()
    return bytes(v)


def _py(v):
    """numpy scalar -> plain python (rows are JSON in the WAL)."""
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.integer, np.bool_)):
        return int(v)
    return v
