from ydb_tpu.datashard.shard import DataShard, LockBroken, TxRejected
from ydb_tpu.datashard.table import RowTable

__all__ = ["DataShard", "RowTable", "LockBroken", "TxRejected"]
