from ydb_tpu.fq.service import FederatedQueryService, StreamingQuery

__all__ = ["FederatedQueryService", "StreamingQuery"]
