"""Federated/streaming query service: continuous SQL over topics.

Mirror of the reference's FQ platform (ydb/core/fq/libs: control
plane storing query definitions, row dispatcher reading shared topic
partitions, checkpoint coordinator persisting operator state —
checkpoint_coordinator.h:25, checkpoint_storage/; SURVEY.md §2.13 row
"FQ / streaming platform"), built on this framework's own planes:

  * source/sink are PersQueue topics; rows travel as JSON objects;
  * each poll() processes one micro-batch through the REAL SQL path
    (parse -> plan -> device execution on a batch ColumnSource) and
    folds the batch aggregates into durable running state — the
    incremental shape of the reference's task graph with a
    WideCombiner state, expressed as batch-fold;
  * exactly-once effects: the tablet checkpoint (source offset, agg
    state, emit seqno) commits AFTER the sink write, and sink writes
    carry producer seqnos — a crash between sink write and checkpoint
    replays the batch, and the PQ producer-dedup drops the duplicate
    emission (topic/pq.py _WriteTx seqno guard). The checkpointing
    contract of dq/checkpoint.py at the service level.

Query shape supported: SELECT <keys and aggregates> FROM stream
[WHERE ...] [GROUP BY ...] with count/sum/min/max (aggregates must be
fold-combinable; avg rewrites to sum+count pairs at the edge are the
caller's concern, matching the two-phase-agg restriction).
"""

from __future__ import annotations

import json

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.sql import ast
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select_full
from ydb_tpu.tablet.executor import TabletExecutor

_FOLD = {
    "count": lambda a, b: a + b,
    "sum": lambda a, b: a + b,
    "min": min,
    "max": max,
}


class StreamingQuery:
    """One continuous query: source topic -> SQL -> sink topic."""

    def __init__(self, name: str, sql: str, schema: dtypes.Schema,
                 source, sink, store: BlobStore,
                 batch_limit: int = 1024,
                 window: tuple | None = None):
        """``window``: optional (event_time_field, size_us,
        lateness_us) — tumbling event-time windows with watermark
        semantics (the compute-actor watermark plane,
        dq_compute_actor_impl.h): the watermark is max event time seen
        minus the allowed lateness; a window finalizes — its groups
        emit ONCE with window bounds and its state drops — when the
        watermark passes its end; events older than a finalized window
        are dropped late arrivals (counted, not applied)."""
        self.name = name
        self.sql = sql
        self.schema = schema
        self.source = source          # Topic
        self.sink = sink              # Topic | None
        self.batch_limit = batch_limit
        self.window = window
        self.executor = TabletExecutor.boot(f"fq/{name}", store)
        stmt = parse(sql)
        if not isinstance(stmt, ast.Select):
            raise ValueError("streaming query must be a SELECT")
        self._select = stmt
        self._key_cols, self._agg_cols = self._classify(stmt)

    @staticmethod
    def _classify(stmt: ast.Select):
        from ydb_tpu.sql.planner import _AGG_FUNCS

        keys, aggs = [], []
        for item in stmt.items:
            name = item.alias or getattr(item.expr, "column", None)
            if isinstance(item.expr, ast.FuncCall) and (
                    item.expr.name in _AGG_FUNCS or item.expr.star):
                kind = "count" if item.expr.star else item.expr.name
                if kind not in _FOLD:
                    raise ValueError(
                        f"aggregate {kind} is not fold-combinable; "
                        "rewrite (e.g. avg -> sum + count) upstream")
                aggs.append((name, kind))
            else:
                keys.append(name)
        return keys, aggs

    # -- durable state --

    def _state(self) -> tuple[dict, dict, int, dict]:
        db = self.executor.db
        meta = db.table("meta").get(("cursor",)) or {
            "offsets": {}, "emit_seqno": 0, "late_dropped": 0}
        state = {}
        for (key_json,), row in db.table("state").range():
            state[key_json] = row["aggs"]
        return meta["offsets"], state, meta["emit_seqno"], meta

    # -- one micro-batch --

    def poll(self) -> int:
        """Process available source messages; returns rows consumed.
        Emits (changed groups, or watermark-finalized windows) to the
        sink, then checkpoints atomically."""
        offsets, state, emit_seqno, meta = self._state()
        rows, new_offsets = [], dict(offsets)
        for pi, part in enumerate(self.source.partitions):
            start = offsets.get(str(pi), 0)
            msgs = part.read(start, limit=self.batch_limit)
            for m in msgs:
                try:
                    rows.append(json.loads(m["data"]))
                except json.JSONDecodeError:
                    continue  # poison messages are skipped, not fatal
            if msgs:
                new_offsets[str(pi)] = msgs[-1]["offset"] + 1
        if not rows:
            return 0

        if self.window is None:
            changed = self._fold(state, self._run_batch(rows))
            payloads = []
            for key_json in sorted(changed):
                rec = dict(zip(self._key_cols, json.loads(key_json)))
                rec.update(state[key_json])
                payloads.append(rec)
            finalized: list = []
            new_meta = {"offsets": new_offsets}
        else:
            payloads, changed, finalized, new_meta = \
                self._poll_windowed(rows, state, meta)
            new_meta["offsets"] = new_offsets

        # 1. emit (idempotent via producer seqno) ...
        if self.sink is not None and payloads:
            self.sink.partitions[0].write(
                [{"data": json.dumps(p)} for p in payloads],
                producer=f"fq/{self.name}",
                first_seqno=emit_seqno + 1)
            emit_seqno += len(payloads)

        # 2. ... THEN checkpoint; a crash in between replays the batch
        # and the seqno guard swallows the duplicate emission
        new_meta["emit_seqno"] = emit_seqno

        finalized_set = set(finalized)

        def fn(txc):
            txc.put("meta", ("cursor",), new_meta)
            for key_json in changed:
                if key_json not in finalized_set:
                    txc.put("state", (key_json,),
                            {"aggs": state[key_json]})
            for key_json in finalized:
                txc.erase("state", (key_json,))
        self.executor.run(fn)
        return len(rows)

    def _poll_windowed(self, rows, state, meta):
        """Tumbling-window batch: bucket rows by event-time window,
        fold per window, finalize windows the watermark passed."""
        ts_field, size, lateness = self.window
        finalized_before = meta.get("finalized_before")
        max_ts = meta.get("max_ts")
        late = meta.get("late_dropped", 0)
        buckets: dict[int, list] = {}
        for r in rows:
            ts = r.get(ts_field)
            if not isinstance(ts, (int, float)):
                continue  # unstamped rows are poison for windowing
            ts = int(ts)
            w = (ts // size) * size
            # late = the row's WINDOW is already finalized; rows below
            # the watermark whose window is still open must fold in
            if finalized_before is not None \
                    and w + size <= finalized_before:
                late += 1
                continue
            buckets.setdefault(w, []).append(r)
            max_ts = ts if max_ts is None else max(max_ts, ts)
        changed: set = set()
        for w, rs in sorted(buckets.items()):
            changed |= self._fold(state, self._run_batch(rs),
                                  window=w)
        # watermark = max event time - lateness; windows fully below
        # it finalize: emit once with bounds, drop their state
        payloads, finalized = [], []
        cut = None if max_ts is None else max_ts - lateness
        if cut is not None:
            # numeric event-time order, not JSON-string order
            for key_json in sorted(state,
                                   key=lambda k: json.loads(k)):
                w, keyvals = json.loads(key_json)
                if w + size <= cut:
                    rec = {"window_start": w, "window_end": w + size}
                    rec.update(zip(self._key_cols, keyvals))
                    rec.update(state[key_json])
                    payloads.append(rec)
                    finalized.append(key_json)
        new_meta = {
            "max_ts": max_ts,
            "finalized_before": (max(cut, finalized_before)
                                 if finalized_before is not None
                                 else cut),
            "late_dropped": late,
        }
        return payloads, changed, finalized, new_meta

    def _run_batch(self, rows: list[dict]) -> list[dict]:
        """Run the SQL over one batch through the normal query path."""
        dicts = DictionarySet()
        arrays: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        for f in self.schema.fields:
            vals = [r.get(f.name) for r in rows]
            ok = np.array([v is not None for v in vals], dtype=bool)
            if f.type.is_string:
                d = dicts.for_column(f.name)
                arrays[f.name] = np.array(
                    [d.add(v or "") for v in vals], dtype=np.int32)
            else:
                arrays[f.name] = np.array(
                    [v if v is not None else 0 for v in vals],
                    dtype=f.type.physical)
            validity[f.name] = ok
        src = ColumnSource(arrays, self.schema, dicts,
                           validity=validity)
        catalog = Catalog(schemas={"stream": self.schema},
                          primary_keys={}, dicts=dicts)
        pq = plan_select_full(parse(self.sql), catalog)
        out = to_host(execute_plan(
            pq.plan, Database(sources={"stream": src}, dicts=dicts)))
        result = []
        n = out.num_rows
        cols = {}
        for f in out.schema.fields:
            v, _ok = out.cols[f.name]
            if f.type.is_string:
                src_d = pq.dict_aliases.get(f.name, f.name)
                cols[f.name] = [x.decode("utf-8", "surrogateescape")
                                for x in dicts[src_d].decode(
                                    np.asarray(v))]
            elif f.type.is_decimal:
                cols[f.name] = [int(x) for x in np.asarray(v)]
            else:
                cols[f.name] = [x.item() for x in np.asarray(v)]
        for i in range(n):
            result.append({k: cols[k][i] for k in cols})
        return result

    def _fold(self, state: dict, batch_out: list[dict],
              window: int | None = None) -> set:
        """Merge batch aggregates into running state; returns the set
        of changed group keys (JSON-encoded; windowed keys carry
        [window_start, [key values...]])."""
        changed = set()
        for row in batch_out:
            keyvals = [row[k] for k in self._key_cols]
            key_json = json.dumps(
                keyvals if window is None else [window, keyvals],
                sort_keys=True)
            cur = state.get(key_json)
            if cur is None:
                state[key_json] = {name: row[name]
                                   for name, _kind in self._agg_cols}
            else:
                for name, kind in self._agg_cols:
                    cur[name] = _FOLD[kind](cur[name], row[name])
            changed.add(key_json)
        return changed

    def results(self) -> list[dict]:
        """Current materialized view: running groups (non-windowed) or
        the still-open windows (windowed)."""
        _offsets, state, _seq, _meta = self._state()
        out = []
        for key_json, aggs in sorted(
                state.items(), key=lambda kv: json.loads(kv[0])):
            decoded = json.loads(key_json)
            if self.window is not None:
                w, keyvals = decoded
                rec = {"window_start": w,
                       "window_end": w + self.window[1]}
                rec.update(zip(self._key_cols, keyvals))
            else:
                rec = dict(zip(self._key_cols, decoded))
            rec.update(aggs)
            out.append(rec)
        return out

    def watermark_info(self) -> dict:
        _offsets, _state, _seq, meta = self._state()
        return {"max_ts": meta.get("max_ts"),
                "finalized_before": meta.get("finalized_before"),
                "late_dropped": meta.get("late_dropped", 0)}


class FederatedQueryService:
    """Control plane: named streaming queries over cluster topics
    (the fq control-plane/row-dispatcher analog, scoped to this
    framework's in-process cluster)."""

    def __init__(self, store: BlobStore):
        self.store = store
        self.queries: dict[str, StreamingQuery] = {}

    def create_query(self, name: str, sql: str, schema: dtypes.Schema,
                     source, sink=None, batch_limit: int = 1024,
                     window: tuple | None = None) -> StreamingQuery:
        if name in self.queries:
            raise ValueError(f"query {name} exists")
        q = StreamingQuery(name, sql, schema, source, sink,
                           self.store, batch_limit, window=window)
        self.queries[name] = q
        return q

    def delete_query(self, name: str) -> None:
        self.queries.pop(name, None)

    def poll_all(self) -> dict[str, int]:
        return {name: q.poll() for name, q in self.queries.items()}
