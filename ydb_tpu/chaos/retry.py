"""Shared retry policy: exponential backoff + jitter + deadline cap.

One policy object serves every transient-failure surface (blob reads in
engine/portion.py, interconnect sends) so backoff shape and counters are
uniform. Retries respect the statement :class:`~ydb_tpu.chaos.deadline.
Deadline` active on the calling thread: no retry ever sleeps past the
statement's budget, and an expired deadline stops retrying immediately
(the last error propagates; the cancellation machinery turns it into a
typed failure at the statement boundary).

Counters + the ``blob.retry`` probe + span annotation live here (see
``note_retry``) so hand-rolled retry loops — the interconnect sender
keeps its own because reconnect state lives between attempts — surface
identically to ``RetryPolicy.call``.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

from ydb_tpu.chaos import deadline as _deadline

_rng = random.Random(0x5EED)  # jitter only; correctness never depends on it
_counters_lock = threading.Lock()
_RETRIES: dict[str, int] = {}


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with +/-``jitter`` randomization, capped per
    attempt at ``max_delay`` and overall by the active deadline."""

    max_attempts: int = 4
    base_delay: float = 0.002
    multiplier: float = 2.0
    max_delay: float = 0.25
    jitter: float = 0.25

    def delay(self, attempt: int, rng: random.Random | None = None
              ) -> float:
        d = min(self.base_delay * self.multiplier ** attempt,
                self.max_delay)
        if self.jitter:
            r = (rng or _rng).random()
            d *= 1.0 + self.jitter * (2.0 * r - 1.0)
        return max(d, 0.0)

    def call(self, fn, *, site: str = "blob.read",
             retry_on: tuple = (OSError,),
             deadline: "_deadline.Deadline | None" = None):
        """Run ``fn()``; on a ``retry_on`` error back off and retry up
        to ``max_attempts`` total tries. The deadline cap uses the
        explicit ``deadline`` or the thread's active statement deadline.
        """
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as e:
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                dl = deadline if deadline is not None \
                    else _deadline.current()
                d = self.delay(attempt - 1)
                if dl is not None:
                    remaining = dl.remaining()
                    if remaining <= 0.0:
                        raise  # no retry budget left for this statement
                    d = min(d, remaining)
                note_retry(site, attempt, e)
                time.sleep(d)


def note_retry(site: str, attempt: int, error: BaseException) -> None:
    """Count a retry and surface it: ``blob.retry`` probe + a ``retries``
    attribute on the active span (EXPLAIN ANALYZE shows absorbed
    retries)."""
    with _counters_lock:
        _RETRIES[site] = _RETRIES.get(site, 0) + 1
    from ydb_tpu.obs import probes, tracing
    pr = probes.probe("blob.retry")
    if pr:
        pr.fire(site=site, attempt=attempt,
                error=type(error).__name__)
    sp = tracing.current_span()
    if sp is not None:
        sp.set(retries=sp.attrs.get("retries", 0) + 1)


def retry_counters() -> dict[str, int]:
    with _counters_lock:
        return dict(_RETRIES)


def clear_counters() -> None:
    with _counters_lock:
        _RETRIES.clear()
