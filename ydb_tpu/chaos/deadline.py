"""Statement deadlines and cooperative cancellation.

``Session.execute(timeout=...)`` activates a :class:`Deadline` on the
dispatching thread; the execution layers check it cooperatively at
their natural block boundaries (scan block loop, staging pipeline,
fused dispatch loop, DQ source pumps) via ``check_current()`` — one
thread-local read plus a clock compare, nothing when no deadline is
active. Crossing a thread boundary is explicit: the conveyor composes
``wrap_current`` into ``submit`` exactly like the tracing span, so a
statement's prefetch producer observes the same deadline as its
consumer.

Expiry raises :class:`StatementCancelled`; the raising frame's normal
unwind (context managers, ``finally`` blocks) is the release path for
conveyor slots, staging queues and shuffle buffers — cancellation adds
no second resource-cleanup protocol.
"""

from __future__ import annotations

import contextlib
import threading
import time


class StatementCancelled(Exception):
    """The statement exceeded its deadline (or was cancelled); surfaced
    in ``sys_top_queries`` as ``error=1`` with reason ``cancelled``."""

    reason = "cancelled"


class Deadline:
    """A wall-clock budget: ``Deadline(seconds=0.5)`` or an absolute
    ``Deadline(at=monotonic_instant)``."""

    __slots__ = ("at",)

    def __init__(self, seconds: float | None = None,
                 at: float | None = None):
        if at is None:
            if seconds is None:
                raise ValueError("Deadline needs seconds= or at=")
            at = time.monotonic() + seconds
        self.at = at

    def remaining(self) -> float:
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self.at

    def check(self, what: str = "statement") -> None:
        if time.monotonic() >= self.at:
            raise StatementCancelled(f"{what}: deadline exceeded")


_tls = threading.local()


def current() -> Deadline | None:
    """The thread's active statement deadline (None when unbounded)."""
    return getattr(_tls, "deadline", None)


@contextlib.contextmanager
def activate(dl: Deadline | None):
    """Make ``dl`` the thread's deadline for the block. ``activate(None)``
    explicitly clears it — background work submitted from inside a
    statement (resident promotions) uses that to NOT inherit the
    statement's budget."""
    prev = getattr(_tls, "deadline", None)
    _tls.deadline = dl
    try:
        yield dl
    finally:
        _tls.deadline = prev


def check_current(what: str = "statement") -> None:
    """The cooperative cancellation point: raise ``StatementCancelled``
    if the thread's deadline has passed. Disabled path = one
    thread-local read."""
    dl = getattr(_tls, "deadline", None)
    if dl is not None and time.monotonic() >= dl.at:
        raise StatementCancelled(f"{what}: deadline exceeded")


def wrap_current(fn):
    """Bind the caller's deadline to ``fn`` for execution on another
    thread (the conveyor submit hook, next to tracing.wrap_current)."""
    dl = getattr(_tls, "deadline", None)
    if dl is None:
        return fn

    def bound(*args, **kwargs):
        with activate(dl):
            return fn(*args, **kwargs)

    return bound
