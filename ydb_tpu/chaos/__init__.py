"""Deterministic, seeded fault injection for the real failure surfaces.

The perf tiers (HBM-resident columns, whole-plan fusion, mesh scale-out)
all assumed the happy path; the serving/multi-host roadmap items assume
the opposite — blob reads fail, devices disappear, statements outlive
their callers, overload arrives in bursts. This package makes the worst
stage injectable so every layer can prove it degrades instead of
deadlocking, leaking, or answering wrongly.

Shape (mirrors the timeline/TSAN gates):

  * Disabled by default. ``chaos.hit(site)`` on the disabled path is one
    module-global bool check returning ``None`` — safe to leave compiled
    into hot paths (blob reads, conveyor task dispatch).
  * Gated twice: the environment switch ``YDB_TPU_CHAOS=1`` (or the
    in-process override ``chaos.CHAOS_FORCE = True``) *allows* arming;
    ``chaos.install(scenario)`` actually arms a :class:`Scenario`.
  * Deterministic: each :class:`FaultPoint` owns a PRNG seeded from
    ``scenario.seed ^ crc32(site)``, so a scenario replays the same
    fault sequence per site for the same sequence of ``hit()`` calls.
  * Observable: fired faults bump per-site counters (exported by the
    cluster background cadence under ``component="chaos"``), fire the
    ``chaos.fault`` probe, and annotate the active trace span so
    ``EXPLAIN ANALYZE`` shows which statements absorbed faults.

Sites are just names; the catalog of the ones threaded through the tree
lives in ``ydb_tpu/chaos/README.md``. The layered-on hardening —
:class:`RetryPolicy` (retry.py), statement :class:`Deadline` /
cancellation (deadline.py) — works whether or not faults come from
here; chaos is how the tests drive it.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import zlib

from ydb_tpu.runtime.failpoints import InjectedFault

from ydb_tpu.chaos.deadline import (  # noqa: F401  (re-exports)
    Deadline,
    StatementCancelled,
)
from ydb_tpu.chaos import retry as _retry_mod
from ydb_tpu.chaos.retry import RetryPolicy, note_retry  # noqa: F401

#: In-process override of the YDB_TPU_CHAOS env gate (the
#: timeline.TIMELINE_FORCE idiom): None = follow the environment,
#: True/False = force. Tests set this instead of mutating os.environ.
CHAOS_FORCE: bool | None = None


def chaos_enabled() -> bool:
    """May a scenario be armed in this process?"""
    if CHAOS_FORCE is not None:
        return CHAOS_FORCE
    return os.environ.get("YDB_TPU_CHAOS", "") not in ("", "0", "off")


class ChaosError(InjectedFault):
    """Base for faults raised (not just described) by the chaos plane.

    Subclasses ``failpoints.InjectedFault`` so existing test plumbing
    that treats injected failures specially keeps working.
    """


class InjectedIOError(ChaosError, OSError):
    """Injected blob/storage IO failure. Also an ``OSError`` so every
    transient-IO retry path treats it as the real thing."""


class DeviceLostError(ChaosError):
    """Injected accelerator loss mid-dispatch; the mesh executor's
    graceful-degradation path (mesh -> single chip -> walk) handles it."""


class Fault:
    """One fired fault: what the injection site should now do.

    ``hit()`` returns a Fault (or None); the site interprets ``kind``:
    raise, truncate, sleep, kill the worker — whatever failure that
    surface really exhibits.
    """

    __slots__ = ("site", "kind", "latency")

    def __init__(self, site: str, kind: str, latency: float = 0.0):
        self.site = site
        self.kind = kind
        self.latency = latency

    def sleep(self) -> None:
        """Apply the latency component (no-op when 0): 'delay' /
        'latency' kinds are pure sleeps, error kinds may also carry a
        latency to model slow failures."""
        if self.latency > 0.0:
            time.sleep(self.latency)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Fault({self.site!r}, {self.kind!r}, {self.latency})"


class FaultPoint:
    """A named injection site armed with probability/budget/seed."""

    def __init__(self, name: str, kind: str, p: float = 1.0,
                 budget: int | None = None, latency: float = 0.0,
                 seed: int = 0):
        self.name = name
        self.kind = kind
        self.p = float(p)
        self.budget = budget
        self.latency = float(latency)
        # per-site stream: scenario seed mixed with the site name, so
        # adding a site never perturbs another site's fault sequence
        self._rng = random.Random((seed ^ zlib.crc32(name.encode()))
                                  & 0xFFFFFFFF)
        self._lock = threading.Lock()
        self.hits = 0
        self.fired = 0

    def roll(self) -> Fault | None:
        with self._lock:
            self.hits += 1
            if self.budget is not None and self.fired >= self.budget:
                return None
            if self.p < 1.0 and self._rng.random() >= self.p:
                return None
            self.fired += 1
        return Fault(self.name, self.kind, self.latency)

    def stats(self) -> dict:
        with self._lock:
            return {"kind": self.kind, "p": self.p,
                    "budget": self.budget, "hits": self.hits,
                    "fired": self.fired}


class Scenario:
    """A replayable set of armed fault points (the chaos DSL).

    JSON shape::

        {"seed": 42,
         "sites": {
           "blob.get_range": {"kind": "io_error", "p": 0.05},
           "mesh.dispatch":  {"kind": "device_lost", "budget": 1},
           "conveyor.task":  {"kind": "delay", "p": 0.1,
                              "latency": 0.002}}}

    ``p`` defaults to 1.0, ``budget`` to unlimited, ``latency`` to 0.
    Same seed + same per-site call sequence => same faults.
    """

    def __init__(self, seed: int = 0,
                 sites: dict[str, dict] | None = None):
        self.seed = int(seed)
        self.spec = {name: dict(cfg) for name, cfg in
                     (sites or {}).items()}

    def build_points(self) -> dict[str, FaultPoint]:
        pts = {}
        for name, cfg in self.spec.items():
            pts[name] = FaultPoint(
                name, kind=cfg.get("kind", "io_error"),
                p=cfg.get("p", 1.0), budget=cfg.get("budget"),
                latency=cfg.get("latency", 0.0), seed=self.seed)
        return pts

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed, "sites": self.spec},
                          indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Scenario":
        doc = json.loads(text)
        return cls(seed=doc.get("seed", 0), sites=doc.get("sites"))

    @classmethod
    def from_file(cls, path: str) -> "Scenario":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_json(f.read())


# ---------------------------------------------------------------------------
# process-wide armed state
# ---------------------------------------------------------------------------

_ARMED = False  # the single check on the disabled hot path
_POINTS: dict[str, FaultPoint] = {}
_FALLBACKS: dict[str, int] = {}
_state_lock = threading.Lock()
_FAULT_PROBE = None  # lazily bound (keeps import graph acyclic)


def install(scenario: Scenario) -> None:
    """Arm a scenario. Requires the gate (env or CHAOS_FORCE) open —
    chaos must never switch on by accident in a serving process."""
    global _ARMED, _POINTS
    if not chaos_enabled():
        raise RuntimeError(
            "chaos is gated off: set YDB_TPU_CHAOS=1 or "
            "chaos.CHAOS_FORCE = True before install()")
    with _state_lock:
        _POINTS = scenario.build_points()
        _ARMED = True


def clear() -> None:
    """Disarm and drop all points/counters (test teardown)."""
    global _ARMED, _POINTS
    with _state_lock:
        _ARMED = False
        _POINTS = {}
        _FALLBACKS.clear()
    _retry_mod.clear_counters()


def armed() -> bool:
    return _ARMED


def hit(site: str, **ctx) -> Fault | None:
    """The injection-site call. Disabled path: one bool check, None.

    When a scenario is armed and the site rolls a fault, returns the
    :class:`Fault` (after surfacing it on probes/spans); the site then
    enacts it. ``ctx`` rides onto the probe event for filtering.
    """
    if not _ARMED:
        return None
    pt = _POINTS.get(site)
    if pt is None:
        return None
    f = pt.roll()
    if f is None:
        return None
    _surface_fault(f, ctx)
    return f


def _surface_fault(f: Fault, ctx: dict) -> None:
    global _FAULT_PROBE
    with _state_lock:
        if _FAULT_PROBE is None:
            from ydb_tpu.obs import probes
            _FAULT_PROBE = probes.probe("chaos.fault")
        probe = _FAULT_PROBE
    if probe:
        probe.fire(site=f.site, kind=f.kind, **ctx)
    from ydb_tpu.obs import tracing
    sp = tracing.current_span()
    if sp is not None:
        sp.set(chaos_faults=sp.attrs.get("chaos_faults", 0) + 1,
               chaos_last=f"{f.site}:{f.kind}")


def note_fallback(site: str) -> None:
    """Count a graceful degradation taken because of a fault (mesh ->
    single chip, fused -> walk, resident -> host)."""
    with _state_lock:
        _FALLBACKS[site] = _FALLBACKS.get(site, 0) + 1


def counters_snapshot() -> dict:
    """Per-site counters for the ``component="chaos"`` export; empty
    dict when nothing armed and nothing counted (the background cadence
    skips the group entirely)."""
    with _state_lock:
        out: dict = {}
        sites = {n: p.stats() for n, p in _POINTS.items()}
        if sites:
            out["sites"] = sites
        if _FALLBACKS:
            out["fallbacks"] = dict(_FALLBACKS)
    retries = _retry_mod.retry_counters()
    if retries:
        out["retries"] = retries
    return out
