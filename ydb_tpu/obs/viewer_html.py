"""Embedded single-page viewer UI.

The reference embeds a monitoring SPA under /monitoring (ydb/core/viewer
serves an asset bundle; viewer.cpp routes /viewer/json/* for data). This
is the lean analog: one self-contained HTML page (no external assets, no
build step) that polls the same /viewer/json/* endpoints this node
already serves and renders them as tables. Served at /viewer by
ydb_tpu.obs.viewer.Viewer.
"""

PAGE = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>ydb_tpu viewer</title>
<style>
  :root { color-scheme: light dark; }
  body { font: 13px/1.5 system-ui, sans-serif; margin: 0;
         background: Canvas; color: CanvasText; }
  header { padding: 10px 16px; border-bottom: 1px solid color-mix(
           in srgb, CanvasText 20%, Canvas); display: flex;
           gap: 16px; align-items: baseline; flex-wrap: wrap; }
  header b { font-size: 15px; }
  header .muted, .muted { opacity: .65; }
  nav a { margin-right: 10px; cursor: pointer; text-decoration: none;
          color: LinkText; }
  nav a.on { font-weight: 700; text-decoration: underline; }
  main { padding: 12px 16px; }
  table { border-collapse: collapse; margin: 8px 0 20px; }
  th, td { border: 1px solid color-mix(in srgb, CanvasText 20%, Canvas);
           padding: 3px 9px; text-align: left;
           font-variant-numeric: tabular-nums; }
  th { background: color-mix(in srgb, CanvasText 8%, Canvas); }
  td.num { text-align: right; }
  .status-GOOD { color: green; font-weight: 700; }
  .status-DEGRADED { color: darkorange; font-weight: 700; }
  .status-EMERGENCY { color: crimson; font-weight: 700; }
  select { font: inherit; }
  pre { white-space: pre-wrap; }
</style>
</head>
<body>
<header>
  <b>ydb_tpu</b>
  <span id="summary" class="muted">loading…</span>
  <nav id="nav"></nav>
  <a href="/counters/prometheus">prometheus</a>
</header>
<main id="main">loading…</main>
<script>
"use strict";
const TABS = ["overview", "profiles", "timeline", "tablets",
              "statistics", "resident", "sysviews", "topics",
              "counters"];
const tabOf = h => TABS.includes(h) ? h : "overview";
let tab = tabOf(location.hash.slice(1));
let sysviewName = "";

const get = p => fetch(p).then(r => r.json());
const esc = s => String(s).replace(/[&<>]/g,
  c => ({"&": "&amp;", "<": "&lt;", ">": "&gt;"}[c]));

function renderTable(rows, cols) {
  if (!rows.length) return "<p class=muted>(empty)</p>";
  cols = cols || Object.keys(rows[0]);
  const th = cols.map(c => `<th>${esc(c)}</th>`).join("");
  const trs = rows.map(r => "<tr>" + cols.map(c => {
    const v = r[c];
    const num = typeof v === "number";
    return `<td class="${num ? "num" : ""}">${
      v === null || v === undefined ? "" : esc(v)}</td>`;
  }).join("") + "</tr>").join("");
  return `<table><tr>${th}</tr>${trs}</table>`;
}

function kv(obj) {
  return renderTable(Object.entries(obj).map(
    ([k, v]) => ({key: k, value: typeof v === "object"
                  ? JSON.stringify(v) : v})));
}

const VIEWS = {
  async overview() {
    const [cluster, health, wb] = await Promise.all([
      get("/viewer/json/cluster"), get("/viewer/json/healthcheck"),
      get("/viewer/json/whiteboard")]);
    const issues = (health.issues || []).map(i =>
      typeof i === "string" ? {issue: i} : i);
    return `<h3>health:
        <span class="status-${esc(health.status)}">${
        esc(health.status)}</span></h3>`
      + (issues.length ? renderTable(issues) : "")
      + "<h3>cluster</h3>" + kv(cluster)
      + "<h3>recent queries</h3>"
      + renderTable(wb.recent_queries || [])
      + "<h3>memory</h3>" + kv(wb.memory || {});
  },
  async profiles() {
    const p = await get("/viewer/json/query_profile");
    const top = (p.top || []).map(q => ({
      query: q.sql, class: q.query_class, seconds: q.seconds,
      rows: q.rows, compile_s: q.compile_seconds,
      execute_s: q.execute_seconds, plan_cache: q.plan_cache,
      compile_cache: q.compile_cache,
      compute_s: (q.stages || {}).compute, read_s: (q.stages || {}).read,
    }));
    let spanHtml = "<p class=muted>(no profiled query yet)</p>";
    if (p.last) {
      const rows = [];
      (function walk(nodes, depth) {
        for (const s of nodes || []) {
          rows.push({span: "\\u00a0".repeat(depth * 2) + s.name,
                     seconds: s.seconds,
                     attrs: JSON.stringify(s.attrs)});
          walk(s.children, depth + 1);
        }
      })(p.last.span_tree, 0);
      spanHtml = renderTable(rows, ["span", "seconds", "attrs"]);
    }
    return "<h3>top queries (most expensive retained)</h3>"
      + renderTable(top)
      + "<h3>last query span tree</h3>" + spanHtml;
  },
  async timeline() {
    const t = await get("/viewer/json/timeline");
    const cats = Object.entries(t.categories || {}).map(
      ([k, v]) => Object.assign({category: k}, v));
    const mv = Object.entries(t.movement_bytes || {}).map(
      ([k, v]) => ({counter: k, bytes: v}));
    const note = t.enabled ? "" :
      "<p class=muted>timeline ring is OFF (set YDB_TPU_TIMELINE=1" +
      " to record events; byte counters below are always on)</p>";
    return "<h3>data-movement timeline</h3>" + note
      + kv({enabled: t.enabled, events: t.events,
            recorded: t.recorded, dropped: t.dropped,
            capacity: t.capacity})
      + "<h3>per-category busy time</h3>" + renderTable(cats)
      + "<h3>movement bytes (cumulative)</h3>" + renderTable(mv)
      + "<h3>active queries</h3>"
      + renderTable(t.active_queries || [])
      + `<p><a href="/viewer/json/timeline?trace=1" download=` +
        `"trace.json">download Chrome trace JSON</a> ` +
        `<span class=muted>(open in ui.perfetto.dev)</span></p>`;
  },
  async tablets() {
    const t = await get("/viewer/json/tablets");
    return "<h3>per-tablet counters</h3>" + renderTable(t.tablets || [])
      + "<h3>aggregates by type</h3>"
      + renderTable(Object.entries(t.aggregates || {}).map(
          ([k, v]) => Object.assign({type: k}, v)));
  },
  async statistics() {
    const s = await get("/viewer/json/statistics");
    return "<h3>column statistics (NDV / null fractions)</h3>"
      + renderTable(s.columns || [])
      + "<h3>scan pruning (cumulative per shard)</h3>"
      + renderTable(s.pruning || []);
  },
  async resident() {
    const r = await get("/viewer/json/resident");
    return "<h3>HBM-resident column tier (totals)</h3>"
      + kv(r.total || {})
      + "<h3>per shard</h3>" + renderTable(r.shards || []);
  },
  async sysviews() {
    const names = await get("/viewer/json/sysview");
    if (!sysviewName) sysviewName = names[0] || "";
    const opts = names.map(n => `<option ${
      n === sysviewName ? "selected" : ""}>${esc(n)}</option>`);
    let body = "<p class=muted>(pick a view)</p>";
    if (sysviewName) {
      const rows = await get(
        "/viewer/json/sysview?name=" + encodeURIComponent(sysviewName));
      body = renderTable(rows);
    }
    return `<h3>system views</h3>
      <select onchange="sysviewName=this.value;render()">${
      opts.join("")}</select>` + body;
  },
  async topics() {
    return "<h3>topic partitions</h3>"
      + renderTable(await get("/viewer/json/topics"));
  },
  async counters() {
    const c = await get("/counters");
    const flat = [];
    (function walk(prefix, node) {
      for (const [k, v] of Object.entries(node)) {
        const p = prefix ? prefix + "." + k : k;
        if (v && typeof v === "object" && !Array.isArray(v))
          walk(p, v);
        else flat.push({counter: p, value: Array.isArray(v)
                        ? JSON.stringify(v) : v});
      }
    })("", c);
    return "<h3>counters</h3>" + renderTable(flat);
  },
};

async function render() {
  document.getElementById("nav").innerHTML = TABS.map(t =>
    `<a class="${t === tab ? "on" : ""}" href="#${t}">${t}</a>`
  ).join("");
  try {
    document.getElementById("main").innerHTML = await VIEWS[tab]();
  } catch (e) {
    document.getElementById("main").innerHTML =
      "<pre>" + esc(e) + "</pre>";
  }
  try {
    const c = await get("/viewer/json/cluster");
    document.getElementById("summary").textContent =
      `node ${c.node_id} · ${c.tables.length} tables · ` +
      `${c.topics.length} topics · up ${c.uptime_seconds}s`;
  } catch (e) { /* header stays */ }
}
window.addEventListener("hashchange", () => {
  tab = tabOf(location.hash.slice(1));
  render();
});
render();
setInterval(render, 15000);
</script>
</body>
</html>
"""
