from ydb_tpu.obs.counters import CounterGroup, root_counters
from ydb_tpu.obs.tracing import Span, Tracer

__all__ = ["CounterGroup", "root_counters", "Span", "Tracer"]
