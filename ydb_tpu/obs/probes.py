"""lwtrace-analog probe points: near-zero-cost named events with
dynamically attached trace sessions.

Reference: the lwtrace library (ydb/library/lwtrace; SURVEY §2.1 row
'lwtrace probes') — probes compiled into hot paths fire only while a
trace session is attached, collecting events into per-session ring
buffers with filters. Same contract here: ``probe(name)`` returns a
module-level Probe whose ``fire(**params)`` is a single attribute check
when nothing is attached; sessions attach by glob pattern and keep a
bounded ring of (name, params) events plus per-probe hit counts.
"""

from __future__ import annotations

import collections
import contextlib
import fnmatch
import threading
import time

from ydb_tpu.analysis import sanitizer
from ydb_tpu.obs import timeline

# module-level registry: built at import, before any test could set
# YDB_TPU_TSAN — so the proxy/lock are always-on variants whose
# recording self-gates per access (idle cost: one flag check on the
# probe() / attach() paths, never on fire())
_registry = sanitizer.share_always({}, "probes._registry")
_lock = sanitizer.TrackedLock("probes._lock")


class Probe:
    __slots__ = ("name", "_sessions")

    def __init__(self, name: str):
        self.name = name
        self._sessions: tuple = ()

    def fire(self, **params) -> None:
        sessions = self._sessions  # snapshot; () when idle (the fast path)
        for s in sessions:
            s._record(self.name, params)

    def __bool__(self) -> bool:
        """Truthy while any session listens: guards costly param
        computation (``if PROBE: PROBE.fire(expensive=...)``)."""
        return bool(self._sessions)


def probe(name: str) -> Probe:
    """Get-or-create the module-level probe point."""
    with _lock:
        p = _registry.get(name)
        if p is None:
            p = _registry[name] = Probe(name)
        return p


def list_probes() -> list[str]:
    with _lock:
        return sorted(_registry)


class TraceSession:
    """One attached collector (lwtrace session analog)."""

    def __init__(self, pattern: str = "*", capacity: int = 4096,
                 predicate=None):
        self.pattern = pattern
        self.predicate = predicate
        self.events: collections.deque = collections.deque(
            maxlen=capacity)
        self.counts: collections.Counter = collections.Counter()
        self._elock = threading.Lock()
        self._attached: list[Probe] = []

    def _record(self, name: str, params: dict) -> None:
        if self.predicate is not None and not self.predicate(name, params):
            return
        with self._elock:
            self.counts[name] += 1
            self.events.append((name, params))

    def attach(self) -> "TraceSession":
        with _lock:
            for name, p in _registry.items():
                if fnmatch.fnmatchcase(name, self.pattern):
                    p._sessions = p._sessions + (self,)
                    self._attached.append(p)
        return self

    def detach(self) -> None:
        with _lock:
            for p in self._attached:
                p._sessions = tuple(
                    s for s in p._sessions if s is not self)
            self._attached = []

    def __enter__(self) -> "TraceSession":
        return self.attach()

    def __exit__(self, *exc) -> None:
        self.detach()


class StageTimer:
    """Per-scan stage accounting: accumulated wall seconds by stage name.

    The scan pipeline spreads one logical query over threads — blob IO
    and K-way merging on the prefetch producer, block staging
    (pad + device transfer) beside it, device compute on the consumer —
    so a single end-to-end duration says nothing about WHERE the time
    went. Each pipeline site charges its own stage (``read`` / ``merge``
    / ``stage`` / ``compute``); concurrent stages may sum past the
    wall-clock total, which is exactly the overlap being measured.
    Thread-safe; ``snapshot()`` is what bench.py surfaces as metric
    extras and what the ``scan.stages`` probe fires.
    """

    #: canonical scan stages, always present in snapshots (zero if unhit)
    STAGES = ("read", "merge", "stage", "compute")

    def __init__(self):
        self._t: collections.defaultdict = collections.defaultdict(float)
        self._lock = threading.Lock()

    def add(self, name: str, seconds: float, **args) -> None:
        # every stage charge ALSO lands on the data-movement timeline
        # (obs.timeline, default off) as an interval ending now — one
        # funnel, so timeline busy sums per stage equal the EXPLAIN
        # ANALYZE stage seconds by construction. ``args`` attach to the
        # ring interval (morsel ids from the streaming pipeline), never
        # to the stage accumulator — occupancy attribution stays exact
        # while each interval stays traceable to the work unit.
        if timeline.timeline_enabled():
            end = time.perf_counter()
            timeline.RING.record(
                f"stage.{name}", name, end - seconds, end,
                timeline.current_trace_id(), args or None)
        with self._lock:
            self._t[name] += seconds

    @contextlib.contextmanager
    def stage(self, name: str, **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0, **args)

    def snapshot(self) -> dict:
        with self._lock:
            out = {s: 0.0 for s in self.STAGES}
            out.update(self._t)
        return {k: round(v, 6) for k, v in out.items()}


def memory_stats() -> dict:
    """Process + device memory observability (SURVEY §2.14 row
    'memory profiling'): VmRSS/VmHWM from /proc plus per-device live
    buffer stats when the backend exposes them."""
    out: dict = {}
    try:
        for line in open("/proc/self/status"):
            if line.startswith(("VmRSS", "VmHWM")):
                k, v = line.split(":", 1)
                out[k.lower() + "_mb"] = round(
                    float(v.split()[0]) / 1024.0, 1)
    except OSError:
        pass
    try:
        import jax

        for i, d in enumerate(jax.local_devices()):
            st = getattr(d, "memory_stats", lambda: None)()
            if st:
                out[f"device{i}_bytes_in_use"] = st.get("bytes_in_use")
                out[f"device{i}_peak_bytes"] = st.get(
                    "peak_bytes_in_use")
    except Exception:
        pass
    return out
