"""Per-query profiles assembled from finished span trees.

The reference surfaces query runtime statistics three ways — the plan
annotated with actuals (``EXPLAIN ANALYZE`` / execution stats in the
query response), ``.sys/top_queries`` + ``.sys/query_metrics`` views
over an in-memory ring of the most expensive recent queries, and
per-pool latency histograms on the counters page (SURVEY.md §2.14,
§5.5). This module is that layer for the TPU build: the session runs
every statement under a traced root span (obs.tracing), the executor /
scan / DQ / conveyor layers attach children, and ``build_profile``
folds the finished tree into one ``QueryProfile`` — per-stage seconds,
device vs host time, rows, cache hits, compile-vs-execute split — that
feeds ``session.last_profile``, the ``sys_top_queries`` /
``sys_query_log`` views, the ``/viewer/json/query_profile`` endpoint
and ``EXPLAIN ANALYZE`` rendering.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time


#: span attrs summed into the per-query stage breakdown; "compute" is
#: device time, the rest is host-side pipeline work
STAGE_KEYS = ("read", "merge", "stage", "compute")
#: span attrs summed into the per-query pruning/row accounting
PRUNING_KEYS = ("portions_total", "portions_skipped", "chunks_read",
                "chunks_skipped", "resident_portions", "resident_rows")
#: span names that carry scan-level stage/pruning/compile attrs
SCAN_SPANS = ("scan", "shard.scan")


@dataclasses.dataclass
class QueryProfile:
    """One query's assembled execution profile."""

    sql: str = ""
    kind: str = ""
    query_class: str = ""
    trace_id: int = 0
    seq: int = 0
    seconds: float = 0.0
    rows: int = 0
    plan_cache: str = ""      # hit | miss | "" (unknown/disabled)
    compile_cache: str = ""   # miss if ANY scan/transform compiled fresh
    compile_seconds: float = 0.0   # lowering + first-trace (XLA) time
    execute_seconds: float = 0.0   # seconds - compile_seconds
    fused_stages: int = 0     # plan nodes folded into one traced dispatch
    fragments_elided: int = 0  # dispatch boundaries removed by fusion
    #: cross-query batching (kqp/batch.py): group id + member count of
    #: the micro-batch that served this statement (0 = unbatched), how
    #: many of its scan sites were served by a staging shared with
    #: batchmates, and the wait-for-window vs shared-execute split
    batch_id: int = 0
    batch_size: int = 0
    shared_scan: int = 0
    batch_wait_seconds: float = 0.0
    batch_execute_seconds: float = 0.0
    stages: dict = dataclasses.field(default_factory=dict)
    pruning: dict = dataclasses.field(default_factory=dict)
    #: host-boundary counters from the sync sanitizer
    #: (analysis.syncsan, YDB_TPU_SYNCSAN=1): h2d/d2h transfers,
    #: blocking syncs and XLA compiles this statement crossed; {} when
    #: the sanitizer is off
    syncsan: dict = dataclasses.field(default_factory=dict)
    #: device-byte counters from the footprint sanitizer
    #: (analysis.memsan, YDB_TPU_MEMSAN=1): peak/live HBM bytes, charge
    #: count and unbudgeted allocations this statement made; {} when
    #: the sanitizer is off
    memsan: dict = dataclasses.field(default_factory=dict)
    device_seconds: float = 0.0
    host_seconds: float = 0.0
    #: per-stage busy fractions + overlap coefficients from the
    #: data-movement timeline (obs.timeline); {} when the ring is off
    stage_occupancy: dict = dataclasses.field(default_factory=dict)
    #: 1 when the statement failed mid-execution (the profile still
    #: lands in the ring so slow-then-failing statements stay visible)
    error: int = 0
    #: why it failed: "cancelled" (deadline), "overloaded" (admission
    #: shed), else the error type name; "" on success
    error_reason: str = ""
    #: workload pool the statement admitted under (serving/tenants.py);
    #: "" for sessions on clusters without a front door
    tenant: str = ""
    spans: list = dataclasses.field(default_factory=list)

    def to_dict(self, include_spans: bool = False) -> dict:
        """JSON-ready summary. Spans are excluded by default — every
        current consumer (bench extras, the viewer's top-N list) wants
        the summary, and span detail is served separately as a tree
        (``span_tree``) — only ``include_spans=True`` ships the raw
        list."""
        d = dataclasses.asdict(self)
        if not include_spans:
            del d["spans"]
            d["span_count"] = len(self.spans)
        d["seconds"] = round(self.seconds, 6)
        d["compile_seconds"] = round(self.compile_seconds, 6)
        d["execute_seconds"] = round(self.execute_seconds, 6)
        return d

    def span_tree(self) -> list[dict]:
        """Spans nested children-under-parents (forest of roots)."""
        by_id = {s["span_id"]: dict(s, children=[]) for s in self.spans}
        roots = []
        for s in by_id.values():
            parent = by_id.get(s["parent_id"])
            if parent is not None:
                parent["children"].append(s)
            else:
                roots.append(s)
        return roots


def _span_dict(s) -> dict:
    return {
        "name": s.name, "span_id": s.span_id,
        "parent_id": s.parent_id,
        "seconds": round(s.seconds, 6), "attrs": dict(s.attrs),
    }


def subtree(spans, root_span_id: int) -> list:
    """The spans descending from ``root_span_id`` (root excluded)."""
    children: dict = {}
    for s in spans:
        children.setdefault(s.parent_id, []).append(s)
    out, stack = [], [root_span_id]
    while stack:
        for s in children.get(stack.pop(), ()):
            out.append(s)
            stack.append(s.span_id)
    return out


def build_profile(spans, sql: str = "", kind: str = "",
                  query_class: str = "", seconds: float | None = None,
                  rows: int | None = None, seq: int = 0) -> QueryProfile:
    """Fold one trace's finished spans into a QueryProfile.

    ``spans`` is ``tracer.spans_for(trace_id)``; the root "query" span
    supplies totals when ``seconds``/``rows`` are not passed."""
    p = QueryProfile(sql=sql, kind=kind, query_class=query_class,
                     seq=seq)
    root = next((s for s in spans if s.parent_id is None), None)
    if root is not None:
        p.trace_id = root.trace_id
        p.kind = p.kind or str(root.attrs.get("kind", ""))
    elif spans:
        p.trace_id = spans[0].trace_id
    p.seconds = (seconds if seconds is not None
                 else (root.seconds if root is not None else 0.0))
    p.stages = {k: 0.0 for k in STAGE_KEYS}
    p.pruning = {k: 0 for k in PRUNING_KEYS}
    rows_out = 0
    for s in spans:
        a = s.attrs
        if a.get("plan_cache") and not p.plan_cache:
            p.plan_cache = str(a["plan_cache"])
        if "syncsan_compiles" in a and not p.syncsan:
            p.syncsan = {
                k[len("syncsan_"):]: int(v) for k, v in a.items()
                if k.startswith("syncsan_")}
        if "memsan_peak" in a and not p.memsan:
            p.memsan = {
                k[len("memsan_"):]: int(v) for k, v in a.items()
                if k.startswith("memsan_")}
        if s.name == "ssa.compile":
            p.compile_seconds += s.seconds
        if s.name == "plan.fuse":
            # whole-plan single-trace execution (ssa.plan_fuse): one
            # span per fused dispatch carrying the fusion accounting
            p.fused_stages = max(p.fused_stages,
                                 int(a.get("fused_stages", 0)))
            p.fragments_elided += int(a.get("fragments_elided", 0))
            if a.get("compile_cache") == "miss":
                p.compile_cache = "miss"
            elif (a.get("compile_cache") == "hit"
                  and not p.compile_cache):
                p.compile_cache = "hit"
            p.compile_seconds += float(
                a.get("first_trace_seconds", 0.0))
            continue
        if s.name == "dispatch.batch":
            # cross-query micro-batch seat (kqp/batch.py): one span per
            # member on its own session thread, so per-statement
            # profiles attribute window wait vs shared execute
            p.batch_id = int(a.get("batch_id", 0))
            p.batch_size = int(a.get("batch_size", 0))
            p.shared_scan = int(a.get("shared_scan", 0))
            p.batch_wait_seconds += float(a.get("wait_seconds", 0.0))
            p.batch_execute_seconds += float(
                a.get("execute_seconds", 0.0))
            continue
        if s.name == "dq.task":
            # DQ queries run their device dispatches inside compute
            # actors (no scan/transform spans on that path): the tasks'
            # accumulated compute seconds ARE the device time
            p.stages["compute"] += float(a.get("compute_seconds", 0.0))
            continue
        if s.name not in SCAN_SPANS and s.name != "transform":
            continue
        if a.get("compile_cache") == "miss":
            p.compile_cache = "miss"
        elif a.get("compile_cache") == "hit" and not p.compile_cache:
            p.compile_cache = "hit"
        p.compile_seconds += float(a.get("first_trace_seconds", 0.0))
        if s.name in SCAN_SPANS:
            rows_out += int(a.get("rows", 0))
            for k in STAGE_KEYS:
                p.stages[k] += float(a.get(f"stage_{k}", 0.0))
            for k in PRUNING_KEYS:
                p.pruning[k] += int(a.get(k, 0))
    p.stages = {k: round(v, 6) for k, v in p.stages.items()}
    p.rows = rows if rows is not None else rows_out
    p.execute_seconds = max(0.0, p.seconds - p.compile_seconds)
    p.device_seconds = p.stages.get("compute", 0.0)
    p.host_seconds = round(sum(
        v for k, v in p.stages.items() if k != "compute"), 6)
    p.spans = [_span_dict(s) for s in spans]
    from ydb_tpu.obs import timeline

    if timeline.timeline_enabled() and p.trace_id:
        p.stage_occupancy = timeline.query_occupancy(
            p.trace_id, wall=p.seconds or None)
    return p


def classify_plan(plan) -> str:
    """Query class for latency-histogram bucketing: joins dominate
    aggregates dominate plain scans."""
    from ydb_tpu.plan.nodes import Concat, ExpandJoin, LookupJoin, \
        Transform
    from ydb_tpu.ssa.program import GroupByStep

    has_join = False
    has_agg = False
    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, (LookupJoin, ExpandJoin)):
            has_join = True
            stack += [n.probe, n.build]
        elif isinstance(n, Transform):
            if any(isinstance(st, GroupByStep)
                   for st in n.program.steps):
                has_agg = True
            stack.append(n.input)
        elif isinstance(n, Concat):
            stack += list(n.inputs)
        else:
            prog = getattr(n, "program", None)
            if prog is not None and any(
                    isinstance(st, GroupByStep) for st in prog.steps):
                has_agg = True
    if has_join:
        return "select_join"
    if has_agg:
        return "select_agg"
    return "select_scan"


class ProfileRing:
    """Bounded ring of recent QueryProfiles (the ``.sys/top_queries``
    backing store). Thread-safe: concurrent sessions append while sys
    views / the viewer snapshot."""

    def __init__(self, capacity: int = 128):
        self.capacity = max(1, int(capacity))
        self._items: list[QueryProfile] = []
        self._lock = threading.Lock()
        self._seq = 0

    def add(self, profile: QueryProfile) -> None:
        with self._lock:
            self._seq += 1
            profile.seq = self._seq
            self._items.append(profile)
            if len(self._items) > self.capacity:
                del self._items[: len(self._items) - self.capacity]

    def recent(self) -> list[QueryProfile]:
        """Arrival order, oldest first."""
        with self._lock:
            return list(self._items)

    def top(self, n: int = 16) -> list[QueryProfile]:
        """The n most expensive retained queries, slowest first."""
        with self._lock:
            items = list(self._items)
        items.sort(key=lambda p: p.seconds, reverse=True)
        return items[:n]

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


def format_plan_analyzed(plan, profile: QueryProfile) -> str:
    """EXPLAIN ANALYZE rendering: the physical plan plus measured
    actuals (per-stage seconds, pruning/row counts, compile-vs-execute
    split). Key=value lines so tests and tools parse them directly."""
    from ydb_tpu.plan.nodes import format_plan

    lines = [format_plan(plan), "-- actuals --"]
    lines.append(
        f"total: seconds={profile.seconds:.6f} rows={profile.rows}")
    lines.append(
        "compile: compile_cache=" + (profile.compile_cache or "none")
        + f" compile_seconds={profile.compile_seconds:.6f}"
        + f" execute_seconds={profile.execute_seconds:.6f}")
    if profile.syncsan:
        ss = profile.syncsan
        lines.append("syncsan: " + " ".join(
            f"{k}={ss.get(k, 0)}"
            for k in ("h2d", "d2h", "syncs", "compiles")))
    if profile.memsan:
        ms = profile.memsan
        lines.append("memsan: " + " ".join(
            f"{k}={ms.get(k, 0)}"
            for k in ("peak", "live", "charges", "unbudgeted")))
    if profile.fused_stages:
        lines.append(
            f"fusion: fused_stages={profile.fused_stages}"
            f" fragments_elided={profile.fragments_elided}")
    if profile.batch_size:
        lines.append(
            f"batching: batch_id={profile.batch_id}"
            f" batch_size={profile.batch_size}"
            f" shared_scan={profile.shared_scan}"
            f" wait_seconds={profile.batch_wait_seconds:.6f}"
            f" execute_seconds={profile.batch_execute_seconds:.6f}")
    st = profile.stages
    lines.append("stages: " + " ".join(
        f"{k}={st.get(k, 0.0):.6f}" for k in STAGE_KEYS))
    pr = profile.pruning
    lines.append("rows: " + " ".join(
        f"{k}={pr.get(k, 0)}" for k in PRUNING_KEYS))
    occ = profile.stage_occupancy
    if occ:
        frac = occ.get("fraction", {})
        bits = [f"{k}={frac.get(k, 0.0):.4f}" for k in STAGE_KEYS
                if k in frac]
        for pair, coeff in sorted(occ.get("overlap", {}).items()):
            bits.append(f"{pair}={coeff:.4f}")
        lines.append("occupancy: " + " ".join(bits))
    for s in profile.spans:
        if s["name"] not in SCAN_SPANS:
            continue
        a = s["attrs"]
        bits = [f"seconds={s['seconds']:.6f}"]
        for k in ("table", "shard", "rows", "compile_cache"):
            if k in a:
                bits.append(f"{k}={a[k]}")
        lines.append(f"  {s['name']}: " + " ".join(bits))
    return "\n".join(lines)


class _Holder:
    profile: QueryProfile | None = None


@contextlib.contextmanager
def profiled(sql: str = "", kind: str = "select",
             query_class: str = "", tracer=None):
    """Run a block under a fresh root span and hand back its profile
    (``holder.profile`` after exit) — the bench.py seam for profiling
    engine-tier scans that never pass through a session."""
    from ydb_tpu.obs.tracing import Tracer, activate

    tr = tracer if tracer is not None else Tracer()
    holder = _Holder()
    root = tr.trace("query")
    t0 = time.perf_counter()
    try:
        with activate(root):
            yield holder
    finally:
        root.finish()
        holder.profile = build_profile(
            tr.spans_for(root.trace_id), sql=sql, kind=kind,
            query_class=query_class,
            seconds=time.perf_counter() - t0)
