"""Tablet counters collection + cluster-wide aggregation.

Mirror of the reference's per-tablet counters plane
(ydb/core/tablet/tablet_counters.cpp + the counters aggregator
tablet_counters_aggregator.cpp merging per-tablet counters by tablet
type for monitoring; SURVEY.md §2.4 row "tablet plumbing"): every
TabletExecutor keeps simple commit/redo/checkpoint counters; this
module walks a Cluster's live tablets, tags each with its type
(derived from the tablet-id prefix: ds/, pq/, kesus/, console, ...)
and folds them into per-type aggregates for the viewer and sys views.
"""

from __future__ import annotations


def _walk_executors(cluster):
    """Yield (tablet_id, executor) for every live tablet."""
    scheme = getattr(cluster, "scheme", None)
    if scheme is not None and hasattr(scheme, "executor"):
        yield scheme.executor.tablet_id, scheme.executor
    for t in getattr(cluster, "tables", {}).values():
        for shard in t.shards:
            ex = getattr(shard, "executor", None)
            if ex is not None:
                yield ex.tablet_id, ex
    for topic in getattr(cluster, "topics", {}).values():
        for part in topic.partitions:
            yield part.executor.tablet_id, part.executor
    coord = getattr(cluster, "coordinator", None)
    ex = getattr(coord, "executor", None)
    if ex is not None:
        yield ex.tablet_id, ex


def tablet_type(tablet_id: str) -> str:
    """First path segment of the tablet id is its type."""
    return tablet_id.split("/", 1)[0] if "/" in tablet_id else tablet_id


def collect(cluster) -> list[dict]:
    """Per-tablet counter rows."""
    out = []
    for tablet_id, ex in _walk_executors(cluster):
        out.append(dict(ex.counters, tablet_id=tablet_id,
                        type=tablet_type(tablet_id),
                        generation=ex.generation,
                        version=ex.version))
    return out


def aggregate(cluster, rows: list[dict] | None = None) -> dict[str, dict]:
    """Per-tablet-type sums (the counters-aggregator merge). Pass
    already-collected ``rows`` to aggregate a consistent snapshot."""
    agg: dict[str, dict] = {}
    for row in (rows if rows is not None else collect(cluster)):
        t = agg.setdefault(row["type"], {
            "tablets": 0, "tx_executed": 0, "tx_committed": 0,
            "redo_bytes": 0, "checkpoints": 0,
        })
        t["tablets"] += 1
        for k in ("tx_executed", "tx_committed", "redo_bytes",
                  "checkpoints"):
            t[k] += row[k]
    return agg
