"""Distributed tracing: spans with trace-id propagation.

Mirror of the reference's Wilson tracing (NWilson::TSpan
wilson/wilson_span.h:50, TTraceId wilson/wilson_trace.h, uploader ->
OTLP wilson/wilson_uploader.cpp; SURVEY.md §5.1): spans open under a
trace id, nest by parent span id, and finished spans collect in a
Tracer which exports OTLP-shaped JSON. The session opens a root span
per query; inner phases (parse/plan/compile/execute/scan/fetch) nest
under it; actor envelopes can carry the id across nodes.

Span threading: the ACTIVE span rides thread-local context
(``activate`` / ``current_span`` / ``span``), so deep layers — the
scan executor, DQ compute actors, the conveyor prefetch pool — attach
children without plumbing a span argument through every signature.
``runtime.conveyor`` captures the submitter's active span and
re-activates it on the worker, so one query's trace id follows its
work across threads; the Tracer is therefore thread-safe (spans
finish from prefetch producers while the session thread records its
own) with a per-trace-id index replacing the old linear scan.

Gating: profiling is ON by default; ``YDB_TPU_PROFILE=0`` keeps the
per-query root span but skips activation, so no child spans (and none
of their attribute computation) happen anywhere below the session.
``PROFILE_FORCE`` is the in-process test override (same contract as
stats.STATS_FORCE).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time

from ydb_tpu.analysis import sanitizer
from ydb_tpu.obs import timeline

_ids = itertools.count(1)

#: test/bench override: True/False forces profiling regardless of the
#: environment (same contract as kernels.FUSED_FORCE).
PROFILE_FORCE: bool | None = None


def profiling_enabled() -> bool:
    """Whether the session threads its span through the query path
    (activation + child spans + profile assembly). Default on;
    ``YDB_TPU_PROFILE=0`` restores the root-span-only behavior."""
    if PROFILE_FORCE is not None:
        return PROFILE_FORCE
    return os.environ.get("YDB_TPU_PROFILE", "1") not in ("0", "", "off")


class Span:
    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 parent_id: int | None = None, clock=time.monotonic):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.attrs: dict = {}
        self._clock = clock
        self.start = clock()
        self.end: float | None = None

    #: real spans record; the shared null span (disabled path) does not
    recording = True

    def child(self, name: str) -> "Span":
        return Span(self.tracer, name, self.trace_id, self.span_id,
                    self._clock)

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    @property
    def seconds(self) -> float:
        """Wall duration (to now while unfinished)."""
        return (self.end if self.end is not None
                else self._clock()) - self.start

    def finish(self) -> None:
        if self.end is None:
            self.end = self._clock()
            self.tracer._record(self)
            if timeline.timeline_enabled():
                # anchor on the duration, not the span's own clock:
                # spans run on ``clock`` (monotonic by default) while
                # the timeline axis is perf_counter — re-basing the
                # interval to end-now keeps one consistent axis
                now = time.perf_counter()
                timeline.RING.record(
                    self.name, "span", now - (self.end - self.start),
                    now, self.trace_id)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self.attrs["error"] = repr(exc)
        self.finish()


class _NullSpan:
    """No-op span: returned by ``span()`` when no trace is active, so
    instrumentation sites need no ``if`` around their annotations."""

    recording = False
    trace_id = 0
    span_id = 0
    parent_id = None
    attrs: dict = {}
    seconds = 0.0

    def child(self, name: str) -> "_NullSpan":
        return self

    def set(self, **attrs) -> "_NullSpan":
        return self

    def finish(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


NULL_SPAN = _NullSpan()

# thread-local active span; workers inherit it via ``wrap_current``
_tls = threading.local()


def current_span() -> Span | None:
    """The thread's active span (None outside any activated trace)."""
    return getattr(_tls, "span", None)


@contextlib.contextmanager
def activate(sp: Span):
    """Make ``sp`` the thread's active span for the block."""
    prev = current_span()
    _tls.span = sp
    try:
        yield sp
    finally:
        _tls.span = prev


@contextlib.contextmanager
def span(name: str, **attrs):
    """Open (and activate) a child of the active span; a shared no-op
    span when no trace is active — the disabled path costs one
    thread-local read."""
    parent = current_span()
    if parent is None:
        yield NULL_SPAN
        return
    s = parent.child(name)
    if attrs:
        s.set(**attrs)
    prev = parent
    _tls.span = s
    try:
        yield s
    except BaseException as e:
        s.attrs["error"] = repr(e)
        raise
    finally:
        _tls.span = prev
        s.finish()


def annotate(**attrs) -> None:
    """Attach attributes to the active span, if any."""
    sp = current_span()
    if sp is not None:
        sp.set(**attrs)


def wrap_current(fn):
    """Bind the submitter's active span to ``fn`` so a worker thread
    runs it under the same trace (the conveyor submit hook)."""
    sp = current_span()
    if sp is None:
        return fn

    def bound(*args, **kwargs):
        with activate(sp):
            return fn(*args, **kwargs)

    return bound


class Tracer:
    """Thread-safe span collector with a per-trace-id index.

    DQ stages and conveyor prefetch producers finish spans from worker
    threads while the session thread records its own — ``finished``
    appends and ``spans_for`` lookups run under a sanitizer-tracked
    lock, and the index makes per-query lookups O(spans in trace)
    instead of a scan over the whole ring."""

    def __init__(self, max_spans: int = 10000, clock=time.monotonic):
        self.max_spans = max_spans
        self.finished: list[Span] = []
        self._by_trace: dict[int, list[Span]] = {}
        self._lock = sanitizer.make_lock(f"tracer.{id(self):x}.lock")
        self._clock = clock
        self._next_tid = 1

    def trace(self, name: str, trace_id: int | None = None) -> Span:
        """Open a root span (new trace id unless one is propagated).
        The local allocator always skips past propagated ids so two
        unrelated traces never share an id."""
        with self._lock:
            if trace_id is not None:
                tid = trace_id
                self._next_tid = max(self._next_tid, trace_id + 1)
            else:
                tid = self._next_tid
                self._next_tid += 1
        return Span(self, name, tid, None, self._clock)

    def _record(self, span: Span) -> None:
        with self._lock:
            self.finished.append(span)
            self._by_trace.setdefault(span.trace_id, []).append(span)
            excess = len(self.finished) - self.max_spans
            if excess > 0:
                evicted = self.finished[:excess]
                del self.finished[:excess]
                for s in evicted:
                    spans = self._by_trace.get(s.trace_id)
                    if spans is not None:
                        spans.remove(s)
                        if not spans:
                            del self._by_trace[s.trace_id]

    def spans_for(self, trace_id: int) -> list[Span]:
        with self._lock:
            return list(self._by_trace.get(trace_id, ()))

    def export_otlp_json(self) -> str:
        """OTLP/JSON-shaped export (the uploader's wire format)."""
        with self._lock:
            spans = list(self.finished)
        return json.dumps({
            "resourceSpans": [{
                "scopeSpans": [{
                    "spans": [{
                        "traceId": f"{s.trace_id:032x}",
                        "spanId": f"{s.span_id:016x}",
                        "parentSpanId": (f"{s.parent_id:016x}"
                                         if s.parent_id else ""),
                        "name": s.name,
                        "startTimeUnixNano": int(s.start * 1e9),
                        "endTimeUnixNano": int((s.end or s.start) * 1e9),
                        "attributes": [
                            {"key": k, "value": {"stringValue": str(v)}}
                            for k, v in s.attrs.items()
                        ],
                    } for s in spans],
                }],
            }],
        })
