"""Distributed tracing: spans with trace-id propagation.

Mirror of the reference's Wilson tracing (NWilson::TSpan
wilson/wilson_span.h:50, TTraceId wilson/wilson_trace.h, uploader ->
OTLP wilson/wilson_uploader.cpp; SURVEY.md §5.1): spans open under a
trace id, nest by parent span id, and finished spans collect in a
Tracer which exports OTLP-shaped JSON. The session opens a root span
per query; inner phases (compile/plan/execute) nest under it; actor
envelopes can carry the id across nodes.
"""

from __future__ import annotations

import itertools
import json
import time


_ids = itertools.count(1)


class Span:
    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 parent_id: int | None = None, clock=time.monotonic):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.attrs: dict = {}
        self._clock = clock
        self.start = clock()
        self.end: float | None = None

    def child(self, name: str) -> "Span":
        return Span(self.tracer, name, self.trace_id, self.span_id,
                    self._clock)

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def finish(self) -> None:
        if self.end is None:
            self.end = self._clock()
            self.tracer._record(self)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self.attrs["error"] = repr(exc)
        self.finish()


class Tracer:
    def __init__(self, max_spans: int = 10000, clock=time.monotonic):
        self.max_spans = max_spans
        self.finished: list[Span] = []
        self._clock = clock
        self._next_tid = 1

    def trace(self, name: str, trace_id: int | None = None) -> Span:
        """Open a root span (new trace id unless one is propagated).
        The local allocator always skips past propagated ids so two
        unrelated traces never share an id."""
        if trace_id is not None:
            tid = trace_id
            self._next_tid = max(self._next_tid, trace_id + 1)
        else:
            tid = self._next_tid
            self._next_tid += 1
        return Span(self, name, tid, None, self._clock)

    def _record(self, span: Span) -> None:
        self.finished.append(span)
        if len(self.finished) > self.max_spans:
            del self.finished[: len(self.finished) - self.max_spans]

    def spans_for(self, trace_id: int) -> list[Span]:
        return [s for s in self.finished if s.trace_id == trace_id]

    def export_otlp_json(self) -> str:
        """OTLP/JSON-shaped export (the uploader's wire format)."""
        return json.dumps({
            "resourceSpans": [{
                "scopeSpans": [{
                    "spans": [{
                        "traceId": f"{s.trace_id:032x}",
                        "spanId": f"{s.span_id:016x}",
                        "parentSpanId": (f"{s.parent_id:016x}"
                                         if s.parent_id else ""),
                        "name": s.name,
                        "startTimeUnixNano": int(s.start * 1e9),
                        "endTimeUnixNano": int((s.end or s.start) * 1e9),
                        "attributes": [
                            {"key": k, "value": {"stringValue": str(v)}}
                            for k, v in s.attrs.items()
                        ],
                    } for s in self.finished],
                }],
            }],
        })
