"""Dynamic counters: hierarchical metric trees + Prometheus text export.

Mirror of the reference's monlib dynamic counters (TDynamicCounters
library/cpp/monlib/dynamic_counters/counters.h; SURVEY.md §2.1, §5.5):
services create named subgroups, counters/gauges/histograms register by
name, and encoders walk the tree. One process-global root; tests make
private roots.
"""

from __future__ import annotations

import bisect
import threading

from ydb_tpu.analysis import sanitizer


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, by: int = 1):
        with self._lock:
            self.value += by

    def set(self, value):
        with self._lock:
            self.value = value


class Histogram:
    """Fixed-bucket histogram (exponential bounds by default).

    Default bounds reach DOWN to one microsecond: warm device ops run
    well under a millisecond, and the old 1ms floor quantized every
    sub-ms p50 up to it. ``percentile`` interpolates linearly WITHIN
    the winning bucket (the Prometheus ``histogram_quantile``
    convention) instead of answering with the bucket edge."""

    def __init__(self, bounds: tuple = ()):
        self.bounds = tuple(bounds) or tuple(
            1e-6 * (4 ** i) for i in range(16))  # 1us .. ~1074s
        self.buckets = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float):
        with self._lock:
            idx = bisect.bisect_left(self.bounds, value)
            self.buckets[idx] += 1
            self.count += 1
            self.total += value

    def percentile(self, q: float) -> float:
        with self._lock:
            if not self.count:
                return 0.0
            target = q * self.count
            acc = 0
            for i, n in enumerate(self.buckets):
                if not n:
                    continue
                acc += n
                if acc >= target:
                    if i >= len(self.bounds):
                        # overflow bucket: no finite upper edge to
                        # interpolate toward — report its lower edge
                        return self.bounds[-1] if self.bounds else 0.0
                    lo = self.bounds[i - 1] if i else 0.0
                    hi = self.bounds[i]
                    frac = (target - (acc - n)) / n
                    return lo + (hi - lo) * frac
            return self.bounds[-1] if self.bounds else 0.0


class CounterGroup:
    def __init__(self, labels: dict | None = None):
        self.labels = dict(labels or {})
        # registry dicts are sanitizer-tracked under YDB_TPU_TSAN=1
        # (services register counters from conveyor workers + API
        # threads concurrently)
        self._children = sanitizer.share(
            {}, f"counters.{id(self):x}.children")
        self._counters = sanitizer.share(
            {}, f"counters.{id(self):x}.counters")
        self._histograms = sanitizer.share(
            {}, f"counters.{id(self):x}.histograms")
        self._lock = sanitizer.make_lock(f"counters.{id(self):x}.lock")

    def group(self, **labels) -> "CounterGroup":
        key = tuple(sorted(labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                merged = dict(self.labels, **labels)
                child = self._children[key] = CounterGroup(merged)
            return child

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def histogram(self, name: str, bounds: tuple = ()) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(bounds)
            return h

    # ---- encoding ----

    def _label_str(self) -> str:
        if not self.labels:
            return ""
        inner = ",".join(f'{k}="{v}"'
                         for k, v in sorted(self.labels.items()))
        return "{" + inner + "}"

    def encode_prometheus(self) -> str:
        lines = []
        self._encode(lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def _encode(self, lines: list):
        ls = self._label_str()
        # registry iteration must share the writers' lock: a service
        # registering a counter mid-scrape would resize the dict under
        # the encoder (dynamic race found by the TSAN stress suite).
        # Child encoding happens OUTSIDE it — parent->child is the only
        # acquisition order, and values render from a stable snapshot.
        with self._lock:
            counters = sorted(self._counters.items())
            hists = sorted(self._histograms.items())
            children = list(self._children.values())
        for name, c in counters:
            lines.append(f"{name}{ls} {c.value}")
        for name, h in hists:
            lines.append(f"{name}_count{ls} {h.count}")
            lines.append(f"{name}_sum{ls} {h.total}")
            acc = 0
            bounds = [str(b) for b in h.bounds] + ["+Inf"]
            for bound, n in zip(bounds, h.buckets):
                acc += n
                le = dict(self.labels, le=bound)
                inner = ",".join(
                    f'{k}="{v}"' for k, v in sorted(le.items()))
                lines.append(f"{name}_bucket{{{inner}}} {acc}")
        for child in children:
            child._encode(lines)

    def snapshot(self) -> dict:
        """Flat dict for sys views / tests."""
        out = {}
        self._snap(out)
        return out

    def _snap(self, out: dict):
        prefix = ",".join(f"{k}={v}"
                          for k, v in sorted(self.labels.items()))
        with self._lock:
            counters = list(self._counters.items())
            hists = list(self._histograms.items())
            children = list(self._children.values())
        for name, c in counters:
            out[f"{name}|{prefix}"] = c.value
        for name, h in hists:
            out[f"{name}_count|{prefix}"] = h.count
        for child in children:
            child._snap(out)


_root = CounterGroup()


def root_counters() -> CounterGroup:
    return _root
