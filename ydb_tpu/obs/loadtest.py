"""Embedded load-test service: config-driven synthetic load actors.

Mirror of the reference's load-test plane (ydb/core/load_test/
service_actor.cpp + per-kind actors: kqp.cpp select/upsert load,
group_write.cpp storage load, ut_ycsb.cpp YCSB-style keyed workload):
a service that runs a named load against the live cluster and returns
a latency/throughput report. Loads run inline in bounded iterations
(the test-friendly shape of the reference's actor loops); the report
carries exact nearest-rank p50/p90/p99 over the recorded latencies
(finer-grained than the counters plane's bucketed histograms, which
track the same requests via the session path).

Kinds:
  * "kv_upsert"  — YCSB-ish keyed upserts through SQL
  * "select"     — point/range selects through SQL
  * "storage_put" — raw blob-store put/get roundtrips
"""

from __future__ import annotations

import time

import numpy as np


def _report(kind: str, latencies_s: list[float],
            errors: int) -> dict:
    lat = np.asarray(sorted(latencies_s), dtype=np.float64)
    n = len(lat)

    def pct(q):
        if n == 0:
            return 0.0
        return float(lat[min(n - 1, int(q * n))]) * 1e3

    total = float(lat.sum())
    return dict(
        kind=kind, requests=n, errors=errors,
        seconds=round(total, 6),
        rps=round(n / total, 1) if total > 0 else 0.0,
        p50_ms=round(pct(0.50), 3), p90_ms=round(pct(0.90), 3),
        p99_ms=round(pct(0.99), 3),
    )


class LoadService:
    """Runs synthetic loads against a Cluster."""

    def __init__(self, cluster, seed: int = 7):
        self.cluster = cluster
        self.rng = np.random.default_rng(seed)
        self.history: list[dict] = []

    def run(self, kind: str, requests: int = 100, **params) -> dict:
        fn = {
            "kv_upsert": self._kv_upsert,
            "select": self._select,
            "storage_put": self._storage_put,
        }.get(kind)
        if fn is None:
            raise KeyError(f"unknown load kind {kind}")
        report = fn(requests, **params)
        self.history.append(report)
        return report

    def _ensure_table(self, session, table: str) -> None:
        if table not in self.cluster.tables:
            session.execute(
                f"CREATE TABLE {table} (k int64, v int64, "
                f"PRIMARY KEY (k)) WITH (store = row)")

    def _kv_upsert(self, requests: int, table: str = "load_kv",
                   key_space: int = 1000) -> dict:
        s = self.cluster.session()
        self._ensure_table(s, table)
        lats, errors = [], 0
        for _ in range(requests):
            k = int(self.rng.integers(0, key_space))
            v = int(self.rng.integers(0, 1 << 31))
            t0 = time.perf_counter()
            try:
                s.execute(f"UPSERT INTO {table} (k, v) "
                          f"VALUES ({k}, {v})")
            except Exception:  # noqa: BLE001 - load keeps going
                errors += 1
            lats.append(time.perf_counter() - t0)
        return _report("kv_upsert", lats, errors)

    def _select(self, requests: int, table: str = "load_kv",
                key_space: int = 1000) -> dict:
        s = self.cluster.session()
        self._ensure_table(s, table)
        lats, errors = [], 0
        for _ in range(requests):
            k = int(self.rng.integers(0, key_space))
            t0 = time.perf_counter()
            try:
                s.execute(f"SELECT v FROM {table} WHERE k = {k}")
            except Exception:  # noqa: BLE001
                errors += 1
            lats.append(time.perf_counter() - t0)
        return _report("select", lats, errors)

    def _storage_put(self, requests: int,
                     blob_bytes: int = 4096) -> dict:
        store = self.cluster.store
        payload = bytes(self.rng.integers(
            0, 256, blob_bytes, dtype=np.uint8))
        lats, errors = [], 0
        for i in range(requests):
            key = f"loadtest/blob/{i}"
            t0 = time.perf_counter()
            try:
                store.put(key, payload)
                if store.get(key) != payload:
                    errors += 1
                store.delete(key)
            except Exception:  # noqa: BLE001
                errors += 1
            lats.append(time.perf_counter() - t0)
        return _report("storage_put", lats, errors)
