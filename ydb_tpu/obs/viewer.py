"""Embedded monitoring HTTP endpoint: viewer JSON APIs + whiteboard.

Mirror of the reference's monitoring plane (core/viewer/viewer.cpp
JSON handlers, core/mon/mon.cpp HTTP core, node whiteboard
tablet/node_whiteboard.cpp; SURVEY.md §2.12 row "embedded UI" and §5.5):
one HTTP listener per node serving live cluster state as JSON plus the
Prometheus counters page. Read-only: handlers snapshot cluster state
under the shared cluster lock; sys-view row materialization and JSON
encoding happen off-lock so monitoring polls stay cheap for query
traffic. When the cluster runs with auth tokens, requests must carry
``Authorization: Bearer <token>``.

Endpoints:
  /                         index (plain text listing)
  /viewer/json/cluster      cluster summary (tables/topics/storage)
  /viewer/json/scheme       scheme path tree
  /viewer/json/tables       per-table partition stats
  /viewer/json/topics       per-topic partition offsets
  /viewer/json/healthcheck  aggregated health (GOOD/DEGRADED/...)
  /viewer/json/whiteboard   per-node live snapshot (uptime, queries,
                            memory, session counts)
  /viewer/json/sysview?name=sys_query_stats   any sys view as rows
  /viewer/json/timeline     data-movement timeline summary + in-flight
                            statements; ?trace=1 = Chrome trace JSON
  /counters                 counters snapshot (JSON tree)
  /counters/prometheus      Prometheus text encoding
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from ydb_tpu.obs import sysview


def _source_rows(src) -> list[dict]:
    """Render a ColumnSource as a list of JSON-ready row dicts."""
    out = []
    n = src.num_rows
    cols = {}
    for f in src.schema.fields:
        vals = np.asarray(src.columns[f.name])
        if f.type.is_string and src.dicts is not None:
            d = src.dicts[f.name]
            cols[f.name] = [
                v.decode("utf-8", "surrogateescape")
                for v in d.decode(vals)]
        elif f.type.is_decimal:
            cols[f.name] = [int(v) / 10 ** f.type.scale for v in vals]
        else:
            cols[f.name] = [v.item() for v in vals]
    for i in range(n):
        out.append({k: v[i] for k, v in cols.items()})
    return out


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet; the access log is not ours
        pass

    def do_GET(self):  # noqa: N802 - http.server API
        viewer: Viewer = self.server.viewer  # type: ignore[attr-defined]
        url = urlparse(self.path)
        if viewer.auth_tokens is not None:
            auth = self.headers.get("Authorization", "")
            token = auth[7:] if auth.startswith("Bearer ") else ""
            if token not in viewer.auth_tokens:
                self.send_error(401, "bad or missing bearer token")
                return
        try:
            body, ctype = viewer.render(url.path, parse_qs(url.query))
        except KeyError as e:
            self.send_error(404, str(e))
            return
        except Exception as e:  # noqa: BLE001 - surface, don't die
            self.send_error(500, repr(e))
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class Viewer:
    """Monitoring HTTP server over a Cluster."""

    def __init__(self, cluster, host: str = "127.0.0.1", port: int = 0,
                 lock: threading.Lock | None = None, node_id: int = 1,
                 auth_tokens: set[str] | None = None):
        self.cluster = cluster
        self.node_id = node_id
        self.auth_tokens = auth_tokens
        self.lock = lock if lock is not None else threading.Lock()
        self.started_at = time.time()
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.viewer = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    # -- lifecycle --

    def start(self) -> "Viewer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="viewer-http")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    # -- rendering --

    def render(self, path: str, query: dict) -> tuple[bytes, str]:
        if path == "/counters/prometheus":
            with self.lock:
                text = self.cluster.counters.encode_prometheus()
            return text.encode(), "text/plain; version=0.0.4"
        if path in ("/viewer", "/monitoring"):
            from ydb_tpu.obs.viewer_html import PAGE

            return PAGE.encode(), "text/html; charset=utf-8"
        handlers = {
            "/": self._index,
            "/viewer/json/cluster": self._cluster,
            "/viewer/json/scheme": self._scheme,
            "/viewer/json/tables": self._tables,
            "/viewer/json/topics": self._topics,
            "/viewer/json/healthcheck": self._health,
            "/viewer/json/whiteboard": self._whiteboard,
            "/viewer/json/sysview": self._sysview,
            "/viewer/json/tablets": self._tablets,
            "/viewer/json/statistics": self._statistics,
            "/viewer/json/resident": self._resident,
            "/viewer/json/query_profile": self._query_profile,
            "/viewer/json/timeline": self._timeline,
            "/counters": self._counters,
        }
        h = handlers.get(path)
        if h is None:
            raise KeyError(f"no endpoint {path}")
        if path == "/":
            return h(query), "text/plain"
        with self.lock:
            payload = h(query)
        # sys-view handlers return a ColumnSource snapshot: its column
        # arrays are materialized (cluster no longer referenced), so the
        # O(rows) python-object conversion runs off-lock
        if hasattr(payload, "schema") and hasattr(payload, "columns"):
            payload = _source_rows(payload)
        return (json.dumps(payload, indent=1).encode(),
                "application/json")

    def _index(self, query) -> bytes:
        return __doc__.encode()

    def _cluster(self, query) -> dict:
        c = self.cluster
        return {
            "tables": sorted(c.tables),
            "topics": sorted(c.topics),
            "store": type(c.store).__name__,
            "node_id": self.node_id,
            "uptime_seconds": round(time.time() - self.started_at, 1),
        }

    def _scheme(self, query) -> list[dict]:
        out = []
        for (p,), row in self.cluster.scheme.executor.db.table(
                "paths").range():
            out.append({"path": p, "type": row["type"]})
        return out

    def _tables(self, query):
        return sysview.sys_source(self.cluster, "sys_partition_stats")

    def _topics(self, query) -> list[dict]:
        out = []
        for name, t in sorted(self.cluster.topics.items()):
            for pi, p in enumerate(t.partitions):
                out.append({
                    "topic": name, "partition": pi,
                    "start_offset": p.tail_offset,
                    "end_offset": p.head_offset,
                })
        return out

    def _health(self, query) -> dict:
        return sysview.health_check(self.cluster)

    def _whiteboard(self, query) -> dict:
        """Per-node live snapshot (node_whiteboard.cpp:23 analog)."""
        from ydb_tpu.obs.probes import memory_stats

        c = self.cluster
        qlog = list(c.query_log)[-10:]
        return {
            "node_id": self.node_id,
            "uptime_seconds": round(time.time() - self.started_at, 1),
            "tables": len(c.tables),
            "topics": len(c.topics),
            "recent_queries": [
                {"sql": q["sql"][:120], "kind": q["kind"],
                 "duration_us": int(q["seconds"] * 1e6)}
                for q in qlog],
            "memory": {k: v for k, v in memory_stats().items()
                       if v is not None},
        }

    def _sysview(self, query):
        names = query.get("name")
        if not names:
            return sorted(sysview.SYS_SCHEMAS)
        return sysview.sys_source(self.cluster, names[0])

    def _resident(self, query) -> dict:
        """HBM-resident column tier (engine/resident.py): per-shard
        pinned bytes vs budget plus the promotion/eviction lifecycle —
        whether the hot set is actually resident, and what pressure is
        doing to it."""
        rows = _source_rows(
            sysview.sys_source(self.cluster, "sys_resident_store"))
        total = {"bytes": 0, "budget": 0, "portions": 0,
                 "promotions": 0, "evictions": 0, "spills": 0,
                 "hits": 0, "misses": 0}
        for r in rows:
            for k in total:
                total[k] += r.get(k, 0)
        return {"shards": rows, "total": total}

    def _statistics(self, query) -> dict:
        """Column statistics + scan-pruning effectiveness (the stats
        subsystem's monitoring face): table NDV/null fractions from the
        aggregator and per-shard pruning counters, so a pruning
        regression is visible without a bench run."""
        return {
            "columns": _source_rows(
                sysview.sys_source(self.cluster, "sys_statistics")),
            "pruning": _source_rows(
                sysview.sys_source(self.cluster, "sys_scan_pruning")),
        }

    def _query_profile(self, query) -> dict:
        """Per-query profiles from the bounded ring (the top-queries /
        EXPLAIN-ANALYZE data over HTTP): the N most expensive recent
        queries plus the latest profile with its full span tree.
        ``?seq=N`` selects one profile by ring sequence number."""
        ring = self.cluster.profiles
        seqs = query.get("seq")
        if seqs:
            want = int(seqs[0])
            for p in ring.recent():
                if p.seq == want:
                    return dict(p.to_dict(), span_tree=p.span_tree())
            raise KeyError(f"no profile seq={want}")
        recent = ring.recent()
        last = recent[-1] if recent else None
        return {
            "top": [p.to_dict() for p in ring.top(16)],
            "recent": [
                {"seq": p.seq, "query_text": p.sql[:120],
                 "kind": p.kind, "query_class": p.query_class,
                 "seconds": round(p.seconds, 6), "rows": p.rows}
                for p in recent],
            "last": (dict(last.to_dict(), span_tree=last.span_tree())
                     if last is not None else None),
        }

    def _timeline(self, query) -> dict:
        """Data-movement timeline (obs.timeline): ring summary with
        per-category busy seconds, movement byte counters and the
        in-flight statement list; ``?trace=1`` returns the full
        Chrome/Perfetto trace_event JSON instead (save it and open in
        chrome://tracing or https://ui.perfetto.dev)."""
        from ydb_tpu.obs import timeline

        if query.get("trace", ["0"])[0] not in ("", "0"):
            return timeline.export_chrome_trace()
        out = timeline.summary()
        out["active_queries"] = self.cluster.active_query_snapshot()
        return out

    def _tablets(self, query) -> dict:
        """Per-tablet counters + per-type aggregates (the counters-
        aggregator merge, tablet_counters_aggregator.cpp)."""
        from ydb_tpu.obs import tablet_counters

        rows = tablet_counters.collect(self.cluster)
        return {
            "tablets": rows,
            "aggregates": tablet_counters.aggregate(
                self.cluster, rows),
        }

    def _counters(self, query) -> dict:
        return self.cluster.counters.snapshot()
