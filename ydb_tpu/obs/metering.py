"""Usage metering: request-unit records + periodic aggregation.

Mirror of the reference's metering plane (ydb/core/metering/
metering.h:57 — billing records emitted per consumed resource as JSON
lines, aggregated per interval per cloud/folder/resource): each served
request books request units (reads by rows returned, writes/DDL a
flat unit), records append to a bounded in-memory log with optional
JSONL sink, and ``aggregate`` folds them into per-(tenant, resource,
interval) totals — the shape a billing pipeline consumes.
"""

from __future__ import annotations

import json
import time
from collections import deque


# request-unit schedule (the RU model): reads bill per 128 rows
# returned (min 1), mutations and DDL a flat unit
READ_ROWS_PER_UNIT = 128


def request_units(kind: str, rows: int) -> int:
    if kind in ("select", "explain"):
        return max(1, (rows + READ_ROWS_PER_UNIT - 1)
                   // READ_ROWS_PER_UNIT)
    return 1


class Metering:
    """Bounded usage-record log with JSONL sink + aggregation."""

    def __init__(self, tenant: str = "/Root", sink=None,
                 max_records: int = 4096, now=time.time):
        self.tenant = tenant
        self.sink = sink      # file-like; one JSON per line when set
        self.now = now
        self.records: deque = deque(maxlen=max_records)

    def record(self, resource: str, units: int,
               tenant: str | None = None) -> dict:
        rec = {
            "tenant": tenant or self.tenant,
            "resource": resource,
            "units": int(units),
            "ts": self.now(),
        }
        self.records.append(rec)
        if self.sink is not None:
            self.sink.write(json.dumps(rec) + "\n")
        return rec

    def aggregate(self, interval_s: float = 3600.0) -> list[dict]:
        """Fold records into per-(tenant, resource, interval) sums,
        sorted by interval start."""
        out: dict[tuple, int] = {}
        for r in self.records:
            start = int(r["ts"] // interval_s) * interval_s
            key = (r["tenant"], r["resource"], start)
            out[key] = out.get(key, 0) + r["units"]
        return [
            {"tenant": t, "resource": res, "interval_start": start,
             "units": units}
            for (t, res, start), units in sorted(out.items(),
                                                 key=lambda kv: kv[0][2])
        ]

    def total_units(self, resource: str | None = None) -> int:
        return sum(r["units"] for r in self.records
                   if resource is None or r["resource"] == resource)
