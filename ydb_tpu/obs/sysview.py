"""System views + health check.

Mirror of the reference's sys_view providers (`SELECT ... FROM .sys
tables`: partition_stats, query_stats, nodes — core/sys_view;
SURVEY.md §2.14) and the health-check service
(core/health_check/health_check.cpp): live cluster state exposed
through the NORMAL query path — sys tables materialize as ColumnSources
injected into the snapshot database, so the planner/executor treat
them like any table (dots become underscores: sys_partition_stats).
"""

from __future__ import annotations

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.engine.scan import ColumnSource


SYS_SCHEMAS = {
    "sys_partition_stats": dtypes.schema(
        ("table_name", dtypes.STRING), ("shard", dtypes.INT32),
        ("store", dtypes.STRING), ("rows", dtypes.INT64),
        ("portions", dtypes.INT32)),
    "sys_query_stats": dtypes.schema(
        ("query_text", dtypes.STRING), ("kind", dtypes.STRING),
        ("duration_us", dtypes.INT64), ("result_rows", dtypes.INT64)),
    "sys_scheme_paths": dtypes.schema(
        ("path", dtypes.STRING), ("kind", dtypes.STRING)),
    # statistics service analog (ydb/core/statistics): per-table stats
    # for cost-based planning, collected from portion metadata (cheap —
    # no scan)
    "sys_table_stats": dtypes.schema(
        ("table_name", dtypes.STRING), ("rows", dtypes.INT64),
        ("portions", dtypes.INT64), ("pk_min", dtypes.INT64),
        ("pk_max", dtypes.INT64)),
    # audit log (ydb/core/audit): state-changing statements
    "sys_audit": dtypes.schema(
        ("kind", dtypes.STRING), ("sql", dtypes.STRING),
        ("status", dtypes.STRING), ("duration_us", dtypes.INT64)),
    # memory observability (memory profiling row): process + device
    "sys_memory": dtypes.schema(
        ("metric", dtypes.STRING), ("value", dtypes.DOUBLE)),
    # per-tablet executor counters (tablet_counters_aggregator feed)
    "sys_tablet_counters": dtypes.schema(
        ("tablet_id", dtypes.STRING), ("type", dtypes.STRING),
        ("generation", dtypes.INT32), ("tx_executed", dtypes.INT64),
        ("tx_committed", dtypes.INT64), ("redo_bytes", dtypes.INT64),
        ("checkpoints", dtypes.INT64)),
    # column statistics (StatisticsAggregator feed, ydb/core/statistics
    # analog): table-level NDV / null fractions / physical value bounds
    # per column — what the planner's estimates are built from
    "sys_statistics": dtypes.schema(
        ("table_name", dtypes.STRING), ("column_name", dtypes.STRING),
        ("ndv", dtypes.INT64), ("null_fraction", dtypes.DOUBLE),
        ("rows", dtypes.INT64), ("vmin", dtypes.DOUBLE),
        ("vmax", dtypes.DOUBLE)),
    # per-shard scan-pruning effectiveness (cumulative since boot):
    # pruning regressions show here without a bench run
    "sys_scan_pruning": dtypes.schema(
        ("table_name", dtypes.STRING), ("shard", dtypes.INT32),
        ("scans", dtypes.INT64), ("portions_total", dtypes.INT64),
        ("portions_skipped", dtypes.INT64),
        ("chunks_read", dtypes.INT64), ("chunks_skipped", dtypes.INT64),
        ("chunks_fastpath", dtypes.INT64),
        ("filters_dropped", dtypes.INT64)),
    # the N most expensive recent queries with their profiles (the
    # reference's .sys/top_queries): backed by the bounded profile ring
    "sys_top_queries": dtypes.schema(
        ("rank", dtypes.INT32), ("query_text", dtypes.STRING),
        ("kind", dtypes.STRING), ("query_class", dtypes.STRING),
        ("seconds", dtypes.DOUBLE), ("rows", dtypes.INT64),
        ("compile_seconds", dtypes.DOUBLE),
        ("execute_seconds", dtypes.DOUBLE),
        ("plan_cache", dtypes.STRING), ("compile_cache", dtypes.STRING),
        ("read_seconds", dtypes.DOUBLE),
        ("merge_seconds", dtypes.DOUBLE),
        ("stage_seconds", dtypes.DOUBLE),
        ("compute_seconds", dtypes.DOUBLE),
        ("portions_skipped", dtypes.INT64),
        ("chunks_read", dtypes.INT64),
        ("chunks_skipped", dtypes.INT64),
        ("error", dtypes.INT32),
        ("error_reason", dtypes.STRING),
        ("batch_id", dtypes.INT64), ("batch_size", dtypes.INT32),
        ("shared_scan", dtypes.INT32), ("tenant", dtypes.STRING)),
    # HBM-resident column tier (engine/resident.py): per-shard pinned
    # bytes vs budget plus promotion/eviction/spill lifecycle counters
    # — the "is the hot set actually resident" dashboard
    "sys_resident_store": dtypes.schema(
        ("table_name", dtypes.STRING), ("shard", dtypes.INT32),
        ("enabled", dtypes.INT32), ("portions", dtypes.INT64),
        ("columns", dtypes.INT64), ("bytes", dtypes.INT64),
        ("budget", dtypes.INT64), ("hits", dtypes.INT64),
        ("misses", dtypes.INT64), ("promotions", dtypes.INT64),
        ("evictions", dtypes.INT64), ("spills", dtypes.INT64),
        ("invalidations", dtypes.INT64), ("errors", dtypes.INT64),
        ("inflight", dtypes.INT64)),
    # recent queries in arrival order with profile summaries (the
    # profile-ring twin of sys_query_stats, which stays text-only)
    "sys_query_log": dtypes.schema(
        ("seq", dtypes.INT64), ("query_text", dtypes.STRING),
        ("kind", dtypes.STRING), ("query_class", dtypes.STRING),
        ("seconds", dtypes.DOUBLE), ("rows", dtypes.INT64),
        ("trace_id", dtypes.INT64), ("spans", dtypes.INT64)),
    # live in-flight statements (the reference's .sys running-queries
    # introspection): fed by the Cluster active-query registry, which
    # sessions enter before admission and leave on completion/failure
    "sys_active_queries": dtypes.schema(
        ("query_text", dtypes.STRING), ("kind", dtypes.STRING),
        ("stage", dtypes.STRING), ("elapsed_seconds", dtypes.DOUBLE),
        ("rows", dtypes.INT64), ("queue_position", dtypes.INT32),
        ("trace_id", dtypes.INT64),
        ("batch_id", dtypes.INT64), ("batch_size", dtypes.INT32),
        ("shared_scan", dtypes.INT32), ("tenant", dtypes.STRING)),
    # device-memory footprint ledger (analysis.memsan,
    # YDB_TPU_MEMSAN=1): per-component live/peak HBM bytes plus
    # charge/release/eviction lifecycle counters, with a "<global>"
    # row carrying the process-wide peak and armed budget — the "where
    # did the HBM go" dashboard; empty while the sanitizer is off
    "sys_device_memory": dtypes.schema(
        ("component", dtypes.STRING), ("live_bytes", dtypes.INT64),
        ("peak_bytes", dtypes.INT64), ("charges", dtypes.INT64),
        ("releases", dtypes.INT64), ("evictions", dtypes.INT64)),
    # the front door's workload pools (serving/): per-tenant weights,
    # budget shares and admission counters — the ".sys resource pools"
    # dashboard an operator reads during an overload
    "sys_tenant_pools": dtypes.schema(
        ("tenant", dtypes.STRING), ("weight", dtypes.DOUBLE),
        ("inflight", dtypes.INT32), ("max_inflight", dtypes.INT32),
        ("queued", dtypes.INT32), ("queue_size", dtypes.INT32),
        ("admitted", dtypes.INT64), ("shed", dtypes.INT64),
        ("pool_limit", dtypes.INT32),
        ("conveyor_workers", dtypes.INT32),
        ("resident_bytes", dtypes.INT64)),
}


def _source(name: str, rows, dicts) -> ColumnSource:
    """rows: per-column python lists, ordered per SYS_SCHEMAS[name]."""
    schema = SYS_SCHEMAS[name]
    arrays = {}
    for f, values in zip(schema.fields, rows):
        if f.type.is_string:
            d = dicts.for_column(f.name)
            arrays[f.name] = np.asarray(
                [d.add(v.encode() if isinstance(v, str) else v)
                 for v in values], dtype=np.int32)
        else:
            arrays[f.name] = np.asarray(values, dtype=f.type.physical)
    return ColumnSource(arrays, schema, dicts)


def _partition_stats_rows(cluster):
    names, shards, kinds, rows_c, extra = [], [], [], [], []
    for tname, t in cluster.tables.items():
        for i, s in enumerate(t.shards):
            names.append(tname)
            shards.append(i)
            if hasattr(s, "portions"):  # ColumnShard
                kinds.append("column")
                vis = s.visible_portions()
                rows_c.append(int(sum(p.num_rows for p in vis)))
                extra.append(len(vis))
            else:                        # DataShard
                kinds.append("row")
                n = sum(len(page) for page in s.read(s.last_step))
                rows_c.append(n)
                extra.append(0)
    return [names, shards, kinds, rows_c, extra]


def _query_stats_rows(cluster):
    log = list(cluster.query_log)
    return [[q["sql"][:256] for q in log], [q["kind"] for q in log],
            [int(q["seconds"] * 1e6) for q in log],
            [q["rows"] for q in log]]


def _scheme_paths_rows(cluster):
    paths, kinds = [], []
    for (p,), row in cluster.scheme.executor.db.table("paths").range():
        paths.append(p)
        kinds.append(row["type"])
    return [paths, kinds]


def table_stats(cluster, cheap: bool = True) -> dict[str, dict]:
    """Per-table statistics from portion metas (the statistics-service
    collection path): row counts + PK bounds; feeds CBO join ordering
    (Catalog.row_counts) and the sys_table_stats view.

    ``cheap`` (the per-plan CBO feed) reads column-shard portion
    METADATA only; row tables report rows=0 (unknown) rather than
    paying a full page walk on every statement plan. The sys view
    passes cheap=False for exact counts."""
    out: dict[str, dict] = {}
    for tname, t in cluster.tables.items():
        rows = 0
        unknown = False
        portions = 0
        pk_min = pk_max = None
        for s in t.shards:
            if not hasattr(s, "portions"):
                if cheap:
                    unknown = True  # no metadata count for row tables
                else:
                    # row table: page walk (exact, O(rows))
                    rows += sum(
                        len(page) for page in s.read(s.last_step))
                continue
            for m in s.visible_portions():
                rows += m.num_rows
                portions += 1
                if m.pk_min is not None:
                    pk_min = (m.pk_min if pk_min is None
                              else min(pk_min, m.pk_min))
                if m.pk_max is not None:
                    pk_max = (m.pk_max if pk_max is None
                              else max(pk_max, m.pk_max))
        out[tname] = {"rows": None if unknown else rows,
                      "portions": portions,
                      "pk_min": pk_min, "pk_max": pk_max}
    return out


def _table_stats_rows(cluster):
    st = table_stats(cluster, cheap=False)
    names = sorted(st)
    return [
        names,
        [st[n]["rows"] for n in names],
        [st[n]["portions"] for n in names],
        [st[n]["pk_min"] or 0 for n in names],
        [st[n]["pk_max"] or 0 for n in names],
    ]


def _audit_rows(cluster):
    log = list(cluster.audit_log)
    return [[a["kind"] for a in log], [a["sql"] for a in log],
            [a["status"] for a in log],
            [a["duration_us"] for a in log]]


def _memory_rows(cluster):
    from ydb_tpu.obs.probes import memory_stats

    st = memory_stats()
    keys = sorted(k for k, v in st.items() if v is not None)
    return [keys, [float(st[k]) for k in keys]]


def _tablet_counters_rows(cluster):
    from ydb_tpu.obs.tablet_counters import collect

    rows = collect(cluster)
    return [[r["tablet_id"] for r in rows],
            [r["type"] for r in rows],
            [r["generation"] for r in rows],
            [r["tx_executed"] for r in rows],
            [r["tx_committed"] for r in rows],
            [r["redo_bytes"] for r in rows],
            [r["checkpoints"] for r in rows]]


def _statistics_rows(cluster):
    """Aggregator column statistics; refreshes tables with no cached
    stats yet (first read after boot) but serves cached snapshots
    otherwise — the run_background cadence owns recomputation."""
    stats = cluster.stats.all_stats()
    missing = {
        name: list(getattr(t, "shards", ()))
        for name, t in cluster.tables.items()
        if name not in stats and hasattr(t, "shards")
        and any(hasattr(s, "portions") for s in t.shards)
    }
    if missing:
        stats.update(cluster.stats.refresh_tables(missing))
    tables, columns, ndv, nullf, rows, vmin, vmax = \
        [], [], [], [], [], [], []
    for tname in sorted(stats):
        st = stats[tname]
        for col in sorted(st.columns):
            cs = st.columns[col]
            tables.append(tname)
            columns.append(col)
            ndv.append(cs.ndv)
            nullf.append(cs.null_fraction)
            rows.append(cs.rows)
            vmin.append(float(cs.vmin) if cs.vmin is not None else 0.0)
            vmax.append(float(cs.vmax) if cs.vmax is not None else 0.0)
    return [tables, columns, ndv, nullf, rows, vmin, vmax]


def _scan_pruning_rows(cluster):
    cols: list[list] = [[] for _ in range(9)]
    for tname, t in cluster.tables.items():
        for i, s in enumerate(getattr(t, "shards", ())):
            totals = getattr(s, "pruning_totals", None)
            if totals is None:
                continue
            lock = getattr(s, "_stats_lock", None)
            if lock is not None:
                with lock:
                    snap = dict(totals)
            else:
                snap = dict(totals)
            row = [tname, i, snap["scans"], snap["portions_total"],
                   snap["portions_skipped"], snap["chunks_read"],
                   snap["chunks_skipped"], snap["chunks_fastpath"],
                   snap["filters_dropped"]]
            for c, v in zip(cols, row):
                c.append(v)
    return cols


def _top_queries_rows(cluster):
    cols: list[list] = [[] for _ in range(23)]
    for rank, p in enumerate(cluster.profiles.top(16), start=1):
        st = p.stages
        pr = p.pruning
        row = [rank, p.sql[:256], p.kind, p.query_class,
               p.seconds, p.rows, p.compile_seconds, p.execute_seconds,
               p.plan_cache or "", p.compile_cache or "",
               st.get("read", 0.0), st.get("merge", 0.0),
               st.get("stage", 0.0), st.get("compute", 0.0),
               pr.get("portions_skipped", 0), pr.get("chunks_read", 0),
               pr.get("chunks_skipped", 0), getattr(p, "error", 0),
               getattr(p, "error_reason", ""),
               getattr(p, "batch_id", 0), getattr(p, "batch_size", 0),
               getattr(p, "shared_scan", 0), getattr(p, "tenant", "")]
        for c, v in zip(cols, row):
            c.append(v)
    return cols


def _resident_store_rows(cluster):
    cols: list[list] = [[] for _ in range(15)]
    for tname, t in cluster.tables.items():
        for i, s in enumerate(t.shards):
            store = getattr(s, "resident", None)
            if store is None:  # DataShard
                continue
            snap = store.snapshot()
            row = [tname, i, int(store.enabled()), snap["portions"],
                   snap["columns"], snap["bytes"], snap["budget"],
                   snap["hits"], snap["misses"], snap["promotions"],
                   snap["evictions"], snap["spills"],
                   snap["invalidations"], snap["errors"],
                   snap["inflight"]]
            for c, v in zip(cols, row):
                c.append(v)
    return cols


def _active_queries_rows(cluster):
    cols: list[list] = [[] for _ in range(11)]
    for e in cluster.active_query_snapshot():
        row = [e["sql"][:256], e["kind"], e["stage"],
               e["elapsed_seconds"], e["rows"], e["queue_position"],
               e["trace_id"], e.get("batch_id", 0),
               e.get("batch_size", 0), e.get("shared_scan", 0),
               e.get("tenant", "")]
        for c, v in zip(cols, row):
            c.append(v)
    return cols


def _tenant_pools_rows(cluster):
    cols: list[list] = [[] for _ in range(11)]
    fd = getattr(cluster, "front_door", None)
    if fd is None:
        return cols  # no front door: the view exists but is empty
    for name, row in fd.snapshot().items():
        vals = [name, row["weight"], row["inflight"],
                row["max_inflight"], row["queued"], row["queue_size"],
                row["admitted"], row["shed"], row["pool_limit"],
                row["conveyor_workers"], row["resident_bytes"]]
        for c, v in zip(cols, vals):
            c.append(v)
    return cols


def _device_memory_rows(cluster):
    from ydb_tpu.analysis import memsan

    cols: list[list] = [[] for _ in range(6)]
    if not memsan.armed():
        return cols  # sanitizer off: the view exists but is empty
    totals = memsan.component_totals()
    for comp in sorted(totals):
        t = totals[comp]
        row = [comp, t["live"], t["peak"], t["charges"],
               t["releases"], t["evictions"]]
        for c, v in zip(cols, row):
            c.append(v)
    live = sum(t["live"] for t in totals.values())
    charges = sum(t["charges"] for t in totals.values())
    releases = sum(t["releases"] for t in totals.values())
    evictions = sum(t["evictions"] for t in totals.values())
    row = ["<global>", live, memsan.global_peak(), charges, releases,
           evictions]
    for c, v in zip(cols, row):
        c.append(v)
    return cols


def _query_log_rows(cluster):
    cols: list[list] = [[] for _ in range(8)]
    for p in cluster.profiles.recent():
        row = [p.seq, p.sql[:256], p.kind, p.query_class, p.seconds,
               p.rows, p.trace_id, len(p.spans)]
        for c, v in zip(cols, row):
            c.append(v)
    return cols


_BUILDERS = {
    "sys_partition_stats": _partition_stats_rows,
    "sys_query_stats": _query_stats_rows,
    "sys_scheme_paths": _scheme_paths_rows,
    "sys_table_stats": _table_stats_rows,
    "sys_audit": _audit_rows,
    "sys_memory": _memory_rows,
    "sys_tablet_counters": _tablet_counters_rows,
    "sys_statistics": _statistics_rows,
    "sys_scan_pruning": _scan_pruning_rows,
    "sys_resident_store": _resident_store_rows,
    "sys_device_memory": _device_memory_rows,
    "sys_top_queries": _top_queries_rows,
    "sys_query_log": _query_log_rows,
    "sys_active_queries": _active_queries_rows,
    "sys_tenant_pools": _tenant_pools_rows,
}


def sys_source(cluster, name: str) -> ColumnSource:
    """Materialize ONE sys view (each has its own cost; the lazy source
    map builds only what a query touches)."""
    return _source(name, _BUILDERS[name](cluster), cluster.dicts)


def sys_sources(cluster) -> dict[str, ColumnSource]:
    return {name: sys_source(cluster, name) for name in SYS_SCHEMAS}


def health_check(cluster) -> dict:
    """Aggregated health (health_check.cpp analog): GOOD | DEGRADED |
    EMERGENCY plus per-issue detail."""
    issues = []
    # storage probe: write/read/delete a canary blob
    try:
        cluster.store.put("health/canary", b"ok")
        if cluster.store.get("health/canary") != b"ok":
            issues.append({"severity": "red",
                           "message": "storage canary mismatch"})
        cluster.store.delete("health/canary")
    except Exception as e:  # noqa: BLE001
        issues.append({"severity": "red",
                       "message": f"storage unavailable: {e}"})
    # degraded erasure groups (when running on a GroupBlobStore)
    proxy = getattr(cluster.store, "proxy", None)
    if proxy is not None:
        down = sum(1 for d in proxy.group.disks if d.down)
        if down:
            sev = ("red" if down > proxy.codec.max_lost else "yellow")
            issues.append({
                "severity": sev,
                "message": f"group {proxy.group.group_id}: {down} "
                           f"disk(s) down",
            })
    # scheme/table agreement
    for desc in cluster.scheme.list_tables():
        if desc.path.strip("/") not in cluster.tables:
            issues.append({
                "severity": "yellow",
                "message": f"table {desc.path} in scheme but not "
                           f"instantiated",
            })
    if any(i["severity"] == "red" for i in issues):
        status = "EMERGENCY"
    elif issues:
        status = "DEGRADED"
    else:
        status = "GOOD"
    return {"status": status, "issues": issues}
